"""Overload autopilot: closed-loop SLO control with a reversible brownout
ladder (docs/autopilot.md).

The controller that turns two PRs of sensors (traces, compile/memory
accounting, the serving histograms) into action: under sustained queue
pressure it widens coalescing toward throughput, sheds low-weight tenants
with typed 429s, and finally spends bounded accuracy (q16 +
``subsample_trees``) — every rung a documented degradation-ladder entry,
every transition an ``autopilot.*`` event, recovery rung-by-rung with
hysteresis. ``python -m isoforest_tpu serve ... --autopilot`` arms it.
"""

from .controller import (
    RUNG_REASONS,
    Autopilot,
    AutopilotConfig,
    current_rung,
    mount_autopilot,
)

__all__ = [
    "RUNG_REASONS",
    "Autopilot",
    "AutopilotConfig",
    "current_rung",
    "mount_autopilot",
]
