"""scikit-learn adapter — the Pipeline-integration analogue.

The reference plugs into ``spark.ml`` as an Estimator/Model usable inside
``Pipeline``s (README.md:31-52). The Python-ecosystem equivalent is the
scikit-learn estimator protocol: this module wraps the TPU models as
``BaseEstimator``/``OutlierMixin`` classes so they compose with
``sklearn.pipeline.Pipeline``, ``GridSearchCV``, etc., while running all
compute through the JAX kernels.

sklearn conventions honoured: ``fit(X, y=None)`` returns self;
``score_samples`` returns the *negated* anomaly score (higher = more normal,
matching ``sklearn.ensemble.IsolationForest``); ``predict`` returns +1
(inlier) / -1 (outlier); ``decision_function = score_samples - offset_``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    from sklearn.base import BaseEstimator, OutlierMixin
    from sklearn.exceptions import NotFittedError
except Exception:  # pragma: no cover - sklearn is in the base image
    class BaseEstimator:  # type: ignore
        pass

    class OutlierMixin:  # type: ignore
        pass

    class NotFittedError(Exception):  # type: ignore
        pass

from .models import ExtendedIsolationForest, IsolationForest
from .utils import ExtendedIsolationForestParams, IsolationForestParams


class TpuIsolationForest(BaseEstimator, OutlierMixin):
    """Drop-in sklearn outlier detector backed by the TPU isolation forest."""

    def __init__(
        self,
        n_estimators: int = 100,
        max_samples: float = 256.0,
        contamination: float = 0.0,
        contamination_error: float = 0.0,
        max_features: float = 1.0,
        bootstrap: bool = False,
        random_state: int = 1,
        extension_level: Optional[int] = None,
        nonfinite: str = "warn",
    ):
        self.n_estimators = n_estimators
        self.max_samples = max_samples
        self.contamination = contamination
        self.contamination_error = contamination_error
        self.max_features = max_features
        self.bootstrap = bootstrap
        self.random_state = random_state
        self.extension_level = extension_level
        # NaN/inf input policy ("warn"/"raise"/"allow"), threaded to
        # fit/score (utils.validation.check_non_finite)
        self.nonfinite = nonfinite

    # ------------------------------------------------------------------ #

    def _build_estimator(self):
        common = dict(
            num_estimators=self.n_estimators,
            max_samples=float(self.max_samples),
            contamination=self.contamination,
            contamination_error=self.contamination_error,
            max_features=float(self.max_features),
            bootstrap=self.bootstrap,
            random_seed=self.random_state,
        )
        if self.extension_level is not None:
            return ExtendedIsolationForest(
                params=ExtendedIsolationForestParams(
                    extension_level=self.extension_level, **common
                )
            )
        return IsolationForest(params=IsolationForestParams(**common))

    def fit(
        self,
        X,
        y=None,
        mesh=None,
        checkpoint_dir=None,
        checkpoint_every=None,
        resume=False,
    ):
        """Fit; ``checkpoint_dir``/``checkpoint_every``/``resume`` enable
        preemption-safe block-wise training with bitwise-identical resume
        (docs/resilience.md §5), threaded straight to the underlying
        estimator's ``fit``."""
        X = np.asarray(X, np.float32)
        self.model_ = self._build_estimator().fit(
            X,
            mesh=mesh,
            nonfinite=self.nonfinite,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            resume=resume,
        )
        thr = self.model_.outlier_score_threshold
        # decision_function offset: sklearn flags decision_function < 0
        self.offset_ = -thr if thr > 0 else -0.5
        self.n_features_in_ = X.shape[1]
        return self

    def score_samples(self, X) -> np.ndarray:
        """Negated anomaly score (sklearn convention: higher = more normal)."""
        self._check_fitted()
        return -self.model_.score(
            np.asarray(X, np.float32), nonfinite=self.nonfinite
        )

    def decision_function(self, X) -> np.ndarray:
        return self.score_samples(X) - self.offset_

    def predict(self, X) -> np.ndarray:
        """+1 inlier / -1 outlier (sklearn convention)."""
        return np.where(self.decision_function(X) < 0, -1, 1)

    def fit_predict(self, X, y=None) -> np.ndarray:
        return self.fit(X).predict(X)

    def anomaly_score(self, X) -> np.ndarray:
        """The reference's raw outlier score in [0, 1] (not negated)."""
        self._check_fitted()
        return self.model_.score(
            np.asarray(X, np.float32), nonfinite=self.nonfinite
        )

    # -- model observability pass-throughs (docs/observability.md §8) ---- #

    def diagnostics(self) -> dict:
        """Forest-structure diagnostics of the fitted model."""
        self._check_fitted()
        return self.model_.diagnostics()

    def enable_monitoring(self, threshold=None, **monitor_kwargs):
        """Attach a drift monitor to the fitted model; every subsequent
        ``score_samples``/``predict``/``anomaly_score`` call folds its batch
        into it. Returns the ScoreMonitor."""
        self._check_fitted()
        return self.model_.enable_monitoring(
            threshold=threshold, **monitor_kwargs
        )

    def disable_monitoring(self) -> None:
        self._check_fitted()
        self.model_.disable_monitoring()

    def rebind_monitoring(self, baseline=None):
        """Re-arm the attached drift monitor against a (possibly new)
        baseline — see ``IsolationForestModel.rebind_monitoring``."""
        self._check_fitted()
        return self.model_.rebind_monitoring(baseline=baseline)

    def manage(
        self,
        work_dir,
        drift_debounce=3,
        window_rows=65536,
        gates=None,
        **manager_kwargs,
    ):
        """Wrap the fitted model in a lifecycle
        :class:`~isoforest_tpu.lifecycle.ModelManager` (drift-triggered
        retraining with validation-gated atomic hot-swap,
        docs/resilience.md §8). The manager knobs pass straight through,
        mirroring the ``checkpoint_dir``/``nonfinite`` pattern:
        ``drift_debounce`` (consecutive over-threshold evaluations before a
        retrain), ``window_rows`` (recent-data reservoir size), ``gates``
        (a :class:`~isoforest_tpu.lifecycle.ValidationGates`), plus any
        other ``ModelManager`` keyword. Score through the returned
        manager (``manager.score``) — after a swap, ``self.model_``
        tracks the active generation."""
        self._check_fitted()
        from .lifecycle import ModelManager

        adapter = self

        class _AdapterTrackingManager(ModelManager):
            # keep the sklearn facade pointing at the live generation so
            # score_samples/predict stay coherent after a hot-swap
            def _swap(self, candidate, seq, target):
                super()._swap(candidate, seq, target)
                adapter.model_ = candidate

        return _AdapterTrackingManager(
            self.model_,
            work_dir,
            drift_debounce=drift_debounce,
            window_rows=window_rows,
            gates=gates,
            **manager_kwargs,
        )

    def _check_fitted(self):
        if not hasattr(self, "model_"):
            raise NotFittedError(
                "This TpuIsolationForest instance is not fitted yet; call fit first"
            )
