"""Persistent measured cost model backing ``strategy="auto"``.

The table maps a decision key — ``(backend, model-shape-bucket,
batch-size-bucket, extended?, restricted?)`` rendered as one string — to the
strategy that *measured* fastest for that regime, plus the per-strategy probe
timings that justified it. It is persisted as schema-versioned JSON next to
the TPU probe cache (:mod:`tools.probe_tpu` keeps its TTL-cached tunnel
verdict in the same temp dir, same atomic tmp+rename discipline), so the
cost of a cold probe is paid once per TTL window per process fleet instead
of once per process.

File format (``docs/autotune.md``)::

    {"schema": 1,
     "entries": {
       "<key>": {"strategy": "native",
                 "timings_s": {"native": 0.021, "gather": 0.098, "dense": null},
                 "probe_rows": 65536, "reps": 2, "unix_s": 1754300000.0}}}

A corrupt file, an unknown schema version, or a non-dict document is
REFUSED: the table starts empty (clean rebuild — the next probe overwrites
the bad file) with a one-shot warning, never a crash and never a
half-trusted entry. Entries age out individually after
``ISOFOREST_TPU_AUTOTUNE_TTL_S`` (default 1 day) — a stale entry reads as a
miss and the next ``auto`` resolution re-probes (source ``"probe"`` with
``refresh=true`` in the decision event).

Concurrency: writes re-read the file and merge per-entry (newest
``unix_s`` wins) before the atomic replace, so two processes probing
different keys both land; readers re-stat the file at most once per
:data:`_RELOAD_EVERY_S` so a fleet member picks up a peer's probe without
paying a stat per scoring call.
"""

from __future__ import annotations

import json
import os
import pathlib
import tempfile
import threading
import time
from typing import Dict, Optional, Tuple

from ..telemetry.events import record_event
from ..utils.logging import logger

SCHEMA_VERSION = 1
DEFAULT_TTL_S = 86_400.0

# readers re-stat the table file at most this often (serving loops resolve
# per batch; a stat per call would be pure overhead)
_RELOAD_EVERY_S = 5.0


def table_path() -> pathlib.Path:
    """Resolved table location: ``ISOFOREST_TPU_AUTOTUNE_PATH`` or the temp
    dir beside the probe cache. Read per call so tests can re-point it."""
    env = os.environ.get("ISOFOREST_TPU_AUTOTUNE_PATH")
    if env:
        return pathlib.Path(env)
    return pathlib.Path(tempfile.gettempdir()) / "isoforest_tpu_autotune.json"


def ttl_s() -> float:
    try:
        return float(os.environ.get("ISOFOREST_TPU_AUTOTUNE_TTL_S", DEFAULT_TTL_S))
    except ValueError:
        return DEFAULT_TTL_S


def _valid_entry(entry: object) -> bool:
    return (
        isinstance(entry, dict)
        and isinstance(entry.get("strategy"), str)
        and isinstance(entry.get("unix_s"), (int, float))
    )


class CostModel:
    """In-memory view of the persisted winner table (one per process)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self._loaded_path: Optional[pathlib.Path] = None
        self._loaded_stat: Optional[Tuple[float, int]] = None
        self._next_stat_s = 0.0
        self._warned_invalid = False

    # -- file I/O ---------------------------------------------------------

    def _read_file(self, path: pathlib.Path) -> Optional[Dict[str, dict]]:
        """Parse + validate the persisted document; None when absent or
        refused (corrupt / wrong schema — warned once, rebuilt clean)."""
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as exc:
            self._refuse(path, f"unreadable/corrupt ({type(exc).__name__}: {exc})")
            return None
        if not isinstance(doc, dict) or doc.get("schema") != SCHEMA_VERSION:
            got = doc.get("schema") if isinstance(doc, dict) else type(doc).__name__
            self._refuse(path, f"schema {got!r} != {SCHEMA_VERSION}")
            return None
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            self._refuse(path, "no 'entries' mapping")
            return None
        return {k: v for k, v in entries.items() if _valid_entry(v)}

    def _refuse(self, path: pathlib.Path, why: str) -> None:
        if not self._warned_invalid:
            self._warned_invalid = True
            logger.warning(
                "autotune table %s refused (%s); rebuilding from fresh probes",
                path,
                why,
            )
        record_event("autotune.table_rejected", path=str(path), reason=why)

    def _maybe_reload_locked(self, force: bool = False) -> None:
        path = table_path()
        now = time.monotonic()
        if path != self._loaded_path:
            force = True
        if not force and now < self._next_stat_s:
            return
        self._next_stat_s = now + _RELOAD_EVERY_S
        try:
            st = os.stat(path)
            stat_key = (st.st_mtime, st.st_size)
        except OSError:
            stat_key = None
        if not force and stat_key == self._loaded_stat:
            return
        entries = self._read_file(path)
        self._entries = entries if entries is not None else {}
        self._loaded_path = path
        self._loaded_stat = stat_key

    # -- API --------------------------------------------------------------

    def lookup(self, key: str, now: Optional[float] = None) -> Tuple[Optional[dict], bool]:
        """``(entry, fresh)`` for a key: entry is None on a miss; ``fresh``
        is False when the entry exists but has aged past the TTL (the
        caller re-probes and records the refresh)."""
        now = time.time() if now is None else now
        with self._lock:
            self._maybe_reload_locked()
            entry = self._entries.get(key)
        if entry is None:
            return None, False
        age = now - float(entry["unix_s"])
        return dict(entry), 0 <= age <= ttl_s()

    def store(self, key: str, entry: dict) -> None:
        """Merge one probed entry into memory AND the persisted file
        (read-merge-replace; newest ``unix_s`` wins per key)."""
        path = table_path()
        with self._lock:
            self._maybe_reload_locked(force=True)
            merged = dict(self._entries)
            prior = merged.get(key)
            if prior is None or float(prior["unix_s"]) <= float(entry["unix_s"]):
                merged[key] = dict(entry)
            self._entries = merged
            doc = {"schema": SCHEMA_VERSION, "entries": merged}
            tmp = f"{path}.tmp-{os.getpid()}"
            try:
                path.parent.mkdir(parents=True, exist_ok=True)
                with open(tmp, "w") as fh:
                    json.dump(doc, fh, sort_keys=True)
                os.replace(tmp, path)
                st = os.stat(path)
                self._loaded_stat = (st.st_mtime, st.st_size)
                self._loaded_path = path
            except OSError as exc:
                # read-only tmp dir: the in-memory table still serves this
                # process; the fleet just re-probes
                logger.warning("autotune table %s unwritable: %s", path, exc)

    def snapshot(self) -> dict:
        """The full persisted document (fresh read merged over memory) —
        what ``python -m isoforest_tpu autotune --format json`` prints, and
        it round-trips ``json.loads`` back to the file contents."""
        with self._lock:
            self._maybe_reload_locked(force=True)
            return {
                "schema": SCHEMA_VERSION,
                "path": str(table_path()),
                "ttl_s": ttl_s(),
                "entries": {k: dict(v) for k, v in sorted(self._entries.items())},
            }

    def clear(self) -> bool:
        """Drop the in-memory table and delete the file; True if a file
        existed."""
        with self._lock:
            self._entries = {}
            self._loaded_stat = None
            try:
                os.unlink(table_path())
                return True
            except FileNotFoundError:
                return False


_MODEL = CostModel()
_MODEL_LOCK = threading.Lock()


def cost_model() -> CostModel:
    return _MODEL


def reset_cost_model() -> None:
    """Forget all in-memory state (tests re-point the table via env)."""
    global _MODEL
    with _MODEL_LOCK:
        _MODEL = CostModel()
