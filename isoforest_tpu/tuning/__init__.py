"""Measured strategy autotuning: probe once per regime, persist the winner.

``strategy="auto"`` resolutions consult a persisted cost model keyed on
``(backend, model-shape-bucket, batch-size-bucket, extended?)``; cold keys
run a short warmed best-of-k probe of every eligible strategy and the
winner table is cached as schema-versioned JSON with a TTL, FastForest-style
(PAPERS.md, arxiv 2004.02423). See docs/autotune.md and
:mod:`.autotuner` / :mod:`.cost_model`.

The streaming executor's chunk policy
(:func:`~isoforest_tpu.ops.streaming.resolve_chunk_rows`, re-exported
here) rides the same bucket formula the table keys on: streamed
micro-batches always land on the pre-warmed, autotuned compiled shapes
(docs/pipeline.md), so a tuned decision for bucket ``b`` covers every
chunk of a streamed run at chunk size ``b``.
"""

from ..ops.streaming import resolve_chunk_rows

from .autotuner import (
    DECISION_SOURCES,
    JITTABLE_STRATEGIES,
    Decision,
    autotune_enabled,
    clear_table,
    decision_counts,
    decision_key,
    eligible_strategies,
    emit_decision,
    model_bucket,
    resolve_decision,
    table_snapshot,
    unkeyed,
)
from .cost_model import (
    DEFAULT_TTL_S,
    SCHEMA_VERSION,
    CostModel,
    cost_model,
    reset_cost_model,
    table_path,
    ttl_s,
)

__all__ = [
    "DECISION_SOURCES",
    "DEFAULT_TTL_S",
    "JITTABLE_STRATEGIES",
    "SCHEMA_VERSION",
    "CostModel",
    "Decision",
    "autotune_enabled",
    "clear_table",
    "cost_model",
    "decision_counts",
    "decision_key",
    "eligible_strategies",
    "emit_decision",
    "model_bucket",
    "reset_cost_model",
    "resolve_chunk_rows",
    "resolve_decision",
    "table_path",
    "table_snapshot",
    "ttl_s",
    "unkeyed",
]
