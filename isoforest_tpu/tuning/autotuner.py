"""Measured strategy autotuner behind ``strategy="auto"``.

Strategy selection is the highest-leverage perf decision in the scoring
path: bench rounds r01-r05 measured the real ranking swinging by orders of
magnitude with shape and backend (gather 0.88 s vs dense 44.5 s vs native
0.075 s on the same workload). The hand-ordered preference table
(:func:`~isoforest_tpu.ops.traversal.default_strategy`) encodes two
backends' worth of those measurements; this module replaces guessing with
measuring, in the FastForest spirit (PAPERS.md, arxiv 2004.02423): on the
first encounter of a decision key — ``(backend, model-shape-bucket,
batch-size-bucket, extended?)`` — run a short warmed best-of-k timed probe
of every *eligible* strategy and persist the winner
(:mod:`.cost_model`), so every later resolution anywhere in the fleet is a
dict hit.

Eligibility is decided BEFORE probing from the same fences ``score_matrix``
applies after resolution (``native.available()``, the EIF-Pallas precision
fence, ``pallas_walk.unsupported_reason``, no interpret-mode kernels
off-TPU), so an ineligible strategy is never probed and a tuned pick never
takes a ladder rung. A probe that still fails (raises) is excluded from the
ranking; if NO eligible strategy yields a measurement, the resolution takes
the ``autotune_probe_failed`` rung and falls back to the static preference
table (the rung is strict-exempt: the static default is a fully supported
strategy, not a silent kernel substitution).

Every ``auto`` resolution — wherever it happens — emits exactly one
``autotune.decision`` timeline event and one
``isoforest_autotune_decisions_total{source=}`` tick, with
``source ∈ {table, probe, pin, fallback}``, so a serving operator can
always tell which mechanism chose the kernel behind a latency series.
Probe executions themselves run with the per-strategy scoring metrics
suppressed (:func:`~isoforest_tpu.ops.traversal.suppress_scoring_metrics`)
so probe wall-clock never pollutes the serving histograms.

Env knobs (docs/autotune.md): ``ISOFOREST_TPU_STRATEGY`` pins a strategy
(source ``"pin"``, beats the table), ``ISOFOREST_TPU_AUTOTUNE=0`` bypasses
the tuner entirely (static table, source ``"fallback"``),
``ISOFOREST_TPU_AUTOTUNE_PROBE_ROWS`` / ``_REPS`` / ``_BUDGET_S`` bound
probe cost, ``_TTL_S`` / ``_PATH`` control the persisted table.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _telemetry_counter
from .cost_model import cost_model

DECISION_SOURCES = ("table", "probe", "pin", "fallback")

# the two shard_map-jittable formulations — the restricted pool
# parallel/sharded.resolve_jittable_strategy tunes over
JITTABLE_STRATEGIES = ("gather", "dense")

DEFAULT_PROBE_ROWS = 65_536
DEFAULT_PROBE_REPS = 2
DEFAULT_PROBE_BUDGET_S = 2.0

_DECISIONS_TOTAL = _telemetry_counter(
    "isoforest_autotune_decisions_total",
    "strategy='auto' resolutions by decision source (docs/autotune.md)",
    labelnames=("source",),
)

# cold probes are serialized: a serving worker pool hitting one cold key
# from many threads must pay the probe once, not once per thread
_PROBE_LOCK = threading.Lock()


class Decision(NamedTuple):
    """One resolved ``auto`` decision (already emitted to telemetry)."""

    strategy: str
    source: str  # one of DECISION_SOURCES
    key: str
    timings_s: Optional[Dict[str, Optional[float]]] = None
    refresh: bool = False


def autotune_enabled() -> bool:
    """``ISOFOREST_TPU_AUTOTUNE`` gate, default ON (0/false/off/no bypass)."""
    return os.environ.get("ISOFOREST_TPU_AUTOTUNE", "1").strip().lower() not in (
        "0",
        "false",
        "off",
        "no",
    )


def _probe_rows_cap() -> int:
    try:
        return max(1, int(os.environ.get("ISOFOREST_TPU_AUTOTUNE_PROBE_ROWS", DEFAULT_PROBE_ROWS)))
    except ValueError:
        return DEFAULT_PROBE_ROWS


def _probe_reps() -> int:
    try:
        return max(1, int(os.environ.get("ISOFOREST_TPU_AUTOTUNE_REPS", DEFAULT_PROBE_REPS)))
    except ValueError:
        return DEFAULT_PROBE_REPS


def _probe_budget_s() -> float:
    try:
        return float(os.environ.get("ISOFOREST_TPU_AUTOTUNE_BUDGET_S", DEFAULT_PROBE_BUDGET_S))
    except ValueError:
        return DEFAULT_PROBE_BUDGET_S


# -- decision keys --------------------------------------------------------


def _pow2_ceil(n: int) -> int:
    return 1 << (max(int(n), 1) - 1).bit_length()


def _feature_class(num_features: int) -> str:
    """The packed layout's feature-id narrowing class (scoring_layout
    boundaries F<=128 / F<=32768): the dtype changes the gathered bytes per
    step, so keys must split at exactly these edges."""
    from ..ops.scoring_layout import _I8_MAX_FEATURES, _I16_MAX_FEATURES

    if num_features <= _I8_MAX_FEATURES:
        return "i8"
    if num_features <= _I16_MAX_FEATURES:
        return "i16"
    return "i32"


def model_bucket(forest, num_features: int) -> str:
    """Shape bucket of a fitted forest: tree count (pow2), heap height,
    feature-id class, and the hyperplane arity for extended forests."""
    from ..ops.tree_growth import StandardForest
    from ..utils.math import height_of

    t = _pow2_ceil(forest.num_trees)
    h = height_of(forest.max_nodes)
    fc = _feature_class(int(num_features))
    if isinstance(forest, StandardForest):
        return f"t{t}h{h}{fc}"
    return f"t{t}h{h}{fc}k{forest.indices.shape[2]}"


def decision_key(
    platform: str,
    forest,
    num_rows: int,
    num_features: int,
    restrict: Optional[Sequence[str]] = None,
) -> str:
    """The persisted-table key. Restricted (shard_map-jittable) resolutions
    key separately: their winner pool differs, and the two must never
    clobber each other's entries."""
    from ..ops.traversal import batch_bucket
    from ..ops.tree_growth import StandardForest

    ext = "ext" if not isinstance(forest, StandardForest) else "std"
    key = (
        f"v1|{platform}|{model_bucket(forest, num_features)}"
        f"|b{batch_bucket(num_rows)}|{ext}"
    )
    if restrict is not None:
        key += "|jittable"
    else:
        from ..ops.scoring_layout import quantized_eligible

        # quantized-plane facet: forests that can take the q16 strategy key
        # separately from ones that cannot, so a winner probed WITH q16 in
        # the pool is never served to a forest whose pool lacks it (and
        # pre-q16 table entries go stale instead of silently excluding the
        # new candidate)
        if quantized_eligible(forest):
            key += "|q16"
    return key


def unkeyed(platform: str, site: str) -> str:
    """Degenerate key for resolutions with no forest/shape in hand (e.g. the
    fused train step builds its program before any data exists)."""
    return f"v1|{platform}|unkeyed|{site}"


# -- eligibility ----------------------------------------------------------


def eligible_strategies(
    forest,
    platform: str,
    restrict: Optional[Sequence[str]] = None,
) -> Tuple[str, ...]:
    """Strategies worth probing for this (forest, backend), in static
    preference order (ties in the timed ranking break toward the front).

    Mirrors every fence ``score_matrix`` applies after resolution, so a
    tuned pick can never take a ladder rung: ``native`` needs the C++
    walker; ``pallas``/``walk`` need a real TPU (off-TPU they only run in
    interpret mode — minutes per batch, never a serving candidate); the EIF
    Pallas kernels are precision-fenced on TPU; ``walk`` additionally
    consults :func:`~isoforest_tpu.ops.pallas_walk.unsupported_reason`;
    ``q16`` consults the quantized-plane capacity fence
    (:func:`~isoforest_tpu.ops.scoring_layout.quantized_eligible` — 16-bit
    feature ids, <= 65535 distinct thresholds / leaf values).
    """
    from ..ops.tree_growth import StandardForest

    extended = not isinstance(forest, StandardForest)
    order = (
        ("pallas", "dense", "q16", "walk", "native", "gather")
        if platform == "tpu"
        else ("native", "q16", "gather", "dense")
    )
    out = []
    for s in order:
        if restrict is not None and s not in restrict:
            continue
        if s == "native":
            from .. import native

            if not native.available():
                continue
        elif s == "pallas":
            if platform != "tpu" or extended:
                continue
        elif s == "walk":
            if platform != "tpu":
                continue
            from ..ops import pallas_walk

            if pallas_walk.unsupported_reason(forest) is not None:
                continue
        elif s == "q16":
            from ..ops.scoring_layout import quantized_eligible

            if not quantized_eligible(forest):
                continue
        out.append(s)
    return tuple(out)


# -- probing --------------------------------------------------------------


def _probe(
    forest,
    X: np.ndarray,
    num_samples: int,
    eligible: Sequence[str],
    layout=None,
) -> Dict[str, Optional[float]]:
    """Warmed best-of-k wall-clock per eligible strategy over the probe
    slice; ``None`` marks a probe failure (strategy excluded from ranking).

    Protocol per strategy: one warm-up run (compiles + builds per-strategy
    prep; ``strict=True`` so any ladder rung surfaces as a clean failure
    instead of silently timing a different kernel), then up to ``reps``
    timed runs, stopping early once the soft budget is spent. A warm-up
    slower than the budget stands as that strategy's (compile-inclusive)
    measurement — a strategy that cannot finish one warmed rep inside the
    budget was never going to win, and bounding the probe is what keeps
    cold-start cost a one-time, fleet-amortised constant.
    """
    from ..ops import traversal

    reps = _probe_reps()
    budget_s = _probe_budget_s()
    timings: Dict[str, Optional[float]] = {}
    with traversal.suppress_scoring_metrics():
        for strat in eligible:
            try:
                t0 = time.perf_counter()
                traversal.score_matrix(
                    forest,
                    X,
                    num_samples,
                    strategy=strat,
                    layout=layout,
                    strict=True,
                )
                warm = time.perf_counter() - t0
            except Exception as exc:  # noqa: BLE001 — excluded, never fatal
                timings[strat] = None
                record_event(
                    "autotune.probe_error",
                    strategy=strat,
                    error=f"{type(exc).__name__}: {exc}",
                )
                continue
            if warm > budget_s:
                timings[strat] = warm
                continue
            best = None
            spent = 0.0
            for _ in range(reps):
                t0 = time.perf_counter()
                traversal.score_matrix(
                    forest,
                    X,
                    num_samples,
                    strategy=strat,
                    layout=layout,
                    strict=True,
                )
                dt = time.perf_counter() - t0
                best = dt if best is None or dt < best else best
                spent += dt
                if spent > budget_s:
                    break
            timings[strat] = best
    return timings


def _probe_slice(X, num_rows: int) -> np.ndarray:
    """Host-resident probe matrix: the leading ``min(num_rows, cap)`` rows
    of the actual batch (tiled up when the caller resolved a bucket larger
    than the data in hand), so probes see real data distribution and the
    real feature width. Never fewer than one row: an empty batch keys to
    the minimum bucket, and score_matrix pads any probe up to it anyway."""
    cap = max(1, min(int(num_rows), _probe_rows_cap()))
    Xh = np.asarray(X[: min(cap, int(X.shape[0]))], np.float32)
    if Xh.shape[0] < cap:
        Xh = np.resize(Xh, (cap, Xh.shape[1]))
    return np.ascontiguousarray(Xh)


# -- resolution -----------------------------------------------------------


def emit_decision(
    strategy: str,
    source: str,
    key: str,
    site: str,
    refresh: bool = False,
) -> None:
    """One counter tick + one timeline event per ``auto`` resolution."""
    _DECISIONS_TOTAL.inc(source=source)
    fields = {"source": source, "strategy": strategy, "key": key, "site": site}
    if refresh:
        fields["refresh"] = True
    record_event("autotune.decision", **fields)


def decision_counts() -> Dict[str, float]:
    """Current ``isoforest_autotune_decisions_total`` values by source."""
    return {s: _DECISIONS_TOTAL.value(source=s) for s in DECISION_SOURCES}


def resolve_decision(
    forest,
    X,
    num_samples: int,
    *,
    platform: Optional[str] = None,
    restrict: Optional[Sequence[str]] = None,
    static_default: Optional[str] = None,
    num_rows: Optional[int] = None,
    strict: bool = False,
    layout=None,
    site: str = "score_matrix",
    refresh: bool = False,
    pin_rung: str = "env_strategy_unknown",
) -> Decision:
    """Resolve ``strategy="auto"`` for one scoring call; emits exactly one
    decision event/counter tick and returns the :class:`Decision`.

    Precedence: a valid ``ISOFOREST_TPU_STRATEGY`` pin always wins
    (source ``"pin"``; an invalid or restricted-out pin takes the existing
    ``env_strategy_unknown`` / ``shard_pin_ineligible`` rung and resolution
    continues); then the fresh persisted table (``"table"``); then a cold
    or stale-entry probe (``"probe"``); and the static preference table
    when the tuner is disabled or probing yielded nothing (``"fallback"``).
    ``restrict`` narrows the candidate pool (the shard_map sites pass
    :data:`JITTABLE_STRATEGIES`); ``num_rows`` overrides the batch-bucket
    row count when the caller scores a different per-device slice than
    ``X`` itself (sharded scoring).
    """
    from ..ops import traversal
    from ..ops.tree_growth import StandardForest
    from ..resilience.degradation import degrade

    if platform is None:
        platform = traversal._live_platform()
    n = int(num_rows) if num_rows is not None else int(X.shape[0])
    num_features = int(X.shape[1])
    extended = not isinstance(forest, StandardForest)
    if static_default is None:
        static_default = traversal.default_strategy(
            num_rows=n, extended=extended, platform=platform
        )
    key = decision_key(platform, forest, n, num_features, restrict)

    pin = os.environ.get("ISOFOREST_TPU_STRATEGY") or None
    if pin is not None:
        valid = pin in traversal.STRATEGIES
        if valid and (restrict is None or pin in restrict):
            emit_decision(pin, "pin", key, site)
            return Decision(pin, "pin", key)
        if pin_rung == "shard_pin_ineligible":
            detail = (
                f"ISOFOREST_TPU_STRATEGY={pin!r} is not eligible inside "
                "shard_map programs (gather/dense only); sharded scoring "
                "resolves its own measured/tuned default"
            )
            degrade(pin_rung, repr(pin), static_default, detail=detail)
        else:
            detail = (
                f"ISOFOREST_TPU_STRATEGY={pin!r} is not one of "
                f"{'/'.join(traversal.STRATEGIES)}; resolving the "
                "measured/tuned default"
            )
            degrade(pin_rung, repr(pin), static_default, detail=detail, strict=strict)

    if not autotune_enabled():
        emit_decision(static_default, "fallback", key, site)
        return Decision(static_default, "fallback", key)

    eligible = eligible_strategies(forest, platform, restrict)
    entry, fresh = cost_model().lookup(key)
    if entry is not None and fresh and not refresh and entry["strategy"] in eligible:
        emit_decision(entry["strategy"], "table", key, site)
        return Decision(entry["strategy"], "table", key, entry.get("timings_s"))

    is_refresh = entry is not None
    with _PROBE_LOCK:
        # a concurrent thread may have probed this key while we waited
        entry2, fresh2 = cost_model().lookup(key)
        if (
            entry2 is not None
            and fresh2
            and not refresh
            and entry2["strategy"] in eligible
        ):
            emit_decision(entry2["strategy"], "table", key, site)
            return Decision(entry2["strategy"], "table", key, entry2.get("timings_s"))
        Xp = _probe_slice(X, n)
        # probe executions compile every eligible strategy once — expected
        # one-time cost even after serving marks steady, so they run under
        # warmup_scope and attribute to their own compile site
        from ..telemetry import resources as _resources

        with _resources.warmup_scope(), _resources.compile_scope(
            "autotune.probe", key=key
        ):
            timings = _probe(forest, Xp, num_samples, eligible, layout=layout)

    finite = {
        s: t for s, t in timings.items() if t is not None and math.isfinite(t)
    }
    if not finite:
        # strict-exempt by design: the static default is a fully supported
        # strategy, not a silent substitution for a pinned kernel
        degrade(
            "autotune_probe_failed",
            "auto",
            static_default,
            detail=(
                f"autotune probe for key {key} produced no measurement over "
                f"eligible strategies {list(eligible)}; using the static "
                f"per-backend default {static_default!r}"
            ),
        )
        emit_decision(static_default, "fallback", key, site)
        return Decision(static_default, "fallback", key, timings)

    order = {s: i for i, s in enumerate(eligible)}
    winner = min(finite, key=lambda s: (finite[s], order[s]))
    new_entry = {
        "strategy": winner,
        "timings_s": {
            s: (round(t, 6) if t is not None else None) for s, t in timings.items()
        },
        "probe_rows": int(Xp.shape[0]),
        "reps": _probe_reps(),
        "unix_s": time.time(),
    }
    cost_model().store(key, new_entry)
    record_event(
        "autotune.probe",
        key=key,
        winner=winner,
        timings_s=new_entry["timings_s"],
        probe_rows=new_entry["probe_rows"],
        refresh=is_refresh,
    )
    emit_decision(winner, "probe", key, site, refresh=is_refresh)
    return Decision(winner, "probe", key, timings, refresh=is_refresh)


def table_snapshot() -> dict:
    """The persisted table document (see :meth:`CostModel.snapshot`)."""
    return cost_model().snapshot()


def clear_table() -> bool:
    """Delete the persisted table; True if a file existed."""
    return cost_model().clear()


__all__ = [
    "DECISION_SOURCES",
    "JITTABLE_STRATEGIES",
    "Decision",
    "autotune_enabled",
    "clear_table",
    "cost_model",
    "decision_counts",
    "decision_key",
    "eligible_strategies",
    "emit_decision",
    "model_bucket",
    "resolve_decision",
    "table_snapshot",
    "unkeyed",
]
