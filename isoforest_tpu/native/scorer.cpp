// Native single-core scoring kernels for the CPU execution path.
//
// The TPU path scores via XLA/Pallas dense level-walks; on CPU the XLA
// lowering of either formulation is gather- or bandwidth-bound and loses to
// hand-scheduled C++ (round-1 bench: 6.3 s to score 1M rows x 100 trees).
// This kernel walks the same implicit-heap struct-of-arrays forest
// (ops/tree_growth.py StandardForest / ops/ext_growth.py ExtendedForest,
// reference semantics IsolationTree.scala:213-229: feature < threshold ->
// left, >= -> right; leaf adds avgPathLength(numInstances)) with the
// per-slot leaf value (depth + c(n)) precomputed host-side.
//
// The walk interleaves TREE_BLOCK independent trees per row so the
// data-dependent node loads pipeline instead of serialising on L2 latency
// (node tables for 100 trees x 511 slots fit comfortably in L2).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {
// Measured on the build host (1-core, 200k rows x 100 trees): 4-wide 552k,
// 8-wide 790k, 16-wide 929k, 32-wide 799k rows/s — 16 chains saturate the
// L2 miss-level parallelism without spilling the node-state registers.
constexpr int TREE_BLOCK = 16;

// Tree-tile byte budget: big forests (1000 trees x 511 slots ~ 6 MB of
// node tables) overflow L2, so trees are processed in table-resident
// groups with rows inner (measured at T=1000: 55k -> 86k rows/s). The
// budget is sized for a ~1 MB L2 with headroom; small forests fall in a
// single tile and take the direct path.
constexpr int64_t TILE_BYTES = 768 * 1024;

inline int64_t tile_trees(int64_t bytes_per_tree) {
  const int64_t t = TILE_BYTES / (bytes_per_tree > 0 ? bytes_per_tree : 1);
  // round down to a TREE_BLOCK multiple, min one block
  return std::max<int64_t>(TREE_BLOCK, (t / TREE_BLOCK) * TREE_BLOCK);
}
}  // namespace

extern "C" {

// Mean path length per row over a standard forest.
//   X[n_rows, n_features] f32 row-major; feature[T, M] i32 (-1 leaf);
//   threshold[T, M] f32; leaf_value[T, M] f32 (depth + c(numInstances) at
//   leaves, 0 elsewhere); out[n_rows] f32.
void if_score_standard(const float* X, int64_t n_rows, int32_t n_features,
                       const int32_t* feature, const float* threshold,
                       const float* leaf_value, int64_t n_trees,
                       int64_t m_nodes, int32_t height, float* out) {
  const int64_t tile = tile_trees(m_nodes * 12);  // feat+thr+leaf per node
  std::vector<double> acc_buf;
  double* acc = nullptr;
  if (n_trees > tile) {
    acc_buf.assign(n_rows, 0.0);
    acc = acc_buf.data();
  }
  for (int64_t g0 = 0; g0 < n_trees; g0 += tile) {
    const int64_t g1 = std::min(n_trees, g0 + tile);
    for (int64_t r = 0; r < n_rows; ++r) {
      const float* x = X + r * n_features;
      double total = 0.0;
      int64_t t0 = g0;
      for (; t0 + TREE_BLOCK <= g1; t0 += TREE_BLOCK) {
        int32_t nd[TREE_BLOCK] = {0};
        for (int32_t s = 0; s < height; ++s) {
          for (int j = 0; j < TREE_BLOCK; ++j) {
            const int64_t base = (t0 + j) * m_nodes;
            const int32_t n = nd[j];
            const int32_t f = feature[base + n];
            const bool internal = f >= 0;
            const float xv = x[internal ? f : 0];
            const int32_t nxt = 2 * n + 1 + (xv >= threshold[base + n] ? 1 : 0);
            nd[j] = internal ? nxt : n;
          }
        }
        for (int j = 0; j < TREE_BLOCK; ++j)
          total += leaf_value[(t0 + j) * m_nodes + nd[j]];
      }
      for (; t0 < g1; ++t0) {
        const int64_t base = t0 * m_nodes;
        int32_t n = 0;
        for (int32_t s = 0; s < height; ++s) {
          const int32_t f = feature[base + n];
          if (f < 0) break;
          n = 2 * n + 1 + (x[f] >= threshold[base + n] ? 1 : 0);
        }
        total += leaf_value[base + n];
      }
      if (acc) {
        acc[r] += total;
      } else {
        out[r] = static_cast<float>(total / static_cast<double>(n_trees));
      }
    }
  }
  if (acc) {
    for (int64_t r = 0; r < n_rows; ++r)
      out[r] = static_cast<float>(acc[r] / static_cast<double>(n_trees));
  }
}

// Extended (hyperplane) variant. indices[T, M, k] i32 (-1 padding; node is a
// leaf iff indices[t, m, 0] < 0); weights[T, M, k] f32 (0 at padding, so the
// unmasked dot matches the XLA gather path bit-for-bit in structure);
// offset[T, M] f32.
void if_score_extended(const float* X, int64_t n_rows, int32_t n_features,
                       const int32_t* indices, const float* weights,
                       const float* offset, const float* leaf_value,
                       int64_t n_trees, int64_t m_nodes, int32_t k,
                       int32_t height, float* out) {
  const int64_t tile = tile_trees(m_nodes * (8 * (int64_t)k + 8));
  std::vector<double> acc_buf;
  double* acc = nullptr;
  if (n_trees > tile) {
    acc_buf.assign(n_rows, 0.0);
    acc = acc_buf.data();
  }
  for (int64_t g0 = 0; g0 < n_trees; g0 += tile) {
    const int64_t g1 = std::min(n_trees, g0 + tile);
    for (int64_t r = 0; r < n_rows; ++r) {
      const float* x = X + r * n_features;
      double total = 0.0;
      int64_t t0 = g0;
      for (; t0 + TREE_BLOCK <= g1; t0 += TREE_BLOCK) {
        int32_t nd[TREE_BLOCK] = {0};
        for (int32_t s = 0; s < height; ++s) {
          for (int j = 0; j < TREE_BLOCK; ++j) {
            const int64_t base = (t0 + j) * m_nodes;
            const int32_t n = nd[j];
            const int64_t sub = (base + n) * k;
            const bool internal = indices[sub] >= 0;
            float dot = 0.0f;
            for (int32_t q = 0; q < k; ++q) {
              const int32_t f = indices[sub + q];
              dot += x[f >= 0 ? f : 0] * weights[sub + q];
            }
            const int32_t nxt = 2 * n + 1 + (dot >= offset[base + n] ? 1 : 0);
            nd[j] = internal ? nxt : n;
          }
        }
        for (int j = 0; j < TREE_BLOCK; ++j)
          total += leaf_value[(t0 + j) * m_nodes + nd[j]];
      }
      for (; t0 < g1; ++t0) {
        const int64_t base = t0 * m_nodes;
        int32_t n = 0;
        for (int32_t s = 0; s < height; ++s) {
          const int64_t sub = (base + n) * k;
          if (indices[sub] < 0) break;
          float dot = 0.0f;
          for (int32_t q = 0; q < k; ++q) {
            const int32_t f = indices[sub + q];
            dot += x[f >= 0 ? f : 0] * weights[sub + q];
          }
          n = 2 * n + 1 + (dot >= offset[base + n] ? 1 : 0);
        }
        total += leaf_value[base + n];
      }
      if (acc) {
        acc[r] += total;
      } else {
        out[r] = static_cast<float>(total / static_cast<double>(n_trees));
      }
    }
  }
  if (acc) {
    for (int64_t r = 0; r < n_rows; ++r)
      out[r] = static_cast<float>(acc[r] / static_cast<double>(n_trees));
  }
}

}  // extern "C"
