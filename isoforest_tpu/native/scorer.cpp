// Native scoring kernels for the CPU execution path.
//
// The TPU path scores via XLA/Pallas dense level-walks; on CPU the XLA
// lowering of either formulation is gather- or bandwidth-bound and loses to
// hand-scheduled C++ (round-1 bench: 6.3 s to score 1M rows x 100 trees).
// This kernel walks the implicit-heap forest in the finalized scoring
// layout (ops/scoring_layout.py; reference semantics
// IsolationTree.scala:213-229: feature < threshold -> left, >= -> right;
// leaf adds avgPathLength(numInstances)): one merged value[T, M] plane
// holds the split threshold at internal slots and the precomputed leaf LUT
// (depth + c(n)) at leaves, so the walk's compare and the exit-leaf credit
// read the same 8-byte-per-node table pair (feature + value) — a third
// less L2 tree-tile footprint than the pre-layout 12-byte triple.
//
// Three levels of parallelism, all outside the floating-point semantics:
//   1. Chain interleaving — the scalar walk runs TREE_BLOCK independent
//      trees per row so data-dependent node loads pipeline on L2 latency.
//   2. SIMD row lanes — where AVX-512F/DQ is present (runtime-dispatched,
//      ISOFOREST_NATIVE_SIMD=0 opts out), 16 rows walk one tree per vector
//      step via vpgatherd{d,ps}, with a small tree interleave on top to keep
//      several gathers in flight.
//   3. Row-range threads — rows are independent, so the entry points
//      partition them across std::thread workers (hardware_concurrency,
//      ISOFOREST_NATIVE_THREADS overrides; single-threaded below 16k rows).
// Every variant takes branch decisions from identical f32 comparisons and
// accumulates leaf values into f64 in ascending-tree order within an L2
// tile, so scalar, SIMD, and any thread count produce bitwise-identical
// scores (pinned by tests/test_native.py).

#include <algorithm>
#include <atomic>
#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <string>
#include <thread>
#include <vector>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define IF_X86 1
#else
#define IF_X86 0
#endif

namespace {
// Measured on the build host (1-core, 200k rows x 100 trees): 4-wide 552k,
// 8-wide 790k, 16-wide 929k, 32-wide 799k rows/s — 16 chains saturate the
// L2 miss-level parallelism without spilling the node-state registers.
constexpr int TREE_BLOCK = 16;

// Tree-tile byte budget: big forests (1000 trees x 511 slots ~ 6 MB of
// node tables) overflow L2, so trees are processed in table-resident
// groups with rows inner (measured at T=1000: 55k -> 86k rows/s). The
// budget is sized for a ~1 MB L2 with headroom; small forests fall in a
// single tile and take the direct path.
constexpr int64_t TILE_BYTES = 768 * 1024;

inline int64_t tile_trees(int64_t bytes_per_tree) {
  const int64_t t = TILE_BYTES / (bytes_per_tree > 0 ? bytes_per_tree : 1);
  // round down to a TREE_BLOCK multiple, min one block
  return std::max<int64_t>(TREE_BLOCK, (t / TREE_BLOCK) * TREE_BLOCK);
}

// ---------------------------------------------------------------------------
// Scalar row-range kernels (the portable baseline and the SIMD remainder).
// ---------------------------------------------------------------------------

void score_standard_rows_scalar(const float* X, int64_t r0, int64_t r1,
                                int32_t n_features, const int32_t* feature,
                                const float* value, int64_t n_trees,
                                int64_t m_nodes, int32_t height, float* out) {
  const int64_t tile = tile_trees(m_nodes * 8);  // feat+value per node
  std::vector<double> acc_buf;
  double* acc = nullptr;
  if (n_trees > tile) {
    acc_buf.assign(r1 - r0, 0.0);
    acc = acc_buf.data();
  }
  for (int64_t g0 = 0; g0 < n_trees; g0 += tile) {
    const int64_t g1 = std::min(n_trees, g0 + tile);
    for (int64_t r = r0; r < r1; ++r) {
      const float* x = X + r * n_features;
      double total = 0.0;
      int64_t t0 = g0;
      for (; t0 + TREE_BLOCK <= g1; t0 += TREE_BLOCK) {
        int32_t nd[TREE_BLOCK] = {0};
        for (int32_t s = 0; s < height; ++s) {
          for (int j = 0; j < TREE_BLOCK; ++j) {
            const int64_t base = (t0 + j) * m_nodes;
            const int32_t n = nd[j];
            const int32_t f = feature[base + n];
            const bool internal = f >= 0;
            const float xv = x[internal ? f : 0];
            const int32_t nxt = 2 * n + 1 + (xv >= value[base + n] ? 1 : 0);
            nd[j] = internal ? nxt : n;
          }
        }
        for (int j = 0; j < TREE_BLOCK; ++j)
          total += value[(t0 + j) * m_nodes + nd[j]];
      }
      for (; t0 < g1; ++t0) {
        const int64_t base = t0 * m_nodes;
        int32_t n = 0;
        for (int32_t s = 0; s < height; ++s) {
          const int32_t f = feature[base + n];
          if (f < 0) break;
          n = 2 * n + 1 + (x[f] >= value[base + n] ? 1 : 0);
        }
        total += value[base + n];
      }
      if (acc) {
        acc[r - r0] += total;
      } else {
        out[r] = static_cast<float>(total / static_cast<double>(n_trees));
      }
    }
  }
  if (acc) {
    for (int64_t r = r0; r < r1; ++r)
      out[r] = static_cast<float>(acc[r - r0] / static_cast<double>(n_trees));
  }
}

// Quantized (q16) standard walk over the rank-space plane
// (ops/scoring_layout.py pack_standard_q): the caller binarizes rows once
// to u16 threshold ranks (rx = #edges <= x), each node is ONE u32 record
// `code << 16 | feature` (0xFFFF feature marks leaves/holes), and the
// branch test is the integer compare `rx > code` — exactly equivalent to
// `x >= threshold`, so the walk visits the same leaves as the f32 kernel.
// Leaves credit the shared f32 LUT (the same bits the f32 merged plane
// holds). Tiling uses the f32 plane's 8 B/node budget, NOT the real
// 4 B/node: the per-tile f64 fold grouping must match if_score_standard's
// exactly for q16 scores to stay bitwise-equal to the f32 walker's.
void score_standard_q16_rows_scalar(const uint16_t* XR, int64_t r0, int64_t r1,
                                    int32_t n_features, const uint32_t* packed,
                                    const float* lut, int64_t n_trees,
                                    int64_t m_nodes, int32_t height,
                                    float* out) {
  const int64_t tile = tile_trees(m_nodes * 8);
  std::vector<double> acc_buf;
  double* acc = nullptr;
  if (n_trees > tile) {
    acc_buf.assign(r1 - r0, 0.0);
    acc = acc_buf.data();
  }
  for (int64_t g0 = 0; g0 < n_trees; g0 += tile) {
    const int64_t g1 = std::min(n_trees, g0 + tile);
    for (int64_t r = r0; r < r1; ++r) {
      const uint16_t* xr = XR + r * n_features;
      double total = 0.0;
      int64_t t0 = g0;
      for (; t0 + TREE_BLOCK <= g1; t0 += TREE_BLOCK) {
        int32_t nd[TREE_BLOCK] = {0};
        for (int32_t s = 0; s < height; ++s) {
          for (int j = 0; j < TREE_BLOCK; ++j) {
            const int64_t base = (t0 + j) * m_nodes;
            const int32_t n = nd[j];
            const uint32_t rec = packed[base + n];
            const uint32_t f = rec & 0xFFFFu;
            const bool internal = f != 0xFFFFu;
            const uint32_t rv = xr[internal ? f : 0];
            const int32_t nxt = 2 * n + 1 + (rv > (rec >> 16) ? 1 : 0);
            nd[j] = internal ? nxt : n;
          }
        }
        for (int j = 0; j < TREE_BLOCK; ++j)
          total += lut[packed[(t0 + j) * m_nodes + nd[j]] >> 16];
      }
      for (; t0 < g1; ++t0) {
        const int64_t base = t0 * m_nodes;
        int32_t n = 0;
        for (int32_t s = 0; s < height; ++s) {
          const uint32_t rec = packed[base + n];
          const uint32_t f = rec & 0xFFFFu;
          if (f == 0xFFFFu) break;
          n = 2 * n + 1 + (xr[f] > (rec >> 16) ? 1 : 0);
        }
        total += lut[packed[base + n] >> 16];
      }
      if (acc) {
        acc[r - r0] += total;
      } else {
        out[r] = static_cast<float>(total / static_cast<double>(n_trees));
      }
    }
  }
  if (acc) {
    for (int64_t r = r0; r < r1; ++r)
      out[r] = static_cast<float>(acc[r - r0] / static_cast<double>(n_trees));
  }
}

void score_extended_rows_scalar(const float* X, int64_t r0, int64_t r1,
                                int32_t n_features, const int32_t* indices,
                                const float* weights, const float* value,
                                int64_t n_trees, int64_t m_nodes, int32_t k,
                                int32_t height, float* out) {
  const int64_t tile = tile_trees(m_nodes * (8 * (int64_t)k + 4));
  std::vector<double> acc_buf;
  double* acc = nullptr;
  if (n_trees > tile) {
    acc_buf.assign(r1 - r0, 0.0);
    acc = acc_buf.data();
  }
  for (int64_t g0 = 0; g0 < n_trees; g0 += tile) {
    const int64_t g1 = std::min(n_trees, g0 + tile);
    for (int64_t r = r0; r < r1; ++r) {
      const float* x = X + r * n_features;
      double total = 0.0;
      int64_t t0 = g0;
      for (; t0 + TREE_BLOCK <= g1; t0 += TREE_BLOCK) {
        int32_t nd[TREE_BLOCK] = {0};
        for (int32_t s = 0; s < height; ++s) {
          for (int j = 0; j < TREE_BLOCK; ++j) {
            const int64_t base = (t0 + j) * m_nodes;
            const int32_t n = nd[j];
            const int64_t sub = (base + n) * k;
            const bool internal = indices[sub] >= 0;
            float dot = 0.0f;
            for (int32_t q = 0; q < k; ++q) {
              const int32_t f = indices[sub + q];
              dot += x[f >= 0 ? f : 0] * weights[sub + q];
            }
            const int32_t nxt = 2 * n + 1 + (dot >= value[base + n] ? 1 : 0);
            nd[j] = internal ? nxt : n;
          }
        }
        for (int j = 0; j < TREE_BLOCK; ++j)
          total += value[(t0 + j) * m_nodes + nd[j]];
      }
      for (; t0 < g1; ++t0) {
        const int64_t base = t0 * m_nodes;
        int32_t n = 0;
        for (int32_t s = 0; s < height; ++s) {
          const int64_t sub = (base + n) * k;
          if (indices[sub] < 0) break;
          float dot = 0.0f;
          for (int32_t q = 0; q < k; ++q) {
            const int32_t f = indices[sub + q];
            dot += x[f >= 0 ? f : 0] * weights[sub + q];
          }
          n = 2 * n + 1 + (dot >= value[base + n] ? 1 : 0);
        }
        total += value[base + n];
      }
      if (acc) {
        acc[r - r0] += total;
      } else {
        out[r] = static_cast<float>(total / static_cast<double>(n_trees));
      }
    }
  }
  if (acc) {
    for (int64_t r = r0; r < r1; ++r)
      out[r] = static_cast<float>(acc[r - r0] / static_cast<double>(n_trees));
  }
}

#if IF_X86
// ---------------------------------------------------------------------------
// AVX-512 row-lane kernels. 16 rows walk one tree per vector step; TREE_IL
// trees are interleaved so several gather chains are in flight (the walk is
// gather-latency-bound: feature, x-value, and threshold loads per level).
// Branch decisions are the same f32 >= comparisons as the scalar walk, leaf
// values accumulate into f64 lanes in ascending-tree order, so results are
// bitwise-equal to the scalar kernel.
//
// Measured on the build host (1 core, avx512f/dq, 2026-07-29): standard
// 200k rows x 100 trees 369k -> 1.75M rows/s (4.8x; TREE_IL 4 vs 8 within
// noise); T=1000 multi-tile 35k -> 95k rows/s (2.7x); F=274 wide 1.3x;
// extended k=4 226k -> 444k rows/s (2.0x).
// ---------------------------------------------------------------------------

constexpr int LANES = 16;   // rows per vector
constexpr int TREE_IL = 4;  // interleaved trees per walk

__attribute__((target("avx512f,avx512dq"))) inline void acc_leaf_f64(
    __m512 lv, __m512d& acc_lo, __m512d& acc_hi) {
  acc_lo = _mm512_add_pd(acc_lo, _mm512_cvtps_pd(_mm512_castps512_ps256(lv)));
  acc_hi = _mm512_add_pd(acc_hi, _mm512_cvtps_pd(_mm512_extractf32x8_ps(lv, 1)));
}

// Advance 16 row lanes one heap level given this level's split feature,
// threshold, and row value per lane: internal lanes (f >= 0) go to 2n+1+b,
// leaves stay.
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
advance_standard(__m512i nd, __m512i f, __m512 thr, __m512 xv) {
  const __m512i zero = _mm512_setzero_si512();
  const __m512i one = _mm512_set1_epi32(1);
  const __mmask16 internal =
      _mm512_cmp_epi32_mask(f, zero, _MM_CMPINT_NLT);  // f >= 0
  const __mmask16 b = _mm512_cmp_ps_mask(xv, thr, _CMP_GE_OQ);
  __m512i nxt = _mm512_add_epi32(_mm512_slli_epi32(nd, 1), one);
  nxt = _mm512_mask_add_epi32(nxt, b, nxt, one);
  return _mm512_mask_mov_epi32(nd, internal, nxt);
}

__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
xindex(__m512i f, __m512i vroff) {
  return _mm512_add_epi32(vroff, _mm512_max_epi32(f, _mm512_setzero_si512()));
}

// One heap level of the standard walk for 16 row lanes of one tree: gather
// the split feature, the row's value of it, and the threshold. The single
// source for both the interleaved and the remainder-tree loops.
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_standard(__m512i nd, const int32_t* featb, const float* thrb,
              const float* Xb, __m512i vroff) {
  const __m512i f = _mm512_i32gather_epi32(nd, featb, 4);
  const __m512 thr = _mm512_i32gather_ps(nd, thrb, 4);
  return advance_standard(nd, f, thr,
                          _mm512_i32gather_ps(xindex(f, vroff), Xb, 4));
}

// Node tables for the first PERM_LEVELS heap levels (node ids 0..30) held in
// two zmm registers: the feature/threshold lookups become vpermi2d/ps (~3
// cycles) instead of vpgatherdd (~20), leaving only the row-value gather.
// Requires m_nodes >= 32 (height >= 5); smaller trees take the gather path.
constexpr int32_t PERM_LEVELS = 5;  // nd entering step s<=4 is <= 30 < 32

// For F <= 4 the whole 16-row X slab (16*F contiguous floats) fits in F zmm
// registers, so the row-value lookup x[j*F + f] (flat index < 64) becomes
// register permutes as well — permute-level steps then issue NO gathers at
// all, and gather-level steps only the feature/threshold pair. This is the
// headline regime (kddcup http F=3).
constexpr int32_t XTAB_MAX_FEATURES = 4;

struct XTable64 {
  __m512 r0, r1, r2, r3;
  bool narrow;  // F <= 2: flat ids < 32, single vpermi2ps
};

__attribute__((target("avx512f,avx512dq"), always_inline)) inline XTable64
load_xtable(const float* Xb, int32_t f) {
  // load only registers the slab covers (16*f floats); alias the rest to
  // r2 so flat ids < 16*f never read past the slab
  const __m512 r0 = _mm512_loadu_ps(Xb);
  const __m512 r1 = f >= 2 ? _mm512_loadu_ps(Xb + 16) : r0;
  const __m512 r2 = f >= 3 ? _mm512_loadu_ps(Xb + 32) : r1;
  const __m512 r3 = f >= 4 ? _mm512_loadu_ps(Xb + 48) : r2;
  return {r0, r1, r2, r3, f <= 2};
}

__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512
xlookup(const XTable64& xt, __m512i i) {
  const __m512 lo = _mm512_permutex2var_ps(xt.r0, i, xt.r1);
  if (xt.narrow) return lo;
  const __m512 hi = _mm512_permutex2var_ps(xt.r2, i, xt.r3);
  const __mmask16 top =
      _mm512_cmp_epi32_mask(i, _mm512_set1_epi32(31), _MM_CMPINT_NLE);
  return _mm512_mask_blend_ps(top, lo, hi);
}

struct NodeTable32 {
  __m512i f_lo, f_hi;
  __m512 t_lo, t_hi;
};

__attribute__((target("avx512f,avx512dq"), always_inline)) inline NodeTable32
load_table32(const int32_t* featb, const float* thrb) {
  return {_mm512_loadu_si512(featb), _mm512_loadu_si512(featb + 16),
          _mm512_loadu_ps(thrb), _mm512_loadu_ps(thrb + 16)};
}

__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_standard_perm(__m512i nd, const NodeTable32& tab, const float* Xb,
                   __m512i vroff) {
  const __m512i f = _mm512_permutex2var_epi32(tab.f_lo, nd, tab.f_hi);
  const __m512 thr = _mm512_permutex2var_ps(tab.t_lo, nd, tab.t_hi);
  return advance_standard(nd, f, thr,
                          _mm512_i32gather_ps(xindex(f, vroff), Xb, 4));
}

// Gather-free variant: node table AND X slab in registers (F <= 4).
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_standard_perm_xt(__m512i nd, const NodeTable32& tab, const XTable64& xt,
                      __m512i vroff) {
  const __m512i f = _mm512_permutex2var_epi32(tab.f_lo, nd, tab.f_hi);
  const __m512 thr = _mm512_permutex2var_ps(tab.t_lo, nd, tab.t_hi);
  return advance_standard(nd, f, thr, xlookup(xt, xindex(f, vroff)));
}

// Heap level 5 (node ids 31..62, 32 of them) also fits one zmm pair per
// array, indexed by nd-31. Lanes that went leaf at an earlier level have
// nd < 31 and would alias into the table, so their fetched feature is
// forced to -1 (leaf) before the advance. Requires m_nodes >= 63.
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_standard_perm_l5(__m512i nd, const NodeTable32& tab, const XTable64& xt,
                      bool use_xt, const float* Xb, __m512i vroff) {
  const __m512i vbase = _mm512_set1_epi32(31);
  const __m512i idx = _mm512_sub_epi32(nd, vbase);
  const __mmask16 in_level =
      _mm512_cmp_epi32_mask(nd, vbase, _MM_CMPINT_NLT);  // nd >= 31
  const __m512i f_raw = _mm512_permutex2var_epi32(tab.f_lo, idx, tab.f_hi);
  const __m512i f =
      _mm512_mask_mov_epi32(_mm512_set1_epi32(-1), in_level, f_raw);
  const __m512 thr = _mm512_permutex2var_ps(tab.t_lo, idx, tab.t_hi);
  const __m512i xi = xindex(f, vroff);
  return advance_standard(
      nd, f, thr, use_xt ? xlookup(xt, xi) : _mm512_i32gather_ps(xi, Xb, 4));
}

// Heap level 6 (node ids 63..126, 64 of them): two zmm pairs per array
// with a 64-entry blended lookup (same shape as xlookup). Same stale-lane
// masking as level 5. Requires m_nodes >= 127.
struct NodeTable64 {
  __m512i f0, f1, f2, f3;
  __m512 t0, t1, t2, t3;
};

__attribute__((target("avx512f,avx512dq"), always_inline)) inline NodeTable64
load_table64(const int32_t* featb, const float* thrb) {
  return {_mm512_loadu_si512(featb),      _mm512_loadu_si512(featb + 16),
          _mm512_loadu_si512(featb + 32), _mm512_loadu_si512(featb + 48),
          _mm512_loadu_ps(thrb),          _mm512_loadu_ps(thrb + 16),
          _mm512_loadu_ps(thrb + 32),     _mm512_loadu_ps(thrb + 48)};
}

__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_standard_perm_l6(__m512i nd, const NodeTable64& tab, const XTable64& xt,
                      bool use_xt, const float* Xb, __m512i vroff) {
  const __m512i vbase = _mm512_set1_epi32(63);
  const __m512i idx = _mm512_sub_epi32(nd, vbase);
  const __mmask16 in_level =
      _mm512_cmp_epi32_mask(nd, vbase, _MM_CMPINT_NLT);  // nd >= 63
  const __mmask16 top = _mm512_cmp_epi32_mask(
      idx, _mm512_set1_epi32(31), _MM_CMPINT_NLE);
  const __m512i f_lo = _mm512_permutex2var_epi32(tab.f0, idx, tab.f1);
  const __m512i f_hi = _mm512_permutex2var_epi32(tab.f2, idx, tab.f3);
  const __m512i f = _mm512_mask_mov_epi32(
      _mm512_set1_epi32(-1), in_level,
      _mm512_mask_blend_epi32(top, f_lo, f_hi));
  const __m512 t_lo = _mm512_permutex2var_ps(tab.t0, idx, tab.t1);
  const __m512 t_hi = _mm512_permutex2var_ps(tab.t2, idx, tab.t3);
  const __m512 thr = _mm512_mask_blend_ps(top, t_lo, t_hi);
  const __m512i xi = xindex(f, vroff);
  return advance_standard(
      nd, f, thr, use_xt ? xlookup(xt, xi) : _mm512_i32gather_ps(xi, Xb, 4));
}

// Deep levels with a register-resident X slab: gather feature/threshold,
// permute the row value.
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_standard_xt(__m512i nd, const int32_t* featb, const float* thrb,
                 const XTable64& xt, __m512i vroff) {
  const __m512i f = _mm512_i32gather_epi32(nd, featb, 4);
  const __m512 thr = _mm512_i32gather_ps(nd, thrb, 4);
  return advance_standard(nd, f, thr, xlookup(xt, xindex(f, vroff)));
}

// One heap level of the extended walk: per-lane sequential hyperplane dot
// over q in the same f32 mul+add order as the scalar walk (no FMA
// contraction), then the offset comparison.
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_extended(__m512i nd, const int32_t* idxb, const float* wb,
              const float* offb, const float* Xb, __m512i vroff, __m512i vk,
              int32_t k, bool use_xt, const XTable64& xt) {
  const __m512i zero = _mm512_setzero_si512();
  const __m512i one = _mm512_set1_epi32(1);
  const __m512i sub = _mm512_mullo_epi32(nd, vk);
  // internal iff indices[n*k + 0] >= 0
  const __m512i f0 = _mm512_i32gather_epi32(sub, idxb, 4);
  const __mmask16 internal = _mm512_cmp_epi32_mask(f0, zero, _MM_CMPINT_NLT);
  __m512 dot = _mm512_setzero_ps();
  __m512i qi = sub;
  for (int32_t q = 0; q < k; ++q) {
    const __m512i f = q == 0 ? f0 : _mm512_i32gather_epi32(qi, idxb, 4);
    const __m512i xi = xindex(f, vroff);
    const __m512 xv =
        use_xt ? xlookup(xt, xi) : _mm512_i32gather_ps(xi, Xb, 4);
    const __m512 w = _mm512_i32gather_ps(qi, wb, 4);
    dot = _mm512_add_ps(dot, _mm512_mul_ps(xv, w));
    qi = _mm512_add_epi32(qi, one);
  }
  const __m512 off = _mm512_i32gather_ps(nd, offb, 4);
  const __mmask16 b = _mm512_cmp_ps_mask(dot, off, _CMP_GE_OQ);
  __m512i nxt = _mm512_add_epi32(_mm512_slli_epi32(nd, 1), one);
  nxt = _mm512_mask_add_epi32(nxt, b, nxt, one);
  return _mm512_mask_mov_epi32(nd, internal, nxt);
}

__attribute__((target("avx512f,avx512dq"))) void score_standard_rows_avx512(
    const float* X, int64_t r0, int64_t r1, int32_t n_features,
    const int32_t* feature, const float* value, int64_t n_trees,
    int64_t m_nodes, int32_t height, float* out) {
  const int64_t tile = tile_trees(m_nodes * 8);
  const __m512i zero = _mm512_setzero_si512();
  // per-lane row offsets into the 16-row slab (lane j -> row r + j)
  alignas(64) int32_t roff_arr[LANES];
  for (int j = 0; j < LANES; ++j) roff_arr[j] = j * n_features;
  const __m512i vroff = _mm512_load_si512(roff_arr);

  int64_t r = r0;
  for (; r + LANES <= r1; r += LANES) {
    const float* Xb = X + r * n_features;
    __m512d acc_lo = _mm512_setzero_pd();
    __m512d acc_hi = _mm512_setzero_pd();
    for (int64_t g0 = 0; g0 < n_trees; g0 += tile) {
      const int64_t g1 = std::min(n_trees, g0 + tile);
      // tile-local f64 subtotal, folded into the row accumulator per tile —
      // the same grouping as the scalar kernel's `acc[r] += total`, so the
      // two paths stay bitwise-equal even for multi-tile forests
      __m512d tot_lo = _mm512_setzero_pd();
      __m512d tot_hi = _mm512_setzero_pd();
      // levels 0..perm-1 resolve feature/threshold by register permute
      // (node ids < 32), the rest by gather; F <= 4 additionally resolves
      // the row value from the register-resident X slab (use_xt), making
      // permute levels gather-free
      const int32_t perm = m_nodes >= 32 ? std::min(height, PERM_LEVELS) : 0;
      const bool use_xt = n_features <= XTAB_MAX_FEATURES;
      const XTable64 xt =
          use_xt ? load_xtable(Xb, n_features) : XTable64{};
      int64_t t = g0;
      for (; t + TREE_IL <= g1; t += TREE_IL) {
        __m512i nd[TREE_IL];
        NodeTable32 tab[TREE_IL];
        for (int u = 0; u < TREE_IL; ++u) {
          nd[u] = zero;
          if (perm)
            tab[u] = load_table32(feature + (t + u) * m_nodes,
                                  value + (t + u) * m_nodes);
        }
        for (int32_t s = 0; s < perm; ++s)
          for (int u = 0; u < TREE_IL; ++u)
            nd[u] = use_xt ? step_standard_perm_xt(nd[u], tab[u], xt, vroff)
                           : step_standard_perm(nd[u], tab[u], Xb, vroff);
        int32_t deep = perm;
        if (perm == PERM_LEVELS && height > PERM_LEVELS && m_nodes >= 63) {
          for (int u = 0; u < TREE_IL; ++u)
            tab[u] = load_table32(feature + (t + u) * m_nodes + 31,
                                  value + (t + u) * m_nodes + 31);
          for (int u = 0; u < TREE_IL; ++u)
            nd[u] = step_standard_perm_l5(nd[u], tab[u], xt, use_xt, Xb, vroff);
          deep = perm + 1;
          if (height > deep && m_nodes >= 127) {
            // level 6: tables loaded per tree (8 zmm each — sequential use
            // keeps register pressure flat across the interleave)
            for (int u = 0; u < TREE_IL; ++u) {
              const NodeTable64 l6 =
                  load_table64(feature + (t + u) * m_nodes + 63,
                               value + (t + u) * m_nodes + 63);
              nd[u] = step_standard_perm_l6(nd[u], l6, xt, use_xt, Xb, vroff);
            }
            deep += 1;
          }
        }
        for (int32_t s = deep; s < height; ++s)
          for (int u = 0; u < TREE_IL; ++u)
            nd[u] = use_xt
                        ? step_standard_xt(nd[u], feature + (t + u) * m_nodes,
                                           value + (t + u) * m_nodes, xt,
                                           vroff)
                        : step_standard(nd[u], feature + (t + u) * m_nodes,
                                        value + (t + u) * m_nodes, Xb,
                                        vroff);
        for (int u = 0; u < TREE_IL; ++u)
          acc_leaf_f64(
              _mm512_i32gather_ps(nd[u], value + (t + u) * m_nodes, 4),
              tot_lo, tot_hi);
      }
      for (; t < g1; ++t) {  // remainder trees, one at a time
        __m512i nd = zero;
        if (perm) {
          const NodeTable32 tab =
              load_table32(feature + t * m_nodes, value + t * m_nodes);
          for (int32_t s = 0; s < perm; ++s)
            nd = use_xt ? step_standard_perm_xt(nd, tab, xt, vroff)
                        : step_standard_perm(nd, tab, Xb, vroff);
        }
        int32_t deep = perm;
        if (perm == PERM_LEVELS && height > PERM_LEVELS && m_nodes >= 63) {
          const NodeTable32 l5 = load_table32(feature + t * m_nodes + 31,
                                              value + t * m_nodes + 31);
          nd = step_standard_perm_l5(nd, l5, xt, use_xt, Xb, vroff);
          deep = perm + 1;
          if (height > deep && m_nodes >= 127) {
            const NodeTable64 l6 = load_table64(feature + t * m_nodes + 63,
                                                value + t * m_nodes + 63);
            nd = step_standard_perm_l6(nd, l6, xt, use_xt, Xb, vroff);
            deep += 1;
          }
        }
        for (int32_t s = deep; s < height; ++s)
          nd = use_xt ? step_standard_xt(nd, feature + t * m_nodes,
                                         value + t * m_nodes, xt, vroff)
                      : step_standard(nd, feature + t * m_nodes,
                                      value + t * m_nodes, Xb, vroff);
        acc_leaf_f64(_mm512_i32gather_ps(nd, value + t * m_nodes, 4),
                     tot_lo, tot_hi);
      }
      acc_lo = _mm512_add_pd(acc_lo, tot_lo);
      acc_hi = _mm512_add_pd(acc_hi, tot_hi);
    }
    const __m512d vn = _mm512_set1_pd(static_cast<double>(n_trees));
    _mm256_storeu_ps(out + r, _mm512_cvtpd_ps(_mm512_div_pd(acc_lo, vn)));
    _mm256_storeu_ps(out + r + 8, _mm512_cvtpd_ps(_mm512_div_pd(acc_hi, vn)));
  }
  if (r < r1)
    score_standard_rows_scalar(X, r, r1, n_features, feature, value,
                               n_trees, m_nodes, height, out);
}

// k <= 4 EIF fast path for the first 4 heap levels (extensionLevel 1-3,
// covering the common extended configs): node ids entering steps 0..3 are
// <= 14, so flat hyperplane ids k*nd + q are <= 15k-1 <= 59 — the
// indices/weights tables live in two zmm pairs each (64-entry lookups, same
// shape as xlookup) and the offsets (node ids < 16) in a single zmm. With
// F <= XTAB_MAX_FEATURES the row values come from the register X slab too,
// making these steps fully gather-free. Requires m_nodes >= 31 and
// m_nodes*k >= 64 (the 64-entry flat loads must be in-bounds).
constexpr int32_t PERM_LEVELS_EXT = 4;
constexpr int32_t EXT_PERM_MAX_K = 4;

struct ExtTableK4 {
  __m512i i0, i1, i2, i3;
  __m512 w0, w1, w2, w3;
  __m512 off;
  __m512i vhi;  // broadcast 31, for the 64-entry blend
};

__attribute__((target("avx512f,avx512dq"), always_inline)) inline ExtTableK4
load_ext_table(const int32_t* idxb, const float* wb, const float* offb) {
  return {_mm512_loadu_si512(idxb),      _mm512_loadu_si512(idxb + 16),
          _mm512_loadu_si512(idxb + 32), _mm512_loadu_si512(idxb + 48),
          _mm512_loadu_ps(wb),           _mm512_loadu_ps(wb + 16),
          _mm512_loadu_ps(wb + 32),      _mm512_loadu_ps(wb + 48),
          _mm512_loadu_ps(offb),         _mm512_set1_epi32(31)};
}

__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
ext_lookup_i32(const ExtTableK4& t, __m512i i) {
  const __m512i lo = _mm512_permutex2var_epi32(t.i0, i, t.i1);
  const __m512i hi = _mm512_permutex2var_epi32(t.i2, i, t.i3);
  return _mm512_mask_blend_epi32(
      _mm512_cmp_epi32_mask(i, t.vhi, _MM_CMPINT_NLE), lo, hi);
}

__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512
ext_lookup_ps(const ExtTableK4& t, __m512i i) {
  const __m512 lo = _mm512_permutex2var_ps(t.w0, i, t.w1);
  const __m512 hi = _mm512_permutex2var_ps(t.w2, i, t.w3);
  return _mm512_mask_blend_ps(
      _mm512_cmp_epi32_mask(i, t.vhi, _MM_CMPINT_NLE), lo, hi);
}

__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_extended_perm(__m512i nd, const ExtTableK4& tab, const float* Xb,
                   __m512i vroff, __m512i vk, int32_t k, bool use_xt,
                   const XTable64& xt) {
  const __m512i zero = _mm512_setzero_si512();
  const __m512i one = _mm512_set1_epi32(1);
  const __m512i sub = _mm512_mullo_epi32(nd, vk);
  const __m512i f0 = ext_lookup_i32(tab, sub);
  const __mmask16 internal = _mm512_cmp_epi32_mask(f0, zero, _MM_CMPINT_NLT);
  // per-lane sequential dot over q — same f32 mul+add order as the scalar
  // walk (no FMA contraction; (0 + m0) + m1 + ... is the scalar grouping)
  __m512 dot = _mm512_setzero_ps();
  __m512i qi = sub;
  for (int32_t q = 0; q < k; ++q) {
    const __m512i f = q == 0 ? f0 : ext_lookup_i32(tab, qi);
    const __m512i xi = xindex(f, vroff);
    const __m512 xv =
        use_xt ? xlookup(xt, xi) : _mm512_i32gather_ps(xi, Xb, 4);
    const __m512 w = ext_lookup_ps(tab, qi);
    dot = _mm512_add_ps(dot, _mm512_mul_ps(xv, w));
    qi = _mm512_add_epi32(qi, one);
  }
  const __m512 off = _mm512_permutexvar_ps(nd, tab.off);
  const __mmask16 b = _mm512_cmp_ps_mask(dot, off, _CMP_GE_OQ);
  __m512i nxt = _mm512_add_epi32(_mm512_slli_epi32(nd, 1), one);
  nxt = _mm512_mask_add_epi32(nxt, b, nxt, one);
  return _mm512_mask_mov_epi32(nd, internal, nxt);
}

__attribute__((target("avx512f,avx512dq"))) void score_extended_rows_avx512(
    const float* X, int64_t r0, int64_t r1, int32_t n_features,
    const int32_t* indices, const float* weights, const float* value,
    int64_t n_trees, int64_t m_nodes, int32_t k, int32_t height, float* out) {
  const int64_t tile = tile_trees(m_nodes * (8 * (int64_t)k + 4));
  const __m512i zero = _mm512_setzero_si512();
  const __m512i vk = _mm512_set1_epi32(k);
  alignas(64) int32_t roff_arr[LANES];
  for (int j = 0; j < LANES; ++j) roff_arr[j] = j * n_features;
  const __m512i vroff = _mm512_load_si512(roff_arr);

  int64_t r = r0;
  for (; r + LANES <= r1; r += LANES) {
    const float* Xb = X + r * n_features;
    __m512d acc_lo = _mm512_setzero_pd();
    __m512d acc_hi = _mm512_setzero_pd();
    for (int64_t g0 = 0; g0 < n_trees; g0 += tile) {
      const int64_t g1 = std::min(n_trees, g0 + tile);
      __m512d tot_lo = _mm512_setzero_pd();
      __m512d tot_hi = _mm512_setzero_pd();
      // EIF nodes issue 3 gathers per hyperplane term; interleave 2 trees
      // (measured: 4-wide regresses 1.97x -> 1.82x on the build host).
      // m_nodes*k >= 64 keeps load_ext_table's 64-entry flat loads
      // in-bounds (k=2 with 31-node trees would only have 62)
      const int32_t perm =
          (k <= EXT_PERM_MAX_K && m_nodes >= 31 && m_nodes * k >= 64)
              ? std::min(height, PERM_LEVELS_EXT)
              : 0;
      const bool use_xt = n_features <= XTAB_MAX_FEATURES;
      const XTable64 xt = use_xt ? load_xtable(Xb, n_features) : XTable64{};
      int64_t t = g0;
      for (; t + 2 <= g1; t += 2) {
        __m512i nd[2] = {zero, zero};
        if (perm) {
          ExtTableK4 tab[2];
          for (int u = 0; u < 2; ++u)
            tab[u] = load_ext_table(indices + (t + u) * m_nodes * k,
                                    weights + (t + u) * m_nodes * k,
                                    value + (t + u) * m_nodes);
          for (int32_t s = 0; s < perm; ++s)
            for (int u = 0; u < 2; ++u)
              nd[u] = step_extended_perm(nd[u], tab[u], Xb, vroff, vk, k,
                                         use_xt, xt);
        }
        for (int32_t s = perm; s < height; ++s)
          for (int u = 0; u < 2; ++u)
            nd[u] = step_extended(nd[u], indices + (t + u) * m_nodes * k,
                                  weights + (t + u) * m_nodes * k,
                                  value + (t + u) * m_nodes, Xb, vroff, vk, k,
                                  use_xt, xt);
        for (int u = 0; u < 2; ++u)
          acc_leaf_f64(
              _mm512_i32gather_ps(nd[u], value + (t + u) * m_nodes, 4),
              tot_lo, tot_hi);
      }
      for (; t < g1; ++t) {
        __m512i nd = zero;
        if (perm) {
          const ExtTableK4 tab =
              load_ext_table(indices + t * m_nodes * k,
                             weights + t * m_nodes * k, value + t * m_nodes);
          for (int32_t s = 0; s < perm; ++s)
            nd = step_extended_perm(nd, tab, Xb, vroff, vk, k, use_xt, xt);
        }
        for (int32_t s = perm; s < height; ++s)
          nd = step_extended(nd, indices + t * m_nodes * k,
                             weights + t * m_nodes * k, value + t * m_nodes,
                             Xb, vroff, vk, k, use_xt, xt);
        acc_leaf_f64(_mm512_i32gather_ps(nd, value + t * m_nodes, 4),
                     tot_lo, tot_hi);
      }
      acc_lo = _mm512_add_pd(acc_lo, tot_lo);
      acc_hi = _mm512_add_pd(acc_hi, tot_hi);
    }
    const __m512d vn = _mm512_set1_pd(static_cast<double>(n_trees));
    _mm256_storeu_ps(out + r, _mm512_cvtpd_ps(_mm512_div_pd(acc_lo, vn)));
    _mm256_storeu_ps(out + r + 8, _mm512_cvtpd_ps(_mm512_div_pd(acc_hi, vn)));
  }
  if (r < r1)
    score_extended_rows_scalar(X, r, r1, n_features, indices, weights, value,
                               n_trees, m_nodes, k, height, out);
}
// Quantized (q16) AVX-512 walk. The 4 B/node record plane halves every
// node-table footprint relative to f32's feature+threshold pair: the
// 32-record table of heap levels 0..4 is TWO zmm (vs four), level 6's
// 64-record table four (vs eight) — so the same permute trick covers the
// same levels at half the register cost. Better still, 16-bit ranks halve
// the row slab: 16 rows x F u16 = 8F dwords, so the WHOLE slab is
// register-resident up to F <= 8 (QTAB_MAX_FEATURES, double f32's F <= 4
// xtable budget) and permute-level steps issue no gathers at all. When a
// gather does remain, the rank gather reads 4 bytes at each u16 offset and
// masks the low half; the caller pads the rank buffer (>= 32 trailing u16)
// so the last slab's register loads and the last element's over-read stay
// in-bounds. Same f64 lane accumulation in ascending-tree order and the
// SAME tile grouping as the f32 kernel, so scalar q16, SIMD q16 and the
// f32 walker all produce bitwise-identical scores.
constexpr int32_t QTAB_MAX_FEATURES = 8;

struct RankTable128 {
  __m512i r0, r1, r2, r3;
  bool narrow;  // F <= 4: dword ids < 32, single vpermi2d
};

__attribute__((target("avx512f,avx512dq"), always_inline)) inline RankTable128
load_rtable(const uint16_t* XRb, int32_t f) {
  // slab = 8f dwords; load only registers it reaches (aliasing the rest)
  // so the worst-case over-read is 8 dwords, inside the caller's padding
  const int32_t* p = reinterpret_cast<const int32_t*>(XRb);
  const __m512i r0 = _mm512_loadu_si512(p);
  const __m512i r1 = f > 2 ? _mm512_loadu_si512(p + 16) : r0;
  const __m512i r2 = f > 4 ? _mm512_loadu_si512(p + 32) : r1;
  const __m512i r3 = f > 6 ? _mm512_loadu_si512(p + 48) : r2;
  return {r0, r1, r2, r3, f <= 4};
}

// rank at flat u16 index xi: permute the containing dword, then shift the
// odd/even half down — pure register traffic, no gather
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
rlookup(const RankTable128& rt, __m512i xi) {
  const __m512i di = _mm512_srli_epi32(xi, 1);
  const __m512i sh =
      _mm512_slli_epi32(_mm512_and_si512(xi, _mm512_set1_epi32(1)), 4);
  __m512i w = _mm512_permutex2var_epi32(rt.r0, di, rt.r1);
  if (!rt.narrow) {
    const __m512i w_hi = _mm512_permutex2var_epi32(rt.r2, di, rt.r3);
    const __mmask16 top =
        _mm512_cmp_epi32_mask(di, _mm512_set1_epi32(31), _MM_CMPINT_NLE);
    w = _mm512_mask_blend_epi32(top, w, w_hi);
  }
  return _mm512_and_si512(_mm512_srlv_epi32(w, sh),
                          _mm512_set1_epi32(0xFFFF));
}

// Shared tail of every q16 step: unpack the record, fetch the row's rank
// for the split feature (register slab or gather), advance internal lanes.
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
advance_q16(__m512i nd, __m512i rec, const uint16_t* XRb, __m512i vroff,
            bool use_rt, const RankTable128& rt) {
  const __m512i fmask = _mm512_set1_epi32(0xFFFF);
  const __m512i one = _mm512_set1_epi32(1);
  const __m512i f = _mm512_and_si512(rec, fmask);
  const __mmask16 internal = _mm512_cmp_epi32_mask(f, fmask, _MM_CMPINT_NE);
  const __m512i code = _mm512_srli_epi32(rec, 16);
  const __m512i xi = _mm512_add_epi32(
      vroff, _mm512_mask_mov_epi32(_mm512_setzero_si512(), internal, f));
  const __m512i rv =
      use_rt ? rlookup(rt, xi)
             : _mm512_and_si512(
                   _mm512_i32gather_epi32(
                       xi, reinterpret_cast<const int*>(XRb), 2),
                   fmask);
  const __mmask16 b = _mm512_cmp_epu32_mask(rv, code, _MM_CMPINT_NLE);
  __m512i nxt = _mm512_add_epi32(_mm512_slli_epi32(nd, 1), one);
  nxt = _mm512_mask_add_epi32(nxt, b, nxt, one);
  return _mm512_mask_mov_epi32(nd, internal, nxt);
}

__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_standard_q16(__m512i nd, const uint32_t* packedb, const uint16_t* XRb,
                  __m512i vroff, bool use_rt, const RankTable128& rt) {
  const __m512i rec =
      _mm512_i32gather_epi32(nd, reinterpret_cast<const int*>(packedb), 4);
  return advance_q16(nd, rec, XRb, vroff, use_rt, rt);
}

struct QNodeTable32 {
  __m512i lo, hi;
};

__attribute__((target("avx512f,avx512dq"), always_inline)) inline QNodeTable32
load_qtable32(const uint32_t* packedb) {
  return {_mm512_loadu_si512(packedb), _mm512_loadu_si512(packedb + 16)};
}

// Levels 0..4 (node ids < 31): the record table lives in one zmm pair.
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_q16_perm(__m512i nd, const QNodeTable32& tab, const uint16_t* XRb,
              __m512i vroff, bool use_rt, const RankTable128& rt) {
  const __m512i rec = _mm512_permutex2var_epi32(tab.lo, nd, tab.hi);
  return advance_q16(nd, rec, XRb, vroff, use_rt, rt);
}

// Level 5 (node ids 31..62), indexed nd-31; lanes that went leaf earlier
// alias into the table, so their record is forced to the leaf sentinel
// (feature 0xFFFF) before the advance. Requires m_nodes >= 63.
__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_q16_perm_l5(__m512i nd, const QNodeTable32& tab, const uint16_t* XRb,
                 __m512i vroff, bool use_rt, const RankTable128& rt) {
  const __m512i vbase = _mm512_set1_epi32(31);
  const __m512i idx = _mm512_sub_epi32(nd, vbase);
  const __mmask16 in_level =
      _mm512_cmp_epi32_mask(nd, vbase, _MM_CMPINT_NLT);  // nd >= 31
  const __m512i rec = _mm512_mask_mov_epi32(
      _mm512_set1_epi32(0xFFFF), in_level,
      _mm512_permutex2var_epi32(tab.lo, idx, tab.hi));
  return advance_q16(nd, rec, XRb, vroff, use_rt, rt);
}

// Level 6 (node ids 63..126, 64 records): two zmm pairs with the same
// 64-entry blended lookup as xlookup/rlookup. Requires m_nodes >= 127.
struct QNodeTable64 {
  __m512i p0, p1, p2, p3;
};

__attribute__((target("avx512f,avx512dq"), always_inline)) inline QNodeTable64
load_qtable64(const uint32_t* packedb) {
  return {_mm512_loadu_si512(packedb), _mm512_loadu_si512(packedb + 16),
          _mm512_loadu_si512(packedb + 32), _mm512_loadu_si512(packedb + 48)};
}

__attribute__((target("avx512f,avx512dq"), always_inline)) inline __m512i
step_q16_perm_l6(__m512i nd, const QNodeTable64& tab, const uint16_t* XRb,
                 __m512i vroff, bool use_rt, const RankTable128& rt) {
  const __m512i vbase = _mm512_set1_epi32(63);
  const __m512i idx = _mm512_sub_epi32(nd, vbase);
  const __mmask16 in_level =
      _mm512_cmp_epi32_mask(nd, vbase, _MM_CMPINT_NLT);  // nd >= 63
  const __mmask16 top =
      _mm512_cmp_epi32_mask(idx, _mm512_set1_epi32(31), _MM_CMPINT_NLE);
  const __m512i rec_lo = _mm512_permutex2var_epi32(tab.p0, idx, tab.p1);
  const __m512i rec_hi = _mm512_permutex2var_epi32(tab.p2, idx, tab.p3);
  const __m512i rec = _mm512_mask_mov_epi32(
      _mm512_set1_epi32(0xFFFF), in_level,
      _mm512_mask_blend_epi32(top, rec_lo, rec_hi));
  return advance_q16(nd, rec, XRb, vroff, use_rt, rt);
}

__attribute__((target("avx512f,avx512dq"))) void score_standard_q16_rows_avx512(
    const uint16_t* XR, int64_t r0, int64_t r1, int32_t n_features,
    const uint32_t* packed, const float* lut, int64_t n_trees,
    int64_t m_nodes, int32_t height, float* out) {
  const int64_t tile = tile_trees(m_nodes * 8);  // match the f32 fold grouping
  const __m512i zero = _mm512_setzero_si512();
  alignas(64) int32_t roff_arr[LANES];
  for (int j = 0; j < LANES; ++j) roff_arr[j] = j * n_features;
  const __m512i vroff = _mm512_load_si512(roff_arr);

  int64_t r = r0;
  for (; r + LANES <= r1; r += LANES) {
    const uint16_t* XRb = XR + r * n_features;
    __m512d acc_lo = _mm512_setzero_pd();
    __m512d acc_hi = _mm512_setzero_pd();
    // same level scheduling as the f32 kernel: levels 0..4 resolve records
    // by register permute when the tree has >= 32 nodes, levels 5/6 by the
    // offset tables, the rest by gather; the rank slab is register-resident
    // whenever F <= QTAB_MAX_FEATURES
    const int32_t perm = m_nodes >= 32 ? std::min(height, PERM_LEVELS) : 0;
    const bool use_rt = n_features <= QTAB_MAX_FEATURES;
    const RankTable128 rt =
        use_rt ? load_rtable(XRb, n_features) : RankTable128{};
    for (int64_t g0 = 0; g0 < n_trees; g0 += tile) {
      const int64_t g1 = std::min(n_trees, g0 + tile);
      __m512d tot_lo = _mm512_setzero_pd();
      __m512d tot_hi = _mm512_setzero_pd();
      int64_t t = g0;
      for (; t + TREE_IL <= g1; t += TREE_IL) {
        __m512i nd[TREE_IL];
        QNodeTable32 tab[TREE_IL];
        for (int u = 0; u < TREE_IL; ++u) {
          nd[u] = zero;
          if (perm) tab[u] = load_qtable32(packed + (t + u) * m_nodes);
        }
        for (int32_t s = 0; s < perm; ++s)
          for (int u = 0; u < TREE_IL; ++u)
            nd[u] = step_q16_perm(nd[u], tab[u], XRb, vroff, use_rt, rt);
        int32_t deep = perm;
        if (perm == PERM_LEVELS && height > PERM_LEVELS && m_nodes >= 63) {
          for (int u = 0; u < TREE_IL; ++u)
            tab[u] = load_qtable32(packed + (t + u) * m_nodes + 31);
          for (int u = 0; u < TREE_IL; ++u)
            nd[u] = step_q16_perm_l5(nd[u], tab[u], XRb, vroff, use_rt, rt);
          deep = perm + 1;
          if (height > deep && m_nodes >= 127) {
            for (int u = 0; u < TREE_IL; ++u) {
              const QNodeTable64 l6 =
                  load_qtable64(packed + (t + u) * m_nodes + 63);
              nd[u] = step_q16_perm_l6(nd[u], l6, XRb, vroff, use_rt, rt);
            }
            deep += 1;
          }
        }
        for (int32_t s = deep; s < height; ++s)
          for (int u = 0; u < TREE_IL; ++u)
            nd[u] = step_standard_q16(nd[u], packed + (t + u) * m_nodes, XRb,
                                      vroff, use_rt, rt);
        for (int u = 0; u < TREE_IL; ++u) {
          const __m512i rec = _mm512_i32gather_epi32(
              nd[u], reinterpret_cast<const int*>(packed + (t + u) * m_nodes),
              4);
          acc_leaf_f64(
              _mm512_i32gather_ps(_mm512_srli_epi32(rec, 16), lut, 4),
              tot_lo, tot_hi);
        }
      }
      for (; t < g1; ++t) {
        __m512i nd = zero;
        if (perm) {
          const QNodeTable32 tab = load_qtable32(packed + t * m_nodes);
          for (int32_t s = 0; s < perm; ++s)
            nd = step_q16_perm(nd, tab, XRb, vroff, use_rt, rt);
        }
        int32_t deep = perm;
        if (perm == PERM_LEVELS && height > PERM_LEVELS && m_nodes >= 63) {
          const QNodeTable32 l5 = load_qtable32(packed + t * m_nodes + 31);
          nd = step_q16_perm_l5(nd, l5, XRb, vroff, use_rt, rt);
          deep = perm + 1;
          if (height > deep && m_nodes >= 127) {
            const QNodeTable64 l6 = load_qtable64(packed + t * m_nodes + 63);
            nd = step_q16_perm_l6(nd, l6, XRb, vroff, use_rt, rt);
            deep += 1;
          }
        }
        for (int32_t s = deep; s < height; ++s)
          nd = step_standard_q16(nd, packed + t * m_nodes, XRb, vroff, use_rt,
                                 rt);
        const __m512i rec = _mm512_i32gather_epi32(
            nd, reinterpret_cast<const int*>(packed + t * m_nodes), 4);
        acc_leaf_f64(_mm512_i32gather_ps(_mm512_srli_epi32(rec, 16), lut, 4),
                     tot_lo, tot_hi);
      }
      acc_lo = _mm512_add_pd(acc_lo, tot_lo);
      acc_hi = _mm512_add_pd(acc_hi, tot_hi);
    }
    const __m512d vn = _mm512_set1_pd(static_cast<double>(n_trees));
    _mm256_storeu_ps(out + r, _mm512_cvtpd_ps(_mm512_div_pd(acc_lo, vn)));
    _mm256_storeu_ps(out + r + 8, _mm512_cvtpd_ps(_mm512_div_pd(acc_hi, vn)));
  }
  if (r < r1)
    score_standard_q16_rows_scalar(XR, r, r1, n_features, packed, lut,
                                   n_trees, m_nodes, height, out);
}
#endif  // IF_X86

// ---------------------------------------------------------------------------
// Rank binarization (the q16 plane's per-call prep).
// ---------------------------------------------------------------------------

// Scalar searchsorted(edges, v, side='right'): count of edges <= v. The
// `v < edges[mid]` comparison is false for NaN, so NaN converges to
// n_edges — numpy's exact behaviour (NaN sorts past every edge).
void binarize_cells_scalar(const float* X, int64_t c0, int64_t c1,
                           const float* edges, int64_t n_edges,
                           uint16_t* out) {
  for (int64_t c = c0; c < c1; ++c) {
    const float v = X[c];
    int64_t lo = 0, hi = n_edges;
    while (lo < hi) {
      const int64_t mid = (lo + hi) >> 1;
      if (v < edges[mid]) {
        hi = mid;
      } else {
        lo = mid + 1;
      }
    }
    out[c] = static_cast<uint16_t>(lo);
  }
}

#if IF_X86
// 16-lane binary search, BIN_IL vectors interleaved: each search step is a
// serial add -> gather -> compare -> blend chain (~35 cycles of latency on
// an L1-resident edge table), so a single vector would run at latency, not
// throughput — interleaving 4 independent vectors keeps ~4 gathers in
// flight and quarters the effective per-step cost, the same trick as
// TREE_IL in the walkers. Same integer algorithm as the scalar loop
// (masked lanes stop moving once lo == hi), so any ISA/interleave combo
// produces identical u16 ranks.
constexpr int BIN_IL = 4;

__attribute__((target("avx512f,avx512dq"))) void binarize_cells_avx512(
    const float* X, int64_t c0, int64_t c1, const float* edges,
    int64_t n_edges, uint16_t* out) {
  const __m512i one = _mm512_set1_epi32(1);
  const __m512i vend = _mm512_set1_epi32(static_cast<int32_t>(n_edges));
  int64_t c = c0;
  for (; c + BIN_IL * LANES <= c1; c += BIN_IL * LANES) {
    __m512 v[BIN_IL];
    __m512i lo[BIN_IL], hi[BIN_IL];
    for (int u = 0; u < BIN_IL; ++u) {
      v[u] = _mm512_loadu_ps(X + c + u * LANES);
      lo[u] = _mm512_setzero_si512();
      hi[u] = vend;
    }
    while (true) {
      __mmask16 active[BIN_IL];
      int any = 0;
      for (int u = 0; u < BIN_IL; ++u) {
        active[u] = _mm512_cmp_epi32_mask(lo[u], hi[u], _MM_CMPINT_LT);
        any |= active[u];
      }
      if (!any) break;
      for (int u = 0; u < BIN_IL; ++u) {
        const __m512i mid =
            _mm512_srli_epi32(_mm512_add_epi32(lo[u], hi[u]), 1);
        const __m512 e =
            _mm512_mask_i32gather_ps(v[u], active[u], mid, edges, 4);
        // ordered-quiet <: false for NaN lanes, matching the scalar loop
        const __mmask16 less =
            _mm512_mask_cmp_ps_mask(active[u], v[u], e, _CMP_LT_OQ);
        hi[u] = _mm512_mask_mov_epi32(hi[u], less, mid);
        lo[u] = _mm512_mask_mov_epi32(
            lo[u], static_cast<__mmask16>(active[u] & ~less),
            _mm512_add_epi32(mid, one));
      }
    }
    for (int u = 0; u < BIN_IL; ++u)
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + c + u * LANES),
                          _mm512_cvtepi32_epi16(lo[u]));
  }
  if (c < c1) binarize_cells_scalar(X, c, c1, edges, n_edges, out);
}
#endif  // IF_X86

// ---------------------------------------------------------------------------
// Dispatch: ISA selection + row-range threading.
// ---------------------------------------------------------------------------

bool use_simd() {
  // opt-out accepts the obvious spellings, not just "0" — an operator
  // debugging with ISOFOREST_NATIVE_SIMD=false must actually get scalar
  const char* s = std::getenv("ISOFOREST_NATIVE_SIMD");
  if (s) {
    std::string v(s);
    for (auto& c : v) c = static_cast<char>(std::tolower(c));
    if (v == "0" || v == "false" || v == "off" || v == "no") return false;
  }
#if IF_X86
  static const bool ok = __builtin_cpu_supports("avx512f") &&
                         __builtin_cpu_supports("avx512dq");
  return ok;
#else
  return false;
#endif
}

int env_threads(int64_t n_rows) {
  // an explicit ISOFOREST_NATIVE_THREADS wins outright (also how the test
  // suite exercises the threaded path on small inputs); the automatic
  // default spawns at most one thread per 16k rows so serving-size batches
  // stay single-threaded (spawn overhead beats the win below that)
  const char* s = std::getenv("ISOFOREST_NATIVE_THREADS");
  if (s && *s) {
    const int v = std::atoi(s);
    // any explicit setting wins: 0 (or junk that parses to <= 0) forces
    // single-threaded rather than silently falling back to the automatic
    // multi-thread default
    return std::max(v, 1);
  }
  constexpr int64_t MIN_ROWS_PER_THREAD = 16 * 1024;
  const unsigned hc = std::thread::hardware_concurrency();
  const int hw = hc ? static_cast<int>(hc) : 1;
  const int64_t cap = std::max<int64_t>(1, n_rows / MIN_ROWS_PER_THREAD);
  return static_cast<int>(std::min<int64_t>(hw, cap));
}

template <typename RangeFn>
void run_row_ranges(int64_t n_rows, RangeFn fn) {
  const int nt = env_threads(n_rows);
  if (nt <= 1) {
    fn(0, n_rows);
    return;
  }
  // 16-row-aligned partition so every thread's slab boundary is also a SIMD
  // block boundary (keeps per-row results independent of the partition);
  // true 16-aligned ceiling with a floor of one SIMD block, so the
  // requested thread count is actually delivered (ADVICE r4: the former
  // "+16" under-spawned and left the last worker systematically short)
  const int64_t chunk =
      std::max<int64_t>(16, ((n_rows + nt - 1) / nt + 15) / 16 * 16);
  std::vector<std::thread> workers;
  workers.reserve(nt);
  // An exception here (thread-ctor resource failure, worker bad_alloc)
  // must not unwind past a joinable std::thread — that std::terminate()s
  // the host Python process. Join whatever spawned, then recompute the
  // whole range sequentially: every row is pure, so overwriting rows some
  // worker already produced yields the identical result.
  std::atomic<bool> worker_failed{false};
  bool spawn_failed = false;
  try {
    for (int64_t start = 0; start < n_rows; start += chunk) {
      const int64_t stop = std::min(n_rows, start + chunk);
      workers.emplace_back([=, &worker_failed] {
        try {
          fn(start, stop);
        } catch (...) {
          worker_failed.store(true);
        }
      });
    }
  } catch (...) {
    spawn_failed = true;
  }
  for (auto& w : workers) w.join();
  if (spawn_failed || worker_failed.load()) fn(0, n_rows);
}
}  // namespace

extern "C" {

// Mean path length per row over a standard forest, in the finalized
// scoring layout (ops/scoring_layout.py):
//   X[n_rows, n_features] f32 row-major; feature[T, M] i32 (-1 leaf);
//   value[T, M] f32 merged plane — split threshold at internal slots, leaf
//   LUT (depth + c(numInstances)) at leaves, 0 at holes. One 8-byte node
//   record instead of the pre-layout 12: the walk's compare and the exit
//   leaf credit read the SAME table, shrinking the L2 tree-tile footprint
//   by a third; out[n_rows] f32.
void if_score_standard(const float* X, int64_t n_rows, int32_t n_features,
                       const int32_t* feature, const float* value,
                       int64_t n_trees, int64_t m_nodes, int32_t height,
                       float* out) {
  const bool simd = use_simd();
  run_row_ranges(n_rows, [=](int64_t r0, int64_t r1) {
#if IF_X86
    if (simd) {
      score_standard_rows_avx512(X, r0, r1, n_features, feature, value,
                                 n_trees, m_nodes, height, out);
      return;
    }
#endif
    (void)simd;
    score_standard_rows_scalar(X, r0, r1, n_features, feature, value,
                               n_trees, m_nodes, height, out);
  });
}

// Extended (hyperplane) variant. indices[T, M, k] i32 (-1 padding; node is a
// leaf iff indices[t, m, 0] < 0); weights[T, M, k] f32 (0 at padding, so the
// unmasked dot matches the XLA gather path bit-for-bit in structure);
// value[T, M] f32 merged plane (hyperplane offset | leaf LUT | 0), same
// layout contract as if_score_standard.
void if_score_extended(const float* X, int64_t n_rows, int32_t n_features,
                       const int32_t* indices, const float* weights,
                       const float* value, int64_t n_trees, int64_t m_nodes,
                       int32_t k, int32_t height, float* out) {
  const bool simd = use_simd();
  run_row_ranges(n_rows, [=](int64_t r0, int64_t r1) {
#if IF_X86
    if (simd) {
      score_extended_rows_avx512(X, r0, r1, n_features, indices, weights,
                                 value, n_trees, m_nodes, k, height, out);
      return;
    }
#endif
    (void)simd;
    score_extended_rows_scalar(X, r0, r1, n_features, indices, weights, value,
                               n_trees, m_nodes, k, height, out);
  });
}

// Quantized (q16) standard walk. The caller pre-binarizes X into per-cell
// ranks xrank[n_rows, n_features] u16 (count of forest threshold edges <=
// x, computed host-side with one vectorized searchsorted) and ships the
// 4 B/node packed plane packed[T, M] u32 (code << 16 | feature, feature
// 0xFFFF at leaves/holes) plus the deduped leaf LUT lut[U] f32. Decisions
// are exact by construction: rank(x) > code  <=>  x >= threshold. xrank
// must carry >= 2 u16 of trailing padding (the SIMD rank gather reads 4
// bytes per lane at 2-byte offsets).
void if_score_standard_q16(const uint16_t* xrank, int64_t n_rows,
                           int32_t n_features, const uint32_t* packed,
                           const float* lut, int64_t n_trees, int64_t m_nodes,
                           int32_t height, float* out) {
  const bool simd = use_simd();
  run_row_ranges(n_rows, [=](int64_t r0, int64_t r1) {
#if IF_X86
    if (simd) {
      score_standard_q16_rows_avx512(xrank, r0, r1, n_features, packed, lut,
                                     n_trees, m_nodes, height, out);
      return;
    }
#endif
    (void)simd;
    score_standard_q16_rows_scalar(xrank, r0, r1, n_features, packed, lut,
                                   n_trees, m_nodes, height, out);
  });
}

// Rank binarization for the q16 plane: out[c] = searchsorted(edges, X[c],
// side='right') — the count of forest threshold edges <= X[c]. This is the
// q16 path's per-call host cost; numpy's generic searchsorted runs
// ~80ns/element at bench scale, so the binarization — not the 16-bit walk
// — dominated the strategy until it moved here (interleaved 16-lane
// AVX-512 search over an L1-resident edge table, scalar fallback,
// row-range threaded). Cell-independent and integer-exact, so every
// ISA/thread combination produces identical ranks.
void if_binarize_ranks(const float* X, int64_t n_cells, const float* edges,
                       int64_t n_edges, uint16_t* out) {
  const bool simd = use_simd();
  run_row_ranges(n_cells, [=](int64_t c0, int64_t c1) {
#if IF_X86
    if (simd) {
      binarize_cells_avx512(X, c0, c1, edges, n_edges, out);
      return;
    }
#endif
    (void)simd;
    binarize_cells_scalar(X, c0, c1, edges, n_edges, out);
  });
}

}  // extern "C"
