// Native IO accelerator: snappy block decompression + Avro node-record
// decoding for the two model schemas.
//
// The reference's IO runs on the JVM (spark-avro + snappy-java); this
// framework's portable path is the pure-Python codec in isoforest_tpu/io/avro.py.
// This translation unit is the native fast path for the record-decoding hot
// loop when loading large models (e.g. 1000-tree forests = ~500k node
// records): the Python loader calls these functions through ctypes and falls
// back transparently when the shared object is unavailable.
//
// Clean-room implementations against the public snappy format description and
// the Avro 1.x binary encoding specification.

#include <cstdint>
#include <cstring>

namespace {

// -- varint / zigzag ---------------------------------------------------------

inline bool read_varint(const uint8_t*& p, const uint8_t* end, uint64_t& out) {
  uint64_t result = 0;
  int shift = 0;
  while (p < end) {
    uint8_t b = *p++;
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      out = result;
      return true;
    }
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}

inline bool read_long(const uint8_t*& p, const uint8_t* end, int64_t& out) {
  uint64_t raw;
  if (!read_varint(p, end, raw)) return false;
  out = static_cast<int64_t>(raw >> 1) ^ -static_cast<int64_t>(raw & 1);
  return true;
}

inline bool read_double(const uint8_t*& p, const uint8_t* end, double& out) {
  if (end - p < 8) return false;
  std::memcpy(&out, p, 8);
  p += 8;
  return true;
}

inline bool read_float(const uint8_t*& p, const uint8_t* end, float& out) {
  if (end - p < 4) return false;
  std::memcpy(&out, p, 4);
  p += 4;
  return true;
}

}  // namespace

extern "C" {

// -- snappy ------------------------------------------------------------------

// Returns the uncompressed length encoded in a raw snappy block, or -1.
int64_t if_snappy_uncompressed_len(const uint8_t* data, int64_t len) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t n;
  if (!read_varint(p, end, n)) return -1;
  return static_cast<int64_t>(n);
}

// Decompress a raw snappy block into out (capacity out_cap).
// Returns bytes written, or -1 on corruption / insufficient capacity.
int64_t if_snappy_decompress(const uint8_t* data, int64_t len, uint8_t* out,
                             int64_t out_cap) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t expected;
  if (!read_varint(p, end, expected)) return -1;
  if (static_cast<int64_t>(expected) > out_cap) return -1;
  int64_t pos = 0;
  while (p < end) {
    uint8_t tag = *p++;
    uint32_t kind = tag & 0x03;
    if (kind == 0) {  // literal
      int64_t n = tag >> 2;
      if (n >= 60) {
        int extra = static_cast<int>(n) - 59;
        if (end - p < extra) return -1;
        n = 0;
        for (int i = 0; i < extra; ++i) n |= static_cast<int64_t>(p[i]) << (8 * i);
        p += extra;
      }
      n += 1;
      if (end - p < n || pos + n > out_cap) return -1;
      std::memcpy(out + pos, p, n);
      p += n;
      pos += n;
    } else {
      int64_t length, offset;
      if (kind == 1) {
        if (p >= end) return -1;
        length = ((tag >> 2) & 0x07) + 4;
        offset = (static_cast<int64_t>(tag >> 5) << 8) | *p++;
      } else if (kind == 2) {
        if (end - p < 2) return -1;
        length = (tag >> 2) + 1;
        offset = p[0] | (static_cast<int64_t>(p[1]) << 8);
        p += 2;
      } else {
        if (end - p < 4) return -1;
        length = (tag >> 2) + 1;
        offset = 0;
        for (int i = 0; i < 4; ++i) offset |= static_cast<int64_t>(p[i]) << (8 * i);
        p += 4;
      }
      if (offset <= 0 || offset > pos || pos + length > out_cap) return -1;
      for (int64_t i = 0; i < length; ++i) {  // overlapping copies: byte-wise
        out[pos] = out[pos - offset];
        ++pos;
      }
    }
  }
  return pos == static_cast<int64_t>(expected) ? pos : -1;
}

// -- Avro node-record decoding ----------------------------------------------

// Decode `count` records of the standard schema
//   {treeID:int, nodeData: union[{id,leftChild,rightChild,splitAttribute:int,
//                                 splitValue:double, numInstances:long}, null]}
// from an uncompressed Avro block body. Union branch 0 = record, 1 = null
// (spark-avro layout). Null nodeData rows get id = -2.
// Returns bytes consumed, or -1 on decode error.
int64_t if_decode_standard(const uint8_t* data, int64_t len, int64_t count,
                           int32_t* tree_id, int32_t* node_id,
                           int32_t* left_child, int32_t* right_child,
                           int32_t* split_attribute, double* split_value,
                           int64_t* num_instances) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  for (int64_t i = 0; i < count; ++i) {
    int64_t v;
    if (!read_long(p, end, v)) return -1;
    tree_id[i] = static_cast<int32_t>(v);
    if (!read_long(p, end, v)) return -1;  // union index
    if (v == 0) {
      int64_t id, lc, rc, sa, ni;
      double sv;
      if (!read_long(p, end, id) || !read_long(p, end, lc) ||
          !read_long(p, end, rc) || !read_long(p, end, sa) ||
          !read_double(p, end, sv) || !read_long(p, end, ni))
        return -1;
      node_id[i] = static_cast<int32_t>(id);
      left_child[i] = static_cast<int32_t>(lc);
      right_child[i] = static_cast<int32_t>(rc);
      split_attribute[i] = static_cast<int32_t>(sa);
      split_value[i] = sv;
      num_instances[i] = ni;
    } else {
      node_id[i] = -2;
    }
  }
  return p - data;
}

// Decode `count` records of the extended schema. Variable-length
// indices/weights are appended to flat buffers (capacity flat_cap) with
// per-record counts in hyper_len. Null rows get id = -2.
// Returns bytes consumed, or -1 on error / capacity overflow.
int64_t if_decode_extended(const uint8_t* data, int64_t len, int64_t count,
                           int32_t* tree_id, int32_t* node_id,
                           int32_t* left_child, int32_t* right_child,
                           double* offset_out, int64_t* num_instances,
                           int32_t* hyper_len, int32_t* flat_indices,
                           float* flat_weights, int64_t flat_cap) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  int64_t flat_pos = 0;
  for (int64_t i = 0; i < count; ++i) {
    int64_t v;
    if (!read_long(p, end, v)) return -1;
    tree_id[i] = static_cast<int32_t>(v);
    if (!read_long(p, end, v)) return -1;  // union index
    if (v != 0) {
      node_id[i] = -2;
      hyper_len[i] = 0;
      continue;
    }
    int64_t id, lc, rc;
    if (!read_long(p, end, id) || !read_long(p, end, lc) || !read_long(p, end, rc))
      return -1;
    node_id[i] = static_cast<int32_t>(id);
    left_child[i] = static_cast<int32_t>(lc);
    right_child[i] = static_cast<int32_t>(rc);
    // indices: union[array[int], null]
    int64_t union_idx;
    if (!read_long(p, end, union_idx)) return -1;
    int64_t n_idx = 0;
    if (union_idx == 0) {
      int64_t block;
      while (true) {
        if (!read_long(p, end, block)) return -1;
        if (block == 0) break;
        if (block < 0) {
          int64_t bytes;
          if (!read_long(p, end, bytes)) return -1;
          block = -block;
        }
        for (int64_t j = 0; j < block; ++j) {
          int64_t item;
          if (!read_long(p, end, item)) return -1;
          if (flat_pos + n_idx >= flat_cap) return -1;
          flat_indices[flat_pos + n_idx] = static_cast<int32_t>(item);
          ++n_idx;
        }
      }
    }
    // weights: union[array[float], null]
    if (!read_long(p, end, union_idx)) return -1;
    int64_t n_w = 0;
    if (union_idx == 0) {
      int64_t block;
      while (true) {
        if (!read_long(p, end, block)) return -1;
        if (block == 0) break;
        if (block < 0) {
          int64_t bytes;
          if (!read_long(p, end, bytes)) return -1;
          block = -block;
        }
        for (int64_t j = 0; j < block; ++j) {
          float w;
          if (!read_float(p, end, w)) return -1;
          if (flat_pos + n_w >= flat_cap) return -1;
          flat_weights[flat_pos + n_w] = w;
          ++n_w;
        }
      }
    }
    if (n_w != n_idx) return -1;
    hyper_len[i] = static_cast<int32_t>(n_idx);
    flat_pos += n_idx;
    double off;
    int64_t ni;
    if (!read_double(p, end, off) || !read_long(p, end, ni)) return -1;
    offset_out[i] = off;
    num_instances[i] = ni;
  }
  return p - data;
}

}  // extern "C"
