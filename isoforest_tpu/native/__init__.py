"""ctypes bindings for the native IO accelerator (build-on-demand).

Compiles ``isoforest_io.cpp`` with the system C++ toolchain on first use and
caches the shared object next to the source. Every entry point has a
pure-Python fallback in :mod:`isoforest_tpu.io.avro`; absence of a compiler
degrades gracefully to the portable path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = pathlib.Path(__file__).parent
_SRCS = (_HERE / "isoforest_io.cpp", _HERE / "scorer.cpp", _HERE / "encoder.cpp")

# Single source for the compile flags AND the cache key: a flags-only
# change (e.g. -pthread, -ffp-contract) must invalidate the cached .so
# exactly like a source change, or hosts keep dlopen-ing a binary built
# with the old, possibly parity-breaking flags.
_CXXFLAGS = (
    "-O3",
    # no FMA contraction: keeps the scalar and SIMD kernels' hyperplane
    # dots rounding identically to each other (the bitwise contract fuzzed
    # in tests/test_properties.py) and to plain separate mul+add. NOTE
    # (r5, measured): XLA:CPU's own k-axis reduce DOES contract to fma, so
    # on tie-heavy quantized data the native EIF dot can still land 1 ulp
    # off growth's offset bits and route exact ties differently — the
    # bounded deviation class documented in PARITY.md and pinned by
    # tests/test_strategies.py::TestQuantizedTieRouting
    "-ffp-contract=off",
    # scorer.cpp spawns std::thread workers; without -pthread some
    # glibc/libstdc++ combinations make the constructor throw
    # system_error at the first multi-threaded call
    "-pthread",
    "-shared",
    "-fPIC",
    "-std=c++17",
)


def _source_digest() -> str:
    h = hashlib.sha256()
    for src in _SRCS:
        h.update(src.read_bytes())
    h.update(" ".join(_CXXFLAGS).encode())
    return h.hexdigest()[:12]


# Output name derived from the source contents: dlopen dedupes by pathname
# within a process, and get_library() trusts an existing file — so ANY source
# change (not just the symbol set) must land at a fresh path or hosts with a
# cached .so silently keep executing the old kernel.
_SO = _HERE / f"_isoforest_native_{_source_digest()}.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    compiler = os.environ.get("CXX", "g++")
    cmd = [compiler, *_CXXFLAGS, *map(str, _SRCS), "-o", str(_SO)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception:
        return None
    for stale in _HERE.glob("_isoforest_native_*.so"):
        if stale != _SO:
            try:
                stale.unlink()
            except OSError:
                pass
    return ctypes.CDLL(str(_SO))


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    i64 = ctypes.c_int64

    lib.if_snappy_uncompressed_len.restype = i64
    lib.if_snappy_uncompressed_len.argtypes = [i8p, i64]
    lib.if_snappy_decompress.restype = i64
    lib.if_snappy_decompress.argtypes = [i8p, i64, i8p, i64]
    lib.if_decode_standard.restype = i64
    lib.if_decode_standard.argtypes = [
        i8p, i64, i64, i32p, i32p, i32p, i32p, i32p, f64p, i64p,
    ]
    lib.if_decode_extended.restype = i64
    lib.if_decode_extended.argtypes = [
        i8p, i64, i64, i32p, i32p, i32p, i32p, f64p, i64p, i32p, i32p, f32p, i64,
    ]
    i32 = ctypes.c_int32
    lib.if_score_standard.restype = None
    lib.if_score_standard.argtypes = [
        f32p, i64, i32, i32p, f32p, i64, i64, i32, f32p,
    ]
    lib.if_score_extended.restype = None
    lib.if_score_extended.argtypes = [
        f32p, i64, i32, i32p, f32p, f32p, i64, i64, i32, i32, f32p,
    ]
    u16p = ctypes.POINTER(ctypes.c_uint16)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.if_score_standard_q16.restype = None
    lib.if_score_standard_q16.argtypes = [
        u16p, i64, i32, u32p, f32p, i64, i64, i32, f32p,
    ]
    lib.if_binarize_ranks.restype = None
    lib.if_binarize_ranks.argtypes = [f32p, i64, f32p, i64, u16p]
    lib.if_encode_standard.restype = i64
    lib.if_encode_standard.argtypes = [
        i32p, i32p, i32p, i32p, i32p, f64p, i64p, i64, i8p, i64,
    ]
    lib.if_encode_extended.restype = i64
    lib.if_encode_extended.argtypes = [
        i32p, i32p, i32p, i32p, f64p, i64p, i32p, i32p, f32p, i64, i8p, i64,
    ]
    return lib


def get_library() -> Optional[ctypes.CDLL]:
    """The bound native library, building it if needed; None if unavailable."""
    global _lib, _build_failed
    from ..resilience import faults

    if faults.native_hidden():
        # fault-injection seam (resilience/faults.py): report unavailable
        # WITHOUT touching the build/bind cache, so behaviour is restored
        # the moment the fault is disarmed
        return None
    if _lib is not None:
        return _lib
    if _build_failed or os.environ.get("ISOFOREST_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        lib = None
        if _SO.exists():
            try:
                lib = ctypes.CDLL(str(_SO))
            except OSError:
                lib = None
        if lib is None:
            lib = _build()
        if lib is None:
            _build_failed = True
            return None
        try:
            _lib = _bind(lib)
        except AttributeError:  # symbol set mismatch: treat as unavailable
            _build_failed = True
            return None
    return _lib


def available() -> bool:
    return get_library() is not None


def _u8ptr(buf: np.ndarray):
    return buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def snappy_decompress(data: bytes) -> Optional[bytes]:
    """Native snappy block decode; None when the library is unavailable.
    Raises ValueError on corrupt input (parity with the Python fallback)."""
    lib = get_library()
    if lib is None:
        return None
    src = np.frombuffer(data, np.uint8)
    n = lib.if_snappy_uncompressed_len(_u8ptr(src), len(data))
    if n < 0:
        raise ValueError("corrupt snappy stream: bad length header")
    out = np.empty(int(n), np.uint8)
    written = lib.if_snappy_decompress(_u8ptr(src), len(data), _u8ptr(out), int(n))
    if written != n:
        raise ValueError("corrupt snappy stream")
    return out.tobytes()


def decode_standard_block(body: bytes, count: int):
    """Decode `count` standard node records from an uncompressed Avro block
    body -> dict of numpy columns; None if the library is unavailable."""
    lib = get_library()
    if lib is None:
        return None
    src = np.frombuffer(body, np.uint8)
    # pre-fill with sentinels: null-union rows only write id (= -2), so every
    # sibling column must hold defined values, not uninitialised memory
    cols = {
        "treeID": np.full(count, -1, np.int32),
        "id": np.full(count, -2, np.int32),
        "leftChild": np.full(count, -1, np.int32),
        "rightChild": np.full(count, -1, np.int32),
        "splitAttribute": np.full(count, -1, np.int32),
        "splitValue": np.zeros(count, np.float64),
        "numInstances": np.full(count, -1, np.int64),
    }
    consumed = lib.if_decode_standard(
        _u8ptr(src), len(body), count,
        cols["treeID"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols["id"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols["leftChild"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols["rightChild"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols["splitAttribute"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols["splitValue"].ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        cols["numInstances"].ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if consumed != len(body):
        raise ValueError("corrupt Avro block (standard node records)")
    return cols


def decode_extended_block(body: bytes, count: int):
    """Extended-schema variant; returns (columns, flat_indices, flat_weights,
    per_record_len) or None."""
    lib = get_library()
    if lib is None:
        return None
    src = np.frombuffer(body, np.uint8)
    flat_cap = max(len(body), 16)  # safe upper bound: >= total array items
    cols = {
        "treeID": np.full(count, -1, np.int32),
        "id": np.full(count, -2, np.int32),
        "leftChild": np.full(count, -1, np.int32),
        "rightChild": np.full(count, -1, np.int32),
        "offset": np.zeros(count, np.float64),
        "numInstances": np.full(count, -1, np.int64),
    }
    hyper_len = np.zeros(count, np.int32)
    flat_indices = np.empty(flat_cap, np.int32)
    flat_weights = np.empty(flat_cap, np.float32)
    consumed = lib.if_decode_extended(
        _u8ptr(src), len(body), count,
        cols["treeID"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols["id"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols["leftChild"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols["rightChild"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols["offset"].ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        cols["numInstances"].ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        hyper_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        flat_indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        flat_weights.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        flat_cap,
    )
    if consumed != len(body):
        raise ValueError("corrupt Avro block (extended node records)")
    total = int(hyper_len.sum())
    return cols, flat_indices[:total], flat_weights[:total], hyper_len


def _f32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


# Per-forest host-side prep (contiguous copies + leaf-value table) cached by
# array identities, same policy as the Pallas prep cache: serving loops that
# score many small batches must not re-copy the forest per call. Holding the
# keyed arrays prevents id() reuse; bounded FIFO.
_PREP_CACHE: dict = {}
_PREP_CACHE_MAX = 8


def _cached(arrays: tuple, build):
    key = tuple(id(a) for a in arrays)
    hit = _PREP_CACHE.get(key)
    if hit is not None and all(a is b for a, b in zip(hit[0], arrays)):
        return hit[1]
    prep = build()
    if len(_PREP_CACHE) >= _PREP_CACHE_MAX:
        _PREP_CACHE.pop(next(iter(_PREP_CACHE)))
    _PREP_CACHE[key] = (arrays, prep)
    return prep


def _merged_value(is_internal, internal_value, num_instances, height: int):
    """Host-side merged value plane of the finalized scoring layout
    (ops/scoring_layout.py): threshold/offset at internal slots, the leaf
    LUT ``depth + c(numInstances)`` at leaves, 0 at holes."""
    from ..utils.math import leaf_value_table

    return np.where(
        is_internal,
        np.asarray(internal_value, np.float32),
        leaf_value_table(num_instances, height),
    ).astype(np.float32)


def score_standard(feature, threshold, num_instances, X, height: int):
    """Mean path length f32[N] via the native walker; None if unavailable.

    Arrays follow ops/tree_growth.StandardForest layout ([T, M] i32/f32/i32);
    the prep merges threshold + leaf LUT into the single value plane the
    packed C++ walker consumes.
    """
    lib = get_library()
    if lib is None:
        return None
    X = np.ascontiguousarray(X, np.float32)
    feature, value = _cached(
        (feature, threshold, num_instances),
        lambda: (
            np.ascontiguousarray(feature, np.int32),
            _merged_value(
                np.asarray(feature) >= 0, threshold, num_instances, height
            ),
        ),
    )
    n, f = X.shape
    t, m = feature.shape
    out = np.empty(n, np.float32)
    lib.if_score_standard(
        _f32ptr(X), n, f, _i32ptr(feature), _f32ptr(value),
        t, m, height, _f32ptr(out),
    )
    return out


# Distinct cache-key sentinel for the quantized prep: score_standard and
# score_standard_q16 key on the SAME forest arrays, and _cached compares by
# identity, so without a marker the two preps would evict each other.
_Q16_KEY = object()


def score_standard_q16(feature, threshold, num_instances, X, height: int):
    """Quantized (q16) mean path length f32[N]; None if unavailable.

    Host prep (cached per forest): rank-space packed plane — sorted deduped
    threshold ``edges``, per-node u32 ``code << 16 | feature`` (0xFFFF at
    leaves/holes, where code indexes the deduped leaf LUT instead of the
    edge table). Per call X is binarized into u16 ranks with one vectorized
    searchsorted; the 4 B/node walk is decision-identical to f32 by
    construction (rank > code  <=>  x >= threshold) and credits the same
    leaf bits as score_standard's merged value plane.
    """
    lib = get_library()
    if lib is None or not hasattr(lib, "if_score_standard_q16"):
        return None

    def build():
        from ..utils.math import leaf_value_table

        feat = np.ascontiguousarray(feature, np.int64)
        thr = np.asarray(threshold, np.float32)
        internal = feat >= 0
        edges = np.unique(thr[internal]).astype(np.float32)
        leaf_vals = np.asarray(
            leaf_value_table(num_instances, height), np.float32
        )
        lut = np.unique(
            np.concatenate([[np.float32(0.0)], leaf_vals[~internal]])
        ).astype(np.float32)
        code = np.empty(feat.shape, np.uint32)
        code[internal] = np.searchsorted(edges, thr[internal]).astype(np.uint32)
        code[~internal] = np.searchsorted(lut, leaf_vals[~internal]).astype(
            np.uint32
        )
        packed = np.ascontiguousarray(
            (code << np.uint32(16))
            | np.where(internal, feat, 0xFFFF).astype(np.uint32)
        )
        return packed, edges, lut

    packed, edges, lut = _cached(
        (feature, threshold, num_instances, _Q16_KEY), build
    )
    X = np.ascontiguousarray(X, np.float32)
    n, f = X.shape
    t, m = packed.shape
    # +32 trailing u16: the SIMD rank gather reads 4 bytes at 2-byte offsets
    # and the register-resident rank slab rounds its loads up to full zmm
    # registers (worst case 32 bytes past an odd-F slab), so the last
    # block's over-read must stay inside the allocation
    xr = np.empty(n * f + 32, np.uint16)
    if n * f:
        if hasattr(lib, "if_binarize_ranks"):
            # threaded native binary search, bitwise np.searchsorted
            # (side='right') semantics incl. NaN -> n_edges; numpy's
            # generic kernel was the q16 path's dominant per-call cost
            lib.if_binarize_ranks(
                _f32ptr(X), n * f, _f32ptr(edges), edges.size,
                xr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            )
        else:
            xr[: n * f] = np.searchsorted(
                edges, X.reshape(-1), side="right"
            ).astype(np.uint16)
    xr[n * f :] = 0
    out = np.empty(n, np.float32)
    lib.if_score_standard_q16(
        xr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)), n, f,
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), _f32ptr(lut),
        t, m, height, _f32ptr(out),
    )
    return out


def score_extended(indices, weights, offset, num_instances, X, height: int):
    """Extended-forest variant ([T, M, k] hyperplanes); None if unavailable."""
    lib = get_library()
    if lib is None:
        return None
    X = np.ascontiguousarray(X, np.float32)
    indices, weights, value = _cached(
        (indices, weights, offset, num_instances),
        lambda: (
            np.ascontiguousarray(indices, np.int32),
            np.ascontiguousarray(weights, np.float32),
            _merged_value(
                np.asarray(indices)[..., 0] >= 0, offset, num_instances, height
            ),
        ),
    )
    n, f = X.shape
    t, m, k = indices.shape
    out = np.empty(n, np.float32)
    lib.if_score_extended(
        _f32ptr(X), n, f, _i32ptr(indices), _f32ptr(weights), _f32ptr(value),
        t, m, k, height, _f32ptr(out),
    )
    return out


def _i64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def encode_standard_records(tree_id, node_id, left, right, attr, value, ni):
    """Columns -> Avro binary body for (treeID, nodeData) rows; None if the
    native library is unavailable."""
    lib = get_library()
    if lib is None:
        return None
    n = len(tree_id)
    cap = 64 * n + 64
    out = np.empty(cap, np.uint8)
    written = lib.if_encode_standard(
        _i32ptr(np.ascontiguousarray(tree_id, np.int32)),
        _i32ptr(np.ascontiguousarray(node_id, np.int32)),
        _i32ptr(np.ascontiguousarray(left, np.int32)),
        _i32ptr(np.ascontiguousarray(right, np.int32)),
        _i32ptr(np.ascontiguousarray(attr, np.int32)),
        _f64ptr(np.ascontiguousarray(value, np.float64)),
        _i64ptr(np.ascontiguousarray(ni, np.int64)),
        n, _u8ptr(out), cap,
    )
    if written < 0:
        return None
    return out[:written].tobytes()


def encode_extended_records(
    tree_id, node_id, left, right, offset, ni, hyper_len, flat_idx, flat_w
):
    """Extended variant; hyperplanes flattened with per-record lengths."""
    lib = get_library()
    if lib is None:
        return None
    n = len(tree_id)
    cap = 96 * n + 14 * len(flat_idx) + 64
    out = np.empty(cap, np.uint8)
    written = lib.if_encode_extended(
        _i32ptr(np.ascontiguousarray(tree_id, np.int32)),
        _i32ptr(np.ascontiguousarray(node_id, np.int32)),
        _i32ptr(np.ascontiguousarray(left, np.int32)),
        _i32ptr(np.ascontiguousarray(right, np.int32)),
        _f64ptr(np.ascontiguousarray(offset, np.float64)),
        _i64ptr(np.ascontiguousarray(ni, np.int64)),
        _i32ptr(np.ascontiguousarray(hyper_len, np.int32)),
        _i32ptr(np.ascontiguousarray(flat_idx, np.int32)),
        _f32ptr(np.ascontiguousarray(flat_w, np.float32)),
        n, _u8ptr(out), cap,
    )
    if written < 0:
        return None
    return out[:written].tobytes()
