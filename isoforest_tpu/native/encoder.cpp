// Native columnar Avro encoders for the model save path — the write-side
// mirror of the columnar decoders in isoforest_io.cpp.
//
// The round-1 save path walked each tree recursively in Python and encoded
// records one dict at a time (~2.25 s for a 1000-tree model). Here the
// heap->pre-order conversion is vectorised numpy (io/persistence.py) and the
// per-record Avro binary encoding is a single C pass over the columns.
//
// Wire format (spark-avro layout, IsolationForestModelReadWrite.scala:36-67):
//   record topLevelRecord { int treeID; union { nodeData, null } }
//   nodeData { int id, leftChild, rightChild, splitAttribute;
//              double splitValue; long numInstances }
// Extended variant (ExtendedIsolationForestModelReadWrite.scala:59-67):
//   extendedNodeData { int id, leftChild, rightChild;
//                      union { array<int>, null } indices;
//                      union { array<float>, null } weights;
//                      double offset; long numInstances }
// Ints/longs are zigzag varints; doubles/floats little-endian; arrays are
// (count, items..., 0). The unions always take branch 0 (present / actual
// array — leaves persist EMPTY arrays, not null, matching the reference).

#include <cstdint>
#include <cstring>

namespace {

inline uint8_t* put_varint(uint8_t* p, uint64_t v) {
  while (v >= 0x80) {
    *p++ = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
  *p++ = static_cast<uint8_t>(v);
  return p;
}

inline uint8_t* put_long(uint8_t* p, int64_t v) {
  return put_varint(p, (static_cast<uint64_t>(v) << 1) ^
                           static_cast<uint64_t>(v >> 63));
}

inline uint8_t* put_double(uint8_t* p, double v) {
  std::memcpy(p, &v, 8);
  return p + 8;
}

inline uint8_t* put_float(uint8_t* p, float v) {
  std::memcpy(p, &v, 4);
  return p + 4;
}

}  // namespace

extern "C" {

// Encode n standard (treeID, nodeData) rows. Returns bytes written, or -1 if
// `cap` could be exceeded (caller sizes cap generously; checked per record).
int64_t if_encode_standard(const int32_t* tree_id, const int32_t* id,
                           const int32_t* left, const int32_t* right,
                           const int32_t* attr, const double* value,
                           const int64_t* ni, int64_t n, uint8_t* out,
                           int64_t cap) {
  uint8_t* p = out;
  const uint8_t* end = out + cap;
  for (int64_t i = 0; i < n; ++i) {
    if (end - p < 64) return -1;  // max record size: 6 varints + 1 double
    p = put_long(p, tree_id[i]);
    p = put_long(p, 0);  // union branch 0: nodeData present
    p = put_long(p, id[i]);
    p = put_long(p, left[i]);
    p = put_long(p, right[i]);
    p = put_long(p, attr[i]);
    p = put_double(p, value[i]);
    p = put_long(p, ni[i]);
  }
  return p - out;
}

// Encode n extended rows. Hyperplane coordinates arrive flattened:
// hyper_len[i] items per record, drawn sequentially from flat_idx /
// flat_w (leaves have hyper_len == 0 -> empty arrays).
int64_t if_encode_extended(const int32_t* tree_id, const int32_t* id,
                           const int32_t* left, const int32_t* right,
                           const double* offset, const int64_t* ni,
                           const int32_t* hyper_len, const int32_t* flat_idx,
                           const float* flat_w, int64_t n, uint8_t* out,
                           int64_t cap) {
  uint8_t* p = out;
  const uint8_t* end = out + cap;
  int64_t flat = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t k = hyper_len[i];
    if (end - p < 96 + 14 * k) return -1;
    p = put_long(p, tree_id[i]);
    p = put_long(p, 0);  // union branch 0: extendedNodeData present
    p = put_long(p, id[i]);
    p = put_long(p, left[i]);
    p = put_long(p, right[i]);
    p = put_long(p, 0);  // indices union branch 0: array
    if (k > 0) {
      p = put_long(p, k);
      for (int64_t q = 0; q < k; ++q) p = put_long(p, flat_idx[flat + q]);
    }
    p = put_long(p, 0);  // indices array terminator
    p = put_long(p, 0);  // weights union branch 0: array
    if (k > 0) {
      p = put_long(p, k);
      for (int64_t q = 0; q < k; ++q) p = put_float(p, flat_w[flat + q]);
    }
    p = put_long(p, 0);  // weights array terminator
    p = put_double(p, offset[i]);
    p = put_long(p, ni[i]);
    flat += k;
  }
  return p - out;
}

}  // extern "C"
