"""Dataset utilities: labeled-CSV loading and the benchmark generators.

The reference's data plumbing is Spark DataFrames + committed ODDS CSVs with
explicit schemas and a VectorAssembler (core/TestUtils.scala:58-135). The
analogues here: a numpy CSV loader with the same ``f1,...,fk,label`` row
contract, and synthetic generators for the BASELINE.json stress
configurations (two-blobs / sinusoid — the Extended Isolation Forest paper's
canonical shapes — and a KDDCup99-HTTP-like mixture).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def load_labeled_csv(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Load ``f1,...,fk,label`` rows (``#`` comments) -> (f32[N,F], labels[N])."""
    data = np.loadtxt(path, delimiter=",", comments="#").astype(np.float32)
    if data.ndim != 2 or data.shape[1] < 2:
        raise ValueError(f"{path}: expected rows of features plus a label column")
    return data[:, :-1], data[:, -1].astype(np.float64)


def two_blobs(
    n: int = 4096, contamination: float = 0.02, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Two dense Gaussian blobs + sparse background anomalies (EIF paper fig. 2:
    the shape where axis-aligned score maps show 'ghost' artifacts that
    hyperplane splits remove)."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    n_in = n - n_out
    a = rng.normal(loc=(0.0, 10.0), scale=1.0, size=(n_in // 2, 2))
    b = rng.normal(loc=(10.0, 0.0), scale=1.0, size=(n_in - n_in // 2, 2))
    outliers = rng.uniform(low=-5.0, high=15.0, size=(n_out, 2))
    X = np.vstack([a, b, outliers]).astype(np.float32)
    y = np.concatenate([np.zeros(n_in), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def sinusoid(
    n: int = 4096, contamination: float = 0.02, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Points along a sine curve + uniform anomalies (EIF paper fig. 3)."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    n_in = n - n_out
    x = rng.uniform(0.0, 10.0, size=n_in)
    y_coord = np.sin(x) + rng.normal(scale=0.15, size=n_in)
    inliers = np.stack([x, y_coord], axis=1)
    outliers = rng.uniform(low=(0.0, -4.0), high=(10.0, 4.0), size=(n_out, 2))
    X = np.vstack([inliers, outliers]).astype(np.float32)
    y = np.concatenate([np.zeros(n_in), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def kddcup_http_like(
    n: int = 1_000_000, contamination: float = 0.004, seed: int = 7
) -> Tuple[np.ndarray, np.ndarray]:
    """KDDCup99-HTTP-like 3-feature mixture (log-scaled duration/src/dst
    bytes) with a dense attack cluster."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    normal = rng.multivariate_normal(
        mean=[0.0, 5.2, 8.0],
        cov=[[0.6, 0.1, 0.0], [0.1, 1.2, 0.3], [0.0, 0.3, 1.5]],
        size=n - n_out,
    )
    attacks = rng.multivariate_normal(
        mean=[4.5, 9.5, 2.0], cov=np.eye(3).tolist(), size=n_out
    )
    X = np.vstack([normal, attacks]).astype(np.float32)
    y = np.concatenate([np.zeros(n - n_out), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def kddcup_http_hard(
    n: int = 1_000_000, contamination: float = 0.004, seed: int = 7
) -> Tuple[np.ndarray, np.ndarray]:
    """Harder KDDCup99-HTTP-like mixture whose AUROC can actually fail.

    :func:`kddcup_http_like` saturates at AUROC 1.0000 for every reasonable
    implementation (VERDICT r1: a benchmark that cannot detect a quality
    regression). Here half the attacks are 'stealth': drawn from the normal
    cloud's own covariance at ~2 Mahalanobis-sigma offset, so they overlap
    the inlier tail and perfect separation is impossible. A healthy isolation
    forest lands at AUROC ~0.93-0.97 on this mixture; degraded tree growth,
    broken bagging, or a mis-set threshold moves the number measurably.
    """
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    n_loud = n_out // 2
    n_stealth = n_out - n_loud
    cov = [[0.6, 0.1, 0.0], [0.1, 1.2, 0.3], [0.0, 0.3, 1.5]]
    normal = rng.multivariate_normal(mean=[0.0, 5.2, 8.0], cov=cov, size=n - n_out)
    loud = rng.multivariate_normal(
        mean=[4.5, 9.5, 2.0], cov=(2.0 * np.eye(3)).tolist(), size=n_loud
    )
    stealth = rng.multivariate_normal(
        mean=[1.4, 6.9, 9.9], cov=cov, size=n_stealth
    )
    X = np.vstack([normal, loud, stealth]).astype(np.float32)
    y = np.concatenate([np.zeros(n - n_out), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def mulcross(
    n: int = 65536, contamination: float = 0.1, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Mulcross-family mixture (Rocke & Woodruff's synthetic generator behind
    the ODDS 'mulcross' set in the reference's published table,
    /root/reference/README.md:444-446): 4-d standard-normal inliers plus TWO
    dense, compact anomaly clusters offset from the mean. Clustered anomalies
    are the regime where the reference's table shows standard IF (0.991)
    beating EIF (0.938-0.940) — dense clumps look like small modes, which
    hyperplane splits carve less cleanly than axis-aligned retries. The
    cluster spread (0.35 sigma) keeps AUROC off the 1.0 ceiling so the gate
    can fail."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    n_a = n_out // 2
    inliers = rng.normal(size=(n - n_out, 4))
    c1 = rng.normal(loc=(3.5, 3.5, 0.0, 0.0), scale=0.35, size=(n_a, 4))
    c2 = rng.normal(loc=(0.0, 0.0, 3.5, -3.5), scale=0.35, size=(n_out - n_a, 4))
    X = np.vstack([inliers, c1, c2]).astype(np.float32)
    y = np.concatenate([np.zeros(n - n_out), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def annthyroid_like(
    n: int = 6000, contamination: float = 0.05, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Annthyroid-family shape: low-dim (6) data whose anomalies deviate on
    ONE axis while the remaining dims are high-variance nuisance.

    The reference's published table shows the starkest EIF_max collapse here
    (StandardIF 0.813 vs ExtendedIF_max 0.646, /root/reference/README.md:418-421).
    Mechanism this generator reproduces: a fully-extended hyperplane draws
    weight ~1/sqrt(6) on the relevant axis, so the anomaly offset is diluted
    by the nuisance dims' variance (split SNR < 1), while axis-aligned splits
    see the offset undiluted whenever they draw the relevant feature."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    n_in = n - n_out
    f0_in = rng.normal(0.0, 0.5, n_in)
    nuis_in = rng.normal(0.0, 3.0, (n_in, 5))
    sign = rng.choice([-1.0, 1.0], n_out)
    f0_out = sign * rng.normal(2.5, 0.4, n_out)
    nuis_out = rng.normal(0.0, 3.0, (n_out, 5))
    X = np.vstack(
        [np.column_stack([f0_in, nuis_in]), np.column_stack([f0_out, nuis_out])]
    ).astype(np.float32)
    y = np.concatenate([np.zeros(n_in), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def forestcover_like(
    n: int = 8000, contamination: float = 0.03, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """ForestCover-family shape: 10-d with strongly correlated nuisance
    structure (3 latent factors over 8 dims, like correlated geospatial
    covariates) and anomalies extreme on 2 marginal dims only.

    Reproduces the published EIF_max collapse at ForestCover's magnitude
    (StandardIF 0.882 vs ExtendedIF_max 0.688, /root/reference/README.md:430-432;
    measured here over seeds 1-3: std ~0.883 vs EIF_max ~0.707) — the
    correlated factors dominate every oblique projection, drowning the two
    relevant coordinates."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    n_in = n - n_out
    basis = rng.normal(size=(3, 8)) * 2.0
    nuis_in = rng.normal(size=(n_in, 3)) @ basis + rng.normal(0, 0.3, (n_in, 8))
    nuis_out = rng.normal(size=(n_out, 3)) @ basis + rng.normal(0, 0.3, (n_out, 8))
    rel_in = rng.normal(0.0, 0.6, (n_in, 2))
    sign = rng.choice([-1.0, 1.0], (n_out, 2))
    rel_out = sign * rng.normal(2.0, 0.5, (n_out, 2))
    X = np.vstack(
        [np.hstack([rel_in, nuis_in]), np.hstack([rel_out, nuis_out])]
    ).astype(np.float32)
    y = np.concatenate([np.zeros(n_in), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def ionosphere_like(
    n: int = 4000, contamination: float = 0.1, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Ionosphere-family shape: 33-d inliers on a rank-6 correlated manifold;
    anomalies approximately match every marginal but break the correlation
    structure (independent coordinates at 1.25x marginal scale).

    The regime where the reference's table shows EIF_max WINNING on high-dim
    correlated data (StandardIF 0.8443 vs ExtendedIF_max 0.9075,
    /root/reference/README.md:436-440; measured here over seeds 1-3: std
    ~0.862 vs EIF_max ~0.919): axis-aligned splits only see marginals, while
    random hyperplanes project onto low-inlier-variance directions orthogonal
    to the manifold where correlation-breaking anomalies stick out."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    n_in = n - n_out
    f, r = 33, 6
    basis = rng.normal(size=(r, f)) / np.sqrt(r)
    inliers = rng.normal(size=(n_in, r)) @ basis + rng.normal(0, 0.15, (n_in, f))
    marg_std = inliers.std(axis=0)
    outliers = rng.normal(0.0, 1.25, (n_out, f)) * marg_std
    X = np.vstack([inliers, outliers]).astype(np.float32)
    y = np.concatenate([np.zeros(n_in), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def smtp_like(
    n: int = 6000, contamination: float = 0.03, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Smtp-family shape: 3-d log-count-like traffic where anomalies deviate
    on one axis with partial overlap and moderate nuisance variance.

    Published smtp row (/root/reference/README.md:454-456, BASELINE.md):
    StandardIF 0.910 > ExtendedIF_0 0.896 > ExtendedIF_max 0.858 — a mild
    EIF_max degradation on low-dim axis-aligned traffic data (same dilution
    mechanism as annthyroid, softened: only 2 nuisance dims at 1.5 sigma).
    Measured here over seeds 1-3: std 0.926 / EIF_0 0.923 / EIF_max 0.883."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    n_in = n - n_out
    f0_in = rng.normal(0.0, 0.6, n_in)
    nuis_in = rng.normal(0.0, 1.5, (n_in, 2))
    sign = rng.choice([-1.0, 1.0], n_out)
    f0_out = sign * np.abs(rng.normal(2.1, 0.7, n_out))
    nuis_out = rng.normal(0.0, 1.5, (n_out, 2))
    X = np.vstack(
        [np.column_stack([f0_in, nuis_in]), np.column_stack([f0_out, nuis_out])]
    ).astype(np.float32)
    y = np.concatenate([np.zeros(n_in), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def pima_like(
    n: int = 4000, contamination: float = 0.34, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Pima-family shape: 8-d clinical-like data at 34% contamination (pima
    is 34% positive), outliers shifted on two axes under heavy overlap plus
    high-variance nuisance axes — the published table's weakest, most
    overlapped dataset (StandardIF 0.668, /root/reference/README.md:448-450).

    Published ordering: StandardIF 0.668 ~ ExtendedIF_0 0.667 >
    ExtendedIF_max 0.644. Measured here over seeds 1-3: std 0.637 /
    EIF_0 0.610 / EIF_max 0.588 — same non-saturated regime and ordering."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    n_in = n - n_out
    X_in = rng.normal(0.0, 1.0, (n_in, 8))
    X_in[:, 2:] *= 2.5  # high-variance nuisance axes (hyperplane dilution)
    X_out = rng.normal(0.0, 1.0, (n_out, 8))
    X_out[:, 2:] *= 2.5
    X_out[:, 0] += np.abs(rng.normal(2.6, 0.6, n_out))
    X_out[:, 1] += np.abs(rng.normal(2.2, 0.6, n_out))
    X = np.vstack([X_in, X_out]).astype(np.float32)
    y = np.concatenate([np.zeros(n_in), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]


def high_dim_blobs(
    n: int = 20000, f: int = 274, contamination: float = 0.02, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """High-dimensional correlated blobs (Arrhythmia-274-like shape) for the
    maxFeatures < 1.0 column-subsampling stress config."""
    rng = np.random.default_rng(seed)
    n_out = int(n * contamination)
    basis = rng.normal(size=(16, f))
    inliers = rng.normal(size=(n - n_out, 16)) @ basis
    # scale 1.8: outlier latents overlap the inlier cloud enough that AUROC
    # sits ~0.9 instead of saturating at 1.0 (a gate that can fail)
    outliers = rng.normal(scale=1.8, size=(n_out, 16)) @ basis
    X = np.vstack([inliers, outliers]).astype(np.float32)
    X += rng.normal(scale=0.1, size=X.shape).astype(np.float32)
    y = np.concatenate([np.zeros(n - n_out), np.ones(n_out)])
    perm = rng.permutation(n)
    return X[perm], y[perm]
