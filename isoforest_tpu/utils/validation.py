"""Input validation — the TPU-native analogue of the reference's schema checks.

Mirrors ``core/Utils.scala:35-72``: the features column must be vector-valued,
the output (score / predicted-label) columns must not already exist, and at
scoring time the feature-vector width must match the training width when it is
known (``validateFeatureVectorSize``, Utils.scala:67-72;
``UnknownTotalNumFeatures = -1``, IsolationForestModel.scala:171).

Inputs here are numpy/JAX arrays or pandas DataFrames instead of Spark
Datasets; the same invariants are enforced eagerly on the host before any
device computation is traced.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

UNKNOWN_TOTAL_NUM_FEATURES = -1


NONFINITE_POLICIES = ("warn", "raise", "allow")


def extract_features(
    data,
    features_col: str = "features",
    output_cols: Tuple[str, ...] = (),
    nonfinite: str = "warn",
) -> Tuple[np.ndarray, Optional[object]]:
    """Normalise user input to a float32 ``[N, F]`` matrix.

    Accepts:
      * an ``[N, F]`` array-like (numpy / JAX / nested lists) — returned as-is;
      * a pandas DataFrame with a vector-valued ``features_col`` (each cell an
        array-like), mirroring the reference's VectorType column contract
        (core/Utils.scala:35-65).

    Returns ``(X, frame_or_None)`` where the frame is passed back so
    ``transform`` can append score/label columns to it. Raises if any
    ``output_cols`` already exist on the frame (Utils.scala:47-58).

    ``nonfinite`` is the NaN/inf policy (:func:`check_non_finite`):
    ``"warn"`` (default), ``"raise"``, or ``"allow"``.
    """
    try:
        import pandas as pd
    except Exception:  # pragma: no cover - pandas is in the base image
        pd = None

    if pd is not None and isinstance(data, pd.DataFrame):
        if features_col not in data.columns:
            raise ValueError(
                f"features column {features_col!r} not found in input DataFrame "
                f"(columns: {list(data.columns)})"
            )
        for col in output_cols:
            if col in data.columns:
                raise ValueError(
                    f"output column {col!r} already exists in the input DataFrame"
                )
        first = data[features_col].iloc[0] if len(data) else None
        if first is not None and np.ndim(first) == 0:
            raise ValueError(
                f"features column {features_col!r} must be vector-valued "
                f"(each cell an array of floats), got scalar {type(first).__name__}"
            )
        X = np.asarray(
            np.stack(data[features_col].to_numpy()) if len(data) else np.zeros((0, 0)),
            dtype=np.float32,
        )
        check_non_finite(X, nonfinite)
        return X, data

    X = np.asarray(data, dtype=np.float32)
    if X.ndim != 2:
        raise ValueError(f"expected a 2-D [num_rows, num_features] matrix, got shape {X.shape}")
    check_non_finite(X, nonfinite)
    return X, None


def check_non_finite(X: np.ndarray, policy: str = "warn") -> None:
    """NaN/inf input policy knob. Non-finite features silently poison
    per-node min/max statistics during growth (NaN comparisons are
    all-false, like the JVM's), so:

    * ``"warn"`` — log once per call (the historical default);
    * ``"raise"`` — ValueError, for pipelines that must not train/score on
      degraded inputs;
    * ``"allow"`` — silent, for callers that checked upstream.
    """
    if policy not in NONFINITE_POLICIES:
        raise ValueError(
            f"nonfinite policy must be one of {NONFINITE_POLICIES}, got {policy!r}"
        )
    if policy == "allow" or not X.size:
        return
    finite = np.isfinite(X)
    if finite.all():
        return
    bad = int(X.size - finite.sum())
    msg = (
        f"input contains {bad} non-finite feature values (nan/inf); isolation "
        "trees treat them as incomparable and scores may be degraded"
    )
    if policy == "raise":
        raise ValueError(msg + " (nonfinite='raise')")
    from .logging import logger

    logger.warning("%s", msg)


def validate_feature_vector_size(num_features: int, expected: int) -> None:
    """Scoring-time width check (core/Utils.scala:67-72): skipped when the
    training width is unknown (legacy models, sentinel -1)."""
    if expected != UNKNOWN_TOTAL_NUM_FEATURES and num_features != expected:
        raise ValueError(
            f"feature vector has {num_features} features, but the model was "
            f"trained on {expected}"
        )
