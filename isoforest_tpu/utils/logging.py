"""Logging and phase tracing.

The reference only logs at phase boundaries via Spark's ``Logging`` mixin
(SURVEY.md §5.1/§5.5 — e.g. SharedTrainLogic.scala:39-42,118-126,147-150).
The TPU build upgrades that to (a) a standard library logger, (b) optional
``jax.profiler`` trace annotations around each phase so traces show up in
TensorBoard/XProf when profiling on real hardware, and (c) the telemetry
subsystem: every :func:`phase` is also a telemetry span, so phase timings
land in ``telemetry.snapshot()`` and the Prometheus exposition
(docs/observability.md).
"""

from __future__ import annotations

import contextlib
import logging
import os
import time

LOGLEVEL_ENV = "ISOFOREST_TPU_LOGLEVEL"

# marks OUR stream handler so a module reload (importlib.reload under
# pytest, a second sys.path alias of the package) re-finds it instead of
# stacking a duplicate and double-printing every record
_HANDLER_MARK = "_isoforest_tpu_handler"

logger = logging.getLogger("isoforest_tpu")


def _configured_level() -> str:
    return os.environ.get(LOGLEVEL_ENV, "WARNING").upper()


if not any(getattr(h, _HANDLER_MARK, False) for h in logger.handlers):
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("%(asctime)s %(name)s %(levelname)s: %(message)s"))
    setattr(_h, _HANDLER_MARK, True)
    logger.addHandler(_h)
    logger.setLevel(_configured_level())


def set_level(level: int | str | None = None) -> str:
    """Set the package log level; ``None`` re-reads ``ISOFOREST_TPU_LOGLEVEL``
    from the CURRENT environment (the module-import read is otherwise
    sticky for the process lifetime). Returns the effective level name::

        os.environ["ISOFOREST_TPU_LOGLEVEL"] = "DEBUG"
        isoforest_tpu.utils.set_level()       # -> "DEBUG"
        isoforest_tpu.utils.set_level("INFO")  # explicit override
    """
    logger.setLevel(_configured_level() if level is None else level)
    return logging.getLevelName(logger.level)


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax profiler trace (TensorBoard/XProf-viewable) around a
    block — the deep-profiling layer the reference lacks (SURVEY.md §5.1):

        with isoforest_tpu.utils.trace("/tmp/trace"):
            model = IsolationForest().fit(X)
    """
    import jax.profiler as _prof

    _prof.start_trace(log_dir)
    try:
        yield
    finally:
        _prof.stop_trace()
        logger.info("profiler trace written to %s", log_dir)


@contextlib.contextmanager
def phase(name: str, log_level: int = logging.INFO):
    """Time a named phase: telemetry span + jax profiler annotation + log.

    With telemetry enabled the phase records as a span (annotated into any
    active jax profiler trace by the span itself); with telemetry disabled
    it falls back to the bare ``TraceAnnotation`` so hardware profiling
    keeps working either way.
    """
    # lazy import: utils.logging is imported by telemetry's own producers
    from ..telemetry import _state as _tstate
    from ..telemetry.spans import span as _span

    if _tstate.enabled():
        ctx = _span(name, annotate=True)
    else:
        try:
            import jax.profiler as _prof

            ctx = _prof.TraceAnnotation(name)
        except Exception:  # pragma: no cover
            ctx = contextlib.nullcontext()
    start = time.perf_counter()
    with ctx:
        yield
    logger.log(log_level, "phase %s took %.3fs", name, time.perf_counter() - start)
