from .math import (
    EULER_GAMMA,
    avg_path_length,
    height_limit,
    max_nodes_for,
    score_from_path_length,
)
from .params import (
    ExtendedIsolationForestParams,
    IsolationForestParams,
    ResolvedParams,
    resolve_extension_level,
    resolve_params,
)
from .validation import (
    NONFINITE_POLICIES,
    UNKNOWN_TOTAL_NUM_FEATURES,
    check_non_finite,
    extract_features,
    validate_feature_vector_size,
)
from .logging import logger, phase, set_level, trace

__all__ = [
    "EULER_GAMMA",
    "avg_path_length",
    "height_limit",
    "max_nodes_for",
    "score_from_path_length",
    "ExtendedIsolationForestParams",
    "IsolationForestParams",
    "ResolvedParams",
    "resolve_extension_level",
    "resolve_params",
    "NONFINITE_POLICIES",
    "UNKNOWN_TOTAL_NUM_FEATURES",
    "check_non_finite",
    "extract_features",
    "validate_feature_vector_size",
    "logger",
    "phase",
    "set_level",
    "trace",
]
