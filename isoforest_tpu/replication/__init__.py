"""Replicated serving tier (docs/replication.md).

A stdlib router process fronting K serving replicas over one sealed
model directory: least-outstanding balancing, health-probe admission,
idempotent retries across replica death, coordinated drains and rolling
model pushes with zero failed requests. Entry points:

* :func:`serve_router` / :class:`RouterHandle` — one-call assembly (the
  ``python -m isoforest_tpu route`` subcommand).
* :class:`Router` / :class:`Replica` / :class:`RouterConfig` — the
  in-process pieces, driveable without subprocesses for tests.
"""

from .router import (
    REPLICAS_PATH,
    NoReplicaError,
    Replica,
    ReplicaRequestError,
    Router,
    RouterConfig,
    RouterHandle,
    mount_router,
    serve_router,
    spawn_replica,
    unmount_router,
)

__all__ = [
    "REPLICAS_PATH",
    "NoReplicaError",
    "Replica",
    "ReplicaRequestError",
    "Router",
    "RouterConfig",
    "RouterHandle",
    "mount_router",
    "serve_router",
    "spawn_replica",
    "unmount_router",
]
