"""Replicated serving tier: a router that survives replica death.

One stdlib process (``python -m isoforest_tpu route --replicas K
--models-dir DIR``) fronts K serving replicas over the **same** sealed
model directory and keeps the client contract — zero failed requests —
through replica crashes, wedges, drains and rolling model pushes
(docs/replication.md):

* **Balancing** — ``POST /score`` / ``POST /score/<model_id>`` forward to
  the admitted replica with the fewest outstanding requests (ties break on
  name, so the schedule is deterministic under test).
* **Health** — a maintenance thread probes every replica each
  ``probe_interval_s``: process exit, a ``GET /healthz`` that fails or
  exceeds ``probe_timeout_s``, or a heartbeat file older than
  ``stale_after_s`` ejects the replica (``router.replica_down``); a
  recovered probe re-admits it (``router.replica_up``). The router's own
  ``/healthz`` reads the replica heartbeat directory, so one curl shows
  the whole tier.
* **Retries** — scoring is idempotent, so a forward that dies on the wire
  (connection severed, timeout — the replica crashed mid-request) is
  retried on another replica under a typed
  :class:`~isoforest_tpu.resilience.retry.RetryPolicy` budget. Every
  forward carries an ``X-Isoforest-Idempotency-Key``: a replica that
  already answered the key replays fold-free, so a retried flush never
  double-counts the drift monitor. A replica's *authoritative* error
  (4xx/5xx response) passes through untouched — the router retries wire
  death, not application answers.
* **Drain** — SIGTERM flips the router to draining (new requests answer
  503), waits for in-flight forwards to finish, then SIGTERMs each
  spawned replica (``router.replica_drain``) so their coalescers drain in
  turn. No request is abandoned mid-flight.
* **Rolling pushes** — the maintenance thread watches each tenant's
  ``CURRENT.json`` generation pointer (the lifecycle manager's durable
  swap record). When a ``manage``-driven swap advances it, the router
  POSTs ``/reload/<model_id>`` to every admitted replica until all ack
  the new generation, then records one ``router.push`` event — a single
  swap reaches the whole tier with zero restarts, and in-flight requests
  answer bitwise old-or-new, never torn.

Every request runs in a ``router.request`` span and echoes
``X-Isoforest-Trace``; ``isoforest_router_*`` series cover forwards,
retries, admitted replicas and outstanding depth; ``GET /replicas`` and
the ``/healthz`` + debug-bundle ``router`` sections expose per-replica
state.

The router's daemon also answers the telemetry built-ins **for the whole
tier** (docs/observability.md §11): its ``GET /metrics``, ``/snapshot``,
``/trace``, ``/traces/recent`` and ``/debug/bundle`` fan out to every
admitted replica and merge (counters sum, histograms bucket-sum with
identical edges enforced, gauges gain ``{replica=}``, events interleave,
traces stitch into one Perfetto document with per-process ``pid`` lanes);
unreachable replicas degrade the answer to a partial one with an explicit
``missing_replicas`` field, and — when the tier runs with
``--journal-dir`` — the bundle recovers a dead replica's flight-recorder
spool off disk.
"""

from __future__ import annotations

import http.client
import json
import math
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..fleet.service import RELOAD_PREFIX, SCORE_PREFIX, discover_models
from ..lifecycle.manager import CURRENT_NAME
from ..resilience import faults
from ..resilience.retry import RetryError, RetryPolicy, retry_call
from ..resilience.watchdog import peer_heartbeat_ages
from ..serving.http import (
    IDEMPOTENCY_HEADER,
    SCORE_PATH,
    TRACE_HEADER,
    inbound_idempotency_key,
    inbound_trace_id,
)
from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _counter
from ..telemetry.metrics import exponential_buckets, gauge as _gauge
from ..telemetry.metrics import histogram as _histogram
from ..telemetry.spans import TraceContext, span, with_context
from ..utils.logging import logger

REPLICAS_PATH = "/replicas"
HEARTBEAT_DIR_NAME = ".router-heartbeats"

# same bucket shape as isoforest_serving_request_seconds so the router's
# added latency reads bucket-for-bucket against the replicas' own series
_ROUTER_REQUEST_SECONDS = _histogram(
    "isoforest_router_request_seconds",
    "End-to-end routed /score request latency (pick + forward + retries)",
    buckets=exponential_buckets(50e-6, 1.3, 36),
)
_ROUTER_REQUESTS = _counter(
    "isoforest_router_requests_total",
    "Routed /score responses by serving replica and HTTP status code",
    labelnames=("replica", "code"),
)
_ROUTER_RETRIES = _counter(
    "isoforest_router_retries_total",
    "Forwards abandoned on a dead/wedged replica and retried elsewhere",
    labelnames=("cause",),
)
_ROUTER_ADMITTED = _gauge(
    "isoforest_router_replicas_admitted",
    "Replicas currently admitted to the balancing pool",
)
_ROUTER_OUTSTANDING = _gauge(
    "isoforest_router_outstanding_requests",
    "Forwards currently in flight across all replicas",
)
_TIER_MISSING = _gauge(
    "isoforest_tier_missing_replicas",
    "1 when the named replica could not contribute to the last federated "
    "telemetry answer (ejected or unreachable), 0 when it answered",
    labelnames=("replica",),
)


class NoReplicaError(RuntimeError):
    """Every replica is ejected — retried under the forward budget (a
    probe may re-admit one between attempts), then a 503."""


class ReplicaRequestError(RuntimeError):
    """A forward died on the wire (the replica crashed/wedged holding the
    request) — retryable on another replica; the idempotency key keeps a
    half-answered flush from double-counting drift."""


@dataclass
class RouterConfig:
    """The router's timing knobs (docs/replication.md §3)."""

    probe_interval_s: float = 1.0    # maintenance cadence (health + push)
    probe_timeout_s: float = 2.0     # /healthz answer budget per replica
    stale_after_s: float = 15.0      # heartbeat age that ejects a replica
    request_timeout_s: float = 30.0  # one forward's wire budget
    drain_timeout_s: float = 30.0    # SIGTERM -> in-flight completion wait
    retry_attempts: int = 3          # forward attempts across replicas
    retry_base_delay_s: float = 0.05
    retry_max_delay_s: float = 0.5

    def retry_policy(self) -> RetryPolicy:
        return RetryPolicy(
            max_attempts=self.retry_attempts,
            base_delay_s=self.retry_base_delay_s,
            multiplier=2.0,
            max_delay_s=self.retry_max_delay_s,
            jitter=0.0,  # deterministic schedule: replicas, not thundering herds
        )


class Replica:
    """One serving replica as the router sees it: its URL, the process the
    router spawned (None for adopted replicas), and its admission state."""

    def __init__(
        self,
        name: str,
        url: str,
        process: Optional[subprocess.Popen] = None,
    ) -> None:
        self.name = str(name)
        self.url = url.rstrip("/")
        self.process = process
        self.admitted = False
        self.outstanding = 0
        self.requests = 0
        self.down_cause: Optional[str] = None
        self.last_error: Optional[str] = None
        # model_id -> generation this replica acked via POST /reload/<id>
        self.acked_generations: Dict[str, int] = {}

    @property
    def pid(self) -> Optional[int]:
        return self.process.pid if self.process is not None else None

    def state(self) -> dict:
        return {
            "name": self.name,
            "url": self.url,
            "pid": self.pid,
            "admitted": self.admitted,
            "outstanding": self.outstanding,
            "requests": self.requests,
            "down_cause": self.down_cause,
            "last_error": self.last_error,
            "acked_generations": dict(self.acked_generations),
        }


class Router:
    """The balancing/health/retry/push brain (module doc). Pure enough to
    drive in-process: injectable ``clock``/``sleep`` (retry backoff) and
    ``wall_clock`` (heartbeat ages), no sockets of its own — probes and
    forwards are plain urllib calls against the replica URLs."""

    def __init__(
        self,
        replicas: List[Replica],
        *,
        models_dir: Optional[str] = None,
        heartbeat_dir: Optional[str] = None,
        work_root: Optional[str] = None,
        journal_dir: Optional[str] = None,
        config: Optional[RouterConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        wall_clock: Callable[[], float] = time.time,
    ) -> None:
        self.replicas = list(replicas)
        self.models_dir = models_dir
        self.heartbeat_dir = heartbeat_dir
        self.work_root = work_root
        self.journal_dir = journal_dir
        self.config = config or RouterConfig()
        self._clock = clock
        self._sleep = sleep
        self._wall_clock = wall_clock
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        self._draining = False
        self._inflight = 0
        # model_id -> generation every admitted replica has acked
        self._pushed: Dict[str, int] = {}
        _ROUTER_ADMITTED.set(0)
        _ROUTER_OUTSTANDING.set(0)

    # ------------------------------------------------------------ health #

    def _set_gauges(self) -> None:
        with self._lock:
            admitted = sum(1 for r in self.replicas if r.admitted)
            outstanding = sum(r.outstanding for r in self.replicas)
        _ROUTER_ADMITTED.set(admitted)
        _ROUTER_OUTSTANDING.set(outstanding)

    def _admit(self, replica: Replica) -> None:
        with self._lock:
            changed = not replica.admitted
            replica.admitted = True
            replica.down_cause = None
        if changed:
            record_event(
                "router.replica_up", replica=replica.name, url=replica.url
            )
            logger.info("router: replica %s admitted (%s)", replica.name,
                        replica.url)
        self._set_gauges()

    def _eject(self, replica: Replica, cause: str, error: Optional[str] = None) -> None:
        with self._lock:
            changed = replica.admitted
            replica.admitted = False
            replica.down_cause = cause
            if error:
                replica.last_error = error
        if changed:
            record_event(
                "router.replica_down",
                replica=replica.name,
                cause=cause,
                error=error,
            )
            logger.warning(
                "router: replica %s ejected (%s)", replica.name, cause
            )
        self._set_gauges()

    def probe_once(self) -> None:
        """One health pass over every replica: process exit, ``/healthz``
        reachability within ``probe_timeout_s``, heartbeat staleness. Each
        verdict flips admission (with the ``router.replica_{up,down}``
        event) only on a state change."""
        ages: Dict[str, float] = {}
        if self.heartbeat_dir:
            ages = peer_heartbeat_ages(self.heartbeat_dir, self._wall_clock)
        for replica in self.replicas:
            cause = error = None
            if replica.process is not None and replica.process.poll() is not None:
                cause = "exited"
                error = f"exit code {replica.process.returncode}"
            else:
                try:
                    with urllib.request.urlopen(
                        replica.url + "/healthz",
                        timeout=self.config.probe_timeout_s,
                    ) as resp:
                        resp.read()
                except urllib.error.HTTPError as exc:
                    cause, error = f"http_{exc.code}", repr(exc)
                except (http.client.HTTPException, OSError) as exc:
                    timed_out = "timed out" in str(exc).lower()
                    cause = "probe_timeout" if timed_out else "probe_failed"
                    error = repr(exc)
            if cause is None and replica.name in ages:
                age = ages[replica.name]
                if not (age <= self.config.stale_after_s):  # inf/nan count stale
                    cause = "heartbeat_stale"
                    error = f"heartbeat age {age!r}s > {self.config.stale_after_s}s"
            if cause is None:
                self._admit(replica)
            else:
                self._eject(replica, cause, error)

    # ----------------------------------------------------------- routing #

    def _pick(self, tried: set) -> Optional[Replica]:
        """The admitted replica with the fewest outstanding forwards,
        preferring ones this request has not tried yet (when every
        admitted replica has been tried, a retry may revisit — the
        idempotency key makes that safe)."""
        with self._lock:
            admitted = [r for r in self.replicas if r.admitted]
            pool = [r for r in admitted if r.name not in tried] or admitted
            if not pool:
                return None
            return min(pool, key=lambda r: (r.outstanding, r.name))

    def handle_score(self, body: bytes, headers, query: str = ""):
        """``POST /score`` (single-model replicas)."""
        return self._proxy(SCORE_PATH, body, headers, query)

    def handle_score_model(self, model_id: str, body: bytes, headers, query: str = ""):
        """``POST /score/<model_id>`` (fleet replicas)."""
        return self._proxy(SCORE_PREFIX + model_id, body, headers, query)

    def _proxy(
        self, path: str, body: bytes, headers, query: str
    ) -> Tuple[int, str, str, Dict[str, str]]:
        t0 = time.perf_counter()
        with self._lock:
            if self._draining:
                draining = True
            else:
                draining = False
                self._inflight += 1
        if draining:
            payload = json.dumps(
                {"error": "router is draining", "status": 503}
            ) + "\n"
            _ROUTER_REQUEST_SECONDS.observe(time.perf_counter() - t0)
            _ROUTER_REQUESTS.inc(replica="none", code=503)
            # a draining router never recovers: the backoff just needs to
            # push the client to another tier within a probe interval
            return 503, "application/json", payload, {
                "Retry-After": self._retry_after_value()
            }
        inbound = inbound_trace_id(headers)
        # the request's identity across retries: adopt the client's key or
        # mint one — either way every forward of THIS request carries the
        # same key, so a replica that already answered it replays fold-free
        idem_key = inbound_idempotency_key(headers) or os.urandom(12).hex()
        content_type = (headers.get("Content-Type") or "") if headers else ""
        tried: set = set()
        served: List[Replica] = []
        trace_id = inbound
        retry_after: Optional[str] = None
        ctx = TraceContext(inbound) if inbound else None
        try:
            with with_context(ctx):
                with span("router.request", path=path) as sp:
                    trace_id = sp.trace_id or inbound

                    def _attempt():
                        replica = self._pick(tried)
                        if replica is None:
                            raise NoReplicaError(
                                "no admitted replicas "
                                f"({len(self.replicas)} registered)"
                            )
                        tried.add(replica.name)
                        return self._forward(
                            replica, path, body, content_type, query,
                            trace_id, idem_key,
                        )

                    try:
                        replica, status, ctype, payload, retry_after = (
                            retry_call(
                                _attempt,
                                policy=self.config.retry_policy(),
                                retry_on=(ReplicaRequestError, NoReplicaError),
                                describe=f"router forward {path}",
                                clock=self._clock,
                                sleep=self._sleep,
                            )
                        )
                        served.append(replica)
                    except RetryError as exc:
                        status, ctype = 503, "application/json"
                        payload = json.dumps(
                            {
                                "error": "no replica answered: "
                                         f"{exc.last_exception!r}",
                                "status": 503,
                                "attempts": exc.attempts,
                            }
                        ) + "\n"
                        # every replica is down/wedged: a probe pass may
                        # re-admit one — tell the client to wait that long
                        retry_after = self._retry_after_value()
                    sp.set_attrs(
                        status=status,
                        replica=served[0].name if served else None,
                        attempts=len(tried),
                    )
        finally:
            with self._lock:
                self._inflight -= 1
                if self._inflight == 0:
                    self._drained.notify_all()
        name = served[0].name if served else "none"
        _ROUTER_REQUEST_SECONDS.observe(time.perf_counter() - t0)
        _ROUTER_REQUESTS.inc(replica=name, code=status)
        resp_headers = {TRACE_HEADER: trace_id} if trace_id else {}
        if retry_after is not None:
            # a replica's backpressure answer travels VERBATIM: its
            # Retry-After is the queue-drain estimate of the machine that
            # actually refused, not anything the router should re-derive
            resp_headers["Retry-After"] = retry_after
        return status, ctype, payload, resp_headers

    def _retry_after_value(self) -> str:
        """The router's own ``Retry-After`` for 503s it mints itself
        (draining, retry budget exhausted): one probe interval — the
        soonest admission state can change — floored to 1 s."""
        return str(max(1, math.ceil(self.config.probe_interval_s)))

    def _forward(
        self,
        replica: Replica,
        path: str,
        body: bytes,
        content_type: str,
        query: str,
        trace_id: Optional[str],
        idem_key: str,
    ) -> Tuple[Replica, int, str, str, Optional[str]]:
        """One forward to one replica. An HTTP response (any status) is the
        replica's authoritative answer and passes through — a 429/503
        backpressure refusal is an ANSWER, not wire death: it consumes no
        retry attempt, ticks no retry counter, emits no
        ``router.replica_retry`` event, and its ``Retry-After`` header
        travels back verbatim (re-forwarding refused load elsewhere would
        convert one replica's backpressure into tier-wide congestion).
        Only wire death (connection severed, timeout) ejects the replica
        and raises the retryable error."""
        with self._lock:
            replica.outstanding += 1
        _ROUTER_OUTSTANDING.inc()
        try:
            url = replica.url + path + (f"?{query}" if query else "")
            req = urllib.request.Request(url, data=body, method="POST")
            if content_type:
                req.add_header("Content-Type", content_type)
            if trace_id:
                req.add_header(TRACE_HEADER, trace_id)
            req.add_header(IDEMPOTENCY_HEADER, idem_key)
            try:
                with urllib.request.urlopen(
                    req, timeout=self.config.request_timeout_s
                ) as resp:
                    payload = resp.read().decode("utf-8")
                    status = resp.status
                    ctype = resp.headers.get("Content-Type") or "application/json"
                    retry_after = resp.headers.get("Retry-After")
            except urllib.error.HTTPError as exc:
                # authoritative pass-through (docstring): 4xx/5xx — and in
                # particular 429/503 backpressure — RETURNS here rather
                # than raising a retryable error, so it never mints a
                # retry attempt
                payload = exc.read().decode("utf-8", errors="replace")
                status = exc.code
                ctype = exc.headers.get("Content-Type") or "application/json"
                retry_after = exc.headers.get("Retry-After")
            except (http.client.HTTPException, OSError) as exc:
                # URLError (incl. timeouts/refused) is an OSError; a severed
                # connection is RemoteDisconnected — all wire death
                self._eject(replica, "request_failed", repr(exc))
                _ROUTER_RETRIES.inc(cause="request_failed")
                record_event(
                    "router.replica_retry",
                    replica=replica.name,
                    path=path,
                    error=repr(exc),
                )
                raise ReplicaRequestError(
                    f"forward to {replica.name} died: {exc!r}"
                ) from exc
            with self._lock:
                replica.requests += 1
            return replica, status, ctype, payload, retry_after
        finally:
            with self._lock:
                replica.outstanding -= 1
            _ROUTER_OUTSTANDING.inc(-1)

    # ------------------------------------------------------ model pushes #

    def _current_path(self, model_id: str, model_dir: str) -> str:
        if self.work_root:
            return os.path.join(self.work_root, model_id, CURRENT_NAME)
        return os.path.join(model_dir + ".lifecycle", CURRENT_NAME)

    def push_once(self) -> Dict[str, int]:
        """One rolling-push pass: read each tenant's ``CURRENT.json``
        generation pointer and ``POST /reload/<model_id>`` to every
        admitted replica that has not acked it yet. Records one
        ``router.push`` event per (tenant, generation) once ALL admitted
        replicas converge. Returns ``{model_id: target generation}`` for
        tenants with a readable pointer."""
        if self.models_dir is None:
            return {}
        if faults.push_stalled():
            return {}  # the chaos seam: push plane wedged, no progress
        targets: Dict[str, int] = {}
        for model_id, model_dir in sorted(
            discover_models(self.models_dir).items()
        ):
            try:
                with open(self._current_path(model_id, model_dir)) as fh:
                    doc = json.load(fh)
                target = int(doc["generation"])
            except (OSError, ValueError, KeyError, TypeError):
                continue  # no swap yet (or torn mid-write): nothing to push
            targets[model_id] = target
            converged = True
            for replica in self.replicas:
                if not replica.admitted:
                    continue
                if replica.acked_generations.get(model_id, -1) >= target:
                    continue
                if self._push_replica(replica, model_id, target):
                    replica.acked_generations[model_id] = target
                else:
                    converged = False
            if converged and self._pushed.get(model_id) != target:
                self._pushed[model_id] = target
                record_event(
                    "router.push", model_id=model_id, generation=target
                )
                logger.info(
                    "router: model %s generation %d reached all replicas",
                    model_id, target,
                )
        return targets

    def _push_replica(self, replica: Replica, model_id: str, target: int) -> bool:
        """True when the replica acks generation ``target`` for
        ``model_id`` (a non-resident tenant acks trivially: its next lazy
        load resumes from ``CURRENT.json`` by construction)."""
        req = urllib.request.Request(
            replica.url + RELOAD_PREFIX + model_id, data=b"", method="POST"
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.config.probe_timeout_s
            ) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
        except Exception as exc:
            replica.last_error = repr(exc)
            return False  # unreachable/refused: the next pass retries
        if doc.get("resident") is False:
            return True
        generation = doc.get("generation")
        return generation is not None and int(generation) >= target

    # ------------------------------------------------------------- drain #

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Stop admitting new requests (they answer 503) and wait — real
        wall time, this is the shutdown path — for in-flight forwards to
        finish. True when the tier drained inside the budget."""
        budget = (
            timeout_s if timeout_s is not None else self.config.drain_timeout_s
        )
        deadline = time.monotonic() + budget
        with self._lock:
            self._draining = True
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._drained.wait(remaining)
            drained = self._inflight == 0
            inflight = self._inflight
        if not drained:
            logger.warning(
                "router: drain timed out with %d request(s) in flight",
                inflight,
            )
        return drained

    def terminate_replicas(self, timeout_s: float = 10.0) -> None:
        """SIGTERM every replica this router spawned (each drains its own
        coalescer on the way down — ``cmd_serve``'s signal handler), then
        reap; a replica that ignores the drain window is killed."""
        spawned = [
            r for r in self.replicas
            if r.process is not None and r.process.poll() is None
        ]
        for replica in spawned:
            record_event(
                "router.replica_drain", replica=replica.name, pid=replica.pid
            )
            replica.process.terminate()
        for replica in spawned:
            try:
                replica.process.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                replica.process.kill()
                replica.process.wait(timeout=5.0)

    # -------------------------------------------- tier-wide observability #

    def federation_sources(
        self, path: str, *, none_on_404: bool = False
    ) -> Tuple[List[Tuple[str, Optional[dict]]], List[str]]:
        """Fan ``GET path`` out to every ADMITTED replica (the probe
        plumbing's timeout budget applies per fetch) and return
        ``(sources, missing)``: ``sources`` pairs each answering replica's
        name with its JSON document; ``missing`` names replicas that could
        not contribute — ejected, unreachable, or answering garbage. With
        ``none_on_404`` a clean 404 still counts as answering (the replica
        is alive, it just has no data for this query — e.g. a trace id it
        never saw) and contributes a ``None`` document. Updates the
        ``isoforest_tier_missing_replicas`` gauge per replica."""
        sources: List[Tuple[str, Optional[dict]]] = []
        missing: List[str] = []
        for replica in self.replicas:
            if not replica.admitted:
                missing.append(replica.name)
                _TIER_MISSING.set(1, replica=replica.name)
                continue
            try:
                with urllib.request.urlopen(
                    replica.url + path, timeout=self.config.probe_timeout_s
                ) as resp:
                    doc = json.loads(resp.read().decode("utf-8"))
            except urllib.error.HTTPError as exc:
                if none_on_404 and exc.code == 404:
                    sources.append((replica.name, None))
                    _TIER_MISSING.set(0, replica=replica.name)
                    continue
                replica.last_error = repr(exc)
                missing.append(replica.name)
                _TIER_MISSING.set(1, replica=replica.name)
                continue
            except (http.client.HTTPException, OSError, ValueError) as exc:
                replica.last_error = repr(exc)
                missing.append(replica.name)
                _TIER_MISSING.set(1, replica=replica.name)
                continue
            sources.append((replica.name, doc))
            _TIER_MISSING.set(0, replica=replica.name)
        return sources, missing

    @staticmethod
    def _json_reply(status: int, doc: dict) -> Tuple[int, str, str]:
        return status, "application/json", json.dumps(doc, sort_keys=True) + "\n"

    @staticmethod
    def _refusal(exc) -> Tuple[int, str, str]:
        from ..telemetry import federation

        payload = dict(federation.error_payload(exc), status=500)
        return Router._json_reply(500, payload)

    def handle_tier_metrics(self, query: str = "") -> Tuple[int, str, str]:
        """Federated ``GET /metrics``: one Prometheus exposition for the
        tier — counters summed, histograms bucket-summed (identical edges
        enforced), gauges labelled ``{replica=}``. Ejected/unreachable
        replicas are reported via ``isoforest_tier_missing_replicas``;
        merge conflicts are a typed 500, never a silently wrong sum."""
        from ..telemetry import federation
        from ..telemetry import metrics as _metrics

        replica_sources, _missing = self.federation_sources("/snapshot")
        # the local snapshot is taken AFTER the fan-out so the freshly
        # updated missing-replica gauge rides this very exposition
        local = ("router", _metrics.registry().snapshot())
        try:
            merged = federation.merge_metrics(
                [
                    local,
                    *[
                        (name, (doc or {}).get("metrics", {}))
                        for name, doc in replica_sources
                    ],
                ]
            )
        except federation.FederationError as exc:
            return self._refusal(exc)
        return (
            200,
            "text/plain; version=0.0.4; charset=utf-8",
            federation.metrics_to_prometheus(merged),
        )

    def handle_tier_snapshot(self, query: str = "") -> Tuple[int, str, str]:
        """Federated ``GET /snapshot``: the merged tier snapshot —
        ``metrics`` keeps the single-process registry shape, ``events``
        interleave with ``source`` labels, and ``missing_replicas`` makes
        a partial answer explicit."""
        from ..telemetry import export, federation

        replica_sources, missing = self.federation_sources("/snapshot")
        local = ("router", export.snapshot())
        try:
            doc = federation.merge_snapshots(
                [local, *[(n, d or {}) for n, d in replica_sources]],
                missing_replicas=missing,
            )
        except federation.FederationError as exc:
            return self._refusal(exc)
        doc["router"] = self.state()
        return self._json_reply(200, doc)

    def handle_tier_trace(self, query: str = "") -> Tuple[int, str, str]:
        """Federated ``GET /trace?trace_id=``: stitch the trace across the
        tier. The router's ``router.request`` span and each replica's
        ``serving.request`` span share the id via ``X-Isoforest-Trace``,
        so ``format=chrome`` (default) renders one Perfetto document with
        a ``pid`` lane per process and flow arrows crossing the boundary;
        ``format=spans`` returns the flat merged span list."""
        from ..telemetry import federation
        from ..telemetry import spans as _spans

        params = urllib.parse.parse_qs(query)
        trace_id = (params.get("trace_id") or [""])[0]
        if not trace_id:
            return self._json_reply(
                400, {"error": "trace_id query parameter required", "status": 400}
            )
        fmt = (params.get("format") or ["chrome"])[0]
        path = (
            f"/trace?trace_id={urllib.parse.quote(trace_id)}&format=spans"
        )
        replica_sources, missing = self.federation_sources(
            path, none_on_404=True
        )
        named: List[Tuple[str, dict]] = []
        local = _spans.get_trace(trace_id)
        if local is not None:
            named.append(("router", local))
        named.extend(
            (name, doc) for name, doc in replica_sources if doc is not None
        )
        if not named:
            return self._json_reply(
                404,
                {
                    "error": f"no captured trace {trace_id} on any tier "
                             "member (never captured, sampled out, or "
                             "evicted)",
                    "status": 404,
                    "missing_replicas": sorted(missing),
                },
            )
        try:
            if fmt == "spans":
                doc = federation.federated_trace_spans(
                    named, trace_id, missing_replicas=missing
                )
            else:
                doc = federation.federated_chrome(
                    [
                        (name, federation.flatten_trace_doc(trace))
                        for name, trace in named
                    ],
                    trace_id,
                    missing_replicas=missing,
                )
        except federation.FederationError as exc:
            return self._refusal(exc)
        return self._json_reply(200, doc)

    def handle_tier_traces_recent(self, query: str = "") -> Tuple[int, str, str]:
        """Federated ``GET /traces/recent``: newest-first trace summaries
        across the tier, each tagged with its ``source``."""
        from ..telemetry import federation
        from ..telemetry import spans as _spans

        params = urllib.parse.parse_qs(query)
        try:
            limit = int((params.get("limit") or ["20"])[0])
        except ValueError:
            limit = 20
        replica_sources, missing = self.federation_sources(
            f"/traces/recent?limit={limit}"
        )
        try:
            doc = federation.merge_recent_traces(
                [
                    ("router", _spans.recent_traces(limit=limit)),
                    *[
                        (name, (d or {}).get("traces", []))
                        for name, d in replica_sources
                    ],
                ],
                limit=limit,
                missing_replicas=missing,
            )
        except federation.FederationError as exc:
            return self._refusal(exc)
        return self._json_reply(200, doc)

    # how many journal records a recovered spool contributes to the tier
    # bundle (newest first; the full spool stays on disk for the CLI)
    BUNDLE_JOURNAL_TAIL = 500

    def handle_tier_bundle(self, query: str = "") -> Tuple[int, str, str]:
        """Federated ``GET /debug/bundle``: the router's own bundle (all
        single-process sections intact) plus every admitted replica's
        bundle under ``replicas`` — and for replicas that can NOT answer,
        their journal spool read off disk (``--journal-dir``), so a
        kill -9 victim still contributes its last events and traces.
        ``missing_replicas`` names every replica whose live bundle is
        absent, journal recovery or not."""
        from ..telemetry import journal as _journal
        from ..telemetry import resources

        try:
            doc = resources.build_bundle()
        except Exception as exc:  # the daemon must never die to this
            return self._json_reply(500, {"error": repr(exc), "status": 500})
        replica_sources, missing = self.federation_sources("/debug/bundle")
        replicas_out: Dict[str, dict] = {
            name: (bundle or {}) for name, bundle in replica_sources
        }
        for name in missing:
            if not self.journal_dir:
                continue
            spool_dir = os.path.join(self.journal_dir, name)
            if not os.path.isdir(spool_dir):
                continue
            try:
                recovered = _journal.read_spool(
                    spool_dir, tail=self.BUNDLE_JOURNAL_TAIL
                )
            except Exception as exc:
                recovered = {"error": repr(exc)}
            replicas_out[name] = {"journal": recovered}
        doc["federated"] = True
        doc["replicas"] = replicas_out
        doc["missing_replicas"] = sorted(missing)
        return self._json_reply(200, doc)

    # ------------------------------------------------------------- state #

    def state(self) -> dict:
        """Operator-facing tier state: the ``/healthz`` ``serving``
        section, ``GET /replicas`` and the debug bundle's ``router``
        section (plain JSON types)."""
        with self._lock:
            return {
                "router": True,
                "draining": self._draining,
                "inflight": self._inflight,
                "models_dir": self.models_dir,
                "heartbeat_dir": self.heartbeat_dir,
                "journal_dir": self.journal_dir,
                "replicas": [r.state() for r in self.replicas],
                "pushed_generations": dict(self._pushed),
            }

    def handle_replicas(self, query: str = "") -> Tuple[int, str, str]:
        """``GET /replicas``: the per-replica admission/outstanding rows."""
        return (
            200,
            "application/json",
            json.dumps(self.state(), sort_keys=True) + "\n",
        )


# ---------------------------------------------------------------- wiring #


def mount_router(server, router: Router) -> None:
    """Register the routed scoring paths + ``GET /replicas`` on a running
    :class:`~isoforest_tpu.telemetry.http.MetricsServer`, surface the
    tier state in ``/healthz`` and the debug bundle, and shadow the
    single-process telemetry built-ins with their tier-FEDERATED versions
    (registered GET routes dispatch before built-ins, so the router's
    daemon answers ``/metrics``, ``/snapshot``, ``/trace``,
    ``/traces/recent`` and ``/debug/bundle`` for the whole replica
    group — docs/observability.md §11)."""
    from ..telemetry import resources

    server.register_post(SCORE_PATH, router.handle_score)
    server.register_post_prefix(SCORE_PREFIX, router.handle_score_model)
    server.register_get(REPLICAS_PATH, router.handle_replicas)
    server.register_get("/metrics", router.handle_tier_metrics)
    server.register_get("/snapshot", router.handle_tier_snapshot)
    server.register_get("/trace", router.handle_tier_trace)
    server.register_get("/traces/recent", router.handle_tier_traces_recent)
    server.register_get("/debug/bundle", router.handle_tier_bundle)
    server.serving_state = router.state
    resources.register_bundle_section("router", router.state)


def unmount_router(server) -> None:
    from ..telemetry import resources

    server.unregister_post(SCORE_PATH)
    server.unregister_post_prefix(SCORE_PREFIX)
    server.unregister_get(REPLICAS_PATH)
    server.unregister_get("/metrics")
    server.unregister_get("/snapshot")
    server.unregister_get("/trace")
    server.unregister_get("/traces/recent")
    server.unregister_get("/debug/bundle")
    server.serving_state = None
    resources.unregister_bundle_section("router")


def spawn_replica(
    name: str,
    models_dir: str,
    heartbeat_dir: str,
    *,
    host: str = "127.0.0.1",
    extra_args: Tuple[str, ...] = (),
    ready_timeout_s: float = 120.0,
) -> Replica:
    """Spawn one ``serve --models-dir`` replica on an ephemeral port and
    parse its JSON ready line for the URL. The child gets ``--replica-name``
    + ``--heartbeat-dir`` (so the ROUTER's ``/healthz`` sees its heartbeat)
    but never ``ISOFOREST_TPU_HEARTBEAT_DIR`` — a replica reading the
    shared directory would 503 its own ``/healthz`` whenever a *peer*
    died, and the router would eject the whole tier."""
    argv = [
        sys.executable, "-m", "isoforest_tpu", "serve",
        "--models-dir", models_dir,
        "--host", host,
        "--port", "0",
        "--replica-name", name,
        "--heartbeat-dir", heartbeat_dir,
        *extra_args,
    ]
    env = dict(os.environ)
    env.pop("ISOFOREST_TPU_METRICS_PORT", None)
    env.pop("ISOFOREST_TPU_HEARTBEAT_DIR", None)
    # the child journals under its REPLICA NAME via --journal-dir (passed in
    # extra_args when the tier journals); inheriting the env var would ALSO
    # open a stray pid-named spool at import time
    env.pop("ISOFOREST_TPU_JOURNAL_DIR", None)
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, env=env, text=True, bufsize=1
    )
    deadline = time.monotonic() + ready_timeout_s
    while True:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica {name} exited with code {proc.returncode} "
                "before printing its ready line"
            )
        if time.monotonic() > deadline:
            proc.terminate()
            raise RuntimeError(f"replica {name} did not become ready")
        line = proc.stdout.readline()
        if not line:
            continue
        try:
            ready = json.loads(line)
        except ValueError:
            continue  # stray banner line, keep scanning
        if ready.get("serving") and ready.get("url"):
            replica = Replica(name, ready["url"], process=proc)
            return replica


class RouterHandle:
    """A running replicated tier: HTTP front + router + maintenance
    thread (+ the spawned replica processes). ``close()`` drains, stops
    the replicas, and tears the server down; usable as a context
    manager."""

    def __init__(self, server, router: Router, stop: threading.Event,
                 maintenance: threading.Thread) -> None:
        self.server = server
        self.router = router
        self._stop = stop
        self._maintenance = maintenance
        self._closed = False

    @property
    def url(self) -> str:
        return self.server.url

    def __enter__(self) -> "RouterHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._maintenance.join(timeout=10.0)
        self.router.drain()
        self.router.terminate_replicas()
        unmount_router(self.server)
        self.server.stop()
        record_event("router.stop", replicas=len(self.router.replicas))


def serve_router(
    models_dir: str,
    *,
    replicas: int = 2,
    port: int = 0,
    host: str = "127.0.0.1",
    config: Optional[RouterConfig] = None,
    work_root: Optional[str] = None,
    replica_args: Tuple[str, ...] = (),
    heartbeat_dir: Optional[str] = None,
    journal_dir: Optional[str] = None,
) -> RouterHandle:
    """Assemble the replicated tier (module doc): spawn ``replicas``
    fleet replicas over ``models_dir``, admit the healthy ones, start the
    telemetry HTTP front with the routed scoring paths mounted, and run
    the probe + rolling-push maintenance loop until ``close()``. With
    ``journal_dir`` every replica flight-records into
    ``<journal_dir>/<replica name>/`` (the child gets ``--journal-dir``)
    and the tier ``/debug/bundle`` recovers dead replicas' spools."""
    config = config or RouterConfig()
    hb_dir = heartbeat_dir or os.path.join(models_dir, HEARTBEAT_DIR_NAME)
    os.makedirs(hb_dir, exist_ok=True)
    spawn_args = tuple(replica_args)
    if journal_dir:
        os.makedirs(journal_dir, exist_ok=True)
        spawn_args = (*spawn_args, "--journal-dir", journal_dir)
    pool: List[Replica] = []
    try:
        for i in range(int(replicas)):
            pool.append(
                spawn_replica(
                    f"replica-{i}", models_dir, hb_dir,
                    host=host, extra_args=spawn_args,
                )
            )
    except Exception:
        for replica in pool:
            if replica.process is not None:
                replica.process.terminate()
        raise
    router = Router(
        pool,
        models_dir=models_dir,
        heartbeat_dir=hb_dir,
        work_root=work_root,
        journal_dir=journal_dir,
        config=config,
    )
    router.probe_once()  # admit the freshly spawned replicas
    from ..telemetry.http import MetricsServer

    server = MetricsServer(
        host=host,
        port=port,
        heartbeat_dir=hb_dir,
        stale_after_s=config.stale_after_s,
    ).start()
    mount_router(server, router)
    stop = threading.Event()

    def _maintain() -> None:
        while not stop.wait(config.probe_interval_s):
            try:
                router.probe_once()
            except Exception:
                logger.exception("router: probe pass failed")
            try:
                router.push_once()
            except Exception:
                logger.exception("router: push pass failed")

    maintenance = threading.Thread(
        target=_maintain, daemon=True, name="isoforest-router-maintenance"
    )
    maintenance.start()
    record_event(
        "router.start",
        port=server.port,
        replicas=[r.name for r in pool],
        models_dir=models_dir,
    )
    logger.info(
        "router: fronting %d replica(s) on %s: %s",
        len(pool), server.url, ", ".join(r.url for r in pool),
    )
    return RouterHandle(server, router, stop, maintenance)
