"""Per-tenant serving behind one port: ``POST /score/<model_id>``.

The wire layer over :class:`~.registry.ModelRegistry` (docs/fleet.md),
mounted on the same telemetry HTTP daemon as everything else — one port
serves ``/metrics``, ``/healthz``, ``/snapshot``, the single-model
``POST /score`` (when one is mounted) AND the fleet routes:

* ``POST /score/<model_id>`` — the single-model wire schema
  (docs/serving.md §2: JSON ``row``/``rows`` or CSV, same response fields
  plus ``model_id``), routed to the tenant's own coalescer. An unknown id
  answers a **404 JSON body** naming the registered models; a tenant whose
  lazy load failed answers 503 (retriable) while every other tenant keeps
  serving. Per-tenant latency/status land in
  ``isoforest_fleet_request_seconds{model_id=}`` /
  ``isoforest_fleet_responses_total{model_id=,code=}`` (the unlabelled
  ``isoforest_serving_*`` series keep deployment-wide totals).
* ``GET /models`` — one state row per tenant (residency, generation,
  queue depth, pin state) plus the fleet budget roll-up.
* ``GET /healthz`` — gains a ``serving`` section with per-tenant
  lifecycle subsections (generation, retrain-in-progress, queue rows), so
  an operator separates a drifting tenant from a healthy fleet without a
  Python prompt.

:func:`serve_fleet` is the one-call assembly the ``serve --models-dir``
subcommand uses: discover sealed model dirs -> register -> mount -> serve.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional, Tuple

from ..serving.http import (
    TRACE_HEADER,
    _BadRequest,
    _error_body,
    _finish as _serving_finish,
    _parse_csv,
    _parse_json,
    inbound_idempotency_key,
    inbound_trace_id,
)
from ..serving.coalescer import ServingError
from ..serving.service import ServingConfig
from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _counter
from ..telemetry.metrics import exponential_buckets, histogram as _histogram
from ..telemetry.spans import TraceContext, span, with_context
from ..utils.logging import logger
from .registry import ModelRegistry, UnknownModelError

SCORE_PREFIX = "/score/"
MODELS_PATH = "/models"
RELOAD_PREFIX = "/reload/"

# same bucket shape as the single-model isoforest_serving_request_seconds
# so per-tenant and deployment-wide latency compare bucket-for-bucket
_FLEET_REQUEST_SECONDS = _histogram(
    "isoforest_fleet_request_seconds",
    "End-to-end /score/<model_id> request latency per tenant "
    "(parse + queue wait + coalesced scoring + encode)",
    labelnames=("model_id",),
    buckets=exponential_buckets(50e-6, 1.3, 36),
)
_FLEET_RESPONSES = _counter(
    "isoforest_fleet_responses_total",
    "/score/<model_id> responses by tenant and HTTP status code",
    labelnames=("model_id", "code"),
)


class FleetService:
    """The HTTP-facing face of one :class:`ModelRegistry` (module doc)."""

    def __init__(self, registry: ModelRegistry) -> None:
        self.registry = registry
        self.started_unix_s = time.time()

    # ------------------------------------------------------------------ #

    def _finish(
        self,
        model_id: str,
        t0: float,
        status: int,
        body: str,
        content_type: str = "application/json",
        retry_after_s: Optional[float] = None,
    ) -> Tuple[int, str, str, dict]:
        """Account one tenant response: the unlabelled serving series keep
        the deployment-wide totals, the ``{model_id=}`` twins separate the
        tenants. Backpressure statuses (429/503) carry ``Retry-After``
        like the single-model path."""
        out = _serving_finish(t0, status, body, content_type, retry_after_s)
        _FLEET_REQUEST_SECONDS.observe(
            time.perf_counter() - t0, model_id=model_id
        )
        _FLEET_RESPONSES.inc(model_id=model_id, code=status)
        return out

    def handle_score(self, model_id: str, body: bytes, headers, query: str = ""):
        """One ``/score/<model_id>`` request -> ``(status, content_type,
        body, headers)``. Pure function of the payload + registry, so the
        status mapping is unit-testable without a socket (the single-model
        ``handle_score`` contract, per tenant). The root span carries the
        tenant's ``model_id`` and the response echoes the effective
        ``X-Isoforest-Trace`` id (docs/observability.md §9)."""
        inbound = inbound_trace_id(headers)
        ctx = TraceContext(inbound) if inbound else None
        with with_context(ctx):
            with span(
                "serving.request", path=SCORE_PREFIX + model_id,
                model_id=model_id,
            ) as sp:
                status, content_type, payload, extra = self._respond(
                    model_id, body, headers, query, sp
                )
                sp.set_attrs(status=status)
                trace_id = sp.trace_id or inbound
        resp_headers = dict(extra)
        if trace_id:
            resp_headers[TRACE_HEADER] = trace_id
        return status, content_type, payload, resp_headers

    def _respond(
        self, model_id: str, body: bytes, headers, query: str, sp
    ) -> Tuple[int, str, str, dict]:
        t0 = time.perf_counter()
        try:
            try:
                self.registry.entry(model_id)
            except UnknownModelError as exc:
                return self._finish(
                    model_id,
                    t0,
                    404,
                    json.dumps(
                        {
                            "error": str(exc),
                            "status": 404,
                            "model_id": model_id,
                            "models": self.registry.model_ids(),
                        }
                    )
                    + "\n",
                )
            content_type = (headers.get("Content-Type") or "").lower()
            csv = "csv" in content_type or "format=csv" in (query or "")
            try:
                rows = _parse_csv(body) if csv else None
                single = False
                if rows is None:
                    rows, single = _parse_json(body)
            except _BadRequest as exc:
                return self._finish(model_id, t0, 400, _error_body(400, str(exc)))
            try:
                scores, info = self.registry.score_detail(
                    model_id,
                    rows,
                    idempotency_key=inbound_idempotency_key(headers),
                )
            except ServingError as exc:
                return self._finish(
                    model_id,
                    t0,
                    exc.status,
                    _error_body(exc.status, str(exc)),
                    retry_after_s=exc.retry_after_s,
                )
            except Exception as exc:  # scoring failure: typed 500, never a hang
                return self._finish(model_id, t0, 500, _error_body(500, repr(exc)))
            flush_ctx = info.get("flush_ctx")
            sp.set_attrs(
                rows=int(rows.shape[0]),
                queue_wait_s=round(float(info.get("queue_wait_s") or 0.0), 6),
                flush_trace_id=flush_ctx.trace_id if flush_ctx else None,
                flush_span_id=flush_ctx.span_id if flush_ctx else None,
            )
            if csv:
                out = "outlierScore\n" + "".join(
                    f"{float(s)!r}\n" for s in scores
                )
                return self._finish(
                    model_id, t0, 200, out, "text/csv; charset=utf-8"
                )
            predictions = info["model"].predict(scores)
            doc = {
                "model_id": model_id,
                "scores": [float(s) for s in scores],
                "predictions": [float(p) for p in predictions],
                "rows": int(rows.shape[0]),
                "single": single,
                "generation": info["generation"],
                "flush_rows": info["flush_rows"],
                "flush_requests": info["flush_requests"],
            }
            if info.get("replayed"):
                # an idempotent retry re-scored fold-free (docs/replication.md §2)
                doc["replayed"] = True
            if info.get("degraded"):
                # autopilot quality rung: degradation reported on the wire
                doc["degraded"] = info["degraded"]
            return self._finish(model_id, t0, 200, json.dumps(doc) + "\n")
        except Exception as exc:  # encoder/accounting bug: still a typed 500
            return self._finish(model_id, t0, 500, _error_body(500, repr(exc)))

    def handle_reload(self, model_id: str, body: bytes, headers, query: str = ""):
        """``POST /reload/<model_id>`` — the per-tenant leg of a rolling
        model push (docs/replication.md): re-read the tenant's
        ``CURRENT.json`` and adopt a newer generation in place. 404 JSON on
        an unknown tenant; a non-resident tenant reloads nothing (its next
        lazy load resumes from ``CURRENT.json`` by construction)."""
        try:
            doc = self.registry.refresh_from_current(model_id)
        except UnknownModelError as exc:
            body_out = json.dumps(
                {
                    "error": str(exc),
                    "status": 404,
                    "model_id": model_id,
                    "models": self.registry.model_ids(),
                }
            ) + "\n"
            return 404, "application/json", body_out
        except Exception as exc:  # a torn push must not kill the route
            return 500, "application/json", _error_body(500, repr(exc))
        return 200, "application/json", json.dumps(doc, sort_keys=True) + "\n"

    def handle_models(self, query: str = "") -> Tuple[int, str, str]:
        """``GET /models``: per-tenant state rows + the fleet roll-up.
        When an overload autopilot is attached the roll-up names its
        current brownout rung (docs/autopilot.md)."""
        from ..autopilot import current_rung

        doc = self.registry.state()
        doc["models"] = self.registry.models_state()
        doc["autopilot_rung"] = current_rung()
        return 200, "application/json", json.dumps(doc, sort_keys=True) + "\n"

    def state(self) -> dict:
        """``/healthz`` serving section: the fleet roll-up plus a
        per-tenant lifecycle subsection each."""
        from ..autopilot import current_rung

        doc = self.registry.state()
        doc["fleet"] = True
        doc["autopilot_rung"] = current_rung()
        doc["tenants"] = {
            row["model_id"]: {
                "resident": row["resident"],
                "generation": row["generation"],
                "queue_rows": row["queue_rows"],
                "retrain_in_progress": row["retrain_in_progress"],
                "pinned": row["pinned"],
                "weight": row["weight"],
                "shed": row["shed"],
                "quality": row["quality"],
            }
            for row in self.registry.models_state()
        }
        return doc


def mount_fleet(server, fleet: FleetService) -> None:
    """Register the fleet routes on a running
    :class:`~isoforest_tpu.telemetry.http.MetricsServer`."""
    server.register_post_prefix(SCORE_PREFIX, fleet.handle_score)
    server.register_post_prefix(RELOAD_PREFIX, fleet.handle_reload)
    server.register_get(MODELS_PATH, fleet.handle_models)
    server.serving_state = fleet.state  # picked up by health()
    server.is_replica = True  # arm the replica chaos seams on this server


def unmount_fleet(server) -> None:
    server.unregister_post_prefix(SCORE_PREFIX)
    server.unregister_post_prefix(RELOAD_PREFIX)
    server.unregister_get(MODELS_PATH)
    server.serving_state = None
    server.is_replica = False


def discover_models(models_dir: str) -> dict:
    """``model_id -> path`` for every sealed model directory directly under
    ``models_dir`` (a subdirectory with the Spark-layout ``metadata/``
    dir); lifecycle work dirs (``*.lifecycle``) are skipped. The subdir
    name becomes the tenant id."""
    out = {}
    for name in sorted(os.listdir(models_dir)):
        path = os.path.join(models_dir, name)
        if name.endswith(".lifecycle") or not os.path.isdir(path):
            continue
        if os.path.isdir(os.path.join(path, "metadata")):
            out[name] = path
    return out


class FleetHandle:
    """A running fleet deployment: HTTP server + registry (+ service).
    ``close()`` tears down in dependency order; usable as a context
    manager."""

    def __init__(self, server, registry: ModelRegistry, fleet: FleetService) -> None:
        self.server = server
        self.registry = registry
        self.fleet = fleet

    @property
    def url(self) -> str:
        return self.server.url

    def __enter__(self) -> "FleetHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        unmount_fleet(self.server)
        self.registry.close()
        self.server.stop()


def serve_fleet(
    models_dir: Optional[str] = None,
    *,
    models: Optional[dict] = None,
    port: int = 0,
    host: str = "127.0.0.1",
    config: Optional[ServingConfig] = None,
    budget_bytes: Optional[int] = None,
    lifecycle: bool = True,
    work_root: Optional[str] = None,
    manager_kwargs: Optional[dict] = None,
    preload: bool = False,
    weights: Optional[dict] = None,
) -> FleetHandle:
    """Assemble a multi-tenant fleet over sealed model directories:

    1. discover tenants (every model dir under ``models_dir``; or pass an
       explicit ``models`` mapping ``model_id -> path``);
    2. register each with the byte-budgeted registry (loads stay lazy
       unless ``preload=True``);
    3. start the telemetry HTTP server and mount ``POST /score/<model_id>``
       + ``GET /models`` on it.

    ``work_root`` hosts per-tenant lifecycle dirs (``<work_root>/<id>``;
    default ``<model_dir>.lifecycle`` next to each model). ``weights``
    maps ``model_id -> priority weight`` for the autopilot's shed rung
    (docs/autopilot.md); unnamed tenants keep ``config.weight``. Returns
    the :class:`FleetHandle`.
    """
    import dataclasses

    from ..telemetry.http import serve as _telemetry_serve

    if (models_dir is None) == (models is None):
        raise ValueError("pass exactly one of models_dir= or models=")
    mapping = dict(models) if models is not None else discover_models(models_dir)
    if not mapping:
        raise ValueError(
            f"no sealed model directories found under {models_dir!r} "
            "(expected subdirectories with a metadata/ dir)"
        )
    registry = ModelRegistry(
        budget_bytes=budget_bytes,
        config=config,
        lifecycle=lifecycle,
        manager_kwargs=manager_kwargs,
    )
    base_config = config or ServingConfig()
    for model_id, path in sorted(mapping.items()):
        work_dir = (
            os.path.join(work_root, model_id) if work_root else None
        )
        tenant_config = None
        if weights and model_id in weights:
            tenant_config = dataclasses.replace(
                base_config, weight=float(weights[model_id])
            )
        registry.register(model_id, path, work_dir=work_dir, config=tenant_config)
    server = _telemetry_serve(port=port, host=host)
    fleet = FleetService(registry)
    mount_fleet(server, fleet)
    if preload:
        for model_id in sorted(mapping):
            registry.ensure_resident(model_id)
    record_event(
        "fleet.start",
        port=server.port,
        models=len(mapping),
        budget_bytes=budget_bytes,
        preloaded=bool(preload),
    )
    logger.info(
        "fleet: serving %d tenant(s) on %s (budget %s bytes, %s): %s",
        len(mapping),
        server.url,
        budget_bytes if budget_bytes is not None else "unbounded",
        "preloaded" if preload else "lazy",
        ", ".join(sorted(mapping)),
    )
    return FleetHandle(server, registry, fleet)
