"""Model fleet registry: many tenants, one process, budgeted residency.

The reference library's own deployment (LinkedIn anti-abuse) runs hundreds
of isolation-forest models — one per surface, region and entity type — and
the inductive-bias analysis (arXiv 2505.12825) says that is the *correct*
unit of operation: per-tenant data distributions differ enough that each
workload wants its own baseline, drift monitor and retrain loop rather
than one global forest. FastForest (arXiv 2004.02423) supplies the other
half of the argument: per-model footprints are small (the packed scoring
layout is ~8 bytes/node, docs/scoring_layout.md), so high-density
co-residency in one process is practical — *if* something manages which
models are resident.

:class:`ModelRegistry` is that something (docs/fleet.md):

* **Registration is cheap.** ``register(model_id, model_dir)`` records the
  sealed on-disk directory and the tenant's serving knobs; nothing loads.
  The on-disk dirs stay authoritative forever — residency is a cache.
* **Loads are lazy and resumable.** A tenant's first request (or the first
  after an eviction) loads the model via the shared
  :func:`~isoforest_tpu.io.persistence.load_model` path, wraps it in a
  :class:`~isoforest_tpu.lifecycle.ModelManager` (which resumes the last
  swapped generation from ``work_dir/CURRENT.json`` — a re-load lands on
  the generation the tenant last swapped to, not its seed) and builds a
  per-tenant :class:`~isoforest_tpu.serving.ScoringService` — its own
  coalescer, its own admission queue, its own backpressure. One tenant's
  429/503, drift debounce, retrain or hot-swap never perturbs another's.
* **Residency is byte-budgeted LRU.** Each resident model pins its packed
  scoring-layout bytes (:func:`layout_nbytes` — the planes every strategy
  actually gathers from); when a load pushes the fleet past
  ``budget_bytes``, the least-recently-used resident tenants are evicted
  (coalescer drained first — in-flight flushes finish on their
  point-in-time model reference, bitwise-exact) until the fleet fits. A
  tenant mid-retrain is **pinned**: eviction is refused until the swap or
  rollback completes, so a background refit is never torn down under a
  cost-pressure race.
* **Everything is observable.** ``fleet.load`` / ``fleet.evict`` /
  ``fleet.evict_refused`` events, the
  ``isoforest_fleet_{resident_models,resident_bytes,loads_total,
  evictions_total}`` series, and two degradation rungs:
  ``fleet_load_failed`` (a broken tenant refuses with a typed 503, the
  rest of the fleet keeps serving) and ``fleet_evict_under_load`` (an
  eviction drained in-flight work — operational note, scores exact).

Lock discipline (audited by ``tools/analysis`` LCK001 and the runtime
witness): the registry lock guards only the entry map and the residency
accounting and never calls out while held; each entry's lock serialises
that tenant's load/evict transitions and may acquire the registry lock
(for accounting) but never another entry's. The scoring hot path holds
neither — it submits to a point-in-time service reference.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..resilience import faults
from ..resilience.degradation import degrade
from ..serving.coalescer import CoalescerClosedError, ServingError
from ..serving.service import ScoringService, ServingConfig
from ..telemetry import resources as _resources
from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _counter, gauge as _gauge
from ..utils.logging import logger

_RESIDENT_MODELS = _gauge(
    "isoforest_fleet_resident_models",
    "Models currently resident (packed scoring layout in memory) in the "
    "fleet registry",
)
_RESIDENT_BYTES = _gauge(
    "isoforest_fleet_resident_bytes",
    "Packed scoring-layout bytes pinned by the resident fleet models "
    "(the quantity the residency budget bounds)",
)
_LOADS_TOTAL = _counter(
    "isoforest_fleet_loads_total",
    "Fleet model loads (first-request lazy loads and post-eviction "
    "re-loads), per tenant",
    labelnames=("model_id",),
)
_EVICTIONS_TOTAL = _counter(
    "isoforest_fleet_evictions_total",
    "Fleet residency evictions by cause "
    "(budget = LRU under byte pressure; explicit = operator/API call; "
    "fault_injected = the evict_during_score seam; close = shutdown)",
    labelnames=("cause",),
)

# eviction causes (the {cause=} label values)
EVICT_BUDGET = "budget"
EVICT_EXPLICIT = "explicit"
EVICT_FAULT = "fault_injected"
EVICT_CLOSE = "close"

# a model id is a URL path segment (POST /score/<model_id>) and a metric
# label value: keep it to a conservative, unescapable alphabet
_MODEL_ID_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._-]{0,63}")


class UnknownModelError(ServingError):
    """No tenant registered under this model id (HTTP 404)."""

    status = 404


class ModelLoadError(ServingError):
    """The tenant's lazy (re)load failed; the registry will retry on its
    next request (HTTP 503 — retriable; other tenants are unaffected)."""

    status = 503
    # loads are retried on the very next request: a short, fixed backoff
    retry_after_s = 1.0


def layout_nbytes(model) -> int:
    """Bytes the model's finalized scoring layout pins while resident — the
    planes of the representation the tenant actually serves from
    (docs/scoring_layout.md). For the default exact representation that is
    the f32 layout: interleaved record, value plane and (standard forests)
    the narrowed feature table. Tenants preferring the quantized plane
    (``scoring_representation == "q16"``) pin the packed u32 records plus
    the shared edge/LUT tables instead — roughly half the bytes — and the
    residency budget must see THAT number, or a fleet standardised on q16
    evicts at f32 density. The raw growth arrays and Python object overhead
    ride along, but the packed planes dominate at fleet density."""
    if getattr(model, "scoring_representation", "f32") == "q16":
        from ..ops.scoring_layout import get_layout_q
        from ..ops.scoring_layout import layout_nbytes as _q16_nbytes

        return _q16_nbytes(get_layout_q(model.forest))
    if getattr(model, "_scoring_layout", None) is None:
        model.finalize_scoring()
    return sum(
        int(arr.size) * int(arr.dtype.itemsize) for arr in model._scoring_layout
    )


class ManagedEntry:
    """One registered tenant: its sealed model dir (authoritative), its
    lifecycle work dir, its serving knobs, and — while resident — its
    loaded model, manager and per-tenant scoring service. The entry lock
    serialises load/evict transitions for this tenant only."""

    def __init__(
        self,
        model_id: str,
        model_dir: str,
        work_dir: str,
        config: ServingConfig,
        lifecycle: bool,
        manager_kwargs: dict,
    ) -> None:
        self.model_id = model_id
        self.model_dir = model_dir
        self.work_dir = work_dir
        self.config = config
        self.lifecycle = lifecycle
        self.manager_kwargs = manager_kwargs
        self._lock = threading.Lock()
        self.model = None
        self.manager = None
        self.service: Optional[ScoringService] = None
        self.resident_bytes = 0
        # host/device split of resident_bytes (telemetry.resources
        # .model_plane_bytes): placement='device' on accelerator backends
        self.plane_bytes: Optional[dict] = None
        self.loads = 0
        self.last_used = 0  # registry LRU sequence number
        self.last_load_error: Optional[str] = None

    @property
    def resident(self) -> bool:
        return self.service is not None

    @property
    def pinned(self) -> bool:
        """True while this tenant's manager is mid-retrain — eviction is
        refused until the swap/rollback completes (docs/fleet.md)."""
        manager = self.manager
        return manager is not None and manager.retrain_in_progress

    @property
    def generation(self) -> Optional[int]:
        manager = self.manager
        return manager.generation if manager is not None else None

    def state(self) -> dict:
        """Operator-facing tenant state (plain JSON types) — one row of
        ``GET /models`` and of the ``/healthz`` fleet section."""
        service = self.service
        manager = self.manager
        doc = {
            "model_id": self.model_id,
            "model_dir": self.model_dir,
            "resident": service is not None,
            "resident_bytes": self.resident_bytes,
            "plane_bytes": dict(self.plane_bytes) if self.plane_bytes else None,
            "loads": self.loads,
            "last_used_seq": self.last_used,
            "pinned": self.pinned,
            "lifecycle": manager is not None,
            "generation": self.generation,
            "queue_rows": service.coalescer.pending_rows if service else None,
            "retrain_in_progress": (
                manager.retrain_in_progress if manager is not None else False
            ),
            "last_load_error": self.last_load_error,
            # autopilot visibility (docs/autopilot.md): the tenant's shed
            # priority class and any active brownout state
            "weight": self.config.weight,
            "shed": service.shed if service is not None else False,
            "quality": service.quality if service is not None else None,
        }
        return doc


class ModelRegistry:
    """``model_id -> ManagedEntry`` with a byte-budgeted residency LRU
    (module docstring; wire routes and policy tables: docs/fleet.md).

    ``budget_bytes=None`` disables eviction (every registered tenant may
    stay resident). ``config`` is the default per-tenant
    :class:`ServingConfig` (override per tenant at :meth:`register`);
    ``lifecycle``/``manager_kwargs`` likewise. ``clock`` is injectable for
    tests.
    """

    def __init__(
        self,
        *,
        budget_bytes: Optional[int] = None,
        config: Optional[ServingConfig] = None,
        lifecycle: bool = True,
        manager_kwargs: Optional[dict] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if budget_bytes is not None and budget_bytes <= 0:
            raise ValueError(f"budget_bytes must be positive, got {budget_bytes}")
        self.budget_bytes = budget_bytes
        self.default_config = config or ServingConfig()
        self.default_lifecycle = bool(lifecycle)
        self.default_manager_kwargs = dict(manager_kwargs or {})
        self.closed = False
        self._clock = clock
        # guards the entry map, the LRU sequence and the residency totals;
        # never held across a load/evict (those hold the entry lock and may
        # acquire THIS lock for accounting — entry -> registry, one way)
        self._lock = threading.Lock()
        self._entries: Dict[str, ManagedEntry] = {}
        self._seq = 0
        self._resident_bytes = 0

    # ------------------------------------------------------------------ #
    # registration / lookup
    # ------------------------------------------------------------------ #

    def register(
        self,
        model_id: str,
        model_dir: str,
        *,
        work_dir: Optional[str] = None,
        config: Optional[ServingConfig] = None,
        lifecycle: Optional[bool] = None,
        manager_kwargs: Optional[dict] = None,
    ) -> ManagedEntry:
        """Register a tenant over a sealed model directory. Nothing loads
        until the tenant's first request (or an explicit
        :meth:`ensure_resident`). Refuses duplicate ids and ids that do not
        fit the URL/label alphabet."""
        model_id = str(model_id)
        if not _MODEL_ID_RE.fullmatch(model_id):
            raise ValueError(
                f"model_id {model_id!r} must match {_MODEL_ID_RE.pattern} "
                "(it becomes a URL path segment and a metric label)"
            )
        if not os.path.isdir(model_dir):
            raise FileNotFoundError(
                f"model_dir {model_dir!r} for tenant {model_id!r} does not exist"
            )
        entry = ManagedEntry(
            model_id,
            str(model_dir),
            str(work_dir or model_dir + ".lifecycle"),
            config or self.default_config,
            self.default_lifecycle if lifecycle is None else bool(lifecycle),
            dict(
                self.default_manager_kwargs
                if manager_kwargs is None
                else manager_kwargs
            ),
        )
        with self._lock:
            if self.closed:
                raise RuntimeError("the registry is closed")
            if model_id in self._entries:
                raise ValueError(f"model_id {model_id!r} is already registered")
            self._entries[model_id] = entry
        record_event("fleet.register", model_id=model_id, path=entry.model_dir)
        return entry

    def entry(self, model_id: str) -> ManagedEntry:
        with self._lock:
            entry = self._entries.get(str(model_id))
        if entry is None:
            raise UnknownModelError(
                f"no model registered under id {str(model_id)!r}"
            )
        return entry

    def model_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def models_state(self) -> List[dict]:
        """Per-tenant state rows (``GET /models``), registration order
        normalised to sorted ids."""
        with self._lock:
            entries = [self._entries[k] for k in sorted(self._entries)]
        return [e.state() for e in entries]

    def resident_services(self) -> List[ScoringService]:
        """Point-in-time references to every resident tenant's scoring
        service (the autopilot's sensor/actuator set, docs/autopilot.md).
        Safe to call from any thread; entries mid-eviction simply drop
        out of the snapshot."""
        with self._lock:
            entries = list(self._entries.values())
        return [e.service for e in entries if e.service is not None]

    def state(self) -> dict:
        """Fleet-level state (plain JSON types)."""
        with self._lock:
            total = len(self._entries)
            resident_bytes = self._resident_bytes
            resident = sum(1 for e in self._entries.values() if e.resident)
        return {
            "models": total,
            "resident_models": resident,
            "resident_bytes": resident_bytes,
            "budget_bytes": self.budget_bytes,
        }

    # ------------------------------------------------------------------ #
    # residency
    # ------------------------------------------------------------------ #

    def ensure_resident(self, model_id: str) -> ManagedEntry:
        """The tenant's entry with a live service, loading (and then
        enforcing the residency budget) if needed; touches the LRU."""
        entry = self.entry(model_id)
        loaded = False
        with entry._lock:
            if entry.service is None:
                self._load_entry_locked(entry)
                loaded = True
        with self._lock:
            self._seq += 1
            entry.last_used = self._seq
        if loaded:
            self._enforce_budget(exclude=entry.model_id)
        return entry

    def _load_entry_locked(self, entry: ManagedEntry) -> None:
        """Load one tenant (caller holds the entry lock): sealed dir ->
        model -> finalized packed layout -> lifecycle manager (resuming the
        last swapped generation from CURRENT.json) -> per-tenant service.
        Any failure takes the ``fleet_load_failed`` rung and refuses with a
        typed 503; the entry stays non-resident and the NEXT request
        retries — one broken tenant must never poison the fleet."""
        from ..io.persistence import load_model
        from ..lifecycle import ModelManager

        t0 = time.perf_counter()
        try:
            # a tenant's lazy first load (or post-eviction re-load) is an
            # EXPECTED one-time cost: any compile it triggers attributes
            # to fleet.load and ticks phase=warmup even after serving has
            # marked steady (docs/observability.md §10)
            with _resources.warmup_scope(), _resources.compile_scope(
                "fleet.load", key=entry.model_id
            ):
                faults.check_fleet_load(entry.model_id)
                model = load_model(entry.model_dir)
                manager = None
                if entry.lifecycle and model.baseline is not None:
                    manager = ModelManager(
                        model,
                        work_dir=entry.work_dir,
                        model_id=entry.model_id,
                        **entry.manager_kwargs,
                    )
                elif entry.lifecycle:
                    logger.warning(
                        "fleet: %s (%s) has no _BASELINE.json sidecar — "
                        "serving WITHOUT the lifecycle manager (no "
                        "drift-triggered retraining); refit and re-save to "
                        "enable it",
                        entry.model_id,
                        entry.model_dir,
                    )
                active = manager.model if manager is not None else model
                # ROADMAP item 2 follow-on: the budget bounds the SCARCE
                # placement — actual device bytes when committed puts land
                # the packed planes on an accelerator, host bytes on CPU
                planes = _resources.model_plane_bytes(active)
                nbytes = (
                    planes["device"]
                    if planes["placement"] == "device"
                    else planes["host"]
                )
                service = ScoringService(
                    model=None if manager is not None else model,
                    manager=manager,
                    config=entry.config,
                    model_id=entry.model_id,
                )
        except Exception as exc:
            entry.last_load_error = repr(exc)
            degrade(
                "fleet_load_failed",
                f"fleet tenant {entry.model_id!r} lazy load",
                "typed 503 refusal (other tenants unaffected)",
                detail=(
                    f"loading {entry.model_dir} for tenant "
                    f"{entry.model_id!r} failed: {exc!r}; the registry "
                    "retries on the tenant's next request"
                ),
            )
            raise ModelLoadError(
                f"model {entry.model_id!r} failed to load ({exc!r}); "
                "retriable — the registry reloads on the next request"
            ) from exc
        entry.model = active
        entry.manager = manager
        entry.service = service
        entry.resident_bytes = nbytes
        entry.plane_bytes = planes
        entry.loads += 1
        entry.last_load_error = None
        with self._lock:
            self._resident_bytes += nbytes
            resident = sum(1 for e in self._entries.values() if e.resident)
            resident_bytes = self._resident_bytes
        _RESIDENT_MODELS.set(resident)
        _RESIDENT_BYTES.set(resident_bytes)
        _LOADS_TOTAL.inc(model_id=entry.model_id)
        _resources.account_resident_plane(
            entry.model_id,
            planes["host"],
            planes["device"],
            plane=planes["plane"],
        )
        record_event(
            "fleet.load",
            model_id=entry.model_id,
            bytes=nbytes,
            placement=planes["placement"],
            generation=entry.generation,
            load_seconds=round(time.perf_counter() - t0, 6),
            resident_models=resident,
            resident_bytes=resident_bytes,
        )
        logger.info(
            "fleet: loaded %s from %s (%d bytes packed, generation %s, "
            "%d resident / %d bytes total)",
            entry.model_id,
            entry.model_dir,
            nbytes,
            entry.generation,
            resident,
            resident_bytes,
        )

    def _enforce_budget(self, exclude: Optional[str] = None) -> None:
        """Evict least-recently-used resident tenants until the fleet fits
        ``budget_bytes``. ``exclude`` protects the tenant whose load
        triggered enforcement (evicting the model a request is about to
        score would thrash). Pinned (mid-retrain) tenants are skipped; if
        nothing is evictable the fleet stays over budget with a warning —
        correctness over the budget, never a torn refit."""
        if self.budget_bytes is None:
            return
        while True:
            with self._lock:
                if self._resident_bytes <= self.budget_bytes:
                    return
                victims = sorted(
                    (
                        e
                        for e in self._entries.values()
                        if e.resident and e.model_id != exclude
                    ),
                    key=lambda e: e.last_used,
                )
            evicted = False
            for victim in victims:
                if self.evict(victim.model_id, cause=EVICT_BUDGET):
                    evicted = True
                    break
            if not evicted:
                with self._lock:
                    over = self._resident_bytes - self.budget_bytes
                logger.warning(
                    "fleet: %d bytes over the residency budget but no tenant "
                    "is evictable (pinned mid-retrain, or only the active "
                    "tenant remains); staying over budget",
                    max(over, 0),
                )
                return

    def evict(self, model_id: str, cause: str = EVICT_EXPLICIT) -> bool:
        """Evict one tenant's resident state: drain its coalescer (every
        in-flight flush completes on its point-in-time model reference,
        bitwise-exact), close its manager, release the packed planes. The
        sealed gen dirs stay authoritative — the next request re-loads,
        resuming the last swapped generation. Returns False (and refuses)
        when the tenant is not resident or is pinned mid-retrain."""
        entry = self.entry(model_id)
        with entry._lock:
            service = entry.service
            if service is None:
                return False
            manager = entry.manager
            if manager is not None and manager.retrain_in_progress:
                record_event(
                    "fleet.evict_refused",
                    model_id=entry.model_id,
                    cause=cause,
                    reason="retrain_in_progress",
                )
                logger.warning(
                    "fleet: refusing to evict %s mid-retrain (pinned until "
                    "the swap or rollback completes)",
                    entry.model_id,
                )
                return False
            in_flight = service.coalescer.pending_rows
            if in_flight > 0:
                degrade(
                    "fleet_evict_under_load",
                    f"fleet tenant {entry.model_id!r} resident with "
                    f"{in_flight} in-flight row(s)",
                    "drain coalescer, then evict",
                    detail=(
                        f"eviction ({cause}) drained {in_flight} queued "
                        "row(s) first — in-flight flushes complete on their "
                        "point-in-time model reference, bitwise-exact"
                    ),
                )
            service.close()  # drain=True: no waiter is stranded
            if manager is not None:
                manager.close()
            freed = entry.resident_bytes
            entry.model = None
            entry.manager = None
            entry.service = None
            entry.resident_bytes = 0
            entry.plane_bytes = None
        with self._lock:
            self._resident_bytes -= freed
            resident = sum(1 for e in self._entries.values() if e.resident)
            resident_bytes = self._resident_bytes
        _RESIDENT_MODELS.set(resident)
        _RESIDENT_BYTES.set(resident_bytes)
        _EVICTIONS_TOTAL.inc(cause=cause)
        _resources.release_resident_plane(entry.model_id)
        record_event(
            "fleet.evict",
            model_id=entry.model_id,
            cause=cause,
            bytes=freed,
            resident_models=resident,
            resident_bytes=resident_bytes,
        )
        logger.info(
            "fleet: evicted %s (%s, %d bytes freed; %d resident / %d bytes "
            "total; gen dirs on disk stay authoritative)",
            entry.model_id,
            cause,
            freed,
            resident,
            resident_bytes,
        )
        return True

    # ------------------------------------------------------------------ #
    # scoring
    # ------------------------------------------------------------------ #

    def score(self, model_id: str, rows: np.ndarray) -> np.ndarray:
        """Score through the tenant's own coalescer (loading it first if
        cold). Raises the tenant's admission errors (429/503),
        :class:`UnknownModelError` or :class:`ModelLoadError` — all typed,
        all scoped to THIS tenant."""
        scores, _ = self.score_detail(model_id, rows)
        return scores

    def score_detail(
        self,
        model_id: str,
        rows: np.ndarray,
        idempotency_key: Optional[str] = None,
    ):
        """(scores, info) where info carries the flush accounting, the
        generation and the active model reference the HTTP layer encodes.
        A request that races an eviction (service closed between lookup
        and submit) retries once against the re-loaded service.
        ``idempotency_key`` is the replicated tier's retry dedup
        (docs/replication.md): a key this tenant's service already answered
        replays fold-free (bitwise-same scores, drift counted once); a
        fresh key is recorded once the flush succeeds."""
        for attempt in (0, 1):
            entry = self.ensure_resident(model_id)
            service = entry.service  # point-in-time: eviction-safe
            if service is None:
                continue  # evicted between load and capture: reload
            # the autopilot's shed rung refuses this tenant before any
            # queue or replay work (typed 429 + Retry-After)
            service.check_admission()
            if idempotency_key is not None and service.idempotency_seen(
                idempotency_key
            ):
                scores, generation = service.score_replay(rows)
                info = {
                    "model": service.model,
                    "generation": generation,
                    "flush_rows": int(np.asarray(rows).shape[0]),
                    "flush_requests": 1,
                    "queue_wait_s": 0.0,
                    "flush_ctx": None,
                    "replayed": True,
                }
                return scores, info
            try:
                pending = service.coalescer.submit(rows)
            except CoalescerClosedError:
                if attempt:
                    raise
                continue  # raced an eviction: one reload retry
            if faults.evict_during_score():
                # the eviction-under-load drill: drain-then-evict while this
                # very request is in flight; its scores must still arrive,
                # bitwise-exact, from the drained flush
                self.evict(model_id, cause=EVICT_FAULT)
            scores = service.coalescer.result(
                pending, timeout_s=entry.config.request_timeout_s
            )
            service.record_idempotency(idempotency_key)
            model = service.model
            manager = service.manager
            info = {
                "model": model,
                "generation": manager.generation if manager is not None else None,
                "flush_rows": pending.flush_rows,
                "flush_requests": pending.flush_requests,
                "queue_wait_s": pending.queue_wait_s,
                "flush_ctx": pending.flush_ctx,
            }
            degraded = service.quality
            if degraded is not None:
                info["degraded"] = degraded
            return scores, info
        raise ModelLoadError(
            f"model {model_id!r} was evicted twice while the request was "
            "being admitted; retry"
        )

    def refresh_from_current(self, model_id: str) -> dict:
        """The per-tenant leg of a rolling model push
        (docs/replication.md): re-read the tenant's ``CURRENT.json`` and
        adopt a newer generation in place. A non-resident tenant reloads
        nothing — its next lazy load resumes from ``CURRENT.json`` anyway,
        so the push reaches it by construction. Raises
        :class:`UnknownModelError` for unregistered ids."""
        entry = self.entry(model_id)
        with entry._lock:
            manager = entry.manager if entry.resident else None
        if manager is None:
            return {
                "model_id": entry.model_id,
                "resident": entry.resident,
                "lifecycle": entry.lifecycle,
                "reloaded": False,
                "generation": entry.generation,
            }
        changed = manager.refresh_from_current()
        return {
            "model_id": entry.model_id,
            "resident": True,
            "lifecycle": True,
            "reloaded": bool(changed),
            "generation": manager.generation,
        }

    # ------------------------------------------------------------------ #
    # teardown
    # ------------------------------------------------------------------ #

    def close(self) -> None:
        """Tear the whole fleet down: wait out in-flight retrains (a
        shutdown never tears a refit), drain every coalescer, release
        everything. Idempotent."""
        with self._lock:
            if self.closed:
                return
            self.closed = True
            entries = list(self._entries.values())
        for entry in entries:
            manager = entry.manager
            if manager is not None:
                manager.wait_retrain()  # un-pins: shutdown is orderly
            self.evict(entry.model_id, cause=EVICT_CLOSE)
