"""Multi-tenant model fleet: registry, budgeted residency, per-tenant
serving behind one port (ROADMAP item 2, docs/fleet.md).

The serving layer built in PR 8 owns exactly one model; a real anti-abuse
deployment (the reference library's own use case) runs *hundreds* — one
per surface, region and entity type. This package turns the single-model
service into a fleet: a :class:`ModelRegistry` maps ``model_id`` to a
lazily loaded per-tenant stack (model + lifecycle manager + coalescing
scoring service), a byte-budgeted LRU bounds how many packed scoring
layouts stay resident (evicted tenants re-load from their sealed gen dirs,
resuming the last swapped generation), and ``POST /score/<model_id>`` /
``GET /models`` ride the same telemetry HTTP daemon as everything else —
one port, one process, per-tenant isolation for backpressure, drift,
retraining and hot-swaps.

Start one with ``python -m isoforest_tpu serve --models-dir <dir>`` or
:func:`serve_fleet`; load-test a tenant with
``tools/serving_latency.py --model-id <id>``.
"""

from .registry import (
    EVICT_BUDGET,
    EVICT_CLOSE,
    EVICT_EXPLICIT,
    EVICT_FAULT,
    ManagedEntry,
    ModelLoadError,
    ModelRegistry,
    UnknownModelError,
    layout_nbytes,
)
from .service import (
    MODELS_PATH,
    SCORE_PREFIX,
    FleetHandle,
    FleetService,
    discover_models,
    mount_fleet,
    serve_fleet,
    unmount_fleet,
)

__all__ = [
    "EVICT_BUDGET",
    "EVICT_CLOSE",
    "EVICT_EXPLICIT",
    "EVICT_FAULT",
    "FleetHandle",
    "FleetService",
    "MODELS_PATH",
    "ManagedEntry",
    "ModelLoadError",
    "ModelRegistry",
    "SCORE_PREFIX",
    "UnknownModelError",
    "discover_models",
    "layout_nbytes",
    "mount_fleet",
    "serve_fleet",
    "unmount_fleet",
]
