"""Command-line front-end: fit / score / convert / inspect.

The reference is consumed as a JVM library from Spark jobs; the equivalent
operational surface here is a small CLI over CSV files:

    python -m isoforest_tpu fit --input data.csv --output /tmp/model \\
        --num-estimators 100 --contamination 0.02 [--extended]
    python -m isoforest_tpu fit --source /data/shards/ --output /tmp/model
        # out-of-core: one streamed pass over .csv/.npy/.avro/.parquet shards
    python -m isoforest_tpu score --model /tmp/model --input data.csv \\
        --output scores.csv
    python -m isoforest_tpu score --model /tmp/model --source /data/shards/ \\
        --output /tmp/scores_sink [--resume] [--strategy gather]
        # resumable: one sealed part per shard; --resume skips sealed parts
    python -m isoforest_tpu convert --model /tmp/model --output model.onnx
    python -m isoforest_tpu inspect --model /tmp/model [--tree 0]
    python -m isoforest_tpu telemetry [--format json|prometheus] \\
        [--input data.csv [--model /tmp/model]]
    python -m isoforest_tpu trace out.json \\
        [--input data.csv [--model /tmp/model]]
    python -m isoforest_tpu debug-bundle out.json \\
        [--input data.csv [--model /tmp/model]]
    python -m isoforest_tpu diagnose /tmp/model [--format json|prometheus]
    python -m isoforest_tpu monitor /tmp/model --input live.csv \\
        [--threshold 0.25] [--port 9101] [--format json|prometheus]
    python -m isoforest_tpu manage /tmp/model --input live.csv \\
        [--work-dir /tmp/model.lifecycle] [--debounce 3] [--window-rows N] \\
        [--mode full|sliding] [--threshold 0.25] [--port 9101]
    python -m isoforest_tpu stream /tmp/model --source live_shards/ \\
        [--window-s 60 --slide-s 30 --lateness-s 5] [--follow] \\
        [--reservoir decay --half-life-s 3600] [--retrain-every 1] \\
        [--port 9101]  # rows are event_ts,f1,...,fn[,label]
    python -m isoforest_tpu autotune [--format json|table] [--clear] \\
        [--warm --input data.csv [--model /tmp/model] \\
         --batch-sizes 1024,65536 [--refresh]]
    python -m isoforest_tpu serve /tmp/model --port 9100 \\
        [--batch-rows 1024] [--linger-ms 2] [--max-queue-rows 8192] \\
        [--queue-deadline-ms 2000] [--no-lifecycle] [--max-seconds N]
    python -m isoforest_tpu serve --models-dir /tmp/models --port 9100 \\
        [--fleet-budget-mb 64] [--preload]  # POST /score/<model_id>
    python -m isoforest_tpu route --models-dir /tmp/models --replicas 2 \\
        [--port 9100] [--journal-dir /tmp/journal]
        # replicated tier: K replicas behind one router; the router's
        # /metrics /snapshot /trace /debug/bundle answer for the WHOLE tier
    python -m isoforest_tpu journal /tmp/journal \\
        [--spool replica-0] [--format json|chrome] [--tail N]
        # dump the crash-durable flight recorder's NDJSON spools

CSV rows are feature columns; ``--labeled`` treats the last column as a label
(excluded from features; used to report AUROC after fit/score).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def _load(path: str, labeled: bool):
    """Materialise (X, y) from any source spec — a single CSV, a directory
    of shards, or a glob — read chunk-by-chunk through the sharded source
    abstraction (io/source.py), so even a single huge CSV never buffers
    more than one parsed chunk transiently above the final matrix."""
    from .io.source import open_source

    return open_source(path, labeled=labeled).read_all()


def _iter_input_chunks(spec: str, labeled: bool, chunk_rows: int):
    """Stream (X, y) chunks from any source spec (file / directory / glob)
    without materialising it — the CLI analogue of Spark scoring a Dataset
    partition by partition."""
    from .io.source import open_source

    for chunk in open_source(spec, labeled=labeled).iter_chunks(chunk_rows=chunk_rows):
        yield chunk.X, chunk.y


def _auroc(scores, labels) -> float:
    order = np.argsort(scores, kind="stable")
    ranks = np.empty(len(scores))
    ranks[order] = np.arange(1, len(scores) + 1)
    pos = labels == 1
    n1, n0 = int(pos.sum()), int((~pos).sum())
    if n1 == 0 or n0 == 0:
        return float("nan")
    return float((ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0))


def _load_model(path: str):
    from .io.persistence import load_model

    return load_model(path)


def cmd_fit(args) -> int:
    from .models import ExtendedIsolationForest, IsolationForest

    kw = dict(
        num_estimators=args.num_estimators,
        max_samples=args.max_samples,
        contamination=args.contamination,
        contamination_error=args.contamination_error,
        max_features=args.max_features,
        bootstrap=args.bootstrap,
        random_seed=args.random_seed,
    )
    if args.extended:
        est = ExtendedIsolationForest(extension_level=args.extension_level, **kw)
    else:
        est = IsolationForest(**kw)
    if args.source:
        # out-of-core path: one streamed sampling pass, bounded memory at
        # any source size (docs/out_of_core.md)
        from .io.source import open_source

        src = open_source(args.source, labeled=args.labeled)
        model = est.fit_source(src, chunk_rows=args.chunk_rows)
        y = None
    else:
        X, y = _load(args.input, args.labeled)
        model = est.fit(X)
    model.save(args.output, overwrite=args.overwrite)
    summary = {
        "model": args.output,
        "numTrees": model.forest.num_trees,
        "numSamples": model.num_samples,
        "threshold": model.outlier_score_threshold,
    }
    if args.source:
        summary["source"] = args.source
        summary["sourceShards"] = src.num_shards
    if y is not None:
        summary["auroc"] = round(_auroc(model.score(X), y), 4)
    print(json.dumps(summary))
    return 0


def cmd_score(args) -> int:
    model = _load_model(args.model)
    if args.source:
        # out-of-core sharded path: scores stream into a resumable sink
        # directory, one sealed part per shard (docs/out_of_core.md §5)
        from .io.outofcore import score_source
        from .io.source import open_source

        if args.output == "-":
            print(
                "error: score --source writes a sink directory; pass "
                "--output <dir>",
                file=sys.stderr,
            )
            return 2
        src = open_source(args.source, labeled=args.labeled)
        summary = score_source(
            model,
            src,
            args.output,
            chunk_rows=args.chunk_rows,
            strategy=args.strategy,
            resume=args.resume,
        )
        summary["sink"] = args.output
        print(json.dumps(summary))
        return 0
    header = "outlierScore,predictedLabel"
    # resolve (and thereby validate) the input BEFORE truncating the output —
    # a missing input must not destroy a pre-existing results file
    from .io.source import open_source

    src = open_source(args.input, labeled=args.labeled)
    out_fh = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        out_fh.write(header + "\n")
        all_scores, all_labels = [], []
        for chunk in src.iter_chunks(chunk_rows=args.chunk_rows):
            scores = model.score(chunk.X, strategy=args.strategy)
            labels = model.predict(scores)
            np.savetxt(out_fh, np.stack([scores, labels], axis=1), delimiter=",")
            if chunk.y is not None:
                all_scores.append(scores)
                all_labels.append(chunk.y)
    finally:
        if out_fh is not sys.stdout:
            out_fh.close()
    if all_labels:
        auroc = _auroc(np.concatenate(all_scores), np.concatenate(all_labels))
        print(json.dumps({"auroc": round(auroc, 4)}), file=sys.stderr)
    return 0


def cmd_convert(args) -> int:
    from .onnx import convert_and_save

    convert_and_save(args.model, args.output)
    print(json.dumps({"onnx": args.output}))
    return 0


def cmd_inspect(args) -> int:
    from .utils.inspect import tree_structure_string

    model = _load_model(args.model)
    if args.tree is not None:
        print(tree_structure_string(model, args.tree))
        return 0
    ni = np.asarray(model.forest.num_instances)
    leaves = (ni >= 0).sum(axis=1)
    print(
        json.dumps(
            {
                "class": type(model).__name__,
                "numTrees": model.forest.num_trees,
                "maxNodes": model.forest.max_nodes,
                "numSamples": model.num_samples,
                "numFeatures": model.num_features,
                "totalNumFeatures": model.total_num_features,
                "outlierScoreThreshold": model.outlier_score_threshold,
                "avgLeavesPerTree": round(float(leaves.mean()), 2),
                "params": model.params.to_param_map(),
            }
        )
    )
    return 0


def cmd_telemetry(args) -> int:
    """Run a workload with full instrumentation and print the telemetry
    snapshot — the operational smoke test for the observability layer
    (docs/observability.md): span timings, metric series and the event
    timeline for a real fit+score, in JSON or Prometheus exposition.

    With ``--input`` the workload is the user's CSV (scored with ``--model``
    when given, else fit+scored); without it, a small synthetic mixture.
    """
    from . import telemetry

    if args.input:
        X, _ = _load(args.input, args.labeled)
        if args.model:
            model = _load_model(args.model)
        else:
            from .models import IsolationForest

            model = IsolationForest(
                num_estimators=args.trees, random_seed=1
            ).fit(X)
    else:
        rng = np.random.default_rng(0)
        X = rng.normal(size=(args.rows, 4)).astype(np.float32)
        X[: max(1, args.rows // 100)] += 4.0
        from .models import IsolationForest

        model = IsolationForest(num_estimators=args.trees, random_seed=1).fit(X)
    model.score(X)
    if args.format == "prometheus":
        print(telemetry.to_prometheus(), end="")
    else:
        print(telemetry.snapshot_json(indent=1))
    return 0


def cmd_trace(args) -> int:
    """Run an instrumented workload and write its scoring trace as
    Chrome trace-event JSON — drop the output file onto
    https://ui.perfetto.dev to see the causal path (root span, strategy
    attribution, per-chunk pipeline timings; docs/observability.md §9).

    Workload selection matches ``telemetry``: ``--input`` CSV (scored
    with ``--model`` when given, else fit+scored), or a small synthetic
    mixture. Capture policy is forced to keep-everything for the run so
    the trace is always present regardless of latency.
    """
    from . import telemetry

    telemetry.set_trace_policy(slow_threshold_s=0.0, sample_every=1)
    if args.input:
        X, _ = _load(args.input, args.labeled)
        if args.model:
            model = _load_model(args.model)
        else:
            from .models import IsolationForest

            model = IsolationForest(
                num_estimators=args.trees, random_seed=1
            ).fit(X)
    else:
        rng = np.random.default_rng(0)
        X = rng.normal(size=(args.rows, 4)).astype(np.float32)
        X[: max(1, args.rows // 100)] += 4.0
        from .models import IsolationForest

        model = IsolationForest(num_estimators=args.trees, random_seed=1).fit(X)
    model.score(X)
    recent = telemetry.recent_traces(limit=50)
    if not recent:
        print(json.dumps({"error": "no traces captured"}))
        return 1
    # prefer the scoring trace; fall back to the newest one
    chosen = next(
        (t for t in recent if t["root"] == "model.score"), recent[0]
    )
    trace = telemetry.get_trace(chosen["trace_id"])
    with open(args.output, "w") as fh:
        fh.write(telemetry.to_chrome_trace_json(trace, indent=1))
        fh.write("\n")
    print(
        json.dumps(
            {
                "trace_id": chosen["trace_id"],
                "root": chosen["root"],
                "spans": chosen["spans"],
                "wall_s": chosen["wall_s"],
                "output": args.output,
            }
        )
    )
    return 0


def cmd_debug_bundle(args) -> int:
    """Run an instrumented workload and write the flight-recorder debug
    bundle (docs/observability.md §10): recent traces, the event timeline
    tail, a metrics snapshot, degradation rungs, the autotune winner
    table, the compile log and memory watermarks — one attachable JSON
    artifact. Workload selection matches ``telemetry``/``trace``:
    ``--input`` CSV (scored with ``--model`` when given, else fit+scored),
    or a small synthetic mixture.
    """
    from . import telemetry

    telemetry.set_trace_policy(slow_threshold_s=0.0, sample_every=1)
    if args.input:
        X, _ = _load(args.input, args.labeled)
        if args.model:
            model = _load_model(args.model)
        else:
            from .models import IsolationForest

            model = IsolationForest(
                num_estimators=args.trees, random_seed=1
            ).fit(X)
    else:
        rng = np.random.default_rng(0)
        X = rng.normal(size=(args.rows, 4)).astype(np.float32)
        X[: max(1, args.rows // 100)] += 4.0
        from .models import IsolationForest

        model = IsolationForest(num_estimators=args.trees, random_seed=1).fit(X)
    model.score(X)
    bundle = telemetry.write_bundle(args.output)
    print(
        json.dumps(
            {
                "output": args.output,
                "schema": bundle["schema"],
                "sections": sorted(k for k in bundle if k != "schema"),
                "compiles": bundle["compiles"]["total"],
                "traces": len(bundle["traces"]),
                "events": len(bundle["events"]),
            }
        )
    )
    return 0


def cmd_diagnose(args) -> int:
    """Forest-structure diagnostics for a saved model
    (docs/observability.md §8): tree depths, leaf sizes, split-feature
    usage, expected-vs-realised average path length and imbalance stats —
    straight from the packed node tables, no data needed."""
    from . import telemetry

    model = _load_model(args.model_dir)
    diag = model.diagnostics()
    if args.format == "prometheus":
        telemetry.publish_gauges(diag)
        print(telemetry.to_prometheus(), end="")
    else:
        print(json.dumps(diag, indent=1, sort_keys=True))
    return 0


def cmd_monitor(args) -> int:
    """Score a CSV through a saved model with the drift monitor attached
    and report PSI/KS of the served scores and input features against the
    model's training baseline (docs/observability.md §8). ``--port`` serves
    the live /metrics endpoint while scoring (0 = ephemeral)."""
    from . import telemetry

    model = _load_model(args.model_dir)
    if model.baseline is None:
        print(
            "error: this model directory has no _BASELINE.json sidecar "
            "(legacy save, or fit with baseline capture disabled) — refit "
            "and re-save to enable drift monitoring",
            file=sys.stderr,
        )
        return 2
    monitor = model.enable_monitoring(
        threshold=args.threshold, min_rows=args.min_rows
    )
    server = telemetry.serve(port=args.port) if args.port is not None else None
    try:
        rows = 0
        for X, _ in _iter_input_chunks(args.input, args.labeled, args.chunk_rows):
            model.score(X)  # folds into the monitor
            rows += len(X)
    finally:
        if server is not None:
            server.stop()
    report = monitor.report()
    report["model"] = args.model_dir
    report["input"] = args.input
    if args.format == "prometheus":
        print(telemetry.to_prometheus(), end="")
        if report["drifted"]:
            print(
                f"# drift alerts: {json.dumps(report['alerts'])}",
                file=sys.stderr,
            )
    else:
        print(json.dumps(report, indent=1, sort_keys=True))
    return 0


def cmd_manage(args) -> int:
    """Serve a CSV through the model lifecycle manager
    (docs/resilience.md §8): score with drift monitoring, and on sustained
    drift retrain on the recent window, validate the candidate against the
    incumbent, and atomically hot-swap generations — synchronously, so the
    command's exit state is deterministic. Prints the lifecycle summary
    (generation, retrain outcomes, drift report) as JSON. ``--port`` serves
    the live /metrics + /healthz endpoint (with the lifecycle section)
    while scoring."""
    from . import telemetry
    from .lifecycle import ModelManager

    model = _load_model(args.model_dir)
    if model.baseline is None:
        print(
            "error: this model directory has no _BASELINE.json sidecar "
            "(legacy save, or fit with baseline capture disabled) — the "
            "lifecycle manager needs the drift baseline; refit and re-save",
            file=sys.stderr,
        )
        return 2
    manager = ModelManager(
        model,
        work_dir=args.work_dir or args.model_dir + ".lifecycle",
        monitor_threshold=args.threshold,
        drift_debounce=args.debounce,
        window_rows=args.window_rows,
        min_window_rows=args.min_window_rows,
        mode=args.mode,
        reservoir=args.reservoir,
        reservoir_half_life_s=args.half_life_s,
        checkpoint_every=args.checkpoint_every,
        background=False,  # retrains run inline: the CLI is deterministic
        monitor_kwargs={"min_rows": args.min_rows},
    )
    server = telemetry.serve(port=args.port) if args.port is not None else None
    if args.journal_dir:
        telemetry.activate_journal(args.journal_dir, "manage")
    try:
        rows = 0
        for X, y in _iter_input_chunks(args.input, args.labeled, args.chunk_rows):
            manager.score(X, y=y)
            rows += len(X)
    finally:
        if args.journal_dir:
            telemetry.deactivate_journal()
        if server is not None:
            server.stop()
    summary = manager.state()
    summary["rows"] = rows
    summary["model"] = args.model_dir
    summary["input"] = args.input
    summary["drift"] = manager.monitor.report()
    if manager.last_validation is not None:
        summary["last_validation"] = manager.last_validation.as_dict()
    manager.close()
    print(json.dumps(summary, indent=1, sort_keys=True))
    return 0


def cmd_stream(args) -> int:
    """Online anomaly detection over an event-time stream
    (docs/streaming.md): tail a shard directory / CSV file, listen on a TCP
    line protocol, or read stdin; score every timestamped row with bounded
    lag through the micro-batch coalescer; and run the window-cadenced
    retrain/validate/swap loop as the steady state. Rows are
    ``event_ts,f1,...,fn[,label]``. Prints the stream summary as JSON;
    ``--port`` serves live /metrics + /traces/recent while streaming, and
    ``--hold-seconds`` keeps that endpoint up after the source ends so a
    harness can pull traces and the debug bundle before SIGTERM."""
    import signal
    import threading
    import time as _time

    from . import telemetry
    from .lifecycle import ModelManager
    from .stream import StreamConfig, StreamEngine, socket_source, tail_source

    model = _load_model(args.model_dir)
    if model.baseline is None:
        print(
            "error: this model directory has no _BASELINE.json sidecar "
            "(legacy save, or fit with baseline capture disabled) — the "
            "streaming lifecycle needs the drift baseline; refit and re-save",
            file=sys.stderr,
        )
        return 2
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:  # pragma: no cover - non-main-thread embedding
            pass
    feed = None
    if args.source == "-":
        from .stream.sources import parse_lines

        def _stdin_batches():
            buf = []
            for line in sys.stdin:
                line = line.strip()
                if line and not line.startswith("#"):
                    buf.append(line)
                if len(buf) >= args.chunk_rows or stop.is_set():
                    if buf:
                        yield parse_lines(buf, args.labeled)
                        buf = []
                    if stop.is_set():
                        return
            if buf:
                yield parse_lines(buf, args.labeled)

        source = _stdin_batches()
    elif args.source.startswith("tcp://"):
        host, _, port_s = args.source[len("tcp://") :].partition(":")
        feed = socket_source(
            int(port_s or 0),
            host or "127.0.0.1",
            labeled=args.labeled,
            chunk_rows=args.chunk_rows,
            should_stop=stop.is_set,
        )
        source = feed.batches()
    else:
        source = tail_source(
            args.source,
            labeled=args.labeled,
            follow=args.follow,
            poll_s=args.poll_s,
            chunk_rows=args.chunk_rows,
            stop=stop.is_set,
        )
    manager = ModelManager(
        model,
        work_dir=args.work_dir or args.model_dir + ".stream",
        monitor_threshold=args.threshold,
        window_rows=args.window_rows,
        min_window_rows=args.min_window_rows,
        mode=args.mode,
        reservoir=args.reservoir,
        reservoir_half_life_s=args.half_life_s,
        checkpoint_every=args.checkpoint_every,
        auto_retrain=False,  # the window-close cadence drives retrains
        background=False,  # inline: the CLI's swap count is deterministic
        monitor_kwargs={"min_rows": args.min_rows},
    )
    engine = StreamEngine(
        manager,
        StreamConfig(
            window_s=args.window_s,
            slide_s=args.slide_s,
            lateness_s=args.lateness_s,
            retrain_every=args.retrain_every,
            batch_rows=args.batch_rows,
            linger_s=args.linger_ms / 1000.0,
        ),
    )
    server = telemetry.serve(port=args.port) if args.port is not None else None
    if args.journal_dir:
        telemetry.activate_journal(args.journal_dir, "stream")
    if server is not None:
        print(
            json.dumps(
                {
                    "stream": args.model_dir,
                    "source": args.source,
                    "url": f"http://127.0.0.1:{server.port}",
                    **({"tcp_port": feed.port} if feed is not None else {}),
                }
            ),
            flush=True,
        )
    try:
        try:
            summary = engine.run(source, max_rows=args.max_rows)
        except KeyboardInterrupt:
            summary = engine.finish()
        summary["model"] = args.model_dir
        summary["source"] = args.source
        summary["drift"] = manager.monitor.report()
        print(json.dumps(summary, indent=1, sort_keys=True), flush=True)
        if server is not None and args.hold_seconds > 0:
            deadline = _time.time() + args.hold_seconds
            while _time.time() < deadline and not stop.is_set():
                _time.sleep(0.1)
    finally:
        if args.journal_dir:
            telemetry.deactivate_journal()
        if feed is not None:
            feed.stop()
        if server is not None:
            server.stop()
        manager.close()
    return 0


def cmd_serve(args) -> int:
    """Serve ``POST /score`` (docs/serving.md): load the model, wrap it in
    the lifecycle manager when it carries a drift baseline (resuming the
    last swapped generation from ``CURRENT.json``), mount the scoring
    endpoint with dynamic micro-batch coalescing on the telemetry HTTP
    server, pre-warm the autotuned batch buckets, print one JSON ready
    line, and serve until SIGTERM/SIGINT (or ``--max-seconds``).

    With ``--models-dir`` the process serves a multi-tenant **fleet**
    instead (docs/fleet.md): every sealed model directory under the dir
    becomes a tenant behind ``POST /score/<model_id>`` (+ ``GET /models``),
    loaded lazily under the ``--fleet-budget-mb`` residency LRU, each with
    its own coalescer, admission queue and lifecycle manager."""
    import signal
    import threading

    from .serving import ServingConfig, serve_model

    if (args.model_dir is None) == (args.models_dir is None):
        print(
            "error: pass exactly one of <model_dir> (single-model serving) "
            "or --models-dir (multi-tenant fleet)",
            file=sys.stderr,
        )
        return 2
    if args.journal_dir:
        # flight-record before anything serves: the first fleet.load must
        # already hit the spool (a spawned replica spools under its tier
        # name — the router recovers it from the tier /debug/bundle)
        from . import telemetry

        telemetry.activate_journal(
            args.journal_dir, args.replica_name or f"serve-{os.getpid()}"
        )
    config = ServingConfig(
        batch_rows=args.batch_rows,
        linger_ms=args.linger_ms,
        max_queue_rows=args.max_queue_rows,
        queue_deadline_ms=args.queue_deadline_ms,
        request_timeout_s=args.request_timeout_s,
        score_timeout_s=args.score_timeout_s,
        weight=args.weight,
    )
    weights = {}
    for spec in args.tenant_weight or ():
        model_id, sep, value = spec.partition("=")
        if not sep or not model_id:
            print(
                f"error: --tenant-weight expects MODEL_ID=WEIGHT, got {spec!r}",
                file=sys.stderr,
            )
            return 2
        weights[model_id] = float(value)
    warm = sorted({int(s) for s in args.warm_batch_sizes.split(",") if s})
    manager_kwargs = {
        "drift_debounce": args.debounce,
        "window_rows": args.window_rows,
        "min_window_rows": args.min_window_rows,
        "mode": args.mode,
        "monitor_kwargs": {"min_rows": args.min_rows},
    }
    if args.threshold is not None:
        manager_kwargs["monitor_threshold"] = args.threshold
    if args.models_dir is not None:
        from .fleet import serve_fleet

        budget = (
            int(args.fleet_budget_mb * (1 << 20))
            if args.fleet_budget_mb is not None
            else None
        )
        handle = serve_fleet(
            args.models_dir,
            port=args.port,
            host=args.host,
            config=config,
            budget_bytes=budget,
            lifecycle=not args.no_lifecycle,
            work_root=args.work_dir,
            manager_kwargs=manager_kwargs,
            preload=args.preload,
            weights=weights or None,
        )
        ready = {
            "serving": True,
            "fleet": True,
            "url": handle.url,
            "endpoint": handle.url + "/score/<model_id>",
            "models": handle.registry.model_ids(),
            "budget_bytes": budget,
            "batch_rows": config.batch_rows,
            "linger_ms": config.linger_ms,
        }
    else:
        handle = serve_model(
            args.model_dir,
            port=args.port,
            host=args.host,
            config=config,
            lifecycle=not args.no_lifecycle,
            work_dir=args.work_dir,
            warm_batch_sizes=warm or (1,),
            manager_kwargs=manager_kwargs,
        )
        ready = {
            "serving": True,
            "url": handle.url,
            "endpoint": handle.url + "/score",
            "model": args.model_dir,
            "lifecycle": handle.manager is not None,
            "generation": (
                handle.manager.generation if handle.manager is not None else None
            ),
            "batch_rows": config.batch_rows,
            "linger_ms": config.linger_ms,
        }
    autopilot = None
    if args.autopilot:
        from .autopilot import Autopilot, AutopilotConfig, mount_autopilot

        ap_config = AutopilotConfig(
            high_water=args.autopilot_high_water,
            low_water=args.autopilot_low_water,
            engage_ticks=args.autopilot_engage_ticks,
            recover_ticks=args.autopilot_recover_ticks,
            tick_interval_s=args.autopilot_interval_s,
            subsample_trees=args.autopilot_subsample_trees,
            strict=args.autopilot_strict,
        )
        if args.models_dir is not None:
            autopilot = Autopilot(registry=handle.registry, config=ap_config)
        else:
            autopilot = Autopilot(services=[handle.service], config=ap_config)
        mount_autopilot(handle.server, autopilot)
        autopilot.start()
        ready["autopilot"] = True
    heartbeat = None
    if args.replica_name and args.heartbeat_dir:
        # replicated tier (docs/replication.md): advertise liveness to the
        # fronting router. Write-only wiring — the replica's own /healthz
        # deliberately does NOT read this directory (a dead PEER must not
        # flip this replica unhealthy)
        from .resilience.watchdog import HeartbeatWriter

        os.makedirs(args.heartbeat_dir, exist_ok=True)
        heartbeat = HeartbeatWriter(args.heartbeat_dir, args.replica_name)
        heartbeat.start()
        ready["replica"] = args.replica_name
    stop = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
    except ValueError:
        pass  # not the main thread (in-process tests drive stop themselves)
    print(json.dumps(ready), flush=True)
    try:
        stop.wait(args.max_seconds)  # None waits until SIGTERM/SIGINT
    except KeyboardInterrupt:
        pass
    finally:
        if heartbeat is not None:
            heartbeat.stop()
        if autopilot is not None:
            autopilot.close()
        handle.close()
        if args.journal_dir:
            from . import telemetry

            telemetry.deactivate_journal()
    return 0


def cmd_route(args) -> int:
    """Front a replicated serving tier (docs/replication.md): spawn
    ``--replicas`` fleet replicas over one ``--models-dir``, balance
    ``POST /score/<model_id>`` across them with health-probe admission and
    idempotent retries, watch ``CURRENT.json`` for rolling model pushes,
    print one JSON ready line, and serve until SIGTERM/SIGINT (draining
    in-flight requests, then the replicas, on the way down)."""
    import signal
    import threading

    from .replication import RouterConfig, serve_router

    config = RouterConfig(
        probe_interval_s=args.probe_interval_s,
        probe_timeout_s=args.probe_timeout_s,
        stale_after_s=args.stale_after_s,
        request_timeout_s=args.request_timeout_s,
        drain_timeout_s=args.drain_timeout_s,
        retry_attempts=args.retry_attempts,
    )
    replica_args = []
    if args.batch_rows is not None:
        replica_args += ["--batch-rows", str(args.batch_rows)]
    if args.linger_ms is not None:
        replica_args += ["--linger-ms", str(args.linger_ms)]
    if args.fleet_budget_mb is not None:
        replica_args += ["--fleet-budget-mb", str(args.fleet_budget_mb)]
    if args.preload:
        replica_args += ["--preload"]
    if args.no_lifecycle:
        replica_args += ["--no-lifecycle"]
    if args.work_dir is not None:
        replica_args += ["--work-dir", args.work_dir]
    if args.journal_dir:
        # the router flight-records its own plane ("router" spool); each
        # spawned replica gets --journal-dir and spools under its tier name
        from . import telemetry

        telemetry.activate_journal(args.journal_dir, "router")
    handle = serve_router(
        args.models_dir,
        replicas=args.replicas,
        port=args.port,
        host=args.host,
        config=config,
        work_root=args.work_dir,
        replica_args=tuple(replica_args),
        journal_dir=args.journal_dir,
    )
    ready = {
        "router": True,
        "url": handle.url,
        "endpoint": handle.url + "/score/<model_id>",
        "models_dir": args.models_dir,
        "journal_dir": args.journal_dir,
        "replicas": [
            {"name": r.name, "url": r.url, "pid": r.pid}
            for r in handle.router.replicas
        ],
    }
    stop = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
    except ValueError:
        pass  # not the main thread (in-process tests drive stop themselves)
    print(json.dumps(ready), flush=True)
    try:
        stop.wait(args.max_seconds)  # None waits until SIGTERM/SIGINT
    except KeyboardInterrupt:
        pass
    finally:
        handle.close()
        if args.journal_dir:
            from . import telemetry

            telemetry.deactivate_journal()
    return 0


def cmd_journal(args) -> int:
    """Dump a flight-recorder journal directory (docs/observability.md
    §12): every spool's NDJSON records as JSON lines (each tagged with its
    ``spool``), or — with ``--format chrome`` — the journaled traces
    merged into ONE Perfetto document with a ``pid`` lane per spool, the
    same stitched rendering as the federated ``GET /trace``. ``--tail N``
    keeps the newest N records per spool; ``--spool NAME`` restricts to
    one process's spool. Torn final lines (a kill -9 mid-write) are
    reported in the summary, never fatal."""
    from . import telemetry

    journal_dir = args.journal_dir
    spool_names = telemetry.list_spools(journal_dir)
    if args.spool:
        if args.spool not in spool_names:
            print(
                f"error: no spool {args.spool!r} under {journal_dir} "
                f"(found: {', '.join(spool_names) or 'none'})",
                file=sys.stderr,
            )
            return 2
        spool_names = [args.spool]
    if not spool_names:
        print(f"error: no journal spools under {journal_dir}", file=sys.stderr)
        return 2
    spools = {
        name: telemetry.read_spool(
            os.path.join(journal_dir, name), tail=args.tail
        )
        for name in spool_names
    }
    out = open(args.output, "w") if args.output else sys.stdout
    try:
        if args.format == "chrome":
            named = [
                (
                    name,
                    [
                        span
                        for record in spool["records"]
                        if record.get("type") == "trace"
                        for span in (record.get("trace") or {}).get("spans", ())
                    ],
                )
                for name, spool in spools.items()
            ]
            doc = telemetry.federated_chrome(named)
            json.dump(doc, out, sort_keys=True)
            out.write("\n")
        else:
            for name, spool in spools.items():
                for record in spool["records"]:
                    out.write(
                        json.dumps({"spool": name, **record}, sort_keys=True)
                        + "\n"
                    )
    except BrokenPipeError:
        # `journal ... | head` closing the pipe is a normal way to read a
        # spool, not an error; mute the interpreter-shutdown stdout flush
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    finally:
        if args.output:
            out.close()
    summary = {
        "journal_dir": journal_dir,
        "spools": {
            name: {
                "records": len(spool["records"]),
                "segments": spool["segments"],
                "torn_tail": spool["torn_tail"],
                "skipped_lines": spool["skipped_lines"],
            }
            for name, spool in spools.items()
        },
        **({"output": args.output} if args.output else {}),
    }
    print(json.dumps(summary, sort_keys=True), file=sys.stderr)
    return 0


def cmd_autotune(args) -> int:
    """Operate the measured strategy autotuner's persisted cost model
    (docs/autotune.md): dump the winner table (default; ``--format json``
    round-trips the persisted file), ``--clear`` it, or ``--warm`` it by
    probing the given workload at each batch bucket so a serving fleet
    never pays a cold probe on a live request."""
    from . import tuning

    if args.clear:
        existed = tuning.clear_table()
        print(json.dumps({"cleared": str(tuning.table_path()), "existed": existed}))
        return 0
    if args.warm:
        if args.input:
            X, _ = _load(args.input, args.labeled)
        else:
            rng = np.random.default_rng(0)
            X = rng.normal(size=(4096, 4)).astype(np.float32)
            X[:40] += 4.0
        if args.model:
            model = _load_model(args.model)
        else:
            from .models import IsolationForest

            model = IsolationForest(num_estimators=args.trees, random_seed=1).fit(X)
        decisions = []
        for b in sorted({int(s) for s in args.batch_sizes.split(",") if s}):
            Xb = np.resize(np.asarray(X, np.float32), (max(b, 1), X.shape[1]))
            d = tuning.resolve_decision(
                model.forest, Xb, model.num_samples, refresh=args.refresh
            )
            decisions.append(
                {"batch": b, "key": d.key, "strategy": d.strategy, "source": d.source}
            )
        print(json.dumps({"warmed": decisions}), file=sys.stderr)
    doc = tuning.table_snapshot()
    if args.format == "table":
        print(f"# {doc['path']} (schema {doc['schema']}, ttl {doc['ttl_s']:g}s)")
        for key, entry in doc["entries"].items():
            timings = " ".join(
                f"{s}={t if t is not None else 'fail'}"
                for s, t in sorted(entry.get("timings_s", {}).items())
            )
            print(f"{key} -> {entry['strategy']} [{timings}]")
    else:
        print(json.dumps(doc, indent=1, sort_keys=True))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="isoforest_tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    fit = sub.add_parser(
        "fit", help="train a model from a CSV or a sharded on-disk source"
    )
    fit_in = fit.add_mutually_exclusive_group(required=True)
    fit_in.add_argument("--input", help="CSV file (materialised in memory)")
    fit_in.add_argument(
        "--source",
        help="sharded source — directory, glob, or file of "
        ".csv/.npy/.avro/.parquet shards; fit streams it out-of-core "
        "(one bounded-memory pass, docs/out_of_core.md)",
    )
    fit.add_argument("--output", required=True)
    fit.add_argument(
        "--chunk-rows",
        type=int,
        default=None,
        help="rows per streamed chunk for --source (default 65536)",
    )
    fit.add_argument("--labeled", action="store_true")
    fit.add_argument("--extended", action="store_true")
    fit.add_argument("--num-estimators", type=int, default=100)
    fit.add_argument("--max-samples", type=float, default=256.0)
    fit.add_argument("--contamination", type=float, default=0.0)
    fit.add_argument("--contamination-error", type=float, default=0.0)
    fit.add_argument("--max-features", type=float, default=1.0)
    fit.add_argument("--bootstrap", action="store_true")
    fit.add_argument("--random-seed", type=int, default=1)
    fit.add_argument("--extension-level", type=int, default=None)
    fit.add_argument("--overwrite", action="store_true")
    fit.set_defaults(func=cmd_fit)

    score = sub.add_parser(
        "score", help="score a CSV or a sharded source with a saved model"
    )
    score.add_argument("--model", required=True)
    score_in = score.add_mutually_exclusive_group(required=True)
    score_in.add_argument("--input", help="CSV file, scored to --output CSV")
    score_in.add_argument(
        "--source",
        help="sharded source — scores stream shard-by-shard into the "
        "--output sink directory with resumable sealed parts "
        "(docs/out_of_core.md §5)",
    )
    score.add_argument("--output", default="-")
    score.add_argument("--labeled", action="store_true")
    score.add_argument(
        "--chunk-rows",
        type=int,
        default=1 << 20,
        help="stream the input in chunks of this many rows — bounded memory "
        "for arbitrarily large unlabeled files (--labeled accumulates "
        "scores+labels for the final AUROC report)",
    )
    score.add_argument(
        "--strategy",
        default="auto",
        help="scoring strategy (default auto); pin e.g. 'gather' to make a "
        "--source sink resumable across machines",
    )
    score.add_argument(
        "--resume",
        action="store_true",
        help="with --source: re-attach to an existing sink, skipping every "
        "intact sealed shard (bitwise-identical final output)",
    )
    score.set_defaults(func=cmd_score)

    conv = sub.add_parser("convert", help="export a saved model to ONNX")
    conv.add_argument("--model", required=True)
    conv.add_argument("--output", required=True)
    conv.set_defaults(func=cmd_convert)

    insp = sub.add_parser("inspect", help="summarise a saved model")
    insp.add_argument("--model", required=True)
    insp.add_argument("--tree", type=int, default=None)
    insp.set_defaults(func=cmd_inspect)

    tele = sub.add_parser(
        "telemetry",
        help="run an instrumented workload and print the telemetry snapshot",
    )
    tele.add_argument(
        "--format", choices=("json", "prometheus"), default="json"
    )
    tele.add_argument("--input", default=None, help="CSV workload (default: synthetic)")
    tele.add_argument("--model", default=None, help="score with a saved model")
    tele.add_argument("--labeled", action="store_true")
    tele.add_argument("--rows", type=int, default=4096, help="synthetic workload rows")
    tele.add_argument("--trees", type=int, default=50)
    tele.set_defaults(func=cmd_telemetry)

    trc = sub.add_parser(
        "trace",
        help="run an instrumented workload and write a Perfetto-loadable trace",
    )
    trc.add_argument("output", help="Chrome trace-event JSON output path")
    trc.add_argument("--input", default=None, help="CSV workload (default: synthetic)")
    trc.add_argument("--model", default=None, help="score with a saved model")
    trc.add_argument("--labeled", action="store_true")
    trc.add_argument("--rows", type=int, default=4096, help="synthetic workload rows")
    trc.add_argument("--trees", type=int, default=50)
    trc.set_defaults(func=cmd_trace)

    dbg = sub.add_parser(
        "debug-bundle",
        help="run an instrumented workload and write the flight-recorder "
        "debug bundle (one JSON artifact)",
    )
    dbg.add_argument("output", help="debug-bundle JSON output path")
    dbg.add_argument("--input", default=None, help="CSV workload (default: synthetic)")
    dbg.add_argument("--model", default=None, help="score with a saved model")
    dbg.add_argument("--labeled", action="store_true")
    dbg.add_argument("--rows", type=int, default=4096, help="synthetic workload rows")
    dbg.add_argument("--trees", type=int, default=50)
    dbg.set_defaults(func=cmd_debug_bundle)

    diag = sub.add_parser(
        "diagnose", help="forest-structure diagnostics for a saved model"
    )
    diag.add_argument("model_dir")
    diag.add_argument("--format", choices=("json", "prometheus"), default="json")
    diag.set_defaults(func=cmd_diagnose)

    mon = sub.add_parser(
        "monitor",
        help="score a CSV with drift monitoring vs the model's baseline",
    )
    mon.add_argument("model_dir")
    mon.add_argument("--input", required=True, help="CSV of serving traffic")
    mon.add_argument("--labeled", action="store_true")
    mon.add_argument(
        "--threshold",
        type=float,
        default=None,
        help="PSI alert threshold (default 0.25, the 'major shift' band)",
    )
    mon.add_argument(
        "--min-rows",
        type=int,
        default=512,
        help="rows to fold before drift is evaluated",
    )
    mon.add_argument("--chunk-rows", type=int, default=1 << 16)
    mon.add_argument(
        "--port",
        type=int,
        default=None,
        help="serve the live /metrics endpoint on this port while scoring "
        "(0 = ephemeral)",
    )
    mon.add_argument("--format", choices=("json", "prometheus"), default="json")
    mon.set_defaults(func=cmd_monitor)

    man = sub.add_parser(
        "manage",
        help="serve a CSV under the drift-triggered retraining lifecycle",
    )
    man.add_argument("model_dir")
    man.add_argument("--input", required=True, help="CSV of serving traffic")
    man.add_argument("--labeled", action="store_true")
    man.add_argument(
        "--work-dir",
        default=None,
        help="lifecycle artifact dir: swapped generations + refit "
        "checkpoints (default: <model_dir>.lifecycle)",
    )
    man.add_argument(
        "--threshold",
        type=float,
        default=None,
        help="PSI alert threshold (default 0.25, the 'major shift' band)",
    )
    man.add_argument(
        "--debounce",
        type=int,
        default=3,
        help="consecutive over-threshold drift evaluations before a retrain",
    )
    man.add_argument(
        "--window-rows",
        type=int,
        default=65536,
        help="recent-data reservoir capacity the refit trains on",
    )
    man.add_argument(
        "--min-window-rows",
        type=int,
        default=1024,
        help="refuse to retrain on a window smaller than this",
    )
    man.add_argument(
        "--min-rows",
        type=int,
        default=512,
        help="rows to fold before drift is evaluated",
    )
    man.add_argument(
        "--mode",
        choices=("full", "sliding"),
        default="full",
        help="full refit, or sliding-window tree refresh (retire oldest "
        "trees, grow replacements on the window)",
    )
    man.add_argument(
        "--reservoir",
        choices=("fifo", "decay"),
        default="fifo",
        help="retrain-window policy: the last N rows, or the seeded "
        "exponential-decay weighted sample (docs/streaming.md §4)",
    )
    man.add_argument(
        "--half-life-s",
        type=float,
        default=3600.0,
        help="decay reservoir half-life: every this many seconds of event "
        "time halves an old row's retention odds",
    )
    man.add_argument(
        "--checkpoint-every",
        type=int,
        default=None,
        help="trees per refit checkpoint block (default 32)",
    )
    man.add_argument("--chunk-rows", type=int, default=1 << 16)
    man.add_argument(
        "--port",
        type=int,
        default=None,
        help="serve the live /metrics + /healthz endpoint on this port "
        "while scoring (0 = ephemeral)",
    )
    man.add_argument(
        "--journal-dir",
        default=None,
        help="flight-record every event and committed trace into an "
        "append-only NDJSON spool under this directory "
        "(docs/observability.md §12)",
    )
    man.set_defaults(func=cmd_manage)

    stm = sub.add_parser(
        "stream",
        help="online anomaly detection over an event-time stream "
        "(docs/streaming.md)",
    )
    stm.add_argument("model_dir")
    stm.add_argument(
        "--source",
        required=True,
        help="append-only stream: a shard dir/glob or CSV file to tail "
        "(rows are event_ts,f1,...,fn[,label]), or tcp://HOST:PORT to "
        "listen on the line protocol",
    )
    stm.add_argument("--labeled", action="store_true")
    stm.add_argument(
        "--follow",
        action="store_true",
        help="keep tailing a file source for appended rows / new shards "
        "after the current end (default: stop at end of data)",
    )
    stm.add_argument(
        "--window-s",
        type=float,
        default=60.0,
        help="event-time window width",
    )
    stm.add_argument(
        "--slide-s",
        type=float,
        default=None,
        help="window slide (must divide --window-s; default = tumbling)",
    )
    stm.add_argument(
        "--lateness-s",
        type=float,
        default=5.0,
        help="allowed lateness: the watermark trails the max event time by "
        "this much; rows behind it are scored but counted late",
    )
    stm.add_argument(
        "--retrain-every",
        type=int,
        default=1,
        help="retrain/validate/swap after every N non-empty window closes",
    )
    stm.add_argument(
        "--mode",
        choices=("full", "sliding"),
        default="sliding",
        help="refit flavour at each window-cadenced retrain (default "
        "sliding: the streaming steady state)",
    )
    stm.add_argument(
        "--reservoir",
        choices=("fifo", "decay"),
        default="decay",
        help="retrain-window policy (default: event-time exponential decay)",
    )
    stm.add_argument(
        "--half-life-s",
        type=float,
        default=3600.0,
        help="decay reservoir half-life in event-time seconds",
    )
    stm.add_argument("--window-rows", type=int, default=65536)
    stm.add_argument("--min-window-rows", type=int, default=1024)
    stm.add_argument("--min-rows", type=int, default=512)
    stm.add_argument("--threshold", type=float, default=None)
    stm.add_argument("--checkpoint-every", type=int, default=None)
    stm.add_argument("--work-dir", default=None, help="default: <model_dir>.stream")
    stm.add_argument("--batch-rows", type=int, default=1024)
    stm.add_argument("--linger-ms", type=float, default=2.0)
    stm.add_argument("--chunk-rows", type=int, default=4096)
    stm.add_argument("--poll-s", type=float, default=0.25)
    stm.add_argument(
        "--max-rows", type=int, default=None, help="stop after ~N ingested rows"
    )
    stm.add_argument(
        "--port",
        type=int,
        default=None,
        help="serve live /metrics + /traces/recent while streaming "
        "(0 = ephemeral; prints a JSON ready line with the URL)",
    )
    stm.add_argument(
        "--hold-seconds",
        type=float,
        default=0.0,
        help="keep the telemetry endpoint up this long after the summary "
        "line (until SIGTERM), so a harness can pull traces + debug bundle",
    )
    stm.add_argument(
        "--journal-dir",
        default=None,
        help="flight-record every event and committed trace into an "
        "append-only NDJSON spool under this directory "
        "(docs/observability.md §12)",
    )
    stm.set_defaults(func=cmd_stream)

    srv = sub.add_parser(
        "serve",
        help="serve POST /score with dynamic micro-batch coalescing "
        "(or a multi-tenant fleet with --models-dir)",
    )
    srv.add_argument(
        "model_dir",
        nargs="?",
        default=None,
        help="single-model mode: the sealed model directory to serve "
        "(mutually exclusive with --models-dir)",
    )
    srv.add_argument(
        "--models-dir",
        default=None,
        help="fleet mode (docs/fleet.md): serve every sealed model "
        "directory under this dir as a tenant behind POST "
        "/score/<model_id> (the subdir name is the model id)",
    )
    srv.add_argument(
        "--fleet-budget-mb",
        type=float,
        default=None,
        help="fleet residency budget in MiB of packed scoring-layout "
        "bytes: past it, least-recently-used tenants are evicted and "
        "re-load lazily from their sealed dirs (default: unbounded)",
    )
    srv.add_argument(
        "--preload",
        action="store_true",
        help="fleet mode: load every tenant at startup instead of lazily "
        "on first request",
    )
    srv.add_argument("--host", default="127.0.0.1")
    srv.add_argument(
        "--port",
        type=int,
        default=0,
        help="HTTP port for /score + /metrics + /healthz (0 = ephemeral, "
        "reported on the ready line)",
    )
    srv.add_argument(
        "--batch-rows",
        type=int,
        default=1024,
        help="coalescer flush size — keep it a power-of-two batch bucket "
        "so flushes land on the pre-warmed autotuned shapes",
    )
    srv.add_argument(
        "--linger-ms",
        type=float,
        default=2.0,
        help="max time the oldest queued request waits for company before "
        "its flush goes out (the tail-latency bound)",
    )
    srv.add_argument(
        "--max-queue-rows",
        type=int,
        default=8192,
        help="admission queue bound; a request past it gets HTTP 429",
    )
    srv.add_argument(
        "--queue-deadline-ms",
        type=float,
        default=2000.0,
        help="once the oldest queued request is older than this the "
        "service answers HTTP 503 (not draining)",
    )
    srv.add_argument(
        "--request-timeout-s",
        type=float,
        default=30.0,
        help="per-request wait budget (queue + scoring) before a 503",
    )
    srv.add_argument(
        "--score-timeout-s",
        type=float,
        default=None,
        help="arm the scoring watchdog per coalesced flush "
        "(docs/resilience.md §6 degradation ladder)",
    )
    srv.add_argument(
        "--warm-batch-sizes",
        default="1",
        help="comma-separated batch sizes to pre-warm at startup (always "
        "includes --batch-rows; bucketed power-of-two)",
    )
    srv.add_argument(
        "--no-lifecycle",
        action="store_true",
        help="serve the bare model even when it carries a drift baseline "
        "(no monitoring, no retraining, no hot-swap)",
    )
    srv.add_argument(
        "--work-dir",
        default=None,
        help="lifecycle artifact dir (default: <model_dir>.lifecycle); "
        "CURRENT.json there resumes the last swapped generation. In fleet "
        "mode this is the work ROOT: each tenant gets <work-dir>/<model_id>",
    )
    srv.add_argument("--threshold", type=float, default=None)
    srv.add_argument("--debounce", type=int, default=3)
    srv.add_argument("--window-rows", type=int, default=65536)
    srv.add_argument("--min-window-rows", type=int, default=1024)
    srv.add_argument("--min-rows", type=int, default=512)
    srv.add_argument("--mode", choices=("full", "sliding"), default="full")
    srv.add_argument(
        "--max-seconds",
        type=float,
        default=None,
        help="exit after this many seconds (default: serve until "
        "SIGTERM/SIGINT) — CI smoke runs use it with `timeout`",
    )
    srv.add_argument(
        "--autopilot",
        action="store_true",
        help="arm the overload autopilot (docs/autopilot.md): under "
        "sustained queue pressure walk the reversible brownout ladder — "
        "widen coalescing, shed low-weight tenants (429 + Retry-After), "
        "degrade quality (q16 + subsampled forest) — and recover "
        "rung-by-rung when pressure drops",
    )
    srv.add_argument(
        "--autopilot-high-water",
        type=float,
        default=0.5,
        help="queue-fill fraction at/above which ticks count toward "
        "engaging the next brownout rung",
    )
    srv.add_argument(
        "--autopilot-low-water",
        type=float,
        default=0.15,
        help="queue-fill fraction at/below which ticks count toward "
        "lifting the deepest engaged rung (hysteresis: must be below "
        "--autopilot-high-water)",
    )
    srv.add_argument(
        "--autopilot-engage-ticks",
        type=int,
        default=3,
        help="consecutive high-water ticks before one rung engages",
    )
    srv.add_argument(
        "--autopilot-recover-ticks",
        type=int,
        default=6,
        help="consecutive low-water ticks before one rung lifts",
    )
    srv.add_argument(
        "--autopilot-interval-s",
        type=float,
        default=0.5,
        help="control-loop tick interval",
    )
    srv.add_argument(
        "--autopilot-subsample-trees",
        type=float,
        default=0.5,
        help="rung 3: fraction of the forest scored while quality is "
        "degraded (FastForest-style prefix subsample)",
    )
    srv.add_argument(
        "--autopilot-strict",
        action="store_true",
        help="report pressure but REFUSE every brownout rung (the "
        "degradation ladder's strict=True opt-out; autopilot.refused "
        "events mark each refusal)",
    )
    srv.add_argument(
        "--weight",
        type=float,
        default=1.0,
        help="this deployment's shed-priority weight class "
        "(docs/autopilot.md; fleet tenants can override per tenant with "
        "--tenant-weight)",
    )
    srv.add_argument(
        "--tenant-weight",
        action="append",
        default=None,
        metavar="MODEL_ID=WEIGHT",
        help="fleet mode: per-tenant shed-priority weight (repeatable); "
        "tenants below the fleet's highest weight class are shed first "
        "under the autopilot's rung 2",
    )
    srv.add_argument(
        "--replica-name",
        default=os.environ.get("ISOFOREST_TPU_REPLICA_NAME") or None,
        help="replicated tier (docs/replication.md): this replica's name; "
        "with --heartbeat-dir, writes heartbeat-<name>.json there so the "
        "fronting router's /healthz tracks this process",
    )
    srv.add_argument(
        "--heartbeat-dir",
        default=None,
        help="directory for this replica's liveness heartbeat file "
        "(requires --replica-name). Deliberately NOT the "
        "ISOFOREST_TPU_HEARTBEAT_DIR env: the replica only WRITES here — "
        "its own /healthz must not 503 when a PEER dies",
    )
    srv.add_argument(
        "--journal-dir",
        default=None,
        help="flight-record every event and committed trace into an "
        "append-only NDJSON spool under this directory, named after "
        "--replica-name when set (docs/observability.md §12) — a kill -9 "
        "victim's last moments survive for the tier /debug/bundle",
    )
    srv.set_defaults(func=cmd_serve)

    rt = sub.add_parser(
        "route",
        help="front a replicated serving tier (docs/replication.md): spawn "
        "K fleet replicas over one --models-dir and balance POST "
        "/score/<model_id> across them with health-probe admission, "
        "idempotent retries, drains and rolling model pushes",
    )
    rt.add_argument(
        "--models-dir",
        required=True,
        help="the sealed model directory every replica serves (fleet "
        "layout, docs/fleet.md)",
    )
    rt.add_argument(
        "--replicas",
        type=int,
        default=2,
        help="how many serving replicas to spawn (default 2)",
    )
    rt.add_argument("--host", default="127.0.0.1")
    rt.add_argument(
        "--port",
        type=int,
        default=0,
        help="the router's HTTP port (0 = ephemeral, reported on the "
        "ready line); replicas always bind ephemeral ports",
    )
    rt.add_argument(
        "--probe-interval-s",
        type=float,
        default=1.0,
        help="maintenance cadence: health probes + rolling-push passes",
    )
    rt.add_argument(
        "--probe-timeout-s",
        type=float,
        default=2.0,
        help="a replica whose /healthz answers slower than this is ejected",
    )
    rt.add_argument(
        "--stale-after-s",
        type=float,
        default=15.0,
        help="a replica whose heartbeat file is older than this is ejected",
    )
    rt.add_argument(
        "--request-timeout-s",
        type=float,
        default=30.0,
        help="one forward's wire budget before the router retries elsewhere",
    )
    rt.add_argument(
        "--drain-timeout-s",
        type=float,
        default=30.0,
        help="SIGTERM: how long to wait for in-flight requests to finish",
    )
    rt.add_argument(
        "--retry-attempts",
        type=int,
        default=3,
        help="forward attempts across replicas before a 503",
    )
    rt.add_argument(
        "--batch-rows", type=int, default=None,
        help="passed through to each spawned replica",
    )
    rt.add_argument(
        "--linger-ms", type=float, default=None,
        help="passed through to each spawned replica",
    )
    rt.add_argument(
        "--fleet-budget-mb", type=float, default=None,
        help="passed through to each spawned replica",
    )
    rt.add_argument(
        "--preload", action="store_true",
        help="passed through to each spawned replica",
    )
    rt.add_argument(
        "--no-lifecycle", action="store_true",
        help="passed through to each spawned replica",
    )
    rt.add_argument(
        "--work-dir",
        default=None,
        help="lifecycle work ROOT shared by all replicas (each tenant at "
        "<work-dir>/<model_id>); the router watches CURRENT.json under it "
        "for rolling pushes. Default: <model_dir>.lifecycle next to each "
        "sealed model",
    )
    rt.add_argument(
        "--max-seconds",
        type=float,
        default=None,
        help="exit after this many seconds (default: serve until "
        "SIGTERM/SIGINT) — CI smoke runs use it with `timeout`",
    )
    rt.add_argument(
        "--journal-dir",
        default=None,
        help="tier flight recorder (docs/observability.md §12): the router "
        "spools under <dir>/router/ and every replica under its tier name; "
        "the tier GET /debug/bundle recovers dead replicas' spools off disk",
    )
    rt.set_defaults(func=cmd_route)

    jrn = sub.add_parser(
        "journal",
        help="dump a flight-recorder journal directory as JSON lines or "
        "one merged Perfetto trace",
    )
    jrn.add_argument(
        "journal_dir",
        help="the --journal-dir a serve/route/manage/stream run spooled "
        "into (one subdirectory per process)",
    )
    jrn.add_argument(
        "--spool",
        default=None,
        help="restrict to one process's spool (default: every spool)",
    )
    jrn.add_argument(
        "--format",
        choices=("json", "chrome"),
        default="json",
        help="json: every record as one JSON line tagged with its spool; "
        "chrome: journaled traces merged into ONE Perfetto document with "
        "a pid lane per spool (load at ui.perfetto.dev)",
    )
    jrn.add_argument(
        "--tail",
        type=int,
        default=None,
        help="keep only the newest N records per spool",
    )
    jrn.add_argument(
        "--output",
        default=None,
        help="write the dump here instead of stdout (the per-spool summary "
        "always prints to stderr)",
    )
    jrn.set_defaults(func=cmd_journal)

    at = sub.add_parser(
        "autotune",
        help="dump/clear/pre-warm the measured strategy cost model",
    )
    at.add_argument("--format", choices=("json", "table"), default="json")
    at.add_argument(
        "--clear", action="store_true", help="delete the persisted winner table"
    )
    at.add_argument(
        "--warm",
        action="store_true",
        help="probe the workload at each --batch-sizes bucket before dumping",
    )
    at.add_argument("--input", default=None, help="CSV workload (default: synthetic)")
    at.add_argument("--model", default=None, help="probe with a saved model")
    at.add_argument("--labeled", action="store_true")
    at.add_argument("--trees", type=int, default=50)
    at.add_argument(
        "--batch-sizes",
        default="1024,65536",
        help="comma-separated batch sizes to pre-warm (bucketed power-of-two)",
    )
    at.add_argument(
        "--refresh",
        action="store_true",
        help="force re-probe even for fresh table entries (--no-cache analogue)",
    )
    at.set_defaults(func=cmd_autotune)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
