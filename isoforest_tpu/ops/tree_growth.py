"""Standard isolation-tree growth as a fixed-shape, level-synchronous XLA program.

The reference grows pointer-based trees recursively, one tree per Spark
partition (``IsolationTree.scala:83-183``). That shape-dynamic recursion
cannot compile to XLA; instead each tree is a **struct-of-arrays implicit
heap** of ``max_nodes = 2**(h+1)-1`` slots with children of slot ``i`` at
``2i+1``/``2i+2`` (SURVEY.md §7.1), and growth proceeds level-synchronously:
at level ``l`` every sample scatters its feature vector into per-node
min/max/count statistics, every level-``l`` node draws its split, and every
sample routes one step down. The whole loop is a ``lax.fori_loop`` of
``h+1`` fixed-shape iterations under ``jit``, ``vmap``-ed over the tree axis.

Reference semantics preserved:
  * height limit ``ceil(log2(n))`` (IsolationTree.scala:60-61);
  * split feature drawn uniformly among *non-constant* features — the
    reference's retry-loop-with-constant-feature-removal
    (IsolationTree.scala:124-150) is equivalent to a uniform draw over the
    features with ``min != max``, realised here as a Gumbel-argmax over the
    non-constant mask;
  * terminate when no splittable feature remains, the height limit is hit, or
    ``n <= 1`` (IsolationTree.scala:155-156);
  * split threshold uniform in ``[min, max)`` of the node's data; routing
    ``x < t`` left / ``x >= t`` right (IsolationTree.scala:158-159).

Known deviation: thresholds are float32 (the reference keeps Double). In the
measure-zero event that a threshold rounds onto the node minimum, an empty
child becomes a ``numInstances = 0`` leaf (``avg_path_length(0) = 0``) rather
than being impossible — same convention the extended forest already uses
(ExtendedNodes.scala:32-35).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from . import level_window as lw
from .bagging import gather_tree_data


class StandardForest(NamedTuple):
    """Struct-of-arrays forest over ``[num_trees, max_nodes]`` heap slots.

    ``feature``: int32 global split-feature id; ``-1`` at leaves and
    non-existent slots. ``threshold``: float32 split value (reference:
    ``splitValue`` Double, Nodes.scala:47-66). ``num_instances``: int32 leaf
    size; ``-1`` at internal and non-existent slots (matching the Avro
    sentinels, IsolationForestModelReadWrite.scala:36-67).
    """

    feature: jax.Array  # i32 [T, M]
    threshold: jax.Array  # f32 [T, M]
    num_instances: jax.Array  # i32 [T, M]

    @property
    def num_trees(self) -> int:
        return self.feature.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.feature.shape[1]

    @property
    def is_internal(self) -> jax.Array:
        return self.feature >= 0

    @property
    def is_leaf(self) -> jax.Array:
        return self.num_instances >= 0

    @property
    def exists(self) -> jax.Array:
        return self.is_internal | self.is_leaf


def _grow_one_tree(key: jax.Array, x: jax.Array, h: int):
    """Grow one tree over ``x: f32[S, F]``; returns local-feature-indexed arrays.

    Per-level statistics are [level_width, feature_chunk] windows instead of
    [max_nodes, F] (the r1 kernel's ~1.1 GB/level transient at T=1000,
    F=274), using the shared scaffolding in :mod:`.level_window`. The
    uniform choice among non-constant features streams across chunks via a
    running Gumbel-argmax — distributionally identical to a single
    Gumbel-argmax over all F.
    """
    S, F = x.shape
    M = 2 ** (h + 1) - 1
    W = 2**h  # widest level; per-level stats never need more rows
    geom = lw.chunk_features(x)
    x, Fc, n_chunks = geom.x, geom.chunk, geom.n_chunks
    level_keys = jax.random.split(key, h + 1)

    state = dict(
        node_id=jnp.zeros((S,), jnp.int32),
        settled=jnp.zeros((S,), jnp.bool_),
        feature=jnp.full((M,), -1, jnp.int32),
        threshold=jnp.zeros((M,), jnp.float32),
        num_instances=jnp.full((M,), -1, jnp.int32),
        exists=jnp.zeros((M,), jnp.bool_).at[0].set(True),
    )

    def level_step(l, st):
        k_feat, k_thr = jax.random.split(level_keys[l])
        win = lw.level_window(l, W, st["node_id"], st["settled"])
        idx_w = win.idx_of_sample
        cnt = jnp.zeros((W,), jnp.int32).at[idx_w].add(1, mode="drop")

        # --- streaming per-node statistics + feature choice, F in chunks ---
        # (IsolationTree.scala:124-156: uniform draw among non-constant
        # features == Gumbel-argmax over the non-constant mask; the running
        # max across chunks keeps that exact distribution)
        best_g = jnp.full((W,), -jnp.inf, jnp.float32)
        best_f = jnp.zeros((W,), jnp.int32)
        best_mn = jnp.zeros((W,), jnp.float32)
        best_mx = jnp.zeros((W,), jnp.float32)
        any_nc = jnp.zeros((W,), jnp.bool_)
        for c in range(n_chunks):
            xc = x[:, c * Fc : (c + 1) * Fc]
            mn_c = jnp.full((W, Fc), jnp.inf, jnp.float32).at[idx_w].min(
                xc, mode="drop"
            )
            mx_c = jnp.full((W, Fc), -jnp.inf, jnp.float32).at[idx_w].max(
                xc, mode="drop"
            )
            nc = mn_c < mx_c
            g = jnp.where(
                nc,
                jax.random.gumbel(jax.random.fold_in(k_feat, c), (W, Fc), jnp.float32),
                -jnp.inf,
            )
            fj = jnp.argmax(g, axis=1).astype(jnp.int32)
            gj = jnp.take_along_axis(g, fj[:, None], axis=1)[:, 0]
            mnj = jnp.take_along_axis(mn_c, fj[:, None], axis=1)[:, 0]
            mxj = jnp.take_along_axis(mx_c, fj[:, None], axis=1)[:, 0]
            upd = gj > best_g
            best_g = jnp.where(upd, gj, best_g)
            best_f = jnp.where(upd, c * Fc + fj, best_f)
            best_mn = jnp.where(upd, mnj, best_mn)
            best_mx = jnp.where(upd, mxj, best_mx)
            any_nc = any_nc | jnp.any(nc, axis=1)

        # --- split decision per level-l node (IsolationTree.scala:124-156) ---
        exists_w = lw.window_slice(st["exists"], win.start, W)
        can_split = exists_w & win.in_level & (cnt > 1) & (l < h) & any_nc
        u = jax.random.uniform(k_thr, (W,), jnp.float32)
        thr_w = best_mn + u * (best_mx - best_mn)
        new_leaf = exists_w & win.in_level & ~can_split

        feature = lw.patch(st["feature"], best_f, can_split, win.start)
        threshold = lw.patch(st["threshold"], thr_w, can_split, win.start)
        num_instances = lw.patch(st["num_instances"], cnt, new_leaf, win.start)

        # children of split nodes materialise at the next level
        exists = lw.spawn_children(st["exists"], can_split, win.slots, M)

        # --- route unsettled samples one level down (x < t left / >= right) ---
        nd = st["node_id"]
        j_s = jnp.clip(nd - win.start, 0, W - 1)
        split_here = jnp.take(can_split, j_s) & ~st["settled"]
        f_s = jnp.take(best_f, j_s)
        go_right = (
            jnp.take_along_axis(x, f_s[:, None], axis=1)[:, 0]
            >= jnp.take(thr_w, j_s)
        )
        node_id = jnp.where(split_here, 2 * nd + 1 + go_right.astype(jnp.int32), nd)
        settled = st["settled"] | ~split_here

        return dict(
            node_id=node_id,
            settled=settled,
            feature=feature,
            threshold=threshold,
            num_instances=num_instances,
            exists=exists,
        )

    state = lax.fori_loop(0, h + 1, level_step, state)
    return state["feature"], state["threshold"], state["num_instances"]


def grow_forest(
    tree_keys: jax.Array,
    X: jax.Array,
    bag_idx: jax.Array,
    feat_idx: jax.Array,
    height: int,
) -> StandardForest:
    """Grow ``T`` standard isolation trees; ``vmap`` over the tree axis.

    ``tree_keys``: per-tree PRNG keys ``[T, ...]`` (see
    :func:`..bagging.per_tree_keys` — passed pre-derived so the tree axis can
    be sharded across devices with disjoint streams); ``X``: f32[N, F_total];
    ``bag_idx``: i32[T, S]; ``feat_idx``: i32[T, F_sub] sorted global feature
    ids; ``height`` static. Local split indices are mapped back to global
    feature ids so persisted ``splitAttribute`` matches the reference layout.
    """
    x_trees = gather_tree_data(X, bag_idx, feat_idx)  # [T, S, F_sub]
    feature_local, threshold, num_instances = jax.vmap(
        lambda k, x: _grow_one_tree(k, x, height)
    )(tree_keys, x_trees)

    feature_global = jnp.where(
        feature_local >= 0,
        jnp.take_along_axis(
            feat_idx, jnp.maximum(feature_local, 0), axis=1
        ),
        -1,
    ).astype(jnp.int32)
    return StandardForest(
        feature=feature_global,
        threshold=threshold,
        num_instances=num_instances,
    )


# jitted entry for block-wise checkpointed growth (models _blockwise_grow):
# the same trace as `grow_forest`, but compiled once per block shape instead
# of re-dispatching op-by-op on every block of every fit — call with
# height as a keyword
grow_forest_block = functools.partial(jax.jit, static_argnames=("height",))(
    grow_forest
)


@functools.partial(
    jax.jit,
    static_argnames=("num_samples", "num_trees", "bootstrap", "num_features", "height"),
)
def grow_forest_fused(
    key: jax.Array,
    X: jax.Array,
    *,
    num_samples: int,
    num_trees: int,
    bootstrap: bool,
    num_features: int,
    height: int,
) -> StandardForest:
    """Whole single-device fit program under ONE jit: key split -> bagging ->
    feature subsets -> per-tree keys -> growth. The estimator's unfused path
    issued ~4 separate device programs; on the TPU tunnel each dispatch is a
    network round trip and the round-2 profiler trace showed fit is
    dispatch-bound, not compute-bound (fit_s 0.467 at 1M rows with trivial
    growth compute). Key-split order matches the unfused estimator path
    exactly, so the grown forest is stream-identical."""
    from .bagging import bagged_indices, feature_subsets, per_tree_keys

    num_rows, num_features_total = X.shape
    k_bag, k_feat, k_grow = jax.random.split(key, 3)
    bag = bagged_indices(k_bag, num_rows, num_samples, num_trees, bootstrap)
    fidx = feature_subsets(k_feat, num_features_total, num_features, num_trees)
    tree_keys = per_tree_keys(k_grow, num_trees)
    return grow_forest(tree_keys, X, bag, fidx, height)
