"""Standard isolation-tree growth as a fixed-shape, level-synchronous XLA program.

The reference grows pointer-based trees recursively, one tree per Spark
partition (``IsolationTree.scala:83-183``). That shape-dynamic recursion
cannot compile to XLA; instead each tree is a **struct-of-arrays implicit
heap** of ``max_nodes = 2**(h+1)-1`` slots with children of slot ``i`` at
``2i+1``/``2i+2`` (SURVEY.md §7.1), and growth proceeds level-synchronously:
at level ``l`` every sample scatters its feature vector into per-node
min/max/count statistics, every level-``l`` node draws its split, and every
sample routes one step down. The whole loop is a ``lax.fori_loop`` of
``h+1`` fixed-shape iterations under ``jit``, ``vmap``-ed over the tree axis.

Reference semantics preserved:
  * height limit ``ceil(log2(n))`` (IsolationTree.scala:60-61);
  * split feature drawn uniformly among *non-constant* features — the
    reference's retry-loop-with-constant-feature-removal
    (IsolationTree.scala:124-150) is equivalent to a uniform draw over the
    features with ``min != max``, realised here as a Gumbel-argmax over the
    non-constant mask;
  * terminate when no splittable feature remains, the height limit is hit, or
    ``n <= 1`` (IsolationTree.scala:155-156);
  * split threshold uniform in ``[min, max)`` of the node's data; routing
    ``x < t`` left / ``x >= t`` right (IsolationTree.scala:158-159).

Known deviation: thresholds are float32 (the reference keeps Double). In the
measure-zero event that a threshold rounds onto the node minimum, an empty
child becomes a ``numInstances = 0`` leaf (``avg_path_length(0) = 0``) rather
than being impossible — same convention the extended forest already uses
(ExtendedNodes.scala:32-35).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .bagging import gather_tree_data


class StandardForest(NamedTuple):
    """Struct-of-arrays forest over ``[num_trees, max_nodes]`` heap slots.

    ``feature``: int32 global split-feature id; ``-1`` at leaves and
    non-existent slots. ``threshold``: float32 split value (reference:
    ``splitValue`` Double, Nodes.scala:47-66). ``num_instances``: int32 leaf
    size; ``-1`` at internal and non-existent slots (matching the Avro
    sentinels, IsolationForestModelReadWrite.scala:36-67).
    """

    feature: jax.Array  # i32 [T, M]
    threshold: jax.Array  # f32 [T, M]
    num_instances: jax.Array  # i32 [T, M]

    @property
    def num_trees(self) -> int:
        return self.feature.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.feature.shape[1]

    @property
    def is_internal(self) -> jax.Array:
        return self.feature >= 0

    @property
    def is_leaf(self) -> jax.Array:
        return self.num_instances >= 0

    @property
    def exists(self) -> jax.Array:
        return self.is_internal | self.is_leaf


def _grow_one_tree(key: jax.Array, x: jax.Array, h: int):
    """Grow one tree over ``x: f32[S, F]``; returns local-feature-indexed arrays."""
    S, F = x.shape
    M = 2 ** (h + 1) - 1
    slots = jnp.arange(M, dtype=jnp.int32)
    level_keys = jax.random.split(key, h + 1)

    state = dict(
        node_id=jnp.zeros((S,), jnp.int32),
        settled=jnp.zeros((S,), jnp.bool_),
        feature=jnp.full((M,), -1, jnp.int32),
        threshold=jnp.zeros((M,), jnp.float32),
        num_instances=jnp.full((M,), -1, jnp.int32),
        exists=jnp.zeros((M,), jnp.bool_).at[0].set(True),
    )

    def level_step(l, st):
        k_feat, k_thr = jax.random.split(level_keys[l])

        # --- per-node statistics via masked scatter (out-of-bounds dropped) ---
        idx = jnp.where(st["settled"], M, st["node_id"])
        cnt = jnp.zeros((M,), jnp.int32).at[idx].add(1, mode="drop")
        minv = jnp.full((M, F), jnp.inf, jnp.float32).at[idx].min(x, mode="drop")
        maxv = jnp.full((M, F), -jnp.inf, jnp.float32).at[idx].max(x, mode="drop")

        level_start = (jnp.int32(1) << l) - 1
        in_level = (slots >= level_start) & (slots < 2 * level_start + 1)

        # --- split decision per level-l node (IsolationTree.scala:124-156) ---
        nonconst = minv < maxv  # [M, F]
        has_feature = jnp.any(nonconst, axis=1)
        can_split = (
            st["exists"] & in_level & (cnt > 1) & (l < h) & has_feature
        )

        # uniform choice among non-constant features == reference's retry loop
        gumbel = jax.random.gumbel(k_feat, (M, F), jnp.float32)
        choice = jnp.argmax(jnp.where(nonconst, gumbel, -jnp.inf), axis=1).astype(
            jnp.int32
        )
        mn = jnp.take_along_axis(minv, choice[:, None], axis=1)[:, 0]
        mx = jnp.take_along_axis(maxv, choice[:, None], axis=1)[:, 0]
        u = jax.random.uniform(k_thr, (M,), jnp.float32)
        thr = mn + u * (mx - mn)

        new_leaf = st["exists"] & in_level & ~can_split

        feature = jnp.where(can_split, choice, st["feature"])
        threshold = jnp.where(can_split, thr, st["threshold"])
        num_instances = jnp.where(new_leaf, cnt, st["num_instances"])

        # children of split nodes materialise at the next level
        child_l = jnp.where(can_split, 2 * slots + 1, M)
        child_r = jnp.where(can_split, 2 * slots + 2, M)
        exists = (
            st["exists"]
            .at[child_l].set(True, mode="drop")
            .at[child_r].set(True, mode="drop")
        )

        # --- route unsettled samples one level down (x < t left / >= right) ---
        nd = st["node_id"]
        split_here = can_split[nd] & ~st["settled"]
        f_s = feature[nd]
        go_right = (
            jnp.take_along_axis(x, jnp.maximum(f_s, 0)[:, None], axis=1)[:, 0]
            >= threshold[nd]
        )
        node_id = jnp.where(split_here, 2 * nd + 1 + go_right.astype(jnp.int32), nd)
        settled = st["settled"] | ~split_here

        return dict(
            node_id=node_id,
            settled=settled,
            feature=feature,
            threshold=threshold,
            num_instances=num_instances,
            exists=exists,
        )

    state = lax.fori_loop(0, h + 1, level_step, state)
    return state["feature"], state["threshold"], state["num_instances"]


def grow_forest(
    tree_keys: jax.Array,
    X: jax.Array,
    bag_idx: jax.Array,
    feat_idx: jax.Array,
    height: int,
) -> StandardForest:
    """Grow ``T`` standard isolation trees; ``vmap`` over the tree axis.

    ``tree_keys``: per-tree PRNG keys ``[T, ...]`` (see
    :func:`..bagging.per_tree_keys` — passed pre-derived so the tree axis can
    be sharded across devices with disjoint streams); ``X``: f32[N, F_total];
    ``bag_idx``: i32[T, S]; ``feat_idx``: i32[T, F_sub] sorted global feature
    ids; ``height`` static. Local split indices are mapped back to global
    feature ids so persisted ``splitAttribute`` matches the reference layout.
    """
    x_trees = gather_tree_data(X, bag_idx, feat_idx)  # [T, S, F_sub]
    feature_local, threshold, num_instances = jax.vmap(
        lambda k, x: _grow_one_tree(k, x, height)
    )(tree_keys, x_trees)

    feature_global = jnp.where(
        feature_local >= 0,
        jnp.take_along_axis(
            feat_idx, jnp.maximum(feature_local, 0), axis=1
        ),
        -1,
    ).astype(jnp.int32)
    return StandardForest(
        feature=feature_global,
        threshold=threshold,
        num_instances=num_instances,
    )
