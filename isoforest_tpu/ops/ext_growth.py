"""Extended isolation-tree growth (random hyperplane splits, Hariri et al. 2018).

Level-synchronous fixed-shape redesign of ``ExtendedIsolationTree.scala:112-260``,
sharing the implicit-heap layout of :mod:`.tree_growth`. Per split node:

  * ``k = min(extensionLevel + 1, dim)`` non-zero coordinates
    (ExtendedIsolationTree.scala:157), chosen as a random distinct subset,
    canonicalised sorted ascending (:220-226);
  * Gaussian weights on those coordinates, L2-normalised in float32
    (:169-195); an exactly-zero norm turns the node into a leaf (:183-184);
  * intercept point drawn per-coordinate uniform in the node's ``[min, max]``
    (``min == max`` degenerates to the constant), ``offset = sum(w_i * p_i)``
    (:201-217);
  * routing ``dot(x, w) < offset`` -> left (:230-232); **no retry on
    degenerate splits** — an empty side becomes a ``numInstances = 0`` leaf
    (ExtendedNodes.scala:32-35), which is exactly why ExtendedIF_0 differs
    statistically from StandardIF (reference README benchmark note).

Storage is the reference's sparse hyperplane form (``ExtendedUtils.scala:21-34``):
``indices`` int32[T, M, k] (sorted, ``-1`` marks leaves/non-existent slots) and
``weights`` float32[T, M, k], with float32 dots matching the reference's
float-cast dot (ExtendedUtils.scala:46-55).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from . import level_window as lw
from .bagging import gather_tree_data


class ExtendedForest(NamedTuple):
    """Struct-of-arrays EIF forest over ``[num_trees, max_nodes]`` heap slots."""

    indices: jax.Array  # i32 [T, M, k]; indices[..., 0] == -1 at leaves
    weights: jax.Array  # f32 [T, M, k]
    offset: jax.Array  # f32 [T, M]
    num_instances: jax.Array  # i32 [T, M]; leaf size, -1 internal/non-existent

    @property
    def num_trees(self) -> int:
        return self.indices.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.indices.shape[1]

    @property
    def k(self) -> int:
        return self.indices.shape[2]

    @property
    def is_internal(self) -> jax.Array:
        return self.indices[..., 0] >= 0

    @property
    def is_leaf(self) -> jax.Array:
        return self.num_instances >= 0

    @property
    def exists(self) -> jax.Array:
        return self.is_internal | self.is_leaf


def _grow_one_extended_tree(key: jax.Array, x: jax.Array, h: int, k_nonzero: int):
    """EIF single-tree growth with bounded per-level memory (shared
    :mod:`.level_window` scaffolding): the per-node uniform k-subset streams
    across feature chunks via a running Gumbel top-k, and per-node statistics
    are computed only at the k chosen coordinates via a per-sample gather ->
    [W, k] scatter — no [M, F] (or even [W, F]) transient anywhere."""
    S, F = x.shape
    M = 2 ** (h + 1) - 1
    W = 2**h
    geom = lw.chunk_features(x)
    x, Fc, pad, n_chunks = geom.x, geom.chunk, geom.pad, geom.n_chunks
    level_keys = jax.random.split(key, h + 1)

    state = dict(
        node_id=jnp.zeros((S,), jnp.int32),
        settled=jnp.zeros((S,), jnp.bool_),
        indices=jnp.full((M, k_nonzero), -1, jnp.int32),
        weights=jnp.zeros((M, k_nonzero), jnp.float32),
        offset=jnp.zeros((M,), jnp.float32),
        num_instances=jnp.full((M,), -1, jnp.int32),
        exists=jnp.zeros((M,), jnp.bool_).at[0].set(True),
    )

    def level_step(l, st):
        k_sub, k_w, k_p = jax.random.split(level_keys[l], 3)
        win = lw.level_window(l, W, st["node_id"], st["settled"])
        idx_w = win.idx_of_sample
        cnt = jnp.zeros((W,), jnp.int32).at[idx_w].add(1, mode="drop")

        # --- subspace choice per node: uniform k distinct coordinates
        # (ExtendedIsolationTree.scala:157-160) as a streaming Gumbel top-k
        # over feature chunks; padded columns draw -inf and are never picked
        best_g = jnp.full((W, k_nonzero), -jnp.inf, jnp.float32)
        best_i = jnp.zeros((W, k_nonzero), jnp.int32)
        for c in range(n_chunks):
            g = jax.random.gumbel(
                jax.random.fold_in(k_sub, c), (W, Fc), jnp.float32
            )
            if pad and c == n_chunks - 1:
                real = jnp.arange(Fc) < (F - c * Fc)
                g = jnp.where(real[None, :], g, -jnp.inf)
            cat_g = jnp.concatenate([best_g, g], axis=1)
            cat_i = jnp.concatenate(
                [
                    best_i,
                    jnp.broadcast_to(
                        c * Fc + jnp.arange(Fc, dtype=jnp.int32), (W, Fc)
                    ),
                ],
                axis=1,
            )
            best_g, top_pos = jax.lax.top_k(cat_g, k_nonzero)
            best_i = jnp.take_along_axis(cat_i, top_pos, axis=1)
        sub = jnp.sort(best_i, axis=1)  # canonical ascending (:220-226)

        # --- per-node stats ONLY at the chosen coordinates: gather each
        # sample's k values for its node's subspace, scatter-min/max [W, k]
        sub_of_sample = jnp.take(
            sub, jnp.clip(idx_w, 0, W - 1), axis=0
        )  # [S, k]
        xv_s = jnp.take_along_axis(x, sub_of_sample, axis=1)  # [S, k]
        mn = jnp.full((W, k_nonzero), jnp.inf, jnp.float32).at[idx_w].min(
            xv_s, mode="drop"
        )
        mx = jnp.full((W, k_nonzero), -jnp.inf, jnp.float32).at[idx_w].max(
            xv_s, mode="drop"
        )

        # --- hyperplane draw (ExtendedIsolationTree.scala:155-226) ---
        w = jax.random.normal(k_w, (W, k_nonzero), jnp.float32)
        nrm = jnp.sqrt(jnp.sum(w * w, axis=1))
        zero_norm = nrm == 0.0
        w = w / jnp.maximum(nrm, jnp.float32(1e-37))[:, None]

        # empty nodes have inf stats; mask so the offset math stays finite
        finite = cnt > 0
        mn = jnp.where(finite[:, None], mn, 0.0)
        mx = jnp.where(finite[:, None], mx, 0.0)
        u = jax.random.uniform(k_p, (W, k_nonzero), jnp.float32)
        p = mn + u * (mx - mn)
        off = jnp.sum(w * p, axis=1)

        exists_w = lw.window_slice(st["exists"], win.start, W)
        can_split = exists_w & win.in_level & (cnt > 1) & (l < h) & ~zero_norm
        new_leaf = exists_w & win.in_level & ~can_split

        indices = lw.patch(st["indices"], sub, can_split, win.start)
        weights = lw.patch(st["weights"], w, can_split, win.start)
        offset = lw.patch(st["offset"], off, can_split, win.start)
        num_instances = lw.patch(st["num_instances"], cnt, new_leaf, win.start)

        exists = lw.spawn_children(st["exists"], can_split, win.slots, M)

        # --- route: dot(x, w) < offset -> left (:230-232) ---
        nd = st["node_id"]
        j_s = jnp.clip(nd - win.start, 0, W - 1)
        split_here = jnp.take(can_split, j_s) & ~st["settled"]
        dot = jnp.sum(xv_s * jnp.take(w, j_s, axis=0), axis=1)
        go_right = dot >= jnp.take(off, j_s)
        node_id = jnp.where(split_here, 2 * nd + 1 + go_right.astype(jnp.int32), nd)
        settled = st["settled"] | ~split_here

        return dict(
            node_id=node_id,
            settled=settled,
            indices=indices,
            weights=weights,
            offset=offset,
            num_instances=num_instances,
            exists=exists,
        )

    state = lax.fori_loop(0, h + 1, level_step, state)
    return state["indices"], state["weights"], state["offset"], state["num_instances"]


def grow_extended_forest(
    tree_keys: jax.Array,
    X: jax.Array,
    bag_idx: jax.Array,
    feat_idx: jax.Array,
    height: int,
    extension_level: int,
) -> ExtendedForest:
    """Grow ``T`` extended isolation trees, ``vmap`` over the tree axis.

    ``tree_keys``: pre-derived per-tree PRNG keys (shardable along the tree
    axis). ``extension_level`` is the *resolved* level
    (ExtendedIsolationForest.scala:56-69); the per-split non-zero count is
    ``min(extension_level + 1, F_sub)``. Local subset coordinates are mapped
    back to global feature ids.
    """
    x_trees = gather_tree_data(X, bag_idx, feat_idx)  # [T, S, F_sub]
    num_trees, _, f_sub = x_trees.shape
    k_nonzero = min(extension_level + 1, f_sub)
    indices_local, weights, offset, num_instances = jax.vmap(
        lambda k, x: _grow_one_extended_tree(k, x, height, k_nonzero)
    )(tree_keys, x_trees)

    # map local subset coords -> global feature ids; keep -1 sentinels
    flat_local = jnp.maximum(indices_local, 0).reshape(num_trees, -1)
    flat_global = jnp.take_along_axis(feat_idx, flat_local, axis=1).reshape(
        indices_local.shape
    )
    indices_global = jnp.where(indices_local >= 0, flat_global, -1).astype(jnp.int32)
    return ExtendedForest(
        indices=indices_global,
        weights=weights,
        offset=offset,
        num_instances=num_instances,
    )


# jitted entry for block-wise checkpointed growth (models _blockwise_grow):
# same trace as `grow_extended_forest`, compiled once per block shape — call
# with height/extension_level as keywords
grow_extended_forest_block = functools.partial(
    jax.jit, static_argnames=("height", "extension_level")
)(grow_extended_forest)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_samples",
        "num_trees",
        "bootstrap",
        "num_features",
        "height",
        "extension_level",
    ),
)
def grow_extended_forest_fused(
    key: jax.Array,
    X: jax.Array,
    *,
    num_samples: int,
    num_trees: int,
    bootstrap: bool,
    num_features: int,
    height: int,
    extension_level: int,
) -> ExtendedForest:
    """Single-jit EIF fit program — same dispatch-fusion rationale and
    key-split order as :func:`..tree_growth.grow_forest_fused`."""
    from .bagging import bagged_indices, feature_subsets, per_tree_keys

    num_rows, num_features_total = X.shape
    k_bag, k_feat, k_grow = jax.random.split(key, 3)
    bag = bagged_indices(k_bag, num_rows, num_samples, num_trees, bootstrap)
    fidx = feature_subsets(k_feat, num_features_total, num_features, num_trees)
    tree_keys = per_tree_keys(k_grow, num_trees)
    return grow_extended_forest(tree_keys, X, bag, fidx, height, extension_level)
