"""Extended isolation-tree growth (random hyperplane splits, Hariri et al. 2018).

Level-synchronous fixed-shape redesign of ``ExtendedIsolationTree.scala:112-260``,
sharing the implicit-heap layout of :mod:`.tree_growth`. Per split node:

  * ``k = min(extensionLevel + 1, dim)`` non-zero coordinates
    (ExtendedIsolationTree.scala:157), chosen as a random distinct subset,
    canonicalised sorted ascending (:220-226);
  * Gaussian weights on those coordinates, L2-normalised in float32
    (:169-195); an exactly-zero norm turns the node into a leaf (:183-184);
  * intercept point drawn per-coordinate uniform in the node's ``[min, max]``
    (``min == max`` degenerates to the constant), ``offset = sum(w_i * p_i)``
    (:201-217);
  * routing ``dot(x, w) < offset`` -> left (:230-232); **no retry on
    degenerate splits** — an empty side becomes a ``numInstances = 0`` leaf
    (ExtendedNodes.scala:32-35), which is exactly why ExtendedIF_0 differs
    statistically from StandardIF (reference README benchmark note).

Storage is the reference's sparse hyperplane form (``ExtendedUtils.scala:21-34``):
``indices`` int32[T, M, k] (sorted, ``-1`` marks leaves/non-existent slots) and
``weights`` float32[T, M, k], with float32 dots matching the reference's
float-cast dot (ExtendedUtils.scala:46-55).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .bagging import gather_tree_data


class ExtendedForest(NamedTuple):
    """Struct-of-arrays EIF forest over ``[num_trees, max_nodes]`` heap slots."""

    indices: jax.Array  # i32 [T, M, k]; indices[..., 0] == -1 at leaves
    weights: jax.Array  # f32 [T, M, k]
    offset: jax.Array  # f32 [T, M]
    num_instances: jax.Array  # i32 [T, M]; leaf size, -1 internal/non-existent

    @property
    def num_trees(self) -> int:
        return self.indices.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.indices.shape[1]

    @property
    def k(self) -> int:
        return self.indices.shape[2]

    @property
    def is_internal(self) -> jax.Array:
        return self.indices[..., 0] >= 0

    @property
    def is_leaf(self) -> jax.Array:
        return self.num_instances >= 0

    @property
    def exists(self) -> jax.Array:
        return self.is_internal | self.is_leaf


def _grow_one_extended_tree(key: jax.Array, x: jax.Array, h: int, k_nonzero: int):
    S, F = x.shape
    M = 2 ** (h + 1) - 1
    slots = jnp.arange(M, dtype=jnp.int32)
    level_keys = jax.random.split(key, h + 1)

    state = dict(
        node_id=jnp.zeros((S,), jnp.int32),
        settled=jnp.zeros((S,), jnp.bool_),
        indices=jnp.full((M, k_nonzero), -1, jnp.int32),
        weights=jnp.zeros((M, k_nonzero), jnp.float32),
        offset=jnp.zeros((M,), jnp.float32),
        num_instances=jnp.full((M,), -1, jnp.int32),
        exists=jnp.zeros((M,), jnp.bool_).at[0].set(True),
    )

    def level_step(l, st):
        k_sub, k_w, k_p = jax.random.split(level_keys[l], 3)

        idx = jnp.where(st["settled"], M, st["node_id"])
        cnt = jnp.zeros((M,), jnp.int32).at[idx].add(1, mode="drop")
        minv = jnp.full((M, F), jnp.inf, jnp.float32).at[idx].min(x, mode="drop")
        maxv = jnp.full((M, F), -jnp.inf, jnp.float32).at[idx].max(x, mode="drop")

        level_start = (jnp.int32(1) << l) - 1
        in_level = (slots >= level_start) & (slots < 2 * level_start + 1)

        # --- hyperplane draw per node (ExtendedIsolationTree.scala:155-226) ---
        node_keys = jax.random.split(k_sub, M)
        perm = jax.vmap(lambda kk: jax.random.permutation(kk, F))(node_keys)
        sub = jnp.sort(perm[:, :k_nonzero], axis=1).astype(jnp.int32)  # [M, k]

        w = jax.random.normal(k_w, (M, k_nonzero), jnp.float32)
        nrm = jnp.sqrt(jnp.sum(w * w, axis=1))
        zero_norm = nrm == 0.0
        w = w / jnp.maximum(nrm, jnp.float32(1e-37))[:, None]

        mn = jnp.take_along_axis(minv, sub, axis=1)
        mx = jnp.take_along_axis(maxv, sub, axis=1)
        # empty nodes have inf stats; mask so the offset math stays finite
        finite = cnt > 0
        mn = jnp.where(finite[:, None], mn, 0.0)
        mx = jnp.where(finite[:, None], mx, 0.0)
        u = jax.random.uniform(k_p, (M, k_nonzero), jnp.float32)
        p = mn + u * (mx - mn)
        off = jnp.sum(w * p, axis=1)

        can_split = st["exists"] & in_level & (cnt > 1) & (l < h) & ~zero_norm
        new_leaf = st["exists"] & in_level & ~can_split

        indices = jnp.where(can_split[:, None], sub, st["indices"])
        weights = jnp.where(can_split[:, None], w, st["weights"])
        offset = jnp.where(can_split, off, st["offset"])
        num_instances = jnp.where(new_leaf, cnt, st["num_instances"])

        child_l = jnp.where(can_split, 2 * slots + 1, M)
        child_r = jnp.where(can_split, 2 * slots + 2, M)
        exists = (
            st["exists"]
            .at[child_l].set(True, mode="drop")
            .at[child_r].set(True, mode="drop")
        )

        # --- route: dot(x, w) < offset -> left (:230-232) ---
        nd = st["node_id"]
        split_here = can_split[nd] & ~st["settled"]
        sub_s = jnp.maximum(indices[nd], 0)  # [S, k]
        xv = jnp.take_along_axis(x, sub_s, axis=1)
        dot = jnp.sum(xv * weights[nd], axis=1)
        go_right = dot >= offset[nd]
        node_id = jnp.where(split_here, 2 * nd + 1 + go_right.astype(jnp.int32), nd)
        settled = st["settled"] | ~split_here

        return dict(
            node_id=node_id,
            settled=settled,
            indices=indices,
            weights=weights,
            offset=offset,
            num_instances=num_instances,
            exists=exists,
        )

    state = lax.fori_loop(0, h + 1, level_step, state)
    return state["indices"], state["weights"], state["offset"], state["num_instances"]


def grow_extended_forest(
    tree_keys: jax.Array,
    X: jax.Array,
    bag_idx: jax.Array,
    feat_idx: jax.Array,
    height: int,
    extension_level: int,
) -> ExtendedForest:
    """Grow ``T`` extended isolation trees, ``vmap`` over the tree axis.

    ``tree_keys``: pre-derived per-tree PRNG keys (shardable along the tree
    axis). ``extension_level`` is the *resolved* level
    (ExtendedIsolationForest.scala:56-69); the per-split non-zero count is
    ``min(extension_level + 1, F_sub)``. Local subset coordinates are mapped
    back to global feature ids.
    """
    x_trees = gather_tree_data(X, bag_idx, feat_idx)  # [T, S, F_sub]
    num_trees, _, f_sub = x_trees.shape
    k_nonzero = min(extension_level + 1, f_sub)
    indices_local, weights, offset, num_instances = jax.vmap(
        lambda k, x: _grow_one_extended_tree(k, x, height, k_nonzero)
    )(tree_keys, x_trees)

    # map local subset coords -> global feature ids; keep -1 sentinels
    flat_local = jnp.maximum(indices_local, 0).reshape(num_trees, -1)
    flat_global = jnp.take_along_axis(feat_idx, flat_local, axis=1).reshape(
        indices_local.shape
    )
    indices_global = jnp.where(indices_local >= 0, flat_global, -1).astype(jnp.int32)
    return ExtendedForest(
        indices=indices_global,
        weights=weights,
        offset=offset,
        num_instances=num_instances,
    )
