"""O(h) Pallas TPU scoring kernel: a true node-id walk via ``tpu.dynamic_gather``.

The dense kernels (:mod:`.dense_traversal`, :mod:`.pallas_traversal`) tolerate
a ~64x algorithmic overhead — every row visits all ``M = 2^(h+1)-1`` heap
slots per tree (511 at the default ``maxSamples=256``) versus the reference
pointer walk's ``h+1`` visits (``IsolationTree.scala:213-229``) — because
XLA's per-lane gathers serialise on TPU (measured: gather 15.1 s vs dense
0.63 s at 1M rows on a live v5e). This kernel gets the walk's O(h) work
profile *without* XLA gathers by mapping the walk onto Mosaic's
``tpu.dynamic_gather`` primitive, which is a full-width per-lane VMEM table
lookup but only spans ONE vreg (128 lanes / 8 sublanes) along the gathered
dimension.

Layout that makes every lookup single-vreg:

* **rows ride lanes** in groups of 128, **trees ride sublanes** in blocks
  of 8 — so one ``[8, 128]`` vreg holds (8 trees x 128 rows) of walk state;
* node tables are **level-major** ("walk layout"): level ``l`` occupies
  ``max(1, 2^l/128)`` 128-lane chunks, nodes within a level in the
  level-concat order of :func:`.pallas_traversal._concat_order` (left
  children first, then right children), so the in-level position update is
  ``p' = p + go_right * 2^l`` — pure int vector math, no pointer chase;
* per level, the current node's threshold / feature / leaf value are ONE
  lane-gather each (plus a select chain over chunks once levels exceed 128
  nodes), and the row's feature value is ONE sublane-gather from the
  transposed ``[8, 128]`` X tile (features on sublanes).

Work per (row, tree): ~8 vector-element ops per level, ~70 for the default
h=8 forest — against the dense walk's ~6,600. The grid is rows-major /
trees-MINOR: each row tile's partial-score block accumulates over
consecutive grid steps (the revisit pattern the shipped dense-pallas kernel
already proves on the remote toolchain) while the small ``[8, L]`` node
tables re-stream per step (~123 KB — ~2 ms of HBM traffic over the 1M-row
headline) and the X tile stays resident across each tree sweep.

The extended variant replaces the feature lookup with ``k`` sublane-gathers
and an f32 multiply-add reduction — **no matmul anywhere**, so it runs at
full f32 precision and is not subject to the bf16-mantissa precision fence
that gates :mod:`.pallas_traversal`'s EIF kernels on the remote Mosaic
toolchain (the fence exists because their hyperplane *matmuls* reject
``Precision.HIGHEST`` there; reference semantics: f32-cast dot,
``ExtendedUtils.scala:46-55``). One bounded caveat: on tie-heavy quantized
data, exact ``dot == offset`` ties can round 1 ulp differently here than
under growth's own XLA reduce and route to the other child — the same
deviation class the native C++ walker already carries; see PARITY.md and
``TestQuantizedTieRouting``.

Correctness is pinned against the gather/dense paths in interpret mode (CI,
CPU) and by the chipless Mosaic machine-compile gate
(``tests/mosaic_aot_worker.py``). Select on TPU via
``score_matrix(strategy="walk")`` or ``ISOFOREST_TPU_STRATEGY=walk``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU memory spaces are unavailable when lowering for CPU interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from ..utils.math import height_of as _height_of, leaf_value_table
from .pallas_traversal import _cached_prep, _concat_order
from .tree_growth import StandardForest

_LANES = 128
_SUBLANES = 8
# Row groups of 128 lanes processed per grid step: 8 keeps the X tile at the
# proven 1024-lane block size and divides the grid-step count (and its
# per-step overhead) by 8.
_ROW_GROUPS = 8
_ROW_TILE = _ROW_GROUPS * _LANES
# Beyond this many hyperplane coordinates the per-level gather+fma chain
# approaches the dense kernels' matmul cost; larger k dispatches elsewhere.
_WALK_K_MAX = 16
# VMEM budget for the per-grid-step node tables. The standard kernel holds
# 3 [8, L] f32 tables, the EIF kernel (2 + 2k) L-lane planes — L grows
# ~2^h/128 lanes past h=7, so a deep forest with a wide k (e.g. k=16, h=12:
# (2+32) * 8 * 8960 * 4 B ~ 9.7 MB) exceeds what fits next to the X tile
# and the Mosaic allocator fails the compile outright. Route such forests
# to dense instead (score_matrix warns once). 4 MB leaves headroom for the
# X tile and double-buffering within a ~16 MB/core VMEM.
_WALK_TABLE_BYTES_MAX = 4 * 1024 * 1024


@functools.lru_cache(maxsize=None)
def _level_layout(h: int) -> tuple[tuple[int, ...], tuple[int, ...], int]:
    """Walk-layout geometry: per-level lane offsets, per-level 128-lane chunk
    counts, and the total padded lane count ``L``."""
    offs, chunks, off = [], [], 0
    for level in range(h + 1):
        c = max(1, (1 << level) >> 7)
        offs.append(off)
        chunks.append(c)
        off += _LANES * c
    return tuple(offs), tuple(chunks), off


def _pad_trees(arr: np.ndarray, fill) -> np.ndarray:
    """Pad the tree axis up to a sublane multiple; padded trees contribute 0
    to every walk (leaf table 0 everywhere)."""
    t = arr.shape[0]
    t_pad = -t % _SUBLANES
    if not t_pad:
        return arr
    pad = np.full((t_pad,) + arr.shape[1:], fill, arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def _to_walk_layout(arr_heap: np.ndarray, h: int, fill) -> np.ndarray:
    """[T, M] heap-order table -> [T, L] level-major walk layout.

    Level ``l``'s nodes sit at lanes ``offs[l] + p`` with ``p`` the in-level
    position in concat order (:func:`.pallas_traversal._concat_order`); lanes
    past the level width are ``fill``."""
    t, m = arr_heap.shape
    offs, _, L = _level_layout(h)
    order = list(_concat_order(m))
    out = np.full((t, L), fill, arr_heap.dtype)
    pos = 0
    for level in range(h + 1):
        w = 1 << level
        ids = order[pos : pos + w]
        pos += w
        out[:, offs[level] : offs[level] + w] = arr_heap[:, ids]
    return out


def walk_tables_standard(forest: StandardForest, h: int):
    """Walk-layout node tables ``(threshold, feature, leaf_value)``, each
    ``[T_pad8, L]``. Non-internal slots (leaves, holes below leaves, padding)
    carry ``threshold=+inf`` (compare is always "go left", keeping the walk
    on the hole chain under a leaf), ``feature=0`` (a safe gather index) and
    the leaf-value table's 0 — so exactly one visited slot per (row, tree)
    contributes, the exit leaf's ``depth + c(numInstances)``."""
    feat_heap = np.asarray(forest.feature, np.int32)
    internal = feat_heap >= 0
    thr = np.where(
        internal, np.asarray(forest.threshold, np.float32), np.inf
    ).astype(np.float32)
    feat = np.maximum(feat_heap, 0).astype(np.int32)
    leaf = leaf_value_table(np.asarray(forest.num_instances), h)
    return (
        jnp.asarray(_pad_trees(_to_walk_layout(thr, h, np.inf), np.inf)),
        jnp.asarray(_pad_trees(_to_walk_layout(feat, h, 0), 0)),
        jnp.asarray(_pad_trees(_to_walk_layout(leaf, h, 0.0), 0.0)),
    )


def walk_tables_extended(forest, h: int):
    """Walk-layout EIF tables ``(offset, idx_packed, w_packed, leaf_value)``.

    The ``k`` hyperplane coordinate/weight planes are packed lane-wise into
    single 2-D arrays ``[T_pad8, k*L]`` (plane ``q`` at lane offset ``q*L``)
    so the kernel takes static 128-lane slices of plain 2-D refs — no 3-D
    block shapes for Mosaic to relayout. Missing coordinates (leaves, holes,
    sparse padding) carry index 0 / weight 0 and contribute nothing to the
    dot; offset ``+inf`` keeps sub-leaf walks on the hole chain."""
    indices = np.asarray(forest.indices, np.int32)  # [T, M, k]
    weights = np.asarray(forest.weights, np.float32)
    internal = indices[:, :, 0] >= 0
    off = np.where(
        internal, np.asarray(forest.offset, np.float32), np.inf
    ).astype(np.float32)
    leaf = leaf_value_table(np.asarray(forest.num_instances), h)
    k = indices.shape[2]
    idx_planes = [
        _to_walk_layout(np.maximum(indices[:, :, q], 0).astype(np.int32), h, 0)
        for q in range(k)
    ]
    w_planes = [
        _to_walk_layout(
            np.where(indices[:, :, q] >= 0, weights[:, :, q], 0.0).astype(
                np.float32
            ),
            h,
            0.0,
        )
        for q in range(k)
    ]
    return (
        jnp.asarray(_pad_trees(_to_walk_layout(off, h, np.inf), np.inf)),
        jnp.asarray(_pad_trees(np.concatenate(idx_planes, axis=1), 0)),
        jnp.asarray(_pad_trees(np.concatenate(w_planes, axis=1), 0.0)),
        jnp.asarray(_pad_trees(_to_walk_layout(leaf, h, 0.0), 0.0)),
    )


def _lookup(ref, p, base: int, chunks: int, dtype):
    """Value of table ``ref`` at in-level position ``p`` — one
    ``tpu.dynamic_gather`` per 128-lane chunk, selected by ``p``'s high bits
    when the level spans several chunks."""
    if chunks == 1:
        tbl = ref[:, base : base + _LANES]
        return jnp.take_along_axis(tbl, p, axis=1, mode="promise_in_bounds")
    p_lo = jnp.bitwise_and(p, _LANES - 1)
    p_hi = jnp.right_shift(p, 7)
    acc = jnp.zeros((_SUBLANES, _LANES), dtype)
    for c in range(chunks):
        tbl = ref[:, base + c * _LANES : base + (c + 1) * _LANES]
        g = jnp.take_along_axis(tbl, p_lo, axis=1, mode="promise_in_bounds")
        acc = jnp.where(p_hi == c, g, acc)
    return acc


def _gather_feature(x_tile, feat_at, fchunks: int):
    """Row feature values ``x[row, feat_at]`` — a sublane dynamic_gather per
    8-feature chunk of the transposed X tile."""
    if fchunks == 1:
        return jnp.take_along_axis(
            x_tile, feat_at, axis=0, mode="promise_in_bounds"
        )
    f_lo = jnp.bitwise_and(feat_at, _SUBLANES - 1)
    f_hi = jnp.right_shift(feat_at, 3)
    acc = jnp.zeros((_SUBLANES, _LANES), jnp.float32)
    for fc in range(fchunks):
        xc = x_tile[fc * _SUBLANES : (fc + 1) * _SUBLANES, :]
        g = jnp.take_along_axis(xc, f_lo, axis=0, mode="promise_in_bounds")
        acc = jnp.where(f_hi == fc, g, acc)
    return acc


def _accumulate(tb, out_ref, res):
    @pl.when(tb == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += res


def _standard_walk_kernel(h, fchunks, xt_ref, thr_ref, feat_ref, leaf_ref, out_ref):
    tb = pl.program_id(1)
    offs, chunks, _ = _level_layout(h)
    x_all = xt_ref[...]  # [fchunks*8, ROW_TILE]
    parts = []
    for r in range(_ROW_GROUPS):
        x_tile = x_all[:, r * _LANES : (r + 1) * _LANES]
        p = jnp.zeros((_SUBLANES, _LANES), jnp.int32)
        total = jnp.zeros((_SUBLANES, _LANES), jnp.float32)
        for level in range(h + 1):
            total = total + _lookup(
                leaf_ref, p, offs[level], chunks[level], jnp.float32
            )
            if level < h:
                thr_at = _lookup(thr_ref, p, offs[level], chunks[level], jnp.float32)
                feat_at = _lookup(feat_ref, p, offs[level], chunks[level], jnp.int32)
                x_at = _gather_feature(x_tile, feat_at, fchunks)
                go_right = (x_at >= thr_at).astype(jnp.int32)
                p = p + go_right * (1 << level)
        parts.append(jnp.sum(total, axis=0, keepdims=True))  # [1, 128]
    _accumulate(tb, out_ref, jnp.concatenate(parts, axis=1))


def _extended_walk_kernel(
    h, fchunks, k, L, xt_ref, off_ref, idx_ref, w_ref, leaf_ref, out_ref
):
    tb = pl.program_id(1)
    offs, chunks, _ = _level_layout(h)
    x_all = xt_ref[...]
    parts = []
    for r in range(_ROW_GROUPS):
        x_tile = x_all[:, r * _LANES : (r + 1) * _LANES]
        p = jnp.zeros((_SUBLANES, _LANES), jnp.int32)
        total = jnp.zeros((_SUBLANES, _LANES), jnp.float32)
        for level in range(h + 1):
            total = total + _lookup(
                leaf_ref, p, offs[level], chunks[level], jnp.float32
            )
            if level < h:
                off_at = _lookup(off_ref, p, offs[level], chunks[level], jnp.float32)
                # Accumulate the hyperplane dot as jnp.sum over stacked
                # products — the same formulation growth (`ext_growth`) and
                # the gather path use. This is load-bearing on tie-heavy
                # quantized data: a constant coordinate makes the intercept
                # term bit-equal to every in-node row's term, so
                # dot == offset EXACTLY iff scoring rounds like growth did;
                # a sequential fold here landed 1 ulp low and flipped ~30%
                # of mammography rows into empty-leaf short-circuits
                # (measured round 5; ExtendedIsolationTree.scala:201-217 is
                # where the reference inherits the same tie structure).
                terms = []
                for q in range(k):
                    base = q * L + offs[level]
                    iq = _lookup(idx_ref, p, base, chunks[level], jnp.int32)
                    wq = _lookup(w_ref, p, base, chunks[level], jnp.float32)
                    terms.append(_gather_feature(x_tile, iq, fchunks) * wq)
                dot = jnp.sum(jnp.stack(terms, axis=0), axis=0)
                # dot >= offset -> right (ExtendedIsolationTree.scala:230-232
                # partitions dot < offset left), f32 exactly like the gather
                # path — no matmul, no bf16 mantissa loss
                go_right = (dot >= off_at).astype(jnp.int32)
                p = p + go_right * (1 << level)
        parts.append(jnp.sum(total, axis=0, keepdims=True))
    _accumulate(tb, out_ref, jnp.concatenate(parts, axis=1))


def _vmem_spec(block_shape, index_map):
    kw = {"memory_space": _VMEM} if _VMEM is not None else {}
    return pl.BlockSpec(block_shape, index_map, **kw)


@functools.partial(jax.jit, static_argnames=("h", "f_raw", "interpret"))
def _standard_walk(X, thr, feat, leaf, h, f_raw, interpret=False):
    """Path-length SUM over all trees for padded ``X [Np, F]``; caller
    divides by the real tree count. Transpose to feature-major happens here,
    on device, so callers keep the natural row-major layout."""
    n_pad, _ = X.shape
    f8 = -(-f_raw // _SUBLANES) * _SUBLANES
    XT = jnp.pad(X, ((0, 0), (0, f8 - f_raw))).T  # [f8, Np]
    t_pad, L = thr.shape
    # Tree blocks MINOR: the out block at (rc) is revisited in CONSECUTIVE
    # grid steps — the accumulation pattern the shipped dense-pallas kernel
    # already proves on the remote Mosaic toolchain. The cost is
    # re-streaming the [8, L] tables per step (~123 KB; ~1.6 GB over the 1M
    # headline, ~2 ms at HBM rate) while the X tile stays resident across
    # each row tile's tree sweep — cheap insurance against an unproven
    # non-consecutive-revisit pattern on chip.
    grid = (n_pad // _ROW_TILE, t_pad // _SUBLANES)
    table = _vmem_spec((_SUBLANES, L), lambda rc, tb: (tb, 0))
    out = pl.pallas_call(
        functools.partial(_standard_walk_kernel, h, f8 // _SUBLANES),
        grid=grid,
        in_specs=[
            _vmem_spec((f8, _ROW_TILE), lambda rc, tb: (0, rc)),
            table,
            table,
            table,
        ],
        out_specs=_vmem_spec((1, _ROW_TILE), lambda rc, tb: (0, rc)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        interpret=interpret,
    )(XT, thr, feat, leaf)
    return out[0]


@functools.partial(jax.jit, static_argnames=("h", "f_raw", "k", "interpret"))
def _extended_walk(X, off, idx_packed, w_packed, leaf, h, f_raw, k, interpret=False):
    n_pad, _ = X.shape
    f8 = -(-f_raw // _SUBLANES) * _SUBLANES
    XT = jnp.pad(X, ((0, 0), (0, f8 - f_raw))).T
    t_pad, L = off.shape
    # trees minor for consecutive out-block accumulation (see _standard_walk)
    grid = (n_pad // _ROW_TILE, t_pad // _SUBLANES)
    table = _vmem_spec((_SUBLANES, L), lambda rc, tb: (tb, 0))
    packed = _vmem_spec((_SUBLANES, k * L), lambda rc, tb: (tb, 0))
    out = pl.pallas_call(
        functools.partial(_extended_walk_kernel, h, f8 // _SUBLANES, k, L),
        grid=grid,
        in_specs=[
            _vmem_spec((f8, _ROW_TILE), lambda rc, tb: (0, rc)),
            table,
            packed,
            packed,
            table,
        ],
        out_specs=_vmem_spec((1, _ROW_TILE), lambda rc, tb: (0, rc)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        interpret=interpret,
    )(XT, off, idx_packed, w_packed, leaf)
    return out[0]


def _table_bytes(forest) -> int:
    """Per-grid-step VMEM footprint of the walk-layout node tables, in bytes."""
    h = _height_of(forest.max_nodes)
    _, _, L = _level_layout(h)
    if isinstance(forest, StandardForest):
        planes = 3  # threshold, feature, leaf
    else:
        planes = 2 + 2 * forest.indices.shape[2]  # offset, leaf, k idx + k w
    return planes * _SUBLANES * L * 4


def unsupported_reason(forest) -> str | None:
    """Why the walk kernel cannot cover this forest (``None`` = supported).

    Two fences: EIF hyperplanes beyond ``_WALK_K_MAX`` coordinates (the
    gather+fma chain stops paying vs the dense matmul), and node tables past
    ``_WALK_TABLE_BYTES_MAX`` (the per-step [8, L] planes would not fit
    VMEM and Mosaic compilation fails, rather than degrades)."""
    if not isinstance(forest, StandardForest):
        k = forest.indices.shape[2]
        if k > _WALK_K_MAX:
            return f"EIF hyperplane k={k} exceeds the kernel's k<={_WALK_K_MAX}"
    bytes_needed = _table_bytes(forest)
    if bytes_needed > _WALK_TABLE_BYTES_MAX:
        return (
            f"walk-layout node tables need {bytes_needed} B of VMEM per grid "
            f"step (height {_height_of(forest.max_nodes)}), over the "
            f"{_WALK_TABLE_BYTES_MAX} B budget"
        )
    return None


def supports(forest) -> bool:
    """Whether the walk kernel covers this forest (see
    :func:`unsupported_reason` for the specific fence)."""
    return unsupported_reason(forest) is None


def path_lengths_walk(forest, X, interpret: bool = False) -> jax.Array:
    """Mean path lengths via the O(h) dynamic-gather walk kernel. Rows are
    padded to the 1024-lane tile internally; pass ``interpret=True`` off-TPU."""
    X = jnp.asarray(X, jnp.float32)
    n, f_raw = X.shape
    pad = (-n) % _ROW_TILE
    if pad:
        X = jnp.pad(X, ((0, pad), (0, 0)))
    h = _height_of(forest.max_nodes)
    t_real = forest.num_instances.shape[0]
    if isinstance(forest, StandardForest):
        thr, feat, leaf = _cached_prep(
            forest, lambda: walk_tables_standard(forest, h), extra_key=("walk",)
        )
        out = _standard_walk(X, thr, feat, leaf, h, f_raw, interpret=interpret)
    else:
        k = forest.indices.shape[2]
        if k > _WALK_K_MAX:
            raise ValueError(
                f"walk kernel supports k <= {_WALK_K_MAX} hyperplane "
                f"coordinates, got {k}; use the dense/pallas strategies"
            )
        off, idx_packed, w_packed, leaf = _cached_prep(
            forest, lambda: walk_tables_extended(forest, h), extra_key=("walk",)
        )
        out = _extended_walk(
            X, off, idx_packed, w_packed, leaf, h, f_raw, k, interpret=interpret
        )
    return out[:n] / jnp.float32(t_real)
