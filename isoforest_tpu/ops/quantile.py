"""Quantile / contamination-threshold computation.

The reference sets the model threshold as
``approxQuantile(scores, 1 - contamination, contaminationError)`` — Spark's
Greenwald-Khanna sketch, which returns an *actual element* of the score column
whose rank error is at most ``contaminationError * N``; ``error = 0`` means
exact (``core/SharedTrainLogic.scala:187-197``). Two TPU-native paths:

  * exact: full device sort (XLA sort is a single fused program) and a rank
    pick — used whenever the scores fit on device, regardless of
    ``contaminationError`` (an exact answer always satisfies the approximate
    contract);
  * sketched: a psum-able fixed-width histogram honoring the rank-error
    contract, for row-sharded multi-host scoring where gathering all scores is
    undesirable (SURVEY.md §5.8 replacement for distributed approxQuantile).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def exact_quantile(scores, q: float) -> float:
    """Element of ``scores`` at rank ``ceil(q * N) - 1`` (clamped), like an
    exact Greenwald-Khanna query: returns a sample element, no interpolation."""
    scores = jnp.asarray(scores)
    n = scores.shape[0]
    rank = min(max(int(np.ceil(q * n)) - 1, 0), n - 1)
    return float(jnp.sort(scores)[rank])


def _f32_resolution(lo: float, hi: float) -> float:
    """Width below which a ``[lo, hi)`` interval cannot separate two distinct
    float32 values — further refinement is a no-op (any remaining bin
    population is a single representable value, i.e. rank error 0)."""
    scale = max(abs(lo), abs(hi), np.finfo(np.float32).tiny)
    return float(scale * 2.0 ** (-24))


def histogram_quantile(
    scores,
    q: float,
    num_bins: int = 1 << 14,
    lo: float | None = None,
    hi: float | None = None,
    eps: float = 1e-3,
    max_passes: int = 24,
) -> float:
    """Iteratively-refined histogram quantile returning an **actual element**.

    Matches the Greenwald-Khanna contract of Spark's ``approxQuantile``
    (``core/SharedTrainLogic.scala:195-197``): the result is a member of
    ``scores`` whose rank is within ``eps * N`` of ``ceil(q*N)``, over an
    **arbitrary value range** — ``[lo, hi]`` defaults to the observed
    min/max. Each pass histograms the scores over the current range, locates
    the bin containing the target rank, and narrows to that bin. The pass
    count is adaptive: refinement continues until the target bin's population
    is within the rank budget (so even a range inflated by a lone extreme
    outlier — heavy-tailed score columns are the norm in anomaly detection —
    converges; each pass shrinks the bin ``num_bins``-fold) or the bin is below
    float32 resolution (tie-heavy data; rank error 0). ``max_passes=24``
    covers the FULL f32 dynamic range (~84 decades at ~4 decades/pass;
    fuzz-caught r5: 12 passes exhausted on a {~-1e-29, 0, ~1e21} column
    one pass short of separating the near-zero tie class, returning an
    element 2 ranks off). The final answer snaps
    to the smallest score ≥ the bin's lower edge, so the returned value is
    always an element of the input. This is the eager/host-driven variant
    (Python loop, host scalars) — it cannot run under jit/shard_map; use
    :func:`histogram_quantile_jit` inside compiled or distributed programs.

    Limitation: subnormal inputs may flush to zero (XLA FTZ); anomaly
    scores live in (0, 1] and are never subnormal.
    """
    scores = jnp.asarray(scores, jnp.float32)
    n = scores.shape[0]
    if lo is None:
        lo = float(jnp.min(scores))
    if hi is None:
        hi = float(jnp.max(scores))
    target = max(int(np.ceil(q * n)), 1)
    rank_budget = max(int(eps * n), 1)
    for _ in range(max_passes):
        width = hi - lo
        if width <= 0:
            break
        rel = jnp.floor((scores - lo) / width * num_bins)
        bins = jnp.clip(rel, -1, num_bins).astype(jnp.int32)
        # the last bin is right-CLOSED: every score <= the current hi must
        # land inside the histogram, not the overflow bucket. Equality alone
        # is not enough — with a huge range the f32 division can round
        # (score - lo) / width up to 1.0 for scores strictly below hi (e.g.
        # lo=-2^25, scores {0, 1} — fuzz-caught), silently understating the
        # chosen bin's population and breaking the rank-error contract.
        bins = jnp.where(scores <= hi, jnp.minimum(bins, num_bins - 1), bins)
        # slot 0 counts scores strictly below lo; one scatter, one transfer
        all_counts = np.asarray(
            jnp.zeros((num_bins + 2,), jnp.int32).at[bins + 1].add(1)
        )
        counts = all_counts[1 : num_bins + 1]
        cum = all_counts[0] + np.cumsum(counts)
        idx = min(int(np.searchsorted(cum, target)), num_bins - 1)
        # Conservative ONE-BIN widening around the target bin (fuzz-caught
        # r5): the f32 bin assignment can place a score one bin away from
        # where the recomputed (higher-precision) edges say it belongs — a
        # zero was binned into a window whose edges evaluated to
        # [27.9, 72984), and the next pass narrowed to an empty range that
        # excluded the true median entirely. Refining to bins
        # [idx-1, idx+1] keeps every possibly-misplaced element inside the
        # range; the shrink per pass is still num_bins/3.
        lo_i = max(idx - 1, 0)
        hi_i = min(idx + 1, num_bins - 1)
        # the bottom/top bins keep the exact lo/hi: recomputing them as
        # lo + k*width/num_bins re-rounds in float and can EXCLUDE the true
        # extremes (e.g. hi=1 with lo=-2^53 gives lo + width == 0) — fuzz-caught
        new_lo = lo if lo_i == 0 else lo + lo_i * width / num_bins
        new_hi = hi if hi_i == num_bins - 1 else lo + (hi_i + 1) * width / num_bins
        window = int(cum[hi_i] - (cum[lo_i - 1] if lo_i > 0 else all_counts[0]))
        lo, hi = new_lo, new_hi
        # Adaptive stop: once the refined window holds <= eps*N elements
        # every element in it satisfies the rank budget; the
        # float-resolution check stops tie-heavy bins that can never thin
        # out (rank error 0 there).
        if window <= rank_budget or (hi - lo) <= _f32_resolution(lo, hi):
            break
    # Snap to an actual element: smallest score >= the refined lower edge.
    return float(jnp.min(jnp.where(scores >= lo, scores, jnp.inf)))


def histogram_quantile_jit(
    scores,
    q: float,
    num_bins: int = 8192,
    eps: float = 1e-3,
    max_passes: int = 24,
    lo=None,
    hi=None,
):
    """Traceable (jit/shard_map-friendly) refined histogram quantile.

    Same adaptive algorithm and element-of-input contract as
    :func:`histogram_quantile`, but every step is a jax op so it composes into
    a fused distributed program: under GSPMD, the initial min/max, each pass's
    scatter-add histogram, and the final element snap reduce with
    psum/pmin-shaped collectives while the score vector stays row-sharded —
    no global gather/sort. The refinement runs as a ``while_loop`` bounded by
    ``max_passes``, exiting early once the target bin's population fits the
    ``eps * N`` rank budget or the bin width falls below float32 resolution,
    so outlier-inflated ranges converge instead of exhausting a fixed pass
    count.
    """
    import jax.lax as lax

    scores = jnp.asarray(scores, jnp.float32)
    n = scores.shape[0]
    target = jnp.maximum(jnp.ceil(q * n), 1.0).astype(jnp.int32)
    rank_budget = jnp.maximum(jnp.int32(eps * n), 1)
    lo0 = jnp.min(scores) if lo is None else jnp.float32(lo)
    hi0 = jnp.max(scores) if hi is None else jnp.float32(hi)

    def resolution(lo_c, hi_c):
        scale = jnp.maximum(
            jnp.maximum(jnp.abs(lo_c), jnp.abs(hi_c)),
            jnp.float32(np.finfo(np.float32).tiny),
        )
        return scale * jnp.float32(2.0 ** (-24))

    def cond(state):
        lo_c, hi_c, bin_count, passes = state
        return (
            (passes < max_passes)
            & (bin_count > rank_budget)
            & ((hi_c - lo_c) > resolution(lo_c, hi_c))
        )

    def body(state):
        lo_c, hi_c, _, passes = state
        width = jnp.maximum(hi_c - lo_c, jnp.float32(np.finfo(np.float32).tiny))
        rel = jnp.floor((scores - lo_c) / width * num_bins)
        bins = jnp.clip(rel, -1, num_bins).astype(jnp.int32)
        # right-closed last bin incl. scores that ROUND up to rel == num_bins
        # (see the eager variant; fuzz-caught)
        bins = jnp.where(scores <= hi_c, jnp.minimum(bins, num_bins - 1), bins)
        counts = jnp.zeros((num_bins + 2,), jnp.int32).at[bins + 1].add(1)
        cum = counts[0] + jnp.cumsum(counts[1 : num_bins + 1])
        idx = jnp.clip(jnp.searchsorted(cum, target), 0, num_bins - 1)
        # conservative one-bin widening + exact bottom/top edges — same
        # f32-misplacement reasoning as the eager variant (fuzz-caught r5)
        lo_i = jnp.maximum(idx - 1, 0)
        hi_i = jnp.minimum(idx + 1, num_bins - 1)
        new_lo = jnp.where(
            lo_i == 0, lo_c, lo_c + lo_i.astype(jnp.float32) * width / num_bins
        )
        new_hi = jnp.where(
            hi_i == num_bins - 1,
            hi_c,
            lo_c + (hi_i + 1).astype(jnp.float32) * width / num_bins,
        )
        below = jnp.where(lo_i > 0, cum[jnp.maximum(lo_i - 1, 0)], counts[0])
        window = cum[hi_i] - below
        return (new_lo, new_hi, window, passes + 1)

    lo_f, _, _, _ = lax.while_loop(
        cond, body, (lo0, hi0, jnp.int32(n), jnp.int32(0))
    )
    return jnp.min(jnp.where(scores >= lo_f, scores, jnp.inf))


def contamination_threshold(
    scores,
    contamination: float,
    contamination_error: float,
    exact_size_limit: int = 1 << 22,
) -> float:
    """Outlier-score threshold for a contamination level; exact when the error
    budget is 0 (SharedTrainLogic.scala:187-197 semantics). An exact answer
    always satisfies the approximate contract, so the sketch only engages
    above ``exact_size_limit`` scores (injectable for tests)."""
    q = 1.0 - contamination
    if contamination_error == 0.0 or np.size(scores) <= exact_size_limit:
        return exact_quantile(scores, q)
    return histogram_quantile(scores, q, eps=contamination_error)


def quantile_rank_error(scores, threshold: float, q: float) -> int:
    """Rank distance between ``threshold`` and the target rank ``ceil(q*N)``.

    The Greenwald-Khanna contract this library's quantiles honor
    (``approxQuantile``'s, ``core/SharedTrainLogic.scala:195-197``): the
    returned threshold must be an **element of** ``scores`` whose rank is
    within ``eps * N`` of ``ceil(q * N)``. With ties, an element occupies the
    1-indexed rank interval ``[count(< thr) + 1, count(<= thr)]``; the
    returned value is the distance from the target rank to that interval
    (0 when covered). Raises ``ValueError`` if ``threshold`` is not an
    element of ``scores`` — a non-member can never satisfy the contract.

    Used by the MULTICHIP dryrun and mesh tests to pin the distributed
    sketch's correctness against gathered scores (VERDICT r2 item 6).
    """
    scores = np.asarray(scores)
    n = scores.size
    target = max(int(np.ceil(q * n)), 1)
    lt = int((scores < threshold).sum())
    le = int((scores <= threshold).sum())
    if le == lt:
        raise ValueError(
            f"threshold {threshold!r} is not an element of the score column"
        )
    if target < lt + 1:
        return (lt + 1) - target
    if target > le:
        return target - le
    return 0


def observed_contamination(scores, threshold: float) -> float:
    """Fraction of training rows labelled outliers by ``threshold`` — used for
    the reference's verification warning (SharedTrainLogic.scala:211-232)."""
    scores = jnp.asarray(scores)
    return float(jnp.mean((scores >= threshold).astype(jnp.float32)))
