"""Quantile / contamination-threshold computation.

The reference sets the model threshold as
``approxQuantile(scores, 1 - contamination, contaminationError)`` — Spark's
Greenwald-Khanna sketch, which returns an *actual element* of the score column
whose rank error is at most ``contaminationError * N``; ``error = 0`` means
exact (``core/SharedTrainLogic.scala:187-197``). Two TPU-native paths:

  * exact: full device sort (XLA sort is a single fused program) and a rank
    pick — used whenever the scores fit on device, regardless of
    ``contaminationError`` (an exact answer always satisfies the approximate
    contract);
  * sketched: a psum-able fixed-width histogram honoring the rank-error
    contract, for row-sharded multi-host scoring where gathering all scores is
    undesirable (SURVEY.md §5.8 replacement for distributed approxQuantile).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def exact_quantile(scores, q: float) -> float:
    """Element of ``scores`` at rank ``ceil(q * N) - 1`` (clamped), like an
    exact Greenwald-Khanna query: returns a sample element, no interpolation."""
    scores = jnp.asarray(scores)
    n = scores.shape[0]
    rank = min(max(int(np.ceil(q * n)) - 1, 0), n - 1)
    return float(jnp.sort(scores)[rank])


def histogram_quantile(
    scores,
    q: float,
    num_bins: int = 1 << 14,
    lo: float = 0.0,
    hi: float = 1.0,
    refine_passes: int = 3,
) -> float:
    """Iteratively-refined histogram quantile over a known value range.

    Isolation-forest scores live in ``(0, 1]``. Each pass histograms the
    scores over the current ``[lo, hi)`` range, locates the bin containing the
    target rank, and narrows the range to that bin — after ``P`` passes the
    returned lower edge is within ``(hi - lo) / B**P`` of the true quantile
    *value* (for the defaults, ~1e-13: below float32 resolution, i.e. exact in
    value even for heavily tied score distributions). Each pass's ``counts``
    reduction is a ``psum`` when run under ``shard_map``, so this serves as
    the multi-host replacement for Spark's distributed approxQuantile
    (SURVEY.md §5.8) at ``refine_passes`` collective rounds.
    """
    scores = jnp.asarray(scores, jnp.float32)
    n = scores.shape[0]
    target = max(int(np.ceil(q * n)), 1)
    for _ in range(refine_passes):
        width = hi - lo
        if width <= 0:
            break
        rel = jnp.floor((scores - lo) / width * num_bins)
        bins = jnp.clip(rel, -1, num_bins).astype(jnp.int32)
        counts = np.asarray(
            jnp.zeros((num_bins,), jnp.int32)
            .at[jnp.where(bins < 0, num_bins, bins)]
            .add(1, mode="drop")
        )
        below = int(np.sum(np.asarray(bins) < 0))  # scores strictly below lo
        cum = below + np.cumsum(counts)
        idx = min(int(np.searchsorted(cum, target)), num_bins - 1)
        lo, hi = lo + idx * width / num_bins, lo + (idx + 1) * width / num_bins
    return float(lo)


def histogram_quantile_jit(
    scores,
    q: float,
    num_bins: int = 8192,
    refine_passes: int = 3,
    lo: float = 0.0,
    hi: float = 1.0,
):
    """Traceable (jit/shard_map-friendly) refined histogram quantile.

    Same algorithm as :func:`histogram_quantile`, but every step is a jax op
    so it composes into a fused distributed program: under GSPMD, each pass's
    scatter-add histogram reduces with one psum-shaped collective while the
    score vector stays row-sharded — no global gather/sort. Resolution after
    ``P`` passes: ``(hi - lo) / num_bins**P`` (defaults ~2e-12, below f32 ulp).
    """
    import jax.lax as lax

    scores = jnp.asarray(scores, jnp.float32)
    n = scores.shape[0]
    target = jnp.maximum(jnp.ceil(q * n), 1.0).astype(jnp.int32)

    def one_pass(carry, _):
        lo_c, hi_c = carry
        width = hi_c - lo_c
        rel = jnp.floor((scores - lo_c) / width * num_bins)
        bins = jnp.clip(rel, -1, num_bins).astype(jnp.int32)
        counts = jnp.zeros((num_bins + 2,), jnp.int32).at[bins + 1].add(1)
        cum = counts[0] + jnp.cumsum(counts[1 : num_bins + 1])
        idx = jnp.clip(jnp.searchsorted(cum, target), 0, num_bins - 1).astype(
            jnp.float32
        )
        return (lo_c + idx * width / num_bins, lo_c + (idx + 1.0) * width / num_bins), None

    (lo_f, _), _ = lax.scan(
        one_pass,
        (jnp.float32(lo), jnp.float32(hi)),
        None,
        length=refine_passes,
    )
    return lo_f


def contamination_threshold(
    scores,
    contamination: float,
    contamination_error: float,
    exact_size_limit: int = 1 << 22,
) -> float:
    """Outlier-score threshold for a contamination level; exact when the error
    budget is 0 (SharedTrainLogic.scala:187-197 semantics). An exact answer
    always satisfies the approximate contract, so the sketch only engages
    above ``exact_size_limit`` scores (injectable for tests)."""
    q = 1.0 - contamination
    if contamination_error == 0.0 or np.size(scores) <= exact_size_limit:
        return exact_quantile(scores, q)
    return histogram_quantile(scores, q)


def observed_contamination(scores, threshold: float) -> float:
    """Fraction of training rows labelled outliers by ``threshold`` — used for
    the reference's verification warning (SharedTrainLogic.scala:211-232)."""
    scores = jnp.asarray(scores)
    return float(jnp.mean((scores >= threshold).astype(jnp.float32)))
