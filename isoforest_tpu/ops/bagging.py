"""Sampling engine — per-tree bagged sample selection and feature subsets.

TPU-native redesign of the reference's bagging pipeline
(``core/BaggedPoint.scala:114-217`` + ``core/SharedTrainLogic.scala:99-153``):
the reference draws a per-(datum, tree) membership weight — Poisson(rate) when
``bootstrap`` (with replacement) else Binomial(1, rate) (without replacement)
— flattens duplicates, shuffles each tree's partition and slices the first
``numSamples`` points. The net effect is: **every tree independently receives
``numSamples`` rows, uniformly at random, with replacement iff bootstrap.**

Here no data moves at all (SURVEY.md §5.8): the feature matrix stays resident
in HBM and each tree materialises only an ``int32[num_samples]`` index buffer.
The Spark shuffle becomes a gather; per-partition reseeding
(``seed + partitionIndex``, BaggedPoint.scala:169-177) becomes
``jax.random.fold_in(key, tree_id)`` — a documented RNG-scheme deviation
(bitwise parity with the JVM RNG chain is impossible and not required; the
acceptance gates are statistical, SURVEY.md §7.4.3).
"""

from __future__ import annotations

import functools
import hashlib
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Below this many transient elements the full per-tree permutation is cheap;
# above it, an N-independent sampler must take over.
_PERMUTATION_MAX_ELEMS = 1 << 26
# Floyd's algorithm is O(S^2) per tree as a sequential scan of length S —
# unbeatable for the reference-default S=256 but pathological for huge bags;
# beyond this S the chunked top-k sampler (O(N log S), bounded transient) wins.
_FLOYD_MAX_SAMPLES = 1 << 12


def per_tree_keys(key: jax.Array, num_trees: int) -> jax.Array:
    """Independent PRNG keys per tree: ``fold_in(key, tree_id)`` over global
    tree ids — the TPU analogue of the reference's per-partition reseeding
    (``seed + partitionIndex``, BaggedPoint.scala:169-177). Computed over the
    full tree axis so sharding trees across devices keeps streams disjoint."""
    return jax.vmap(lambda t: jax.random.fold_in(key, t))(
        jnp.arange(num_trees, dtype=jnp.uint32)
    )


def _floyd_sample(key: jax.Array, num_rows: int, num_samples: int) -> jax.Array:
    """Exact uniform ``num_samples``-subset of ``[0, num_rows)`` via Floyd's
    algorithm (Bentley & Floyd 1987): for j = N-S .. N-1 draw t ~ U[0, j]; keep
    t unless already drawn, else keep j. Every S-subset is equally likely,
    distinctness is guaranteed by construction, and cost is O(S^2) per tree
    with O(S) memory — independent of N, so it stays exact in the large-N
    regime where a full permutation would materialise [T, N] in HBM."""
    start = num_rows - num_samples

    def step(buf, i):
        j = start + i
        t = jax.random.randint(
            jax.random.fold_in(key, i), (), 0, j + 1, dtype=jnp.int32
        )
        val = jnp.where(jnp.any(buf == t), j, t)
        return buf.at[i].set(val), None

    buf0 = jnp.full((num_samples,), -1, dtype=jnp.int32)
    buf, _ = jax.lax.scan(step, buf0, jnp.arange(num_samples, dtype=jnp.int32))
    return buf


def _topk_sample(
    tree_keys: jax.Array, num_rows: int, num_samples: int
) -> jax.Array:
    """Exact uniform subsets for the large-S regime: per tree, rank rows by a
    64-bit random key (two uint32 draws compared lexicographically via a
    two-key ``lax.sort``) and keep the ``num_samples`` highest-ranked — a
    symmetric function of i.i.d. draws, so every S-subset is equally likely
    (to within the ~2^-64 chance of a full 64-bit boundary tie) and indices
    are distinct by construction. float32 keys would NOT work here: they take
    only ~2^23 distinct values, and deterministic tie-breaking would bias
    bags toward low row indices at exactly these row counts. Trees are
    processed in ``lax.map`` chunks so the ``[chunk, N]`` transient stays
    bounded instead of materialising [T, N]."""

    def chunk_sample(keys_c):
        def one(k):
            k1, k2 = jax.random.split(k)
            r1 = jax.random.bits(k1, (num_rows,), dtype=jnp.uint32)
            r2 = jax.random.bits(k2, (num_rows,), dtype=jnp.uint32)
            idx = jnp.arange(num_rows, dtype=jnp.int32)
            _, _, sorted_idx = jax.lax.sort((r1, r2, idx), num_keys=2)
            return sorted_idx[num_rows - num_samples :]

        return jax.vmap(one)(keys_c)

    num_trees = tree_keys.shape[0]
    chunk = max(1, min(num_trees, _PERMUTATION_MAX_ELEMS // max(num_rows, 1)))
    if chunk >= num_trees:
        return chunk_sample(tree_keys)
    pad = (-num_trees) % chunk
    keys_p = (
        jnp.concatenate([tree_keys, tree_keys[:pad]], axis=0) if pad else tree_keys
    )
    out = jax.lax.map(
        chunk_sample, keys_p.reshape(-1, chunk, *tree_keys.shape[1:])
    )
    return out.reshape(-1, num_samples)[:num_trees]


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6))
def _bagged_indices_jit(
    key, num_rows, num_samples, num_trees, bootstrap, perm_max, floyd_max
):
    # the dispatch thresholds are static args (not read as globals) so tests
    # that override them can't hit a stale compiled cache entry.
    # Cost model (measured, 1-core CPU): Floyd ~S^2 cheap ops per tree;
    # XLA sort (permutation) ~200 ops per element per tree — so Floyd wins
    # whenever S^2 < 200*N, i.e. everywhere except huge-bag regimes.
    tree_keys = per_tree_keys(key, num_trees)
    if bootstrap:
        sample = lambda k: jax.random.randint(
            k, (num_samples,), 0, num_rows, dtype=jnp.int32
        )
    elif num_samples <= floyd_max and num_samples * num_samples <= 200 * num_rows:
        sample = lambda k: _floyd_sample(k, num_rows, num_samples)
    elif num_rows * num_trees <= perm_max:
        sample = lambda k: jax.random.permutation(k, num_rows)[:num_samples].astype(
            jnp.int32
        )
    elif num_samples <= floyd_max:
        sample = lambda k: _floyd_sample(k, num_rows, num_samples)
    else:
        return _topk_sample(tree_keys, num_rows, num_samples)
    return jax.vmap(sample)(tree_keys)


def bagged_indices(
    key: jax.Array,
    num_rows: int,
    num_samples: int,
    num_trees: int,
    bootstrap: bool,
) -> jax.Array:
    """Return ``int32[num_trees, num_samples]`` row indices, one bag per tree.

    ``bootstrap=True`` samples with replacement (Poisson branch,
    BaggedPoint.scala:122-129); ``bootstrap=False`` without replacement
    (Binomial(1, rate) branch + shuffle/slice, BaggedPoint.scala:130-139 and
    SharedTrainLogic.scala:283-287) — **exact at every N**: rows within a bag
    are guaranteed distinct, matching the reference's Binomial(1, rate)
    semantics, with no large-N approximation. Jitted (shape-static args):
    eager re-tracing of the vmapped samplers cost seconds per fit; compiled
    programs land in the persistent compilation cache.
    """
    if not bootstrap and num_samples > num_rows:
        raise ValueError(
            f"cannot draw {num_samples} distinct rows from {num_rows} without "
            "replacement (bootstrap=False)"
        )
    return _bagged_indices_jit(
        key,
        num_rows,
        num_samples,
        num_trees,
        bootstrap,
        _PERMUTATION_MAX_ELEMS,
        _FLOYD_MAX_SAMPLES,
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def feature_subsets(
    key: jax.Array,
    total_num_features: int,
    num_features: int,
    num_trees: int,
) -> jax.Array:
    """Per-tree sorted random feature subsets, ``int32[num_trees, num_features]``.

    Mirrors ``shuffle(0..F-1).take(numFeatures).sorted``
    (SharedTrainLogic.scala:300-304). Sorted ascending so persisted
    ``splitAttribute`` ids are canonical.
    """
    tree_keys = per_tree_keys(key, num_trees)

    def subset(k):
        perm = jax.random.permutation(k, total_num_features)[:num_features]
        return jnp.sort(perm).astype(jnp.int32)

    return jax.vmap(subset)(tree_keys)


# --------------------------------------------------------------------------- #
# Streamed one-pass sampling (out-of-core fit, docs/out_of_core.md §3)
# --------------------------------------------------------------------------- #
#
# The jitted samplers above need the full [N, F] matrix resident; an
# out-of-core source only ever exposes one chunk at a time, in one sequential
# pass. The streamed sampler keys every (tree, global_row) pair with a 64-bit
# splitmix64 hash of (seed, tree, row) and keeps, per tree, the S rows with the
# smallest keys — a symmetric function of i.i.d. draws, so every S-subset is
# equally likely (the same argument as _topk_sample, with the opposite
# extremum). Because keys depend only on the seed and the *absolute* row
# index, the selected bags are bitwise-identical for any chunk-size choice and
# across re-reads of the same source — the property the fit-parity and
# resume guarantees are built on. Host-side numpy on purpose: the stream
# arrives on the host, S*T rows is tiny, and no device round-trip is needed.

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_MIX_1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX_2 = np.uint64(0x94D049BB133111EB)
_KEY_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)
_ROW_SENTINEL = np.int64(2**63 - 1)
# Rows hashed per inner block: keeps the [T, block] key transient ~tens of MB.
_STREAM_BLOCK_ROWS = 1 << 16


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over uint64 arrays (Steele et al. 2014) — a
    bijective avalanche mix, implemented directly so the key stream is
    independent of the numpy/jax RNG implementations."""
    x = np.asarray(x, dtype=np.uint64).copy()
    x ^= x >> np.uint64(30)
    x *= _MIX_1
    x ^= x >> np.uint64(27)
    x *= _MIX_2
    x ^= x >> np.uint64(31)
    return x


def _tree_salts(seed: int, num_trees: int) -> np.ndarray:
    """Per-tree uint64 salts: mix(seed) advanced by the golden-gamma per tree
    (the splitmix64 stream), then finalized — independent streams per tree."""
    base = np.uint64(seed & 0xFFFFFFFFFFFFFFFF)
    t = np.arange(1, num_trees + 1, dtype=np.uint64)
    return _mix64(base + t * _GOLDEN)


def _row_hash(global_rows: np.ndarray) -> np.ndarray:
    """``uint64[C]`` fully-mixed per-row values — i.i.d.-uniform-quality keys
    from absolute row indices, shared across trees (one splitmix per row)."""
    return _mix64((global_rows.astype(np.uint64) + np.uint64(1)) * _GOLDEN)


def _row_keys(
    xor_salts: np.ndarray, mul_salts: np.ndarray, row_hash: np.ndarray
) -> np.ndarray:
    """``uint64[T, C]`` keys for (tree, absolute row) pairs.

    Two-stage construction, chosen for throughput at the [T, C] scale (the
    sampler's dominant cost at 100M+ rows): the expensive 8-op splitmix64
    finalizer runs once per ROW (:func:`_row_hash`), and the per-tree stage
    is a 2-round multiplicative scramble — xor a per-tree salt, multiply by
    a per-tree odd constant, xor-shift, multiply by a fixed odd constant.
    Per tree this is a bijection of uint64 composed with an i.i.d.-uniform
    row key, so keys stay exactly i.i.d.-uniform per tree (bottom-S of them
    is an exactly uniform S-subset); the per-tree salts + multipliers
    decorrelate trees (cross-tree bag overlap is pinned at the binomial
    S^2/N level in tests/test_out_of_core.py)."""
    keys = np.bitwise_xor(xor_salts[:, None], row_hash[None, :])
    keys *= mul_salts[:, None]
    keys ^= keys >> np.uint64(29)
    keys *= _MIX_2
    return keys


class StreamedSample(NamedTuple):
    """The materialised output of a streamed sampling pass.

    ``X`` is the union matrix of every selected row (``f32[U, F]``, rows in
    ascending global-row order); ``bag`` indexes into it per tree
    (``int32[T, S]``); ``rows`` holds the corresponding absolute source rows
    (``int64[U]``); ``total_rows`` is the stream length consumed.
    ``sha256`` fingerprints the sample content for checkpoint gating.
    """

    X: np.ndarray
    bag: np.ndarray
    rows: np.ndarray
    total_rows: int
    sha256: str


def _sample_sha256(X: np.ndarray, bag: np.ndarray, rows: np.ndarray) -> str:
    h = hashlib.sha256()
    h.update(repr((X.shape, str(X.dtype), bag.shape)).encode())
    h.update(np.ascontiguousarray(X).tobytes())
    h.update(np.ascontiguousarray(bag).tobytes())
    h.update(np.ascontiguousarray(rows).tobytes())
    return h.hexdigest()


class StreamedBagger:
    """One-pass bottom-S reservoir over an arbitrarily long row stream.

    Feed sequential chunks with :meth:`consume` (absolute row order, no gaps),
    then :meth:`finalize`. Memory is bounded by the reservoirs
    (``[T, S]`` keys + rows) plus the store of currently-selected feature rows
    (at most ``T * S`` rows, typically far fewer due to overlap) — independent
    of stream length. Sampling is without replacement per tree; for
    ``bootstrap=True`` see :func:`streamed_bootstrap_indices`.
    """

    def __init__(self, seed: int, num_trees: int, num_samples: int):
        if num_trees <= 0 or num_samples <= 0:
            raise ValueError(
                f"need num_trees > 0 and num_samples > 0, got "
                f"{num_trees}/{num_samples}"
            )
        self.num_trees = int(num_trees)
        self.num_samples = int(num_samples)
        self._xor_salts = _tree_salts(seed, num_trees)
        # independent odd multipliers per tree (odd => bijective mod 2^64)
        self._mul_salts = _tree_salts(~seed & 0xFFFFFFFFFFFFFFFF, num_trees) | np.uint64(1)
        # Reservoirs kept sorted ascending by (key, row): column -1 is the
        # per-tree admission threshold.
        self._res_keys = np.full(
            (num_trees, num_samples), _KEY_SENTINEL, dtype=np.uint64
        )
        self._res_rows = np.full(
            (num_trees, num_samples), _ROW_SENTINEL, dtype=np.int64
        )
        self._store: dict = {}  # global row -> f32 feature row
        self._rows_seen = 0
        self._num_features: Optional[int] = None

    def consume(self, X_chunk: np.ndarray) -> None:
        X = np.asarray(X_chunk, dtype=np.float32)
        if X.ndim != 2:
            raise ValueError(f"chunk must be 2-D, got shape {X.shape}")
        if self._num_features is None:
            self._num_features = X.shape[1]
        elif X.shape[1] != self._num_features:
            raise ValueError(
                f"chunk width {X.shape[1]} != source width {self._num_features}"
            )
        start = self._rows_seen
        for off in range(0, X.shape[0], _STREAM_BLOCK_ROWS):
            self._consume_block(X[off : off + _STREAM_BLOCK_ROWS], start + off)
        self._rows_seen += X.shape[0]

    def _consume_block(self, X: np.ndarray, start: int) -> None:
        rows = np.arange(start, start + X.shape[0], dtype=np.int64)
        keys = _row_keys(self._xor_salts, self._mul_salts, _row_hash(rows))  # [T, C]
        # A new row is admitted iff its key beats the tree's current max;
        # key ties lose to the incumbent (smaller row index — the stream is
        # sequential, so incumbents always predate the block).
        cand = keys < self._res_keys[:, -1][:, None]
        touched = np.nonzero(cand.any(axis=1))[0]
        for t in touched:
            ck, cr = keys[t, cand[t]], rows[cand[t]]
            mk = np.concatenate([self._res_keys[t], ck])
            mr = np.concatenate([self._res_rows[t], cr])
            order = np.lexsort((mr, mk))[: self.num_samples]
            self._res_keys[t] = mk[order]
            self._res_rows[t] = mr[order]
        if len(touched) == 0:
            return
        # Refresh the row store: add this block's survivors, drop evictees.
        live = np.unique(self._res_rows)
        live = live[live != _ROW_SENTINEL]
        fresh = live[(live >= start) & (live < start + X.shape[0])]
        for r in fresh.tolist():
            self._store[r] = X[r - start].copy()
        if len(self._store) > live.size:
            live_set = set(live.tolist())
            for r in [r for r in self._store if r not in live_set]:
                del self._store[r]

    def finalize(self) -> StreamedSample:
        """Materialise ``(X, bag)``. Raises if the stream was shorter than
        ``num_samples`` (cannot draw S distinct rows from fewer)."""
        if self._rows_seen < self.num_samples:
            raise ValueError(
                f"cannot draw {self.num_samples} distinct rows from a "
                f"{self._rows_seen}-row stream (bootstrap=False)"
            )
        rows = np.unique(self._res_rows)
        rows = rows[rows != _ROW_SENTINEL]
        X = np.stack([self._store[r] for r in rows.tolist()]).astype(np.float32)
        bag = np.searchsorted(rows, self._res_rows).astype(np.int32)
        return StreamedSample(
            X=X,
            bag=bag,
            rows=rows,
            total_rows=self._rows_seen,
            sha256=_sample_sha256(X, bag, rows),
        )


def streamed_bootstrap_indices(
    seed: int, num_trees: int, num_samples: int, total_rows: int
) -> np.ndarray:
    """With-replacement bags for the streamed path: ``int64[T, S]`` absolute
    row indices, each slot an independent draw ``key(t, s) mod N`` from the
    same splitmix64 stream as the reservoir (modulo bias ~N/2^64 —
    negligible at any feasible N). Needs ``total_rows`` up front, so
    bootstrap sources pay a row-counting pass before the data pass."""
    if total_rows <= 0:
        raise ValueError(f"dataset is empty (totalRows={total_rows})")
    salts = _tree_salts(~seed & 0xFFFFFFFFFFFFFFFF, num_trees)
    slots = np.arange(1, num_samples + 1, dtype=np.uint64) * _GOLDEN
    keys = _mix64(salts[:, None] ^ _mix64(slots)[None, :])
    return (keys % np.uint64(total_rows)).astype(np.int64)


def materialise_bootstrap_sample(
    chunks, indices: np.ndarray
) -> StreamedSample:
    """Collect the rows named by :func:`streamed_bootstrap_indices` in one
    sequential pass over ``chunks`` (an iterable of objects with ``.X`` and
    ``.global_start``). Returns the same :class:`StreamedSample` shape as the
    reservoir path — ``X`` is the union of distinct rows, ``bag`` maps each
    (tree, slot) to its union position."""
    rows = np.unique(indices)
    X_parts: dict = {}
    total = 0
    for chunk in chunks:
        start = chunk.global_start
        stop = start + chunk.X.shape[0]
        total = stop
        lo, hi = np.searchsorted(rows, [start, stop])
        for r in rows[lo:hi].tolist():
            X_parts[r] = np.asarray(
                chunk.X[r - start], dtype=np.float32
            ).copy()
    missing = [r for r in rows.tolist() if r not in X_parts]
    if missing:
        raise ValueError(
            f"bootstrap drew row {missing[0]} but the stream ended at "
            f"{total} rows (source shrank between the counting and data passes?)"
        )
    X = np.stack([X_parts[r] for r in rows.tolist()]).astype(np.float32)
    bag = np.searchsorted(rows, indices).astype(np.int32)
    return StreamedSample(
        X=X,
        bag=bag,
        rows=rows,
        total_rows=total,
        sha256=_sample_sha256(X, bag, rows),
    )


def gather_tree_data(X: jax.Array, bag_idx: jax.Array, feat_idx: jax.Array) -> jax.Array:
    """Materialise per-tree training slabs ``f32[T, S, num_features]``.

    ``X`` is the full ``[N, F]`` matrix (replicated or all-gathered in HBM);
    the double gather replaces the reference's shuffle-to-partition data
    movement (SharedTrainLogic.scala:140-145).
    """
    rows = X[bag_idx]  # [T, S, F]
    return jnp.take_along_axis(rows, feat_idx[:, None, :], axis=2)
