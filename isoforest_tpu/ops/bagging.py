"""Sampling engine — per-tree bagged sample selection and feature subsets.

TPU-native redesign of the reference's bagging pipeline
(``core/BaggedPoint.scala:114-217`` + ``core/SharedTrainLogic.scala:99-153``):
the reference draws a per-(datum, tree) membership weight — Poisson(rate) when
``bootstrap`` (with replacement) else Binomial(1, rate) (without replacement)
— flattens duplicates, shuffles each tree's partition and slices the first
``numSamples`` points. The net effect is: **every tree independently receives
``numSamples`` rows, uniformly at random, with replacement iff bootstrap.**

Here no data moves at all (SURVEY.md §5.8): the feature matrix stays resident
in HBM and each tree materialises only an ``int32[num_samples]`` index buffer.
The Spark shuffle becomes a gather; per-partition reseeding
(``seed + partitionIndex``, BaggedPoint.scala:169-177) becomes
``jax.random.fold_in(key, tree_id)`` — a documented RNG-scheme deviation
(bitwise parity with the JVM RNG chain is impossible and not required; the
acceptance gates are statistical, SURVEY.md §7.4.3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Exact without-replacement sampling costs a full N-row permutation per tree
# (O(T*N)); when N >> S the expected duplicate count of plain uniform draws is
# ~S^2/(2N) per tree — under 1% of the bag at N > 50*S — so the approximate
# path is statistically indistinguishable and keeps bagging O(T*S).
_EXACT_SAMPLING_ROWS_PER_SAMPLE = 50


def per_tree_keys(key: jax.Array, num_trees: int) -> jax.Array:
    """Independent PRNG keys per tree: ``fold_in(key, tree_id)`` over global
    tree ids — the TPU analogue of the reference's per-partition reseeding
    (``seed + partitionIndex``, BaggedPoint.scala:169-177). Computed over the
    full tree axis so sharding trees across devices keeps streams disjoint."""
    return jax.vmap(lambda t: jax.random.fold_in(key, t))(
        jnp.arange(num_trees, dtype=jnp.uint32)
    )


def bagged_indices(
    key: jax.Array,
    num_rows: int,
    num_samples: int,
    num_trees: int,
    bootstrap: bool,
) -> jax.Array:
    """Return ``int32[num_trees, num_samples]`` row indices, one bag per tree.

    ``bootstrap=True`` samples with replacement (Poisson branch,
    BaggedPoint.scala:122-129); ``bootstrap=False`` without replacement
    (Binomial(1, rate) branch + shuffle/slice, BaggedPoint.scala:130-139 and
    SharedTrainLogic.scala:283-287).
    """
    tree_keys = per_tree_keys(key, num_trees)
    if bootstrap or num_rows > _EXACT_SAMPLING_ROWS_PER_SAMPLE * num_samples:
        sample = lambda k: jax.random.randint(
            k, (num_samples,), 0, num_rows, dtype=jnp.int32
        )
    else:
        sample = lambda k: jax.random.permutation(k, num_rows)[:num_samples].astype(
            jnp.int32
        )
    return jax.vmap(sample)(tree_keys)


def feature_subsets(
    key: jax.Array,
    total_num_features: int,
    num_features: int,
    num_trees: int,
) -> jax.Array:
    """Per-tree sorted random feature subsets, ``int32[num_trees, num_features]``.

    Mirrors ``shuffle(0..F-1).take(numFeatures).sorted``
    (SharedTrainLogic.scala:300-304). Sorted ascending so persisted
    ``splitAttribute`` ids are canonical.
    """
    tree_keys = per_tree_keys(key, num_trees)

    def subset(k):
        perm = jax.random.permutation(k, total_num_features)[:num_features]
        return jnp.sort(perm).astype(jnp.int32)

    return jax.vmap(subset)(tree_keys)


def gather_tree_data(X: jax.Array, bag_idx: jax.Array, feat_idx: jax.Array) -> jax.Array:
    """Materialise per-tree training slabs ``f32[T, S, num_features]``.

    ``X`` is the full ``[N, F]`` matrix (replicated or all-gathered in HBM);
    the double gather replaces the reference's shuffle-to-partition data
    movement (SharedTrainLogic.scala:140-145).
    """
    rows = X[bag_idx]  # [T, S, F]
    return jnp.take_along_axis(rows, feat_idx[:, None, :], axis=2)
