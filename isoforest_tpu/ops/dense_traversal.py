"""Dense (gather-free) path-length scoring — the TPU-native fast path.

The pointer-walk formulation of :mod:`.traversal` performs ``height`` rounds
of data-dependent gathers per (row, tree). TPUs have no fast per-lane vector
gather (dynamic indexing in the hardware is slice-granular), so that lowering
serialises; CPUs fare little better on scattered access. This module
restructures scoring as pure dense algebra over the implicit heap, consuming
the finalized scoring layout of :mod:`.scoring_layout` — the merged
``value`` plane (threshold at internal slots, leaf path-length LUT at
leaves) and the width-narrowed ``feature`` table (i8/i16 when the feature
count permits), which halves-or-better the node-table bytes each level walk
streams:

  1. **Node comparisons without gathers**: the go-right bit of node ``n`` for
     row ``c`` is ``B[c, n] = x[c, feat[n]] >= value[n]`` (value IS the
     threshold wherever the bit can matter — leaf/hole bits are masked by
     the reachability recurrence). Two formulations, dispatched on feature
     count (crossover measured on a live v5e chip,
     ``tools/dense_experiments.py``):

     * ``F <= _SELECT_MAX_FEATURES``: per-level *select* — ``F`` masked
       lane-broadcast passes build ``x[c, feat[n]]`` with no matmul and no
       ``[C, M]`` materialisation; every op fuses into the level walk
       (0.35 s vs the HIGHEST-precision contraction's 0.46 s at 524k rows
       x 100 trees, F=3, live v5e).
     * large ``F``: one-hot feature-selection contraction ``X @ FOH^T`` at
       ``lax.Precision.HIGHEST``. The MXU's *default* f32 precision is
       bfloat16-mantissa passes — measured 0.24 max path-length error vs the
       exact walk — so the full-precision contraction is mandatory, not a
       nicety (0.20 s vs the select loop's 1.20 s at F=274).

     For the extended forest the per-node test is ``dot(x, w_n) >= value_n``
     — a *real* matmul per heap level (``X @ W_l^T``, HIGHEST) that lands on
     the MXU (BASELINE.json north star: "hyperplane splits lower directly to
     XLA matmul").
  2. **Reachability by level**: a row reaches heap slot ``2i+1+b`` iff it
     reaches ``i`` and its bit matches. Expanding level ``l`` to ``l+1`` is a
     mask-and-interleave of the ``[C, 2^l]`` reach matrix — stack + reshape,
     no indexing at all.
  3. **Path length**: sum over levels of ``reach * (value at non-internal
     slots)`` — leaf slots hold exactly ``l + c(n)`` in the merged plane and
     holes hold 0, so no separate leaf table exists anywhere (kept off the
     MXU so leaf values never round through bf16).

Work per tree is ``O(C * M)`` dense ops versus ``O(C * h)`` gathers — a
~57x op-count increase (M=511, h=8) that is nonetheless far faster on vector
hardware because every op is a fused, full-width VPU/MXU instruction. Trees
are processed in blocks of :data:`_TREE_BLOCK` under ``lax.scan`` (row-tile
x tree-tile schedule: one block's node tables stay live across the caller's
whole row chunk), rows chunked by the caller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.math import height_of as _height_of
from .ext_growth import ExtendedForest
from .scoring_layout import pack_forest
from .tree_growth import StandardForest

# Feature-count crossover between the fused per-level select formulation and
# the one-hot HIGHEST-precision contraction. Measured on a v5e chip
# (tools/dense_experiments.py + on-chip sweep, 2026-07-29): F=3 select
# 0.35 s vs matmul 0.46 s (524k rows); at 262k rows F=8 select 0.43 vs
# 0.46, F=16 select 0.82 vs matmul 0.79, F=24 1.22 vs 1.11, F=274 select
# 1.20 s vs matmul 0.20 s — the flip sits between 8 and 16.
_SELECT_MAX_FEATURES = 12

# Trees per lax.scan step (row-tile x tree-tile blocking knob). The tree
# bodies are PYTHON-unrolled inside each step — a vmap would batch the
# per-tree HIGHEST-precision contractions and change their reduction
# order, breaking exact dot == offset tie routing (TestQuantizedTieRouting)
# — so G > 1 multiplies the step's HLO and its compile time. The r2 sweep
# measured G in {2..100} as a wash-to-loss at runtime on BOTH backends
# (0.532s at G=1 vs 0.55-0.61s, 524k rows x 100 trees, live v5e): the
# dense bottleneck is the [C, width] walk intermediates, which blocking
# does not shrink. Default therefore 1; tools/unroll_sweep.py re-measures
# (override the module global to sweep).
_TREE_BLOCK = 1


def _tree_block(num_trees: int) -> int:
    return max(1, min(int(_TREE_BLOCK), num_trees))


def _level_walk(bits_fn, is_internal: jax.Array, value: jax.Array, C: int, h: int):
    """Shared reach-propagation over the implicit heap.

    ``bits_fn(start, width)`` returns the ``[C, width]`` go-right bits of one
    heap level (lazy so the select formulation never materialises ``[C, M]``);
    ``is_internal``: [M]; ``value``: [M] merged plane (``depth +
    c(numInstances)`` at leaves, threshold at internal slots, 0 at holes).
    Returns [C] path lengths. Python loop over levels is static (h+1
    iterations) and fuses into one XLA computation.
    """
    leaf_value = jnp.where(is_internal, 0.0, value)
    total = jnp.zeros((C,), jnp.float32)
    reach = jnp.ones((C, 1), jnp.bool_)
    for level in range(h + 1):
        start = (1 << level) - 1
        width = 1 << level
        value_l = leaf_value[start : start + width]  # [W]
        # leaves contribute once, where reached (elementwise, not einsum:
        # MXU default precision would round leaf values to bf16 mantissas)
        total = total + jnp.sum(jnp.where(reach, value_l[None, :], 0.0), axis=1)
        if level < h:
            B_l = bits_fn(start, width)
            alive = reach & is_internal[start : start + width][None, :]
            left = alive & ~B_l
            right = alive & B_l
            reach = jnp.stack([left, right], axis=2).reshape(C, 2 * width)
    return total


def _pad_tree_axis(arr: jax.Array, block: int, fill) -> jax.Array:
    pad = (-arr.shape[0]) % block
    if not pad:
        return arr
    return jnp.concatenate(
        [arr, jnp.full((pad,) + arr.shape[1:], fill, arr.dtype)], axis=0
    )


def _scan_tree_blocks(one_tree, tables: tuple, fills: tuple, num_trees: int, C: int):
    """Sum ``one_tree(*tree_tables) -> f32[C]`` over all trees: scan over
    blocks of :data:`_TREE_BLOCK`, the G tree bodies python-unrolled inside
    each step. NOT a vmap: batching the per-tree HIGHEST-precision
    contractions changes their reduction order, and exact ``dot == offset``
    ties on quantized/constant data must round exactly like the unblocked
    per-tree matmul (the tie-exactness TestQuantizedTieRouting pins).
    Padding trees use neutral ``fills`` (leaf-at-root records with value 0)
    and contribute 0."""
    g = _tree_block(num_trees)
    padded = tuple(_pad_tree_axis(a, g, f) for a, f in zip(tables, fills))
    blocks = tuple(a.reshape(a.shape[0] // g, g, *a.shape[1:]) for a in padded)

    def block_step(total, blk):
        for i in range(g):
            total = total + one_tree(*(a[i] for a in blk))
        return total, None

    total, _ = lax.scan(block_step, jnp.zeros((C,), jnp.float32), blocks)
    return total / num_trees


def standard_path_lengths_dense(
    forest: StandardForest, X: jax.Array, layout=None
) -> jax.Array:
    """Dense scoring for the standard forest; ``f32[C]`` mean path lengths."""
    if layout is None:
        layout = pack_forest(forest, num_features=int(X.shape[1]))
    h = _height_of(forest.max_nodes)
    C, F = X.shape

    def one_tree(feature, value):
        internal = feature >= 0

        if F <= _SELECT_MAX_FEATURES:

            def bits(start, width):
                feat_l = feature[start : start + width]
                val_l = value[start : start + width]
                xv = jnp.zeros((C, width), X.dtype)
                for f in range(F):
                    xv = jnp.where(feat_l[None, :] == f, X[:, f][:, None], xv)
                return xv >= val_l[None, :]

        else:
            # one-hot feature selection: xv[c, n] = X[c, feature[n]]
            foh = jax.nn.one_hot(
                jnp.maximum(feature, 0).astype(jnp.int32), F, dtype=X.dtype
            )  # [M, F]
            xv_all = jnp.einsum(
                "cf,mf->cm", X, foh, precision=lax.Precision.HIGHEST
            )
            B_all = xv_all >= value[None, :]

            def bits(start, width):
                return B_all[:, start : start + width]

        return _level_walk(bits, internal, value, C, h)

    return _scan_tree_blocks(
        one_tree,
        (layout.feature, layout.value),
        (-1, 0.0),
        forest.num_trees,
        C,
    )


def extended_path_lengths_dense(
    forest: ExtendedForest, X: jax.Array, layout=None
) -> jax.Array:
    """Dense EIF scoring: per-level hyperplane tests as HIGHEST-precision
    MXU matmuls (f32 dot parity with ExtendedUtils.scala:46-55; measured
    7.6e-6 max path-length deviation from the elementwise walk vs 0.24 at
    the TPU default bf16 passes)."""
    if layout is None:
        layout = pack_forest(forest)
    h = _height_of(forest.max_nodes)
    C, F = X.shape

    def one_tree(indices, weights, value):
        # densify the sparse hyperplanes: W[n, f] = sum_j w[n,j][indices[n,j]==f]
        foh = jax.nn.one_hot(jnp.maximum(indices, 0), F, dtype=X.dtype)  # [M,k,F]
        valid = (indices >= 0).astype(X.dtype)
        W = jnp.einsum(
            "mk,mkf->mf", weights * valid, foh, precision=lax.Precision.HIGHEST
        )  # [M, F]

        def bits(start, width):
            W_l = W[start : start + width]  # [W, F]
            val_l = value[start : start + width]
            dots = jnp.matmul(X, W_l.T, precision=lax.Precision.HIGHEST)  # [C, W]
            return dots >= val_l[None, :]

        return _level_walk(bits, indices[:, 0] >= 0, value, C, h)

    return _scan_tree_blocks(
        one_tree,
        (forest.indices, forest.weights, layout.value),
        (-1, 0.0, 0.0),
        forest.num_trees,
        C,
    )


def standard_path_lengths_dense_q(
    forest: StandardForest, X: jax.Array, qlayout=None
) -> jax.Array:
    """Dense level-walk over the QUANTIZED plane (scoring_layout
    ``pack_standard_q``): rows binarize once to threshold ranks and the
    per-node go-right bit becomes the integer compare ``rx[c, feat] >
    code`` — decision-identical to ``x >= threshold`` — while leaves credit
    the shared LUT's f32 bits (the f32 plane's own leaf values), so scores
    are bitwise equal to :func:`standard_path_lengths_dense`. Ranks are
    <= 65535 < 2^24, exactly representable in f32, so the one-hot HIGHEST
    contraction stays exact on the wide-F branch."""
    from .scoring_layout import _Q16_FEATURE_SENTINEL, get_layout_q

    if qlayout is None:
        qlayout = get_layout_q(forest)
    h = _height_of(forest.max_nodes)
    C, F = X.shape
    packed = jnp.asarray(qlayout.packed)
    lut = jnp.asarray(qlayout.lut)
    rx = jnp.searchsorted(jnp.asarray(qlayout.edges), X, side="right").astype(
        jnp.int32
    )
    feat_u = (packed & jnp.uint32(_Q16_FEATURE_SENTINEL)).astype(jnp.int32)
    feature = jnp.where(feat_u == _Q16_FEATURE_SENTINEL, -1, feat_u)  # [T, M]
    code = (packed >> jnp.uint32(16)).astype(jnp.int32)  # [T, M]
    # leaf credit plane: lut[code] at leaves, 0 at internal slots (internal
    # codes are ranks — mask them out before the take)
    value = jnp.where(
        feature >= 0,
        0.0,
        jnp.take(lut, jnp.where(feature >= 0, 0, code)),
    ).astype(jnp.float32)

    def one_tree(feature_t, code_t, value_t):
        internal = feature_t >= 0

        if F <= _SELECT_MAX_FEATURES:

            def bits(start, width):
                feat_l = feature_t[start : start + width]
                code_l = code_t[start : start + width]
                rxv = jnp.zeros((C, width), jnp.int32)
                for f in range(F):
                    rxv = jnp.where(feat_l[None, :] == f, rx[:, f][:, None], rxv)
                return rxv > code_l[None, :]

        else:
            foh = jax.nn.one_hot(
                jnp.maximum(feature_t, 0).astype(jnp.int32), F, dtype=X.dtype
            )
            rxv_all = jnp.einsum(
                "cf,mf->cm",
                rx.astype(jnp.float32),
                foh,
                precision=lax.Precision.HIGHEST,
            )
            B_all = rxv_all > code_t[None, :].astype(jnp.float32)

            def bits(start, width):
                return B_all[:, start : start + width]

        return _level_walk(bits, internal, value_t, C, h)

    return _scan_tree_blocks(
        one_tree,
        (feature, code, value),
        (-1, 0, 0.0),
        forest.num_trees,
        C,
    )


def path_lengths_dense(forest, X: jax.Array, layout=None) -> jax.Array:
    if isinstance(forest, StandardForest):
        return standard_path_lengths_dense(forest, X, layout)
    return extended_path_lengths_dense(forest, X, layout)
