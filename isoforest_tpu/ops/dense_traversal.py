"""Dense (gather-free) path-length scoring — the TPU-native fast path.

The pointer-walk formulation of :mod:`.traversal` performs ``height`` rounds
of data-dependent gathers per (row, tree). TPUs have no fast per-lane vector
gather (dynamic indexing in the hardware is slice-granular), so that lowering
serialises; CPUs fare little better on scattered access. This module
restructures scoring as pure dense algebra over the implicit heap:

  1. **All comparisons at once**: the go-right bit of every node for every
     row is ``B[c, n] = x[c, feat[n]] >= thr[n]`` — computed densely as a
     one-hot feature-selection contraction ``(X @ FOH^T)`` followed by an
     elementwise compare. For the extended forest, the per-node test is
     ``dot(x, w_n) >= offset_n``: ``X @ W^T`` — a *real* matmul that lands on
     the MXU (the BASELINE.json north star: "hyperplane splits lower directly
     to XLA matmul").
  2. **Reachability by level**: a row reaches heap slot ``2i+1+b`` iff it
     reaches ``i`` and its bit matches. Expanding level ``l`` to ``l+1`` is a
     mask-and-interleave of the ``[C, 2^l]`` reach matrix — stack + reshape,
     no indexing at all.
  3. **Path length**: sum over levels of ``reach * leaf * (l + c(n))`` — a
     masked reduction.

Work per tree is ``O(C * M)`` dense ops versus ``O(C * h)`` gathers — a
~57x op-count increase (M=511, h=8) that is nonetheless far faster on vector
hardware because every op is a fused, full-width VPU/MXU instruction. Trees
are processed under ``lax.scan`` (constant memory in T), rows chunked by the
caller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.math import avg_path_length, height_of as _height_of
from .ext_growth import ExtendedForest
from .tree_growth import StandardForest


def _level_walk(B: jax.Array, is_internal: jax.Array, leaf_value: jax.Array, h: int):
    """Shared reach-propagation over the implicit heap.

    ``B``: [C, M] go-right bits; ``is_internal``: [M]; ``leaf_value``: [M]
    (``depth + c(numInstances)`` at leaves, 0 elsewhere). Returns [C] path
    lengths. Python loop over levels is static (h+1 iterations) and fuses into
    one XLA computation.
    """
    C = B.shape[0]
    total = jnp.zeros((C,), jnp.float32)
    reach = jnp.ones((C, 1), jnp.bool_)
    for level in range(h + 1):
        start = (1 << level) - 1
        width = 1 << level
        internal_l = is_internal[start : start + width]  # [W]
        value_l = leaf_value[start : start + width]  # [W]
        # leaves contribute once, where reached
        total = total + jnp.einsum(
            "cw,w->c", reach.astype(jnp.float32), value_l
        )
        if level < h:
            B_l = B[:, start : start + width]
            alive = reach & internal_l[None, :]
            left = alive & ~B_l
            right = alive & B_l
            reach = jnp.stack([left, right], axis=2).reshape(C, 2 * width)
    return total


def _leaf_values(num_instances: jax.Array, h: int) -> jax.Array:
    """Per-slot ``depth + c(numInstances)`` at leaves, 0 elsewhere."""
    depth = jnp.concatenate(
        [jnp.full(((1 << level),), float(level), jnp.float32) for level in range(h + 1)]
    )  # exact static per-slot depth (slot levels of the implicit heap)
    is_leaf = num_instances >= 0
    return jnp.where(is_leaf, depth + avg_path_length(num_instances), 0.0)


def standard_path_lengths_dense(forest: StandardForest, X: jax.Array) -> jax.Array:
    """Dense scoring for the standard forest; ``f32[C]`` mean path lengths."""
    h = _height_of(forest.max_nodes)
    F = X.shape[1]

    def one_tree(carry, tree):
        feature, threshold, num_instances = tree
        # one-hot feature selection: xv[c, n] = X[c, feature[n]]
        foh = jax.nn.one_hot(jnp.maximum(feature, 0), F, dtype=X.dtype)  # [M, F]
        xv = jnp.einsum("cf,mf->cm", X, foh)
        B = xv >= threshold[None, :]
        leaf_value = _leaf_values(num_instances, h)
        pl = _level_walk(B, feature >= 0, leaf_value, h)
        return carry + pl, None

    total, _ = lax.scan(
        one_tree,
        jnp.zeros((X.shape[0],), jnp.float32),
        (forest.feature, forest.threshold, forest.num_instances),
    )
    return total / forest.num_trees


def extended_path_lengths_dense(forest: ExtendedForest, X: jax.Array) -> jax.Array:
    """Dense EIF scoring: hyperplane tests as one MXU matmul per tree."""
    h = _height_of(forest.max_nodes)
    F = X.shape[1]

    def one_tree(carry, tree):
        indices, weights, offset, num_instances = tree
        # densify the sparse hyperplanes: W[n, f] = sum_j w[n,j][indices[n,j]==f]
        foh = jax.nn.one_hot(jnp.maximum(indices, 0), F, dtype=X.dtype)  # [M,k,F]
        valid = (indices >= 0).astype(X.dtype)[..., None]
        W = jnp.einsum("mk,mkf->mf", weights * valid[..., 0], foh)  # [M, F]
        dots = X @ W.T  # [C, M] — MXU
        B = dots >= offset[None, :]
        leaf_value = _leaf_values(num_instances, h)
        pl = _level_walk(B, indices[:, 0] >= 0, leaf_value, h)
        return carry + pl, None

    total, _ = lax.scan(
        one_tree,
        jnp.zeros((X.shape[0],), jnp.float32),
        (forest.indices, forest.weights, forest.offset, forest.num_instances),
    )
    return total / forest.num_trees


def path_lengths_dense(forest, X: jax.Array) -> jax.Array:
    if isinstance(forest, StandardForest):
        return standard_path_lengths_dense(forest, X)
    return extended_path_lengths_dense(forest, X)
