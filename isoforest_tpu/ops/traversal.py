"""Batched path-length traversal (scoring) over heap-tensor forests.

The reference scores one row at a time inside a Spark UDF — a tail-recursive
pointer walk per tree (``IsolationTree.scala:196-229``;
``ExtendedIsolationTree.scala:283-355``), with the forest broadcast to every
executor. Here the forest is a set of HBM-resident arrays and traversal is a
``[trees, rows]`` batched gather program: a ``fori_loop`` of ``height`` steps,
each step gathering every row's current node record and advancing
``node -> 2*node + 1 + (go_right)``. Rows that reached a leaf stop moving —
the loop is fixed-trip so the whole thing stays a single fused XLA program
(and vectorises perfectly on TPU; this is also the Pallas candidate of
SURVEY.md §7.2.4).

Path length = (depth of final leaf) + ``avg_path_length(leaf.numInstances)``
(IsolationTree.scala:213-229); score ``2^(-E[h]/c(n))``
(IsolationForestModel.scala:135-138).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.math import avg_path_length, height_of as _height_of, score_from_path_length
from .ext_growth import ExtendedForest
from .tree_growth import StandardForest


def standard_path_lengths(forest: StandardForest, X: jax.Array) -> jax.Array:
    """Per-row mean path length over the forest; ``f32[C]`` for ``X: f32[C, F]``."""
    h = _height_of(forest.max_nodes)
    C = X.shape[0]

    def one_tree(feature, threshold, num_instances):
        def step(_, carry):
            node, depth = carry
            f = feature[node]  # [C]
            leaf = f < 0
            xv = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
            go_right = (xv >= threshold[node]).astype(jnp.int32)
            nxt = 2 * node + 1 + go_right
            node = jnp.where(leaf, node, nxt)
            depth = jnp.where(leaf, depth, depth + 1)
            return node, depth

        node0 = jnp.zeros((C,), jnp.int32)
        depth0 = jnp.zeros((C,), jnp.int32)
        node, depth = lax.fori_loop(0, h, step, (node0, depth0))
        return depth.astype(jnp.float32) + avg_path_length(num_instances[node])

    per_tree = jax.vmap(one_tree)(
        forest.feature, forest.threshold, forest.num_instances
    )  # [T, C]
    return jnp.mean(per_tree, axis=0)


def extended_path_lengths(forest: ExtendedForest, X: jax.Array) -> jax.Array:
    """EIF variant: hyperplane test ``dot(x, w) < offset`` -> left
    (ExtendedIsolationTree.scala:333-355, float32 dot per ExtendedUtils.scala:46-55)."""
    h = _height_of(forest.max_nodes)
    C = X.shape[0]

    def one_tree(indices, weights, offset, num_instances):
        def step(_, carry):
            node, depth = carry
            sub = indices[node]  # [C, k]
            leaf = sub[:, 0] < 0
            xv = jnp.take_along_axis(X, jnp.maximum(sub, 0), axis=1)  # [C, k]
            dot = jnp.sum(xv * weights[node], axis=1)
            go_right = (dot >= offset[node]).astype(jnp.int32)
            nxt = 2 * node + 1 + go_right
            node = jnp.where(leaf, node, nxt)
            depth = jnp.where(leaf, depth, depth + 1)
            return node, depth

        node0 = jnp.zeros((C,), jnp.int32)
        depth0 = jnp.zeros((C,), jnp.int32)
        node, depth = lax.fori_loop(0, h, step, (node0, depth0))
        return depth.astype(jnp.float32) + avg_path_length(num_instances[node])

    per_tree = jax.vmap(one_tree)(
        forest.indices, forest.weights, forest.offset, forest.num_instances
    )
    return jnp.mean(per_tree, axis=0)


def path_lengths(forest, X: jax.Array) -> jax.Array:
    if isinstance(forest, StandardForest):
        return standard_path_lengths(forest, X)
    return extended_path_lengths(forest, X)


# Per-backend winners for strategy="auto", both MEASURED. CPU: the
# hand-scheduled C++ walker beats the XLA gather path ~4x single-core,
# which itself beats dense ~50x (benchmarks/README.md). TPU (measured
# 2026-07-29 on a live v5e chip): dense 0.22 s vs gather 3.86 s on a
# 131k-row slice — per-lane gathers serialise in the XLA lowering while
# the dense level-walk is full-width VPU/MXU work (docs/DESIGN.md §3).
# bench.py re-measures the ranking on whatever backend is live and pins
# its own process via ISOFOREST_TPU_STRATEGY; if the fixed Pallas kernel
# out-measures dense in the next live window, this table is the one
# source to update.
PLATFORM_DEFAULT_STRATEGY = {
    "cpu": "native",
    "tpu": "dense",
}

# Measured batch-regime crossover on a live v5e (benchmarks/README.md,
# 2026-07-29): the Pallas kernel is a single fused launch and wins small
# batches (0.31 s vs dense 0.73 s at 131k rows; 0.071 s vs 0.074 s at 8k
# re-confirmed by bench.py --full), while the dense scan wins large batches
# (1.04 s vs 2.21 s at the 1M headline; 0.53 s vs ~1.0 s at 524k rows).
# The flip sits between 131k and 524k rows; 2^18 splits the measured
# bracket — refine with an on-chip point at 262k when a live window allows.
# Standard forests only: the EIF Pallas kernels are precision-fenced on
# real TPU (see the fence in :func:`score_matrix`).
PALLAS_MAX_ROWS = 1 << 18

STRATEGIES = ("gather", "dense", "pallas", "walk", "native")

_warned_native_fallback = False
_warned_eif_pallas_fence = False
_warned_walk_wide_k = False


def _live_platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:  # backend bring-up failed; any strategy works on CPU
        return "cpu"


def default_strategy(
    num_rows: int | None = None,
    extended: bool = False,
    platform: str | None = None,
) -> str:
    """Resolve the measured/predicted best strategy for the live backend.

    With ``num_rows`` the TPU choice is batch-regime-aware (VERDICT r2
    item 3): standard-forest batches at or below :data:`PALLAS_MAX_ROWS`
    take the Pallas kernel's single fused launch; larger batches (or no
    row-count information) keep the dense level-walk. Extended forests
    always resolve dense on TPU — their Pallas kernels are fenced at
    bf16-mantissa precision on the current toolchain.
    """
    if platform is None:
        platform = _live_platform()
    choice = PLATFORM_DEFAULT_STRATEGY.get(platform, "gather")
    if (
        platform == "tpu"
        and not extended
        and num_rows is not None
        and 0 < num_rows <= PALLAS_MAX_ROWS
    ):
        choice = "pallas"
    if choice == "native":
        from .. import native

        if not native.available():  # no C++ toolchain: portable jax path
            return "gather"
    return choice


def _score_native(forest, X, num_samples: int):
    """C++ walker path: pure numpy in/out, no jax, no chunking/padding.
    Returns None when the native library is unavailable."""
    from .. import native

    h = _height_of(forest.max_nodes)
    X = np.ascontiguousarray(X, np.float32)
    if isinstance(forest, StandardForest):
        pl = native.score_standard(
            forest.feature, forest.threshold, forest.num_instances, X, h
        )
    else:
        pl = native.score_extended(
            forest.indices,
            forest.weights,
            forest.offset,
            forest.num_instances,
            X,
            h,
        )
    if pl is None:
        return None
    c = float(avg_path_length(num_samples))
    return np.exp2(-pl / c).astype(np.float32)


@functools.partial(jax.jit, static_argnames=("num_samples", "strategy"))
def _score_chunk(forest, X, num_samples: int, strategy: str = "dense") -> jax.Array:
    if strategy == "dense":
        from .dense_traversal import path_lengths_dense

        pl = path_lengths_dense(forest, X)
    else:
        pl = path_lengths(forest, X)
    return score_from_path_length(pl, num_samples)


# Measured on a live v5e (2026-07-29, 524k rows x 100 trees, dense): bigger
# chunks win monotonically — 0.81 s at 2^17, 0.64 s at 2^18, 0.53 s at 2^19
# (single chunk) vs 0.35 s for the raw kernel on resident data; the gap is
# per-chunk dispatch + tunnel transfer overhead. CPU keeps the smaller
# working set (the XLA:CPU paths are latency- not dispatch-bound).
PLATFORM_DEFAULT_CHUNK = {"tpu": 1 << 19, "cpu": 1 << 18}


def _default_chunk_size() -> int:
    return PLATFORM_DEFAULT_CHUNK.get(_live_platform(), 1 << 18)


def score_matrix(
    forest,
    X,
    num_samples: int,
    chunk_size: int | None = None,
    strategy: str = "auto",
) -> np.ndarray:
    """Score a full ``[N, F]`` matrix, chunked along rows.

    Chunking bounds the traversal state so big-N scoring streams through a
    fixed working set; ``chunk_size=None`` resolves the measured per-backend
    default (:data:`PLATFORM_DEFAULT_CHUNK`). Row counts are always padded
    up to a power-of-two bucket (min 1024) so varying batch sizes reuse a
    handful of compiled programs instead of recompiling per distinct ``n``.

    ``strategy``:
      * ``"gather"`` — pointer-walk formulation, ``O(C * h)`` gathers.
        Fastest on CPU (measured ~50x over dense; the CPU auto default).
      * ``"dense"`` — gather-free level-walk (:mod:`.dense_traversal`),
        ``O(C * M)`` full-width vector ops; the hyperplane variant runs on
        the MXU. Candidate fast path on TPU where per-lane gathers
        serialise.
      * ``"pallas"`` — hand-blocked TPU kernel of the dense algorithm
        (:mod:`.pallas_traversal`).
      * ``"walk"`` — O(h) dynamic-gather node-id walk (:mod:`.pallas_walk`):
        the reference pointer walk's work profile (~70 element-ops per
        row-tree vs dense's ~6,600) mapped onto Mosaic's single-vreg
        ``tpu.dynamic_gather``. Falls back to dense for EIF hyperplanes
        wider than 16 coordinates.
      * ``"native"`` — hand-scheduled C++ walker (:mod:`..native` scorer),
        the CPU fast path; no jax involvement at all.
      * ``"auto"`` — ``ISOFOREST_TPU_STRATEGY`` env var if set, else the
        per-backend, batch-regime-aware default (:func:`default_strategy`:
        native C++ on CPU; on TPU, pallas for standard-forest batches up
        to :data:`PALLAS_MAX_ROWS` and dense above — both crossovers
        measured on a live v5e) — a fresh process on each backend picks
        its measured/predicted winner with no env var and no bench run.
        ``bench.py`` measures all strategies on the live backend and
        reports the ranking.
    """
    if not isinstance(X, (np.ndarray, jax.Array)):
        X = np.asarray(X, np.float32)
    n = X.shape[0]
    extended = not isinstance(forest, StandardForest)
    if strategy == "auto":
        strategy = os.environ.get("ISOFOREST_TPU_STRATEGY") or default_strategy(
            num_rows=n, extended=extended
        )
        if strategy not in STRATEGIES:
            from ..utils import logger

            logger.warning(
                "ISOFOREST_TPU_STRATEGY=%r is not one of %s; using %s",
                strategy,
                "/".join(STRATEGIES),
                default_strategy(num_rows=n, extended=extended),
            )
            strategy = default_strategy(num_rows=n, extended=extended)
    if strategy not in STRATEGIES:
        raise ValueError(
            f"unknown scoring strategy {strategy!r}; expected one of "
            f"'auto', {', '.join(repr(s) for s in STRATEGIES)}"
        )
    if strategy == "walk":
        from . import pallas_walk

        if not pallas_walk.supports(forest):
            # wide-k EIF hyperplanes: the gather+fma chain stops paying;
            # dense keeps HIGHEST-precision semantics. Warn once so pinned
            # measurements are never silently mislabeled (same contract as
            # the pallas fence / native fallback below).
            global _warned_walk_wide_k
            if not _warned_walk_wide_k:
                _warned_walk_wide_k = True
                from ..utils import logger

                logger.warning(
                    "strategy='walk' supports EIF hyperplanes up to k=%d "
                    "coordinates; this forest has k=%d — scoring with the "
                    "dense strategy instead",
                    pallas_walk._WALK_K_MAX,
                    forest.indices.shape[2],
                )
            strategy = "dense"
    if strategy == "pallas" and extended and _live_platform() == "tpu":
        # Precision fence (VERDICT r2 item 4 / ADVICE r2 medium): the EIF
        # Pallas kernels' hyperplane contractions run at the TPU's default
        # bf16-mantissa matmul precision — Precision.HIGHEST inside them
        # crashes the remote Mosaic compile helper (the only compile path
        # on this toolchain; benchmarks/tpu_probe_history.log 16:10Z) — the
        # same error class measured at up to 0.24 max path-length deviation
        # on the dense path before its r2 fix. CI's interpret-mode (CPU)
        # equivalence runs are exact f32 and cannot catch it, so real-TPU
        # extended scoring routes to the dense HIGHEST-precision path.
        global _warned_eif_pallas_fence
        if not _warned_eif_pallas_fence:
            _warned_eif_pallas_fence = True
            from ..utils import logger

            logger.warning(
                "strategy='pallas' for extended forests is fenced on TPU: "
                "the kernel's hyperplane matmul runs at bf16-mantissa "
                "precision on the current toolchain (measured error class: "
                "up to 0.24 path-length deviation); scoring with the dense "
                "HIGHEST-precision path instead"
            )
        strategy = "dense"
    if strategy == "native":
        out = _score_native(forest, X, num_samples)
        if out is not None:
            return out
        global _warned_native_fallback
        if not _warned_native_fallback:  # once, not per serving-loop call
            _warned_native_fallback = True
            from ..utils import logger

            logger.warning(
                "native scoring strategy unavailable (no C++ toolchain?); "
                "falling back to the ~4x-slower gather kernel"
            )
        strategy = "gather"
    if strategy == "pallas":
        from .pallas_traversal import path_lengths_pallas

        interpret = _live_platform() != "tpu"

        def run_chunk(chunk):
            pl_len = path_lengths_pallas(forest, chunk, interpret=interpret)
            return score_from_path_length(pl_len, num_samples)

    elif strategy == "walk":
        from .pallas_walk import path_lengths_walk

        interpret = _live_platform() != "tpu"

        def run_chunk(chunk):
            pl_len = path_lengths_walk(forest, chunk, interpret=interpret)
            return score_from_path_length(pl_len, num_samples)

    else:

        def run_chunk(chunk):
            return _score_chunk(forest, chunk, num_samples, strategy)

    if chunk_size is None:
        chunk_size = _default_chunk_size()
    if n == 0:
        return np.zeros((0,), np.float32)
    if n <= chunk_size:
        X = jnp.asarray(X, jnp.float32)
        bucket = max(1024, 1 << int(np.ceil(np.log2(n))))
        pad = bucket - n
        if pad:
            X = jnp.pad(X, ((0, pad), (0, 0)))
        return np.asarray(run_chunk(X)[:n])

    # Multi-chunk: (a) host-resident inputs are uploaded PER CHUNK inside
    # the loop — async dispatch overlaps chunk k+1's host->device transfer
    # with chunk k's compute (measured 26% faster than one upfront transfer
    # at 2M rows on a live v5e; the upfront copy serialises ~120 MB through
    # the tunnel before any compute starts at 10M rows); (b) every chunk is
    # dispatched before any result is pulled back, so device compute also
    # overlaps the device->host score transfers.
    streaming = not isinstance(X, jax.Array)
    Xd = X if streaming else jnp.asarray(X, jnp.float32)
    outs = []
    for start in range(0, n, chunk_size):
        chunk = Xd[start : start + chunk_size]
        if streaming:
            chunk = jnp.asarray(chunk, jnp.float32)
        pad = chunk_size - chunk.shape[0]
        if pad:
            chunk = jnp.pad(chunk, ((0, pad), (0, 0)))
        scores = run_chunk(chunk)
        outs.append(scores[: chunk_size - pad] if pad else scores)
    return np.concatenate([np.asarray(o) for o in outs])
