"""Batched path-length traversal (scoring) over heap-tensor forests.

The reference scores one row at a time inside a Spark UDF — a tail-recursive
pointer walk per tree (``IsolationTree.scala:196-229``;
``ExtendedIsolationTree.scala:283-355``), with the forest broadcast to every
executor. Here the forest is a set of HBM-resident arrays and traversal is a
batched gather program over the **finalized scoring layout** of
:mod:`.scoring_layout`: each step gathers every row's current PACKED node
record — value (threshold | leaf path-length LUT) and feature interleaved in
one contiguous buffer, ONE coalesced gather instead of three strided ones —
and advances ``node -> 2*node + 1 + (go_right)``. The loop is a
``lax.while_loop`` bounded at ``height + 1`` trips that exits as soon as
every row in the chunk sits at a leaf (Liu et al. 2008's short-path insight:
most rows terminate in few levels, so shallow forests pay only the levels
they use), and trees are processed in blocks of :data:`_TREE_BLOCK` under
``lax.scan`` so a block's node tables stay cache-resident across the whole
row tile (the caller's chunk).

Path length = the LUT value at the exit leaf — bitwise equal to
``depth + avg_path_length(leaf.numInstances)`` (IsolationTree.scala:213-229)
with the final ``numInstances`` gather and the transcendental hoisted to
layout build time; score ``2^(-E[h]/c(n))``
(IsolationForestModel.scala:135-138).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..resilience import faults
from ..resilience.degradation import degrade
from ..telemetry import _state as _telemetry_state
from ..telemetry.metrics import counter as _telemetry_counter
from ..telemetry.metrics import histogram as _telemetry_histogram
from ..telemetry.spans import set_span_attrs as _set_span_attrs
from ..telemetry.spans import span as _span
from ..utils.math import avg_path_length, height_of as _height_of, score_from_path_length
from ..utils.validation import validate_feature_vector_size
from .ext_growth import ExtendedForest
from .scoring_layout import (
    _Q16_FEATURE_SENTINEL,
    PackedStandardLayout,
    bitcast_f32_to_i32,
    get_layout,
    get_layout_q,
    pack_forest,
    quantized_unsupported_reason,
)
from .streaming import PLATFORM_DEFAULT_CHUNK, StreamingExecutor, pipeline_enabled
from .tree_growth import StandardForest

# Trees per lax.scan step of the gather walk. Blocking bounds the live
# [G, C] walk state while amortising per-step dispatch, and keeps one
# block's packed tables (G * M * 8 B ~ 32 KB at the default M=511) hot in
# cache across the entire row tile — the row-tile x tree-tile schedule the
# native walker applies at L2 scale (scorer.cpp TILE_BYTES).
_TREE_BLOCK = 8


def _pad_tree_blocks(packed: jax.Array, block: int) -> jax.Array:
    """Pad the tree axis to a block multiple with NEUTRAL records: feature
    -1 (immediate leaf) and value 0, so padded trees credit exactly 0 path
    length and the block sum needs no masking."""
    t = packed.shape[0]
    pad = (-t) % block
    if not pad:
        return packed
    neutral = jnp.zeros((pad,) + packed.shape[1:], packed.dtype)
    feat_lane = lax.bitcast_convert_type(
        jnp.full((), -1, jnp.int32), jnp.float32
    )
    if packed.shape[-1] == 2:  # standard record: (value, feature)
        neutral = neutral.at[..., 1].set(feat_lane)
    else:  # extended record: (value, indices..., weights...)
        k = (packed.shape[-1] - 1) // 2
        neutral = neutral.at[..., 1 : 1 + k].set(feat_lane)
    return jnp.concatenate([packed, neutral], axis=0)


def _walk_blocks(packed: jax.Array, num_trees: int, num_rows: int, one_tree) -> jax.Array:
    """Mean path length over all trees: scan over tree blocks, vmap inside.

    ``one_tree(packed_tree) -> f32[C]`` is the early-exit walk for a single
    packed ``[M, R]`` table.
    """
    padded = _pad_tree_blocks(packed, _TREE_BLOCK)
    g = min(_TREE_BLOCK, padded.shape[0])
    blocks = padded.reshape(padded.shape[0] // g, g, *padded.shape[1:])

    def block_step(total, blk):
        pl = jax.vmap(one_tree)(blk)  # [G, C]
        return total + jnp.sum(pl, axis=0), None

    total, _ = lax.scan(block_step, jnp.zeros((num_rows,), jnp.float32), blocks)
    return total / num_trees


def _walk_one_standard(packed: jax.Array, X: jax.Array, h: int) -> jax.Array:
    """Early-exit packed walk of one standard tree; ``packed: f32[M, 2]``."""
    C = X.shape[0]

    def cond(carry):
        i, node, out, done = carry
        return (i < h + 1) & ~jnp.all(done)

    def body(carry):
        i, node, out, done = carry
        rec = jnp.take(packed, node, axis=0)  # [C, 2] — ONE coalesced gather
        value = rec[:, 0]
        f = bitcast_f32_to_i32(rec[:, 1])
        leaf = f < 0
        out = jnp.where(leaf & ~done, value, out)
        xv = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        go_right = (xv >= value).astype(jnp.int32)
        node = jnp.where(leaf | done, node, 2 * node + 1 + go_right)
        return i + 1, node, out, done | leaf

    init = (
        jnp.zeros((), jnp.int32),
        jnp.zeros((C,), jnp.int32),
        jnp.zeros((C,), jnp.float32),
        jnp.zeros((C,), jnp.bool_),
    )
    _, _, out, _ = lax.while_loop(cond, body, init)
    return out


def _walk_one_extended(packed: jax.Array, X: jax.Array, h: int, k: int) -> jax.Array:
    """Early-exit packed walk of one EIF tree; ``packed: f32[M, 1 + 2k]``."""
    C = X.shape[0]

    def cond(carry):
        i, node, out, done = carry
        return (i < h + 1) & ~jnp.all(done)

    def body(carry):
        i, node, out, done = carry
        rec = jnp.take(packed, node, axis=0)  # [C, 1 + 2k] — one gather
        value = rec[:, 0]
        sub = bitcast_f32_to_i32(rec[:, 1 : 1 + k])  # [C, k]
        w = rec[:, 1 + k :]
        leaf = sub[:, 0] < 0
        out = jnp.where(leaf & ~done, value, out)
        xv = jnp.take_along_axis(X, jnp.maximum(sub, 0), axis=1)  # [C, k]
        # jnp.sum over the k axis — the same XLA reduce growth used, which
        # keeps exact dot == offset ties routing like growth did (PARITY.md)
        dot = jnp.sum(xv * w, axis=1)
        go_right = (dot >= value).astype(jnp.int32)
        node = jnp.where(leaf | done, node, 2 * node + 1 + go_right)
        return i + 1, node, out, done | leaf

    init = (
        jnp.zeros((), jnp.int32),
        jnp.zeros((C,), jnp.int32),
        jnp.zeros((C,), jnp.float32),
        jnp.zeros((C,), jnp.bool_),
    )
    _, _, out, _ = lax.while_loop(cond, body, init)
    return out


def _validate_width_host(forest, X, expected: int | None) -> None:
    """Width check for the path-length entry points: only when the input is
    a host array (inside jit/shard_map traces X is a tracer and the check
    already ran — or could not run — at the score_matrix boundary)."""
    if isinstance(X, np.ndarray):
        _validate_width(forest, int(X.shape[1]), expected)


def standard_path_lengths(
    forest: StandardForest,
    X: jax.Array,
    layout: PackedStandardLayout | None = None,
    expected_features: int | None = None,
) -> jax.Array:
    """Per-row mean path length over the forest; ``f32[C]`` for ``X: f32[C, F]``.

    ``layout`` is the prebuilt packed layout; ``None`` packs inline (pure
    jnp, so this stays legal — and the packed buffer stays sharded — inside
    ``jit``/``shard_map`` regions).
    """
    _validate_width_host(forest, X, expected_features)
    if layout is None:
        layout = pack_forest(forest)
    h = _height_of(forest.max_nodes)
    return _walk_blocks(
        layout.packed,
        forest.num_trees,
        X.shape[0],
        lambda p: _walk_one_standard(p, X, h),
    )


def extended_path_lengths(
    forest: ExtendedForest, X: jax.Array, layout=None, expected_features: int | None = None
) -> jax.Array:
    """EIF variant: hyperplane test ``dot(x, w) < offset`` -> left
    (ExtendedIsolationTree.scala:333-355, float32 dot per ExtendedUtils.scala:46-55)."""
    _validate_width_host(forest, X, expected_features)
    if layout is None:
        layout = pack_forest(forest)
    h = _height_of(forest.max_nodes)
    k = forest.indices.shape[2]
    return _walk_blocks(
        layout.packed,
        forest.num_trees,
        X.shape[0],
        lambda p: _walk_one_extended(p, X, h, k),
    )


def path_lengths(
    forest, X: jax.Array, layout=None, expected_features: int | None = None
) -> jax.Array:
    if isinstance(forest, StandardForest):
        return standard_path_lengths(forest, X, layout, expected_features)
    return extended_path_lengths(forest, X, layout, expected_features)


# -- quantized (q16) walk ---------------------------------------------------
# The rank-space plane of scoring_layout.pack_standard_q: rows binarize once
# per chunk to threshold ranks, each step gathers ONE u32 node record (4 B
# vs the f32 record's 8), and the branch test becomes an integer compare
# `rx > code` — exactly equivalent to `x >= threshold`, so the walk visits
# the same nodes and credits the same f32 leaf bits as the f32 plane
# (bitwise score parity pinned in tests/test_strategies.py).


def binarize_ranks(edges: jax.Array, X: jax.Array) -> jax.Array:
    """``rx[c, f]`` = number of edges <= ``X[c, f]`` (``side='right'``
    counts the edge itself, which is what makes ``rx > code`` identical to
    ``x >= threshold``)."""
    return jnp.searchsorted(jnp.asarray(edges), X, side="right").astype(
        jnp.int32
    )


def _pad_tree_axis(arr: jax.Array, block: int, fill) -> jax.Array:
    pad = (-arr.shape[0]) % block
    if not pad:
        return arr
    return jnp.concatenate(
        [arr, jnp.full((pad,) + arr.shape[1:], fill, arr.dtype)], axis=0
    )


def _walk_one_standard_q(
    packed: jax.Array, rx: jax.Array, lut: jax.Array, h: int
) -> jax.Array:
    """Early-exit rank walk of one quantized tree; ``packed: u32[M]``."""
    C = rx.shape[0]
    sentinel = jnp.uint32(_Q16_FEATURE_SENTINEL)

    def cond(carry):
        i, node, out, done = carry
        return (i < h + 1) & ~jnp.all(done)

    def body(carry):
        i, node, out, done = carry
        rec = jnp.take(packed, node, axis=0)  # [C] u32 — one 4 B gather
        f = (rec & sentinel).astype(jnp.int32)
        code = (rec >> jnp.uint32(16)).astype(jnp.int32)
        leaf = f == _Q16_FEATURE_SENTINEL
        # internal codes are ranks, not LUT indices — mask before the take
        out = jnp.where(
            leaf & ~done, jnp.take(lut, jnp.where(leaf, code, 0)), out
        )
        rxv = jnp.take_along_axis(
            rx, jnp.where(leaf, 0, f)[:, None], axis=1
        )[:, 0]
        go_right = (rxv > code).astype(jnp.int32)
        node = jnp.where(leaf | done, node, 2 * node + 1 + go_right)
        return i + 1, node, out, done | leaf

    init = (
        jnp.zeros((), jnp.int32),
        jnp.zeros((C,), jnp.int32),
        jnp.zeros((C,), jnp.float32),
        jnp.zeros((C,), jnp.bool_),
    )
    _, _, out, _ = lax.while_loop(cond, body, init)
    return out


def standard_path_lengths_q(
    forest: StandardForest, X: jax.Array, qlayout=None
) -> jax.Array:
    """Quantized-plane mean path lengths; bitwise equal to
    :func:`standard_path_lengths` (same block schedule, same leaf bits)."""
    if qlayout is None:
        qlayout = get_layout_q(forest)
    h = _height_of(forest.max_nodes)
    rx = binarize_ranks(qlayout.edges, X)
    # neutral padding record: leaf sentinel + code 0 -> credits lut[0] == 0
    padded = _pad_tree_axis(
        jnp.asarray(qlayout.packed), _TREE_BLOCK, np.uint32(_Q16_FEATURE_SENTINEL)
    )
    g = min(_TREE_BLOCK, padded.shape[0])
    blocks = padded.reshape(padded.shape[0] // g, g, *padded.shape[1:])
    lut = jnp.asarray(qlayout.lut)

    def block_step(total, blk):
        pl = jax.vmap(lambda p: _walk_one_standard_q(p, rx, lut, h))(blk)
        return total + jnp.sum(pl, axis=0), None

    total, _ = lax.scan(
        block_step, jnp.zeros((X.shape[0],), jnp.float32), blocks
    )
    return total / forest.num_trees


def extended_path_lengths_q(
    forest: ExtendedForest, X: jax.Array, qlayout=None
) -> jax.Array:
    """Quantized extended walk: i16 hyperplane indices (half the index
    stream), exact f32 weights/offsets — the decision arithmetic is the f32
    arithmetic unchanged, so parity with :func:`extended_path_lengths` is
    bitwise by construction."""
    if qlayout is None:
        qlayout = get_layout_q(forest)
    h = _height_of(forest.max_nodes)
    C = X.shape[0]
    idx_p = _pad_tree_axis(jnp.asarray(qlayout.indices), _TREE_BLOCK, np.int16(-1))
    w_p = _pad_tree_axis(jnp.asarray(qlayout.weights), _TREE_BLOCK, 0.0)
    v_p = _pad_tree_axis(jnp.asarray(qlayout.value), _TREE_BLOCK, 0.0)
    g = min(_TREE_BLOCK, idx_p.shape[0])
    blocks = tuple(
        a.reshape(a.shape[0] // g, g, *a.shape[1:]) for a in (idx_p, w_p, v_p)
    )

    def one_tree(idx, w, val):
        def cond(carry):
            i, node, out, done = carry
            return (i < h + 1) & ~jnp.all(done)

        def body(carry):
            i, node, out, done = carry
            value = jnp.take(val, node)
            sub = jnp.take(idx, node, axis=0).astype(jnp.int32)  # [C, k]
            w_n = jnp.take(w, node, axis=0)
            leaf = sub[:, 0] < 0
            out = jnp.where(leaf & ~done, value, out)
            xv = jnp.take_along_axis(X, jnp.maximum(sub, 0), axis=1)
            # same reduce as _walk_one_extended — tie routing identical
            dot = jnp.sum(xv * w_n, axis=1)
            go_right = (dot >= value).astype(jnp.int32)
            node = jnp.where(leaf | done, node, 2 * node + 1 + go_right)
            return i + 1, node, out, done | leaf

        init = (
            jnp.zeros((), jnp.int32),
            jnp.zeros((C,), jnp.int32),
            jnp.zeros((C,), jnp.float32),
            jnp.zeros((C,), jnp.bool_),
        )
        _, _, out, _ = lax.while_loop(cond, body, init)
        return out

    def block_step(total, blk):
        pl = jax.vmap(one_tree)(*blk)
        return total + jnp.sum(pl, axis=0), None

    total, _ = lax.scan(block_step, jnp.zeros((C,), jnp.float32), blocks)
    return total / forest.num_trees


def path_lengths_q(forest, X: jax.Array, qlayout=None) -> jax.Array:
    if isinstance(forest, StandardForest):
        return standard_path_lengths_q(forest, X, qlayout)
    return extended_path_lengths_q(forest, X, qlayout)


# Per-backend winners for strategy="auto", both MEASURED. CPU: the
# hand-scheduled C++ walker beats the XLA gather path ~4x single-core,
# which itself beats dense ~50x (benchmarks/README.md). TPU (measured
# 2026-07-29 on a live v5e chip): dense 0.22 s vs gather 3.86 s on a
# 131k-row slice — per-lane gathers serialise in the XLA lowering while
# the dense level-walk is full-width VPU/MXU work (docs/DESIGN.md §3).
# bench.py re-measures the ranking on whatever backend is live and pins
# its own process via ISOFOREST_TPU_STRATEGY; if the fixed Pallas kernel
# out-measures dense in the next live window, this table is the one
# source to update.
PLATFORM_DEFAULT_STRATEGY = {
    "cpu": "native",
    "tpu": "dense",
}

# Measured batch-regime crossover on a live v5e (benchmarks/README.md,
# 2026-07-29): the Pallas kernel is a single fused launch and wins small
# batches (0.31 s vs dense 0.73 s at 131k rows; 0.071 s vs 0.074 s at 8k
# re-confirmed by bench.py --full), while the dense scan wins large batches
# (1.04 s vs 2.21 s at the 1M headline; 0.53 s vs ~1.0 s at 524k rows).
# The flip sits between 131k and 524k rows; 2^18 splits the measured
# bracket — refine with an on-chip point at 262k when a live window allows.
# Standard forests only: the EIF Pallas kernels are precision-fenced on
# real TPU (see the fence in :func:`score_matrix`).
PALLAS_MAX_ROWS = 1 << 18

STRATEGIES = ("gather", "dense", "pallas", "walk", "native", "q16")

# Scoring telemetry (docs/observability.md): per-strategy wall-clock of the
# RESOLVED strategy's execution (post-ladder, so a native→gather fallback
# times as gather) and rows scored. Module-cached metric objects: the
# serving path calls score_matrix in a tight loop and must not pay a
# registry lookup per batch. Autotune probes (docs/autotune.md) run real
# strategies through score_matrix and suppress these series for their
# thread (suppress_scoring_metrics) so probe wall-clock never pollutes a
# serving latency histogram.
_SCORING_SECONDS = _telemetry_histogram(
    "isoforest_scoring_seconds",
    "Wall-clock seconds per score_matrix execution, by resolved strategy",
    labelnames=("strategy",),
)
_SCORED_ROWS_TOTAL = _telemetry_counter(
    "isoforest_scored_rows_total",
    "Rows scored by score_matrix, by resolved strategy",
    labelnames=("strategy",),
)

_METRICS_LOCAL = threading.local()


@contextlib.contextmanager
def suppress_scoring_metrics():
    """Suppress the per-strategy scoring histogram/counter for the calling
    thread — used by autotune probes so timed probe executions never land
    in the serving latency series (docs/autotune.md)."""
    prev = getattr(_METRICS_LOCAL, "suppress", False)
    _METRICS_LOCAL.suppress = True
    try:
        yield
    finally:
        _METRICS_LOCAL.suppress = prev


def _scoring_metrics_on() -> bool:
    return _telemetry_state.enabled() and not getattr(
        _METRICS_LOCAL, "suppress", False
    )

# Forest -> minimum input width (1 + max referenced feature id), cached by
# array identity: serving loops score small batches in a tight loop and the
# [T, M] reduction (plus a device->host copy for jax-resident forests) must
# not re-run per call. Bounded FIFO, same policy as the native prep cache.
_MIN_FEATURES_CACHE: dict = {}
_MIN_FEATURES_CACHE_MAX = 16


def forest_min_features(forest) -> int:
    """Smallest feature-vector width the forest can traverse without an
    out-of-range gather: ``1 + max(feature id)`` (0 for all-leaf forests)."""
    ids = forest.feature if isinstance(forest, StandardForest) else forest.indices
    key = id(ids)
    hit = _MIN_FEATURES_CACHE.get(key)
    if hit is not None and hit[0] is ids:
        return hit[1]
    width = int(np.max(np.asarray(ids))) + 1 if np.asarray(ids).size else 0
    width = max(width, 0)  # all-leaf forests hold only -1 sentinels
    if len(_MIN_FEATURES_CACHE) >= _MIN_FEATURES_CACHE_MAX:
        _MIN_FEATURES_CACHE.pop(next(iter(_MIN_FEATURES_CACHE)))
    _MIN_FEATURES_CACHE[key] = (ids, width)
    return width


def _validate_width(forest, num_features: int, expected: int | None) -> None:
    """Wrong-width X must raise a clear host-side error before dispatch, not
    an XLA shape error (or a silently clamped gather) deep in a kernel."""
    if expected is not None:
        validate_feature_vector_size(num_features, expected)
    floor = forest_min_features(forest)
    if num_features < floor:
        raise ValueError(
            f"feature vector has {num_features} features, but the forest "
            f"splits on feature index {floor - 1} — the model was trained on "
            f">= {floor} features"
        )


def _live_platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:  # backend bring-up failed; any strategy works on CPU
        return "cpu"


def default_strategy(
    num_rows: int | None = None,
    extended: bool = False,
    platform: str | None = None,
) -> str:
    """Resolve the measured/predicted best strategy for the live backend.

    With ``num_rows`` the TPU choice is batch-regime-aware (VERDICT r2
    item 3): standard-forest batches at or below :data:`PALLAS_MAX_ROWS`
    take the Pallas kernel's single fused launch; larger batches (or no
    row-count information) keep the dense level-walk. Extended forests
    always resolve dense on TPU — their Pallas kernels are fenced at
    bf16-mantissa precision on the current toolchain.
    """
    if platform is None:
        platform = _live_platform()
    choice = PLATFORM_DEFAULT_STRATEGY.get(platform, "gather")
    if (
        platform == "tpu"
        and not extended
        and num_rows is not None
        and 0 < num_rows <= PALLAS_MAX_ROWS
    ):
        choice = "pallas"
    if choice == "native":
        from .. import native

        if not native.available():  # no C++ toolchain: portable jax path
            return "gather"
    return choice


def _score_native(forest, X, num_samples: int):
    """C++ walker path: pure numpy in/out, no jax, no chunking/padding.
    Returns None when the native library is unavailable."""
    from .. import native

    h = _height_of(forest.max_nodes)
    X = np.ascontiguousarray(X, np.float32)
    if isinstance(forest, StandardForest):
        pl = native.score_standard(
            forest.feature, forest.threshold, forest.num_instances, X, h
        )
    else:
        pl = native.score_extended(
            forest.indices,
            forest.weights,
            forest.offset,
            forest.num_instances,
            X,
            h,
        )
    if pl is None:
        return None
    c = float(avg_path_length(num_samples))
    return np.exp2(-pl / c).astype(np.float32)


def _score_chunk_impl(
    forest, layout, X, num_samples: int, strategy: str = "dense"
) -> jax.Array:
    if strategy == "dense":
        from .dense_traversal import path_lengths_dense

        pl = path_lengths_dense(forest, X, layout)
    else:
        pl = path_lengths(forest, X, layout)
    return score_from_path_length(pl, num_samples)


def _score_chunk_q_impl(
    forest, qlayout, X, num_samples: int, formulation: str = "gather"
) -> jax.Array:
    """Quantized-plane chunk scorer: the gather-style rank walk everywhere,
    or the dense rank level-walk on TPU (where per-lane gathers serialise —
    the same dispatch logic as the f32 auto default)."""
    if formulation == "dense" and isinstance(forest, StandardForest):
        from .dense_traversal import standard_path_lengths_dense_q

        pl = standard_path_lengths_dense_q(forest, X, qlayout)
    else:
        pl = path_lengths_q(forest, X, qlayout)
    return score_from_path_length(pl, num_samples)


_score_chunk_q = jax.jit(
    _score_chunk_q_impl, static_argnames=("num_samples", "formulation")
)
_score_chunk_q_donated = jax.jit(
    _score_chunk_q_impl,
    static_argnames=("num_samples", "formulation"),
    donate_argnums=(2,),
)


def _score_native_q16(forest, X, num_samples: int):
    """Native q16 walker path (standard forests): host-side rank
    binarization + the 16-bit-gather C++ kernel. None when the native
    library (or the q16 symbol) is unavailable."""
    from .. import native

    if not isinstance(forest, StandardForest):
        return None
    h = _height_of(forest.max_nodes)
    X = np.ascontiguousarray(X, np.float32)
    pl = native.score_standard_q16(
        forest.feature, forest.threshold, forest.num_instances, X, h
    )
    if pl is None:
        return None
    c = float(avg_path_length(num_samples))
    return np.exp2(-pl / c).astype(np.float32)


_score_chunk = jax.jit(
    _score_chunk_impl, static_argnames=("num_samples", "strategy")
)
# Donating variant (ROADMAP item 3 / ISSUE 6 satellite): steady-state
# serving scores a fresh chunk buffer per batch; donating it lets XLA
# reuse the allocation for intermediates/outputs instead of growing the
# arena per call. Selected only when score_matrix OWNS the buffer (it was
# uploaded/padded here, never the caller's array — donation deletes the
# input) and the backend honors donation (donation_supported).
_score_chunk_donated = jax.jit(
    _score_chunk_impl,
    static_argnames=("num_samples", "strategy"),
    donate_argnums=(2,),
)


def donation_supported(platform: str | None = None) -> bool:
    """XLA honors input-buffer donation on TPU/GPU; XLA:CPU silently ignores
    it and jax warns ('Some donated buffers were not usable'), so CPU keeps
    the non-donating programs."""
    if platform is None:
        platform = _live_platform()
    return platform in ("tpu", "gpu")


def batch_bucket(n: int) -> int:
    """Power-of-two padding bucket (min 1024) for a row count — ONE formula
    shared by score_matrix padding, ``model.warmup`` and the autotuner's
    batch keys (docs/autotune.md), so tuned decisions, warmed programs and
    actual executions always land on the same compiled shapes."""
    return max(1024, 1 << (max(int(n), 1) - 1).bit_length())


def _pad_buckets_enabled(override: bool | None) -> bool:
    if override is not None:
        return bool(override)
    return os.environ.get("ISOFOREST_TPU_PAD_BUCKETS", "1").strip().lower() not in (
        "0",
        "false",
        "off",
        "no",
    )


def _default_chunk_size() -> int:
    return PLATFORM_DEFAULT_CHUNK.get(_live_platform(), 1 << 18)


def _score_matrix_impl(
    forest,
    X,
    num_samples: int,
    chunk_size: int | None = None,
    strategy: str = "auto",
    layout=None,
    strict: bool = False,
    expected_features: int | None = None,
    timeout_s: float | None = None,
    pad_to_bucket: bool | None = None,
    pipeline: bool | None = None,
) -> np.ndarray:
    """Score a full ``[N, F]`` matrix, chunked along rows.

    Chunking bounds the traversal state so big-N scoring streams through a
    fixed working set; ``chunk_size=None`` resolves the measured per-backend
    default (:data:`PLATFORM_DEFAULT_CHUNK`). Row counts are padded up to a
    power-of-two bucket (min 1024, :func:`batch_bucket` — the same buckets
    the autotuner keys on) so varying batch sizes reuse a handful of
    compiled programs instead of recompiling per distinct ``n``;
    ``pad_to_bucket=False`` (or ``ISOFOREST_TPU_PAD_BUCKETS=0``) opts out
    and compiles per exact row count.

    ``strategy``:
      * ``"gather"`` — pointer-walk formulation, ``O(C * h)`` gathers.
        Fastest on CPU (measured ~50x over dense; the CPU auto default).
      * ``"dense"`` — gather-free level-walk (:mod:`.dense_traversal`),
        ``O(C * M)`` full-width vector ops; the hyperplane variant runs on
        the MXU. Candidate fast path on TPU where per-lane gathers
        serialise.
      * ``"pallas"`` — hand-blocked TPU kernel of the dense algorithm
        (:mod:`.pallas_traversal`).
      * ``"walk"`` — O(h) dynamic-gather node-id walk (:mod:`.pallas_walk`):
        the reference pointer walk's work profile (~70 element-ops per
        row-tree vs dense's ~6,600) mapped onto Mosaic's single-vreg
        ``tpu.dynamic_gather``. Falls back to dense for EIF hyperplanes
        wider than 16 coordinates.
      * ``"native"`` — hand-scheduled C++ walker (:mod:`..native` scorer),
        the CPU fast path; no jax involvement at all.
      * ``"q16"`` — quantized scoring plane
        (:func:`~isoforest_tpu.ops.scoring_layout.pack_standard_q`): 4-byte
        rank-coded node records + shared leaf LUT, decision-identical (and
        score-bitwise-identical per family) to the f32 plane. On CPU it
        runs the native 16-bit-gather walker when available, else the jax
        rank walk; on TPU the dense rank level-walk. Forests past the u16
        capacity fences take the ``q16_unsupported`` rung onto gather.
      * ``"auto"`` — resolved by the measured autotuner
        (:mod:`~isoforest_tpu.tuning`, docs/autotune.md): an
        ``ISOFOREST_TPU_STRATEGY`` pin always wins; else the persisted
        cost-model table for this (backend, model-shape, batch-bucket) key;
        a cold/stale key runs a short warmed probe of every eligible
        strategy and persists the winner; with the tuner disabled
        (``ISOFOREST_TPU_AUTOTUNE=0``) or a failed probe, the static
        per-backend preference table (:func:`default_strategy`) stands.
        Every resolution emits one ``autotune.decision`` telemetry event
        with ``source ∈ {table, probe, pin, fallback}``.

    ``layout``: prebuilt finalized scoring layout
    (:func:`~isoforest_tpu.ops.scoring_layout.pack_forest`); ``None``
    resolves the per-forest cache (:func:`.scoring_layout.get_layout`).
    The full strategy-selection table lives in docs/scoring_layout.md.

    ``strict=True`` raises :class:`~isoforest_tpu.resilience.DegradationError`
    wherever the resolved strategy would otherwise fall back to a different
    one (the degradation ladder, docs/resilience.md) — for serving stacks
    whose latency SLO depends on the pinned kernel actually running.
    ``expected_features`` (the fitted model's recorded width) turns a
    wrong-width ``X`` into an immediate ValueError; independent of it, a
    matrix narrower than the forest's highest split feature is always
    refused before dispatch.

    ``timeout_s`` arms the scoring watchdog
    (:mod:`~isoforest_tpu.resilience.watchdog`): the resolved strategy's
    whole execution runs under a hard wall-clock deadline, and a stall
    (wedged native walker, hung Pallas compile) is abandoned and retried
    once on the portable gather kernel via the ``scoring_timeout`` rung —
    ``strict=True`` raises at the timeout instead. A gather run that
    itself times out raises
    :class:`~isoforest_tpu.resilience.WatchdogTimeout`.

    Multi-chunk execution runs through the streaming micro-batch executor
    (:mod:`.streaming`, docs/pipeline.md): host-resident inputs stage
    chunk *k+1* into a reusable host buffer and issue its (committed,
    async) ``device_put`` while chunk *k* computes, with results fetched
    at a lag of one — H2D, compute and D2H overlap, scores bitwise equal
    to the single-shot path. ``pipeline=False`` (or
    ``ISOFOREST_TPU_PIPELINE=0``) keeps chunking but uploads each chunk
    synchronously; backends without committed async ``device_put`` take
    the ``pipeline_fallback`` rung onto the same synchronous path.
    """
    if not isinstance(X, (np.ndarray, jax.Array)):
        X = np.asarray(X, np.float32)
    n = X.shape[0]
    _validate_width(forest, int(X.shape[1]), expected_features)
    extended = not isinstance(forest, StandardForest)
    if strategy == "auto":
        from ..tuning import resolve_decision

        decision = resolve_decision(
            forest,
            X,
            num_samples,
            platform=_live_platform(),
            strict=strict,
            layout=layout,
        )
        strategy = decision.strategy
        # the enclosing score_matrix span answers "which kernel ran and
        # WHY": the resolved winner plus where the decision came from
        # (table/probe/pin/fallback — docs/autotune.md)
        _set_span_attrs(
            strategy=strategy, strategy_source=decision.source, rows=n
        )
    else:
        _set_span_attrs(strategy=strategy, strategy_source="explicit", rows=n)
    if strategy not in STRATEGIES:
        raise ValueError(
            f"unknown scoring strategy {strategy!r}; expected one of "
            f"'auto', {', '.join(repr(s) for s in STRATEGIES)}"
        )
    if strategy == "walk":
        from . import pallas_walk

        if _live_platform() != "tpu" and not os.environ.get(
            "ISOFOREST_TPU_INTERPRET"
        ):
            # Off-TPU the walk kernel can only run in Pallas interpret mode
            # — minutes per rep, never what an operator pinning
            # ISOFOREST_TPU_STRATEGY=walk on a CPU host meant. Take the
            # portable gather path through the ladder. CI's
            # kernel-equivalence tests opt back into interpret mode via
            # ISOFOREST_TPU_INTERPRET=1 (tests/conftest.py).
            strategy = degrade(
                "walk_off_tpu",
                "walk",
                "gather",
                detail=(
                    "strategy='walk' requires a TPU backend (off-TPU it "
                    "would run the Pallas kernel in interpret mode, minutes "
                    "per batch); scoring with the gather strategy instead. "
                    "Set ISOFOREST_TPU_INTERPRET=1 to force interpret mode."
                ),
                strict=strict,
            )
        else:
            reason = pallas_walk.unsupported_reason(forest)
            if reason is not None:
                # wide-k EIF hyperplanes (the gather+fma chain stops
                # paying) or node tables past the VMEM budget (Mosaic
                # compilation would fail outright): dense keeps
                # HIGHEST-precision semantics.
                strategy = degrade(
                    "walk_unsupported",
                    "walk",
                    "dense",
                    detail=(
                        f"strategy='walk' does not cover this forest "
                        f"({reason}); scoring with the dense strategy instead"
                    ),
                    strict=strict,
                )
    if strategy == "pallas" and extended and _live_platform() == "tpu":
        # Precision fence (VERDICT r2 item 4 / ADVICE r2 medium): the EIF
        # Pallas kernels' hyperplane contractions run at the TPU's default
        # bf16-mantissa matmul precision — Precision.HIGHEST inside them
        # crashes the remote Mosaic compile helper (the only compile path
        # on this toolchain; benchmarks/tpu_probe_history.log 16:10Z) — the
        # same error class measured at up to 0.24 max path-length deviation
        # on the dense path before its r2 fix. CI's interpret-mode (CPU)
        # equivalence runs are exact f32 and cannot catch it, so real-TPU
        # extended scoring routes to the dense HIGHEST-precision path.
        strategy = degrade(
            "eif_pallas_fence",
            "pallas",
            "dense",
            detail=(
                "strategy='pallas' for extended forests is fenced on TPU: "
                "the kernel's hyperplane matmul runs at bf16-mantissa "
                "precision on the current toolchain (measured error class: "
                "up to 0.24 path-length deviation); scoring with the dense "
                "HIGHEST-precision path instead"
            ),
            strict=strict,
        )
    if strategy == "q16":
        q_reason = quantized_unsupported_reason(forest)
        if q_reason is not None:
            # capacity fence: the u16 code/feature lanes cannot represent
            # this forest (docs/scoring_layout.md §quantization); gather is
            # the always-eligible portable stand-in
            strategy = degrade(
                "q16_unsupported",
                "q16",
                "gather",
                detail=(
                    f"strategy='q16' does not cover this forest ({q_reason}); "
                    "scoring with the gather strategy instead"
                ),
                strict=strict,
            )
    if (
        strategy == "q16"
        and isinstance(forest, StandardForest)
        and _live_platform() == "cpu"
    ):
        # CPU q16 executor: the native 16-bit-gather walker when the C++
        # toolchain is present. An absent library is NOT a rung — the jax
        # rank walk below is the same strategy on the same representation,
        # just the portable executor for it.
        faults.check_strategy("q16")
        timed_out = False
        t0 = time.perf_counter() if _scoring_metrics_on() else 0.0
        if timeout_s is None:
            out = _score_native_q16(forest, X, num_samples)
        else:
            from ..resilience import watchdog as _watchdog

            def _native_q16_run():
                faults.maybe_slow_collective("q16")
                return _score_native_q16(forest, X, num_samples)

            try:
                out = _watchdog.run_with_deadline(
                    _native_q16_run, timeout_s, describe="scoring strategy 'q16'"
                )
            except _watchdog.WatchdogTimeout:
                timed_out = True
                out = None
        if out is not None:
            if _scoring_metrics_on():
                _SCORING_SECONDS.observe(time.perf_counter() - t0, strategy="q16")
                _SCORED_ROWS_TOTAL.inc(n, strategy="q16")
            return out
        if timed_out:
            strategy = degrade(
                "scoring_timeout",
                "q16",
                "gather",
                detail=(
                    f"scoring strategy 'q16' missed its {timeout_s:g}s "
                    "watchdog deadline (stalled walker abandoned); retrying "
                    "the batch once on the portable gather kernel"
                ),
                strict=strict,
            )
    if strategy == "native":
        faults.check_strategy("native")
        timed_out = False
        t0 = time.perf_counter() if _scoring_metrics_on() else 0.0
        if timeout_s is None:
            out = _score_native(forest, X, num_samples)
        else:
            # the native walker is the canonical wedge-not-raise strategy
            # (a pathological input loops in C++ with the GIL released), so
            # it runs under the same watchdog deadline as the jax kernels
            from ..resilience import watchdog as _watchdog

            def _native_run():
                # hung-walker fault seam — docs/resilience.md §3
                faults.maybe_slow_collective("native")
                return _score_native(forest, X, num_samples)

            try:
                out = _watchdog.run_with_deadline(
                    _native_run, timeout_s, describe="scoring strategy 'native'"
                )
            except _watchdog.WatchdogTimeout:
                timed_out = True
                out = None
        if out is not None:
            if _scoring_metrics_on():
                _SCORING_SECONDS.observe(
                    time.perf_counter() - t0, strategy="native"
                )
                _SCORED_ROWS_TOTAL.inc(n, strategy="native")
            return out
        if timed_out:
            strategy = degrade(
                "scoring_timeout",
                "native",
                "gather",
                detail=(
                    f"scoring strategy 'native' missed its {timeout_s:g}s "
                    "watchdog deadline (stalled walker abandoned); retrying "
                    "the batch once on the portable gather kernel"
                ),
                strict=strict,
            )
        else:
            strategy = degrade(
                "native_unavailable",
                "native",
                "gather",
                detail=(
                    "native scoring strategy unavailable (no C++ toolchain?); "
                    "falling back to the ~4x-slower gather kernel"
                ),
                strict=strict,
            )
    # degradation rungs above may have moved the strategy; the span attr
    # must name the kernel that actually executes
    _set_span_attrs(strategy=strategy)
    faults.check_strategy(strategy)
    if strategy == "pallas":
        from .pallas_traversal import path_lengths_pallas

        interpret = _live_platform() != "tpu"

        def run_chunk(chunk, owned=False):
            pl_len = path_lengths_pallas(forest, chunk, interpret=interpret)
            return score_from_path_length(pl_len, num_samples)

    elif strategy == "walk":
        from .pallas_walk import path_lengths_walk

        interpret = _live_platform() != "tpu"

        def run_chunk(chunk, owned=False):
            pl_len = path_lengths_walk(forest, chunk, interpret=interpret)
            return score_from_path_length(pl_len, num_samples)

    elif strategy == "q16":
        # the q16 path resolves its OWN cached quantized layout — the
        # caller's `layout=` contract (f32 plane) is untouched, so models
        # serving mixed strategies keep one f32 layout and one q16 plane
        qlayout = get_layout_q(forest)
        formulation = "dense" if _live_platform() == "tpu" else "gather"
        donate_ok = donation_supported()

        def run_chunk(chunk, owned=False):
            fn = _score_chunk_q_donated if (owned and donate_ok) else _score_chunk_q
            return fn(forest, qlayout, chunk, num_samples, formulation)

    else:
        if layout is None:
            layout = get_layout(forest, num_features=int(X.shape[1]))
        donate_ok = donation_supported()

        def run_chunk(chunk, owned=False):
            # donate the chunk buffer back to XLA whenever WE materialised
            # it (upload/pad/slice) — steady-state serving then reuses the
            # allocation instead of growing the device arena per batch
            fn = _score_chunk_donated if (owned and donate_ok) else _score_chunk
            return fn(forest, layout, chunk, num_samples, strategy)

    if chunk_size is None:
        chunk_size = _default_chunk_size()
    if n == 0:
        return np.zeros((0,), np.float32)

    # One executor owns chunking, staging, donation and the watchdog
    # (ops/streaming.py, docs/pipeline.md): multi-chunk host inputs
    # double-buffer chunk k+1's committed device_put under chunk k's
    # compute and fetch results at a lag of one (the loop here previously
    # leaned on bare async dispatch — measured 26% faster than one upfront
    # transfer at 2M rows on a live v5e; the executor adds the committed
    # staging + bounded live buffers the shard_map paths need too). The
    # slow_collective fault seam runs as the executor's prelude so stalls
    # land inside the watchdog scope — docs/resilience.md §3.
    executor = StreamingExecutor(
        run_chunk,
        chunk_size,
        site="score_matrix",
        single_pad=(
            batch_bucket if _pad_buckets_enabled(pad_to_bucket) else None
        ),
        streaming=pipeline_enabled(pipeline),
        timeout_s=timeout_s,
        describe=f"scoring strategy {strategy!r}",
        prelude=lambda: faults.maybe_slow_collective(strategy),
    )

    def _execute_timed() -> np.ndarray:
        if not _scoring_metrics_on():
            return executor.execute(X, n)
        t0 = time.perf_counter()
        out = executor.execute(X, n)
        _SCORING_SECONDS.observe(time.perf_counter() - t0, strategy=strategy)
        _SCORED_ROWS_TOTAL.inc(n, strategy=strategy)
        return out

    if timeout_s is None:
        return _execute_timed()

    # scoring watchdog (docs/resilience.md §6), armed by the executor:
    # a wedged native walker or a stalled Pallas compile is abandoned to
    # its daemon thread and the batch retried ONCE on the portable gather
    # kernel through the ladder. A gather run that itself times out
    # raises: there is no lower rung to stand on.
    from ..resilience import watchdog as _watchdog

    try:
        return _execute_timed()
    except _watchdog.WatchdogTimeout:
        if strategy == "gather":
            raise
        degrade(
            "scoring_timeout",
            strategy,
            "gather",
            detail=(
                f"scoring strategy {strategy!r} missed its {timeout_s:g}s "
                "watchdog deadline (stalled kernel/compile abandoned); "
                "retrying the batch once on the portable gather kernel"
            ),
            strict=strict,
        )
        return score_matrix(
            forest,
            X,
            num_samples,
            chunk_size=chunk_size,
            strategy="gather",
            strict=strict,
            expected_features=expected_features,
            timeout_s=timeout_s,
            pad_to_bucket=pad_to_bucket,
            pipeline=pipeline,
        )


def score_matrix(
    forest,
    X,
    num_samples: int,
    chunk_size: int | None = None,
    strategy: str = "auto",
    layout=None,
    strict: bool = False,
    expected_features: int | None = None,
    timeout_s: float | None = None,
    pad_to_bucket: bool | None = None,
    pipeline: bool | None = None,
) -> np.ndarray:
    # Tracing shell around _score_matrix_impl (which carries the full
    # docstring, mirrored below): the span records the resolved strategy +
    # autotune decision source as attributes, and the watchdog-timeout
    # retry re-enters through here so the gather rerun traces as its own
    # nested span (docs/observability.md §9).
    with _span("score_matrix", requested_strategy=strategy):
        return _score_matrix_impl(
            forest,
            X,
            num_samples,
            chunk_size=chunk_size,
            strategy=strategy,
            layout=layout,
            strict=strict,
            expected_features=expected_features,
            timeout_s=timeout_s,
            pad_to_bucket=pad_to_bucket,
            pipeline=pipeline,
        )


score_matrix.__doc__ = _score_matrix_impl.__doc__
