"""Finalized scoring layout: packed node records + leaf path-length LUT.

``BENCH_r05.json`` pinned scoring as bandwidth-bound: every traversal step of
the pointer-walk strategies gathered three separate full-width node arrays
(``feature: i32``, ``threshold: f32`` and — at walk exit — ``num_instances:
i32`` followed by the ``avg_path_length`` transcendental) per (row, tree).
This module builds, once per fitted/loaded forest, the layout every scoring
strategy consumes instead of the raw growth arrays:

  1. **Leaf path-length LUT, merged into the value slot.** Internal slots
     carry their split threshold (standard) / hyperplane offset (extended);
     leaf slots carry ``depth + c(numInstances)`` — the exact quantity a walk
     ending there must credit (IsolationTree.scala:213-229). Slot depth is
     static in the implicit heap, so the merge is exact and bitwise equal to
     computing ``depth + avg_path_length(n)`` at walk exit: the final
     ``num_instances`` gather AND the per-row transcendental disappear from
     every inner loop, and threshold + leaf tables collapse into ONE array
     (node tables shrink 12 -> 8 bytes/slot).
  2. **Packed node record.** The value slot and the split feature id (int
     bits placed in a float lane via bitcast) interleave into one contiguous
     ``f32[T, M, 2]`` buffer (extended: ``f32[T, M, 1 + 2k]`` with the
     hyperplane coordinates and weights inline), so a traversal step issues
     ONE coalesced gather of the whole record instead of three strided ones.
  3. **Narrowed feature ids.** For strategies that stream the feature table
     separately (the dense level-walk), ``feature`` is stored at the
     narrowest width the feature count permits — ``i8`` up to F=128, ``i16``
     up to F=32768 — cutting that stream 4x/2x. The ``-1`` leaf sentinel
     fits every width.

Builders are pure ``jnp`` so they run inside ``jit``/``shard_map`` regions
(tree-sharded scoring packs its LOCAL tree shard — the packed buffer is
sharded exactly like the forest, never materialised replicated). For the
eager ``score_matrix`` path, :func:`get_layout` caches the built layout per
forest identity so serving loops pay the build once. Persistence never sees
this layout: models round-trip through the reference Avro node arrays
unchanged and rebuild the layout on first score (docs/scoring_layout.md).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.math import avg_path_length, height_of as _height_of
from .ext_growth import ExtendedForest
from .tree_growth import StandardForest

# i8 features cover ids 0..127 (plus the -1 sentinel), i16 up to 32767 —
# hence the F <= 128 / F <= 32768 boundaries pinned in
# tests/test_scoring_layout.py.
_I8_MAX_FEATURES = 128
_I16_MAX_FEATURES = 32768


def feature_dtype(num_features: Optional[int]):
    """Narrowest integer dtype that holds every feature id in ``[0, F)`` plus
    the ``-1`` sentinel; ``None`` (width unknown, e.g. legacy persisted
    models) keeps i32."""
    if num_features is None:
        return jnp.int32
    if num_features <= _I8_MAX_FEATURES:
        return jnp.int8
    if num_features <= _I16_MAX_FEATURES:
        return jnp.int16
    return jnp.int32


def _slot_depths(max_nodes: int) -> np.ndarray:
    """Static per-heap-slot depth ``f32[M]`` (slot levels of the implicit heap)."""
    h = _height_of(max_nodes)
    return np.concatenate(
        [np.full((1 << lv,), float(lv), np.float32) for lv in range(h + 1)]
    )


def _bitcast_i32_to_f32(a: jax.Array) -> jax.Array:
    return lax.bitcast_convert_type(a.astype(jnp.int32), jnp.float32)


def bitcast_f32_to_i32(a: jax.Array) -> jax.Array:
    return lax.bitcast_convert_type(a, jnp.int32)


class PackedStandardLayout(NamedTuple):
    """Finalized standard-forest scoring layout (see module docstring).

    ``packed[t, m] = (value, bitcast(feature))``: value is the split
    threshold at internal slots, the leaf LUT ``depth + c(numInstances)`` at
    leaves, and 0 at non-existent slots; feature is the raw i32 split id
    (-1 at leaves/holes) in float bits.
    """

    packed: jax.Array  # f32 [T, M, 2]
    value: jax.Array  # f32 [T, M] — the unpacked value plane (dense strategy)
    feature: jax.Array  # i8/i16/i32 [T, M], -1 at leaves/holes

    @property
    def num_trees(self) -> int:
        return self.packed.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.packed.shape[1]


class PackedExtendedLayout(NamedTuple):
    """Extended-forest analogue: ``packed[t, m] = (value, bitcast(indices),
    weights)`` — one ``1 + 2k``-float record per node, value merging the
    hyperplane offset with the leaf LUT exactly like the standard layout."""

    packed: jax.Array  # f32 [T, M, 1 + 2k]
    value: jax.Array  # f32 [T, M]

    @property
    def num_trees(self) -> int:
        return self.packed.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.packed.shape[1]

    @property
    def k(self) -> int:
        return (self.packed.shape[2] - 1) // 2


def leaf_lut(num_instances: jax.Array, max_nodes: int) -> jax.Array:
    """Leaf path-length LUT ``f32[T, M]``: ``depth + c(numInstances)`` at
    leaves, 0 elsewhere — the jnp twin of
    :func:`~isoforest_tpu.utils.math.leaf_value_table` (kept host-side for
    the native walker), usable inside ``jit``/``shard_map``."""
    ni = jnp.asarray(num_instances)
    depth = jnp.asarray(_slot_depths(max_nodes))
    return jnp.where(ni >= 0, depth[None, :] + avg_path_length(ni), 0.0).astype(
        jnp.float32
    )


def pack_standard(
    forest: StandardForest, num_features: Optional[int] = None
) -> PackedStandardLayout:
    """Build the finalized layout for a standard forest (pure jnp)."""
    feature = jnp.asarray(forest.feature, jnp.int32)
    internal = feature >= 0
    value = jnp.where(
        internal,
        jnp.asarray(forest.threshold, jnp.float32),
        leaf_lut(forest.num_instances, forest.max_nodes),
    )
    packed = jnp.stack([value, _bitcast_i32_to_f32(feature)], axis=-1)
    return PackedStandardLayout(
        packed=packed,
        value=value,
        feature=feature.astype(feature_dtype(num_features)),
    )


def pack_extended(
    forest: ExtendedForest, num_features: Optional[int] = None
) -> PackedExtendedLayout:
    """Build the finalized layout for an extended forest (pure jnp).

    ``num_features`` is accepted for signature parity with
    :func:`pack_standard`; the sparse hyperplane coordinates stay i32 in the
    record's float lanes (a bitcast is width-preserving).
    """
    del num_features
    indices = jnp.asarray(forest.indices, jnp.int32)  # [T, M, k]
    internal = indices[..., 0] >= 0
    value = jnp.where(
        internal,
        jnp.asarray(forest.offset, jnp.float32),
        leaf_lut(forest.num_instances, forest.max_nodes),
    )
    packed = jnp.concatenate(
        [
            value[..., None],
            _bitcast_i32_to_f32(indices),
            jnp.asarray(forest.weights, jnp.float32),
        ],
        axis=-1,
    )
    return PackedExtendedLayout(packed=packed, value=value)


def pack_forest(forest, num_features: Optional[int] = None):
    if isinstance(forest, StandardForest):
        return pack_standard(forest, num_features)
    return pack_extended(forest, num_features)


# ---------------------------------------------------------------------------
# Quantized (q16) scoring plane — rank-space thresholds + shared leaf LUT.
#
# The f32 packed plane is 8 B/node (value + bitcast feature). The quantized
# standard plane stores one u32 per node — ``code << 16 | feature`` — for an
# exact 2.0x plane shrink, PLUS one shared per-forest edge table and one
# shared deduplicated leaf LUT:
#
#   * ``edges`` — the sorted, deduplicated f32 array of EVERY internal
#     threshold in the forest. An internal node's 16-bit ``code`` is its
#     threshold's rank in ``edges``. Rows are binarized once per chunk to
#     ranks ``rx = searchsorted(edges, x, side='right')`` (= #edges <= x),
#     and the traversal decision becomes ``rx[c, feat] > code`` — EXACTLY
#     equivalent to ``x >= threshold`` because searchsorted counts every
#     edge <= x and the threshold itself sits at rank ``code``. No affine
#     grid, no rounding, no tie ambiguity: split DECISIONS are preserved
#     bit-for-bit by construction (docs/scoring_layout.md has the proof).
#   * ``lut`` — the deduplicated f32 leaf values ``depth + c(numInstances)``
#     shared across ALL trees; a leaf node's ``code`` is its LUT index.
#     ``lut[0]`` is forced to 0.0 so holes/padding (code 0) credit exactly
#     the f32 plane's 0.
#
# Leaves/holes carry the 0xFFFF feature sentinel (the quantized twin of the
# f32 record's -1). Every traversal family credits the SAME f32 leaf bits
# the f32 plane holds and takes the SAME branch at every node, so scores
# are bitwise-identical per strategy family (pinned in tests).
#
# Unlike the f32 packers these builders are host-side numpy (np.unique /
# searchsorted are not jittable); the eager score_matrix q16 path caches
# them per forest via get_layout_q, mirroring get_layout.
# ---------------------------------------------------------------------------

# u16 code capacity: ranks 0..E fit u16 only when E <= 65535; LUT indices
# when U <= 65535; feature ids must stay below the 0xFFFF leaf sentinel.
_Q16_MAX_EDGES = 65535
_Q16_MAX_LUT = 65535
_Q16_FEATURE_SENTINEL = 0xFFFF
_Q16_MAX_FEATURE_ID = _Q16_FEATURE_SENTINEL - 1  # ids 0..65534
# extended indices narrow to i16 (-1 padding sentinel): ids 0..32767
_Q16_EXT_MAX_FEATURE_ID = 32767


class QuantizedStandardLayout(NamedTuple):
    """Quantized standard-forest scoring plane (see the section comment).

    Array-only fields on purpose: fleet residency accounting
    (``fleet.registry.layout_nbytes``) sums ``size * itemsize`` over every
    field, so the bytes reported are exactly the bytes resident.
    """

    packed: jax.Array  # u32 [T, M] — code<<16 | feature (0xFFFF leaf/hole)
    edges: jax.Array  # f32 [E] sorted unique internal thresholds
    lut: jax.Array  # f32 [U] shared dedup leaf values; lut[0] == 0.0

    @property
    def num_trees(self) -> int:
        return self.packed.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.packed.shape[1]


class QuantizedExtendedLayout(NamedTuple):
    """Quantized extended-forest plane: hyperplane coordinate indices
    narrowed i32 -> i16 (halving the index stream); weights and the merged
    value plane stay exact f32 — the rank trick does not apply to
    hyperplane dots, so the decision math is the f32 math unchanged and
    bitwise parity is trivial. Array-only fields (fleet accounting)."""

    indices: jax.Array  # i16 [T, M, k], -1 padding
    weights: jax.Array  # f32 [T, M, k]
    value: jax.Array  # f32 [T, M] merged plane (offset | leaf LUT | 0)

    @property
    def num_trees(self) -> int:
        return self.value.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.value.shape[1]

    @property
    def k(self) -> int:
        return self.indices.shape[2]


# Forest -> quantization-eligibility verdict, cached by array identity (the
# unique-threshold count is a host reduction over [T, M]; serving loops must
# not pay it per call). Bounded FIFO, same policy as _MIN_FEATURES_CACHE.
_Q_ELIGIBLE_CACHE: dict = {}
_Q_ELIGIBLE_CACHE_MAX = 16


def quantized_unsupported_reason(forest) -> Optional[str]:
    """None when the forest fits the q16 representation, else a human
    reason. The fences mirror what the u16 code/feature lanes can hold:
    distinct internal thresholds <= 65535, distinct leaf values <= 65535,
    feature ids below the 0xFFFF sentinel (i16's 32767 for extended)."""
    arrays = tuple(forest)
    key = tuple(id(a) for a in arrays)
    hit = _Q_ELIGIBLE_CACHE.get(key)
    if hit is not None and all(a is b for a, b in zip(hit[0], arrays)):
        return hit[1]
    reason = _quantized_unsupported_reason_uncached(forest)
    if len(_Q_ELIGIBLE_CACHE) >= _Q_ELIGIBLE_CACHE_MAX:
        _Q_ELIGIBLE_CACHE.pop(next(iter(_Q_ELIGIBLE_CACHE)))
    _Q_ELIGIBLE_CACHE[key] = (arrays, reason)
    return reason


def _quantized_unsupported_reason_uncached(forest) -> Optional[str]:
    if isinstance(forest, StandardForest):
        feat = np.asarray(forest.feature)
        internal = feat >= 0
        max_id = int(feat.max()) if feat.size else -1
        if max_id > _Q16_MAX_FEATURE_ID:
            return (
                f"feature id {max_id} exceeds the u16 plane's maximum "
                f"{_Q16_MAX_FEATURE_ID}"
            )
        n_edges = np.unique(np.asarray(forest.threshold)[internal]).size
        if n_edges > _Q16_MAX_EDGES:
            return (
                f"{n_edges} distinct thresholds exceed the u16 rank "
                f"capacity {_Q16_MAX_EDGES}"
            )
    else:
        idx = np.asarray(forest.indices)
        max_id = int(idx.max()) if idx.size else -1
        if max_id > _Q16_EXT_MAX_FEATURE_ID:
            return (
                f"hyperplane coordinate {max_id} exceeds the i16 index "
                f"maximum {_Q16_EXT_MAX_FEATURE_ID}"
            )
        return None
    n_lut = np.unique(
        np.asarray(
            leaf_lut(np.asarray(forest.num_instances), forest.max_nodes)
        )
    ).size
    if n_lut > _Q16_MAX_LUT:
        return (
            f"{n_lut} distinct leaf values exceed the u16 LUT capacity "
            f"{_Q16_MAX_LUT}"
        )
    return None


def quantized_eligible(forest) -> bool:
    return quantized_unsupported_reason(forest) is None


def pack_standard_q(forest: StandardForest) -> QuantizedStandardLayout:
    """Build the rank-space quantized plane for a standard forest.

    Host-side numpy build (cached via :func:`get_layout_q`); the leaf LUT
    entries are the f32 plane's own leaf bits (``leaf_lut``), so every
    strategy credits identical float bits at identical leaves.
    """
    feat = np.asarray(forest.feature, np.int64)
    internal = feat >= 0
    thr = np.asarray(forest.threshold, np.float32)
    # leaf/hole values exactly as the f32 plane computes them (jnp leaf_lut
    # pulled to host), so lut[code] is bit-identical to the f32 value lane
    leaf_vals = np.asarray(
        leaf_lut(np.asarray(forest.num_instances), forest.max_nodes)
    ).astype(np.float32)
    edges = np.unique(thr[internal]).astype(np.float32)
    # lut[0] == 0.0 (all leaf values are >= 0, and holes contribute 0.0)
    lut = np.unique(np.concatenate([[np.float32(0.0)], leaf_vals[~internal]]))
    lut = lut.astype(np.float32)
    code = np.zeros(feat.shape, np.uint32)
    code[internal] = np.searchsorted(edges, thr[internal]).astype(np.uint32)
    code[~internal] = np.searchsorted(lut, leaf_vals[~internal]).astype(
        np.uint32
    )
    feat_u16 = np.where(internal, feat, _Q16_FEATURE_SENTINEL).astype(np.uint32)
    packed = (code << np.uint32(16)) | feat_u16
    return QuantizedStandardLayout(
        packed=jnp.asarray(packed.astype(np.uint32)),
        edges=jnp.asarray(edges),
        lut=jnp.asarray(lut),
    )


def pack_extended_q(forest: ExtendedForest) -> QuantizedExtendedLayout:
    """Quantized extended plane: i16 hyperplane indices, exact f32 weights
    and merged value plane (identical bits to :func:`pack_extended`'s)."""
    f32 = pack_extended(forest)
    return QuantizedExtendedLayout(
        indices=jnp.asarray(forest.indices, jnp.int16),
        weights=jnp.asarray(forest.weights, jnp.float32),
        value=f32.value,
    )


def pack_forest_q(forest):
    if isinstance(forest, StandardForest):
        return pack_standard_q(forest)
    return pack_extended_q(forest)


def layout_nbytes(layout) -> int:
    """Total bytes of a layout NamedTuple's resident arrays (f32 or
    quantized) — the one formula fleet residency accounting and bench byte
    reporting share."""
    return sum(
        int(np.asarray(a).size) * int(np.asarray(a).dtype.itemsize)
        # NamedTuple fields only — properties are derived, not resident
        for a in tuple(layout)
    )


def quantized_plane_nbytes(layout) -> int:
    """Bytes of the per-node plane alone (excludes the shared edge/LUT
    side tables) — the number the >= 1.8x shrink acceptance gate measures,
    because the side tables are O(distinct values), not O(T*M)."""
    if isinstance(layout, QuantizedStandardLayout):
        a = layout.packed
    elif isinstance(layout, QuantizedExtendedLayout):
        return layout_nbytes(layout)
    elif isinstance(layout, PackedStandardLayout):
        a = layout.packed
    else:  # PackedExtendedLayout
        a = layout.packed
    return int(np.asarray(a).size) * int(np.asarray(a).dtype.itemsize)


# Per-forest layout cache for the eager score_matrix path, keyed by the
# identities of ALL forest arrays (a _replace of any field must miss) plus
# the feature width (it picks the narrow dtype). Holding strong references
# to the keyed arrays prevents id() reuse; bounded FIFO — the same policy as
# the Pallas/native prep caches.
_LAYOUT_CACHE: dict = {}
_LAYOUT_CACHE_MAX = 8


def _layout_cached(cache: dict, forest, num_features, build):
    arrays = tuple(forest)
    key = (
        tuple(id(a) for a in arrays),
        tuple(forest[0].shape),
        num_features,
    )
    hit = cache.get(key)
    if hit is not None and all(a is b for a, b in zip(hit[0], arrays)):
        return hit[1]
    layout = build()
    if len(cache) >= _LAYOUT_CACHE_MAX:
        cache.pop(next(iter(cache)))
    cache[key] = (arrays, layout)
    return layout


def get_layout(forest, num_features: Optional[int] = None):
    """Cached :func:`pack_forest`: serving loops that score many batches
    against one fitted model build the layout exactly once."""
    return _layout_cached(
        _LAYOUT_CACHE, forest, num_features, lambda: pack_forest(forest, num_features)
    )


# Separate cache for the quantized plane: a model serving both f32 and q16
# strategies (e.g. during an autotune probe) must not thrash one cache.
_LAYOUT_Q_CACHE: dict = {}


def get_layout_q(forest):
    """Cached :func:`pack_forest_q` (quantized plane), mirroring
    :func:`get_layout`."""
    return _layout_cached(_LAYOUT_Q_CACHE, forest, None, lambda: pack_forest_q(forest))
