"""Finalized scoring layout: packed node records + leaf path-length LUT.

``BENCH_r05.json`` pinned scoring as bandwidth-bound: every traversal step of
the pointer-walk strategies gathered three separate full-width node arrays
(``feature: i32``, ``threshold: f32`` and — at walk exit — ``num_instances:
i32`` followed by the ``avg_path_length`` transcendental) per (row, tree).
This module builds, once per fitted/loaded forest, the layout every scoring
strategy consumes instead of the raw growth arrays:

  1. **Leaf path-length LUT, merged into the value slot.** Internal slots
     carry their split threshold (standard) / hyperplane offset (extended);
     leaf slots carry ``depth + c(numInstances)`` — the exact quantity a walk
     ending there must credit (IsolationTree.scala:213-229). Slot depth is
     static in the implicit heap, so the merge is exact and bitwise equal to
     computing ``depth + avg_path_length(n)`` at walk exit: the final
     ``num_instances`` gather AND the per-row transcendental disappear from
     every inner loop, and threshold + leaf tables collapse into ONE array
     (node tables shrink 12 -> 8 bytes/slot).
  2. **Packed node record.** The value slot and the split feature id (int
     bits placed in a float lane via bitcast) interleave into one contiguous
     ``f32[T, M, 2]`` buffer (extended: ``f32[T, M, 1 + 2k]`` with the
     hyperplane coordinates and weights inline), so a traversal step issues
     ONE coalesced gather of the whole record instead of three strided ones.
  3. **Narrowed feature ids.** For strategies that stream the feature table
     separately (the dense level-walk), ``feature`` is stored at the
     narrowest width the feature count permits — ``i8`` up to F=128, ``i16``
     up to F=32768 — cutting that stream 4x/2x. The ``-1`` leaf sentinel
     fits every width.

Builders are pure ``jnp`` so they run inside ``jit``/``shard_map`` regions
(tree-sharded scoring packs its LOCAL tree shard — the packed buffer is
sharded exactly like the forest, never materialised replicated). For the
eager ``score_matrix`` path, :func:`get_layout` caches the built layout per
forest identity so serving loops pay the build once. Persistence never sees
this layout: models round-trip through the reference Avro node arrays
unchanged and rebuild the layout on first score (docs/scoring_layout.md).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.math import avg_path_length, height_of as _height_of
from .ext_growth import ExtendedForest
from .tree_growth import StandardForest

# i8 features cover ids 0..127 (plus the -1 sentinel), i16 up to 32767 —
# hence the F <= 128 / F <= 32768 boundaries pinned in
# tests/test_scoring_layout.py.
_I8_MAX_FEATURES = 128
_I16_MAX_FEATURES = 32768


def feature_dtype(num_features: Optional[int]):
    """Narrowest integer dtype that holds every feature id in ``[0, F)`` plus
    the ``-1`` sentinel; ``None`` (width unknown, e.g. legacy persisted
    models) keeps i32."""
    if num_features is None:
        return jnp.int32
    if num_features <= _I8_MAX_FEATURES:
        return jnp.int8
    if num_features <= _I16_MAX_FEATURES:
        return jnp.int16
    return jnp.int32


def _slot_depths(max_nodes: int) -> np.ndarray:
    """Static per-heap-slot depth ``f32[M]`` (slot levels of the implicit heap)."""
    h = _height_of(max_nodes)
    return np.concatenate(
        [np.full((1 << lv,), float(lv), np.float32) for lv in range(h + 1)]
    )


def _bitcast_i32_to_f32(a: jax.Array) -> jax.Array:
    return lax.bitcast_convert_type(a.astype(jnp.int32), jnp.float32)


def bitcast_f32_to_i32(a: jax.Array) -> jax.Array:
    return lax.bitcast_convert_type(a, jnp.int32)


class PackedStandardLayout(NamedTuple):
    """Finalized standard-forest scoring layout (see module docstring).

    ``packed[t, m] = (value, bitcast(feature))``: value is the split
    threshold at internal slots, the leaf LUT ``depth + c(numInstances)`` at
    leaves, and 0 at non-existent slots; feature is the raw i32 split id
    (-1 at leaves/holes) in float bits.
    """

    packed: jax.Array  # f32 [T, M, 2]
    value: jax.Array  # f32 [T, M] — the unpacked value plane (dense strategy)
    feature: jax.Array  # i8/i16/i32 [T, M], -1 at leaves/holes

    @property
    def num_trees(self) -> int:
        return self.packed.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.packed.shape[1]


class PackedExtendedLayout(NamedTuple):
    """Extended-forest analogue: ``packed[t, m] = (value, bitcast(indices),
    weights)`` — one ``1 + 2k``-float record per node, value merging the
    hyperplane offset with the leaf LUT exactly like the standard layout."""

    packed: jax.Array  # f32 [T, M, 1 + 2k]
    value: jax.Array  # f32 [T, M]

    @property
    def num_trees(self) -> int:
        return self.packed.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.packed.shape[1]

    @property
    def k(self) -> int:
        return (self.packed.shape[2] - 1) // 2


def leaf_lut(num_instances: jax.Array, max_nodes: int) -> jax.Array:
    """Leaf path-length LUT ``f32[T, M]``: ``depth + c(numInstances)`` at
    leaves, 0 elsewhere — the jnp twin of
    :func:`~isoforest_tpu.utils.math.leaf_value_table` (kept host-side for
    the native walker), usable inside ``jit``/``shard_map``."""
    ni = jnp.asarray(num_instances)
    depth = jnp.asarray(_slot_depths(max_nodes))
    return jnp.where(ni >= 0, depth[None, :] + avg_path_length(ni), 0.0).astype(
        jnp.float32
    )


def pack_standard(
    forest: StandardForest, num_features: Optional[int] = None
) -> PackedStandardLayout:
    """Build the finalized layout for a standard forest (pure jnp)."""
    feature = jnp.asarray(forest.feature, jnp.int32)
    internal = feature >= 0
    value = jnp.where(
        internal,
        jnp.asarray(forest.threshold, jnp.float32),
        leaf_lut(forest.num_instances, forest.max_nodes),
    )
    packed = jnp.stack([value, _bitcast_i32_to_f32(feature)], axis=-1)
    return PackedStandardLayout(
        packed=packed,
        value=value,
        feature=feature.astype(feature_dtype(num_features)),
    )


def pack_extended(
    forest: ExtendedForest, num_features: Optional[int] = None
) -> PackedExtendedLayout:
    """Build the finalized layout for an extended forest (pure jnp).

    ``num_features`` is accepted for signature parity with
    :func:`pack_standard`; the sparse hyperplane coordinates stay i32 in the
    record's float lanes (a bitcast is width-preserving).
    """
    del num_features
    indices = jnp.asarray(forest.indices, jnp.int32)  # [T, M, k]
    internal = indices[..., 0] >= 0
    value = jnp.where(
        internal,
        jnp.asarray(forest.offset, jnp.float32),
        leaf_lut(forest.num_instances, forest.max_nodes),
    )
    packed = jnp.concatenate(
        [
            value[..., None],
            _bitcast_i32_to_f32(indices),
            jnp.asarray(forest.weights, jnp.float32),
        ],
        axis=-1,
    )
    return PackedExtendedLayout(packed=packed, value=value)


def pack_forest(forest, num_features: Optional[int] = None):
    if isinstance(forest, StandardForest):
        return pack_standard(forest, num_features)
    return pack_extended(forest, num_features)


# Per-forest layout cache for the eager score_matrix path, keyed by the
# identities of ALL forest arrays (a _replace of any field must miss) plus
# the feature width (it picks the narrow dtype). Holding strong references
# to the keyed arrays prevents id() reuse; bounded FIFO — the same policy as
# the Pallas/native prep caches.
_LAYOUT_CACHE: dict = {}
_LAYOUT_CACHE_MAX = 8


def get_layout(forest, num_features: Optional[int] = None):
    """Cached :func:`pack_forest`: serving loops that score many batches
    against one fitted model build the layout exactly once."""
    arrays = tuple(forest)
    key = (
        tuple(id(a) for a in arrays),
        tuple(forest[0].shape),
        num_features,
    )
    hit = _LAYOUT_CACHE.get(key)
    if hit is not None and all(a is b for a, b in zip(hit[0], arrays)):
        return hit[1]
    layout = pack_forest(forest, num_features)
    if len(_LAYOUT_CACHE) >= _LAYOUT_CACHE_MAX:
        _LAYOUT_CACHE.pop(next(iter(_LAYOUT_CACHE)))
    _LAYOUT_CACHE[key] = (arrays, layout)
    return layout
