from .bagging import bagged_indices, feature_subsets, gather_tree_data
from .dense_traversal import (
    extended_path_lengths_dense,
    path_lengths_dense,
    standard_path_lengths_dense,
)
from .ext_growth import ExtendedForest, grow_extended_forest
from .quantile import (
    contamination_threshold,
    exact_quantile,
    histogram_quantile,
    observed_contamination,
)
from .scoring_layout import (
    PackedExtendedLayout,
    PackedStandardLayout,
    get_layout,
    pack_forest,
)
from .streaming import (
    StreamingExecutor,
    pipeline_enabled,
    pipeline_stats,
    resolve_chunk_rows,
)
from .traversal import (
    extended_path_lengths,
    path_lengths,
    score_matrix,
    standard_path_lengths,
)
from .tree_growth import StandardForest, grow_forest

__all__ = [
    "bagged_indices",
    "feature_subsets",
    "gather_tree_data",
    "extended_path_lengths_dense",
    "path_lengths_dense",
    "standard_path_lengths_dense",
    "ExtendedForest",
    "grow_extended_forest",
    "contamination_threshold",
    "exact_quantile",
    "histogram_quantile",
    "observed_contamination",
    "PackedExtendedLayout",
    "PackedStandardLayout",
    "get_layout",
    "pack_forest",
    "StreamingExecutor",
    "pipeline_enabled",
    "pipeline_stats",
    "resolve_chunk_rows",
    "extended_path_lengths",
    "path_lengths",
    "score_matrix",
    "standard_path_lengths",
    "StandardForest",
    "grow_forest",
]
