"""Streaming micro-batch executor: a double-buffered host→device pipeline.

The reference scales scoring by broadcasting the forest and mapping row
partitions (``ScoringLogic.scala`` via Spark); our mesh analogue shards
rows over devices — but until this module the shard_map paths materialised
and uploaded the ENTIRE padded batch synchronously before any compute
started, serialising host→device transfer with traversal (ROADMAP item 3).
The throughput-oriented forest-inference literature (RAPIDS-FIL-style
batched traversal, PAPERS.md) treats transfer/compute overlap as the
standard shape for this model class; this executor is that shape, shared
by every chunked scoring path in the package:

* **one chunking policy** — ``X`` splits into ``chunk_rows`` micro-batches
  (:func:`resolve_chunk_rows`: explicit > ``ISOFOREST_TPU_PIPELINE_CHUNK``
  > the measured per-platform default, bucket-aligned via the autotuner's
  shared :func:`~isoforest_tpu.ops.traversal.batch_bucket` formula so every
  chunk lands on a pre-warmed compiled shape);
* **double-buffered staging** — host rows for chunk *k+1* are packed into
  one of TWO reusable host buffers (the pinned-host analogue; jax copies
  out of the buffer during ``device_put``) and issued as a *committed*
  ``jax.device_put`` against the target sharding while the program computes
  on chunk *k*, so H2D rides under compute instead of in front of it;
* **lag-1 result fetch** — chunk *k-1*'s scores are pulled to host only
  after chunk *k*'s transfer + compute are dispatched, overlapping D2H with
  compute and bounding live device buffers to two chunks. The fetch of
  chunk *k-1* completing is also what proves chunk *k-1*'s input transfer
  finished — which is exactly when its host buffer is reused (chunk
  *k+1*), so two buffers are always sufficient;
* **donation** — every staged chunk buffer is executor-owned, so callers
  may safely donate it back to XLA (``run_chunk(chunk, owned=True)``);
* **timeout arming** — ``timeout_s`` runs the whole streamed execution
  under the scoring watchdog
  (:func:`~isoforest_tpu.resilience.watchdog.run_with_deadline`), raising
  :class:`~isoforest_tpu.resilience.watchdog.WatchdogTimeout` for the
  caller's ladder logic.

Scores are **bitwise identical** to the single-shot path: every scoring
formulation in the package is row-independent (each row's walk never reads
another row), so splitting the row axis — and zero-padding the final chunk
— cannot change any valid row's arithmetic.

Backends/jax builds where a committed async ``device_put`` is unavailable
take the ``pipeline_fallback`` degradation rung ONCE per execution
(log-once warning; docs/resilience.md): chunks then upload synchronously —
no overlap, scores still bitwise identical. The ``break_pipeline_stage``
fault seam forces that rung in tests.

Telemetry (docs/observability.md): ``isoforest_pipeline_chunks_total``
(micro-batches executed, by ``site``), ``isoforest_pipeline_h2d_seconds``
(host-blocking staging seconds per streamed run) and
``isoforest_pipeline_overlap_efficiency`` (fraction of the streamed run's
wall-clock NOT exposed as blocking staging — ~1.0 when transfers hide
under compute), plus one ``pipeline.run`` event per streamed (multi-chunk)
execution. Policy prose in docs/pipeline.md.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..resilience import faults
from ..resilience.degradation import degrade
from ..telemetry import _state as _telemetry_state
from ..telemetry import resources as _resources
from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _telemetry_counter
from ..telemetry.metrics import gauge as _telemetry_gauge
from ..telemetry.metrics import histogram as _telemetry_histogram
from ..telemetry.spans import span as _span

# Measured on a live v5e (2026-07-29, 524k rows x 100 trees, dense): bigger
# chunks win monotonically — 0.81 s at 2^17, 0.64 s at 2^18, 0.53 s at 2^19
# (single chunk) vs 0.35 s for the raw kernel on resident data; the gap is
# per-chunk dispatch + tunnel transfer overhead. CPU keeps the smaller
# working set (the XLA:CPU paths are latency- not dispatch-bound).
PLATFORM_DEFAULT_CHUNK = {"tpu": 1 << 19, "cpu": 1 << 18}

_PIPELINE_CHUNKS = _telemetry_counter(
    "isoforest_pipeline_chunks_total",
    "Micro-batches executed by the streaming executor, by call site",
    labelnames=("site",),
)
_PIPELINE_H2D = _telemetry_histogram(
    "isoforest_pipeline_h2d_seconds",
    "Host-blocking host->device staging seconds per streamed execution",
    labelnames=("site",),
)
_PIPELINE_OVERLAP = _telemetry_gauge(
    "isoforest_pipeline_overlap_efficiency",
    "1 - (blocking staging seconds / streamed-run wall-clock) of the last "
    "streamed execution per site: ~1.0 when H2D hides under compute",
    labelnames=("site",),
)


def pipeline_enabled(override: Optional[bool] = None) -> bool:
    """``ISOFOREST_TPU_PIPELINE`` gate (default ON); an explicit
    ``pipeline=`` argument wins over the environment."""
    if override is not None:
        return bool(override)
    return os.environ.get("ISOFOREST_TPU_PIPELINE", "1").strip().lower() not in (
        "0",
        "false",
        "off",
        "no",
    )


def _live_platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:  # backend bring-up failed; CPU defaults apply
        return "cpu"


def default_chunk_rows(platform: Optional[str] = None) -> int:
    env = os.environ.get("ISOFOREST_TPU_PIPELINE_CHUNK")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    if platform is None:
        platform = _live_platform()
    return PLATFORM_DEFAULT_CHUNK.get(platform, 1 << 18)


def resolve_chunk_rows(
    chunk_rows: Optional[int] = None,
    platform: Optional[str] = None,
    multiple: int = 1,
) -> int:
    """The executor's chunk policy: explicit ``chunk_rows`` > env override >
    the measured per-platform default, rounded UP to the autotuner's shared
    power-of-two bucket (so streamed chunks reuse the pre-warmed, autotuned
    compiled shapes; docs/autotune.md) and DOWN to a ``multiple`` (the mesh
    device count — shard_map row axes must divide the mesh)."""
    from .traversal import batch_bucket

    rows = chunk_rows if chunk_rows is not None else default_chunk_rows(platform)
    rows = batch_bucket(rows)
    return max(multiple, rows - rows % multiple)


# -- committed staging ------------------------------------------------------

# sharding (or None = default device) -> probed availability; the
# break_pipeline_stage fault seam is consulted BEFORE the cache so tests
# can force the fallback rung against an already-probed sharding
_STAGE_PROBED: dict = {}


def _probe_rows(sharding) -> int:
    mesh = getattr(sharding, "mesh", None)
    if mesh is None:
        return 1
    return int(np.prod([mesh.shape[name] for name in mesh.shape]))


def stage_available(sharding=None) -> bool:
    """Whether a committed ``jax.device_put`` onto ``sharding`` (None = the
    default device) works on this backend/jax build. Probed once per
    sharding with a tiny array; the ``break_pipeline_stage`` fault forces
    False (docs/resilience.md §3)."""
    if faults.get("break_pipeline_stage"):
        return False
    key = sharding
    hit = _STAGE_PROBED.get(key)
    if hit is not None:
        return hit
    try:
        probe = np.zeros((_probe_rows(sharding), 1), np.float32)
        if sharding is None:
            jax.device_put(probe)
        else:
            jax.device_put(probe, sharding)
        ok = True
    except Exception:  # noqa: BLE001 — any refusal means the sync fallback
        ok = False
    _STAGE_PROBED[key] = ok
    return ok


class _HostStager:
    """Two reusable zero-padded host buffers (the pinned-host analogue).

    Buffer *i % 2* carries chunk *i*'s rows into ``device_put``; it is
    reused at chunk *i+2*, by which point the executor's lag-1 fetch of
    chunk *i+1* has proven chunk *i*'s transfer complete (module doc)."""

    def __init__(self, chunk_rows: int, width: int) -> None:
        self._bufs = [
            np.zeros((chunk_rows, width), np.float32),
            np.zeros((chunk_rows, width), np.float32),
        ]
        self._next = 0

    def pack(self, rows: np.ndarray) -> np.ndarray:
        buf = self._bufs[self._next]
        self._next ^= 1
        n = rows.shape[0]
        buf[:n] = rows
        if n < buf.shape[0]:
            buf[n:] = 0.0
        return buf


class StreamingExecutor:
    """One owner for chunking, staging, donation and timeout arming across
    every chunked scoring path (module doc).

    ``run_chunk(chunk, owned)`` scores one ``[chunk_rows, F]`` device (or
    host) chunk and returns its per-row scores *without* forcing them to
    host — the executor fetches with a lag of one. ``owned=True`` marks the
    buffer as executor-materialised (donation-safe). ``sharding`` commits
    staged chunks to a mesh sharding (the shard_map paths); ``None`` stages
    onto the default device. ``single_pad`` maps a row count to the padded
    single-shot size (``score_matrix`` passes its bucket formula; sharded
    callers their device-count multiple). ``prelude`` runs inside the
    watchdog scope before the first chunk (the fault-stall seam).
    ``clock`` is injectable for deterministic tests (SLP001)."""

    def __init__(
        self,
        run_chunk: Callable,
        chunk_rows: int,
        *,
        sharding=None,
        site: str = "score_matrix",
        single_pad: Optional[Callable[[int], int]] = None,
        streaming: bool = True,
        timeout_s: Optional[float] = None,
        describe: str = "streamed scoring",
        prelude: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.perf_counter,
    ) -> None:
        if chunk_rows < 1:
            raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
        self._run_chunk = run_chunk
        self.chunk_rows = int(chunk_rows)
        self._sharding = sharding
        self._site = site
        self._single_pad = single_pad
        self._streaming = streaming
        self._timeout_s = timeout_s
        self._describe = describe
        self._prelude = prelude
        self._clock = clock

    # ------------------------------------------------------------------ #

    def execute(self, X, n: int) -> np.ndarray:
        """Score ``X[:n]``; arms the watchdog when ``timeout_s`` was given
        (a streamed run that stalls raises ``WatchdogTimeout`` for the
        caller's ladder logic — the executor never takes a strategy rung
        itself)."""
        if n == 0:
            return np.zeros((0,), np.float32)
        if self._timeout_s is None:
            return self._run(X, n)
        from ..resilience import watchdog as _watchdog

        return _watchdog.run_with_deadline(
            lambda: self._run(X, n), self._timeout_s, describe=self._describe
        )

    def _run(self, X, n: int) -> np.ndarray:
        if self._prelude is not None:
            self._prelude()
        if n <= self.chunk_rows:
            return self._run_single(X, n)
        return self._run_streamed(X, n)

    def _run_single(self, X, n: int) -> np.ndarray:
        # one chunk: nothing to overlap. Host inputs pad host-side and the
        # result slices host-side: ``jnp.pad`` / a lazy ``[:n]`` each
        # compile a tiny program per exact row count, which would tick the
        # steady-phase compile counter on every novel batch size even when
        # the bucket-shaped scoring program is warm — only the bucket shape
        # may touch XLA (docs/observability.md §10)
        padded = self._single_pad(n) if self._single_pad is not None else n
        pad = padded - n
        if not isinstance(X, jax.Array):
            Xnp = np.asarray(X, np.float32)
            if pad:
                Xnp = np.pad(Xnp, ((0, pad), (0, 0)))
            Xc = jnp.asarray(Xnp, jnp.float32)
            owned = True
        else:
            Xc = jnp.asarray(X, jnp.float32)
            owned = Xc is not X
            if pad:
                Xc = jnp.pad(Xc, ((0, pad), (0, 0)))
                owned = True
        if _telemetry_state.enabled():
            _PIPELINE_CHUNKS.inc(1, site=self._site)
        # the executor is the one shared dispatch seam for every chunked
        # scoring path, so an XLA compile fired by this call attributes
        # here by default; semantic callers (serving.prewarm, autotune
        # probes) wrap their own outer compile_scope and win attribution
        with _resources.compile_scope(self._site, key=f"rows={padded}"):
            scores = self._run_chunk(Xc, owned)
        return np.asarray(scores)[:n]

    def _run_streamed(self, X, n: int) -> np.ndarray:
        chunk = self.chunk_rows
        committed = self._streaming and stage_available(self._sharding)
        if self._streaming and not committed:
            # strict-exempt by design (like drift_alert): the sync path
            # computes bitwise-identical scores — only the overlap is lost
            degrade(
                "pipeline_fallback",
                "pipeline",
                "sync_upload",
                detail=(
                    "committed async device_put is unavailable on this "
                    "backend/jax build (or fault-injected away); streaming "
                    "chunks will upload synchronously — H2D no longer "
                    "overlaps compute, scores are unchanged"
                ),
            )
        host = not isinstance(X, jax.Array)
        stager = (
            _HostStager(chunk, int(X.shape[1])) if (host and committed) else None
        )
        if stager is not None:
            # both reusable staging buffers, live for the whole streamed
            # run — the host-memory watermark the resource plane reports
            _resources.note_host_staging(
                self._site, 2 * chunk * int(X.shape[1]) * 4
            )
        t_start = self._clock()
        h2d_s = 0.0
        parts = []
        pending = None  # chunk k-1's device scores, fetched at lag one
        n_chunks = 0
        for start in range(0, n, chunk):
            stop = min(start + chunk, n)
            valid = stop - start
            # per-chunk trace span (docs/observability.md §9): the phase
            # timings are THIS chunk's blocking H2D stage + compute
            # dispatch, and the lag-one D2H fetch of the PREVIOUS chunk's
            # scores (the overlap the pipeline exists to create)
            with _span(
                "pipeline.chunk",
                site=self._site,
                index=n_chunks,
                rows=valid,
            ) as csp:
                t0 = self._clock()
                if stager is not None:
                    buf = stager.pack(np.asarray(X[start:stop], np.float32))
                    dev = (
                        jax.device_put(buf, self._sharding)
                        if self._sharding is not None
                        else jax.device_put(buf)
                    )
                elif host:
                    # same per-exact-n compile hazard as _run_single: pad
                    # the tail host-side so only the chunk shape hits XLA
                    buf = np.asarray(X[start:stop], np.float32)
                    if valid < chunk:
                        buf = np.pad(buf, ((0, chunk - valid), (0, 0)))
                    dev = jnp.asarray(buf, jnp.float32)
                else:
                    dev = jnp.asarray(X[start:stop], jnp.float32)
                    if valid < chunk:
                        dev = jnp.pad(dev, ((0, chunk - valid), (0, 0)))
                chunk_h2d = self._clock() - t0
                h2d_s += chunk_h2d
                t1 = self._clock()
                with _resources.compile_scope(
                    self._site, key=f"rows={chunk}"
                ):
                    scores = self._run_chunk(dev, True)
                dispatch_s = self._clock() - t1
                t2 = self._clock()
                if pending is not None:
                    parts.append(np.asarray(pending))
                csp.set_attrs(
                    h2d_s=round(chunk_h2d, 6),
                    compute_dispatch_s=round(dispatch_s, 6),
                    d2h_s=round(self._clock() - t2, 6),
                )
                # the tail slice happens host-side after the fetch — a lazy
                # device [:valid] would compile per exact tail size
                pending = scores
            n_chunks += 1
        parts.append(np.asarray(pending)[:valid])
        total_s = max(self._clock() - t_start, 1e-9)
        if _telemetry_state.enabled():
            eff = max(0.0, min(1.0, 1.0 - h2d_s / total_s))
            _PIPELINE_CHUNKS.inc(n_chunks, site=self._site)
            _PIPELINE_H2D.observe(h2d_s, site=self._site)
            _PIPELINE_OVERLAP.set(eff, site=self._site)
            record_event(
                "pipeline.run",
                site=self._site,
                chunks=n_chunks,
                rows=n,
                h2d_s=round(h2d_s, 6),
                overlap_efficiency=round(eff, 4),
                fallback=not committed,
            )
        return np.concatenate(parts)


def pipeline_stats(site: str = "score_matrix") -> dict:
    """Current pipeline telemetry for one call site — the roll-up bench.py
    reports next to its roofline (``h2d_seconds`` is the cumulative
    blocking staging time across streamed runs)."""
    return {
        "chunks": int(_PIPELINE_CHUNKS.value(site=site)),
        "h2d_seconds": round(float(_PIPELINE_H2D.summary(site=site)["sum"]), 6),
        "overlap_efficiency": round(
            float(_PIPELINE_OVERLAP.value(site=site)), 4
        ),
    }


__all__ = [
    "PLATFORM_DEFAULT_CHUNK",
    "StreamingExecutor",
    "default_chunk_rows",
    "pipeline_enabled",
    "pipeline_stats",
    "resolve_chunk_rows",
    "stage_available",
]
