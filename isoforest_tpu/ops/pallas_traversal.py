"""Pallas TPU kernel for dense forest scoring.

Same gather-free algorithm as :mod:`.dense_traversal`, hand-blocked for the
TPU memory hierarchy: the grid is ``(row_blocks, trees)`` with trees minor,
so each row-block's accumulator stays resident in VMEM while the per-tree
node tables (a few KB each) stream HBM -> VMEM. Every instruction is a
full-width VPU op or (for the extended forest's hyperplane tests) an MXU
matmul; there is no data-dependent indexing anywhere.

Correctness is pinned against the XLA dense path in interpret mode (tests run
CPU-only); on TPU hardware select it via ``score_matrix(strategy="pallas")``
or ``ISOFOREST_TPU_STRATEGY=pallas``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU memory spaces are unavailable when lowering for CPU interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from ..utils.math import avg_path_length, height_of as _height_of
from .tree_growth import StandardForest

_ROW_BLOCK = 1024


def _leaf_value_tables(num_instances: np.ndarray, h: int) -> jax.Array:
    """[T, M] ``depth + c(numInstances)`` at leaves, 0 elsewhere (host prep)."""
    depth = np.concatenate(
        [np.full((1 << level,), float(level), np.float32) for level in range(h + 1)]
    )
    ni = np.asarray(num_instances)
    leaf = ni >= 0
    return jnp.asarray(
        np.where(leaf, depth[None, :] + np.asarray(avg_path_length(ni)), 0.0).astype(
            np.float32
        )
    )


def _walk_levels(B, internal_f32, leaf_value, h: int):
    """Reach propagation on [C_blk, M] blocks — mirrors dense_traversal."""
    C = B.shape[0]
    total = jnp.zeros((C,), jnp.float32)
    reach = jnp.ones((C, 1), jnp.float32)
    for level in range(h + 1):
        start = (1 << level) - 1
        width = 1 << level
        total = total + jnp.sum(reach * leaf_value[:, start : start + width], axis=1)
        if level < h:
            B_l = B[:, start : start + width]
            alive = reach * internal_f32[:, start : start + width]
            left = alive * (1.0 - B_l)
            right = alive * B_l
            reach = jnp.stack([left, right], axis=2).reshape(C, 2 * width)
    return total


def _standard_kernel(h, F, T, x_ref, feat_ref, thr_ref, leaf_ref, out_ref):
    t = pl.program_id(1)
    x = x_ref[...]  # [C_blk, F]
    feature = feat_ref[...]  # [1, M] f32 (feature id; -1 leaf)
    thr = thr_ref[...]
    # dense one-hot feature select without gathers: F static passes
    xv = jnp.zeros((x.shape[0], feature.shape[1]), jnp.float32)
    for f in range(F):
        sel = (feature == float(f)).astype(jnp.float32)  # [1, M]
        xv = xv + x[:, f : f + 1] * sel
    B = (xv >= thr).astype(jnp.float32)
    internal = (feature >= 0.0).astype(jnp.float32) + jnp.zeros_like(xv)
    pl_len = _walk_levels(B, internal, leaf_ref[...] + jnp.zeros_like(xv), h)

    @pl.when(t == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += pl_len[:, None] / T


def _extended_kernel(h, T, x_ref, w_ref, off_ref, internal_ref, leaf_ref, out_ref):
    t = pl.program_id(1)
    x = x_ref[...]  # [C_blk, F]
    W = w_ref[0]  # block is [1, M, F] -> [M, F] dense hyperplanes
    dots = jax.lax.dot_general(
        x, W, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [C_blk, M] — MXU
    B = (dots >= off_ref[...]).astype(jnp.float32)
    internal = internal_ref[...] + jnp.zeros_like(dots)
    pl_len = _walk_levels(B, internal, leaf_ref[...] + jnp.zeros_like(dots), h)

    @pl.when(t == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += pl_len[:, None] / T


def _vmem_spec(block_shape, index_map):
    kw = {"memory_space": _VMEM} if _VMEM is not None else {}
    return pl.BlockSpec(block_shape, index_map, **kw)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _standard_pallas(X, feature_f32, threshold, leaf_value, interpret=False):
    C, F = X.shape
    T, M = threshold.shape
    h = _height_of(M)
    grid = (C // _ROW_BLOCK, T)
    return pl.pallas_call(
        functools.partial(_standard_kernel, h, F, T),
        grid=grid,
        in_specs=[
            _vmem_spec((_ROW_BLOCK, F), lambda rb, t: (rb, 0)),
            _vmem_spec((1, M), lambda rb, t: (t, 0)),
            _vmem_spec((1, M), lambda rb, t: (t, 0)),
            _vmem_spec((1, M), lambda rb, t: (t, 0)),
        ],
        out_specs=_vmem_spec((_ROW_BLOCK, 1), lambda rb, t: (rb, 0)),
        out_shape=jax.ShapeDtypeStruct((C, 1), jnp.float32),
        interpret=interpret,
    )(X, feature_f32, threshold, leaf_value)[:, 0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _extended_pallas(X, W_dense, offset, internal, leaf_value, interpret=False):
    C, F = X.shape
    T, M = offset.shape
    h = _height_of(M)
    grid = (C // _ROW_BLOCK, T)
    return pl.pallas_call(
        functools.partial(_extended_kernel, h, T),
        grid=grid,
        in_specs=[
            _vmem_spec((_ROW_BLOCK, F), lambda rb, t: (rb, 0)),
            _vmem_spec((1, M, F), lambda rb, t: (t, 0, 0)),
            _vmem_spec((1, M), lambda rb, t: (t, 0)),
            _vmem_spec((1, M), lambda rb, t: (t, 0)),
            _vmem_spec((1, M), lambda rb, t: (t, 0)),
        ],
        out_specs=_vmem_spec((_ROW_BLOCK, 1), lambda rb, t: (rb, 0)),
        out_shape=jax.ShapeDtypeStruct((C, 1), jnp.float32),
        interpret=interpret,
    )(X, W_dense, offset, internal, leaf_value)[:, 0]


# The forest is immutable once trained/loaded, but the kernel needs host-side
# prep (leaf-value tables; densified hyperplanes for EIF — O(T*M*F)). Cache
# prep per forest, keyed by the identities of ALL its arrays (a _replace of
# any single field must miss); holding strong references to the keyed arrays
# prevents id() reuse. Bounded FIFO.
_PREP_CACHE: dict = {}
_PREP_CACHE_MAX = 8


def _cached_prep(forest, build, extra_key=()):
    arrays = tuple(forest)
    key = (tuple(id(a) for a in arrays), tuple(forest[0].shape), extra_key)
    hit = _PREP_CACHE.get(key)
    if hit is not None and all(a is b for a, b in zip(hit[0], arrays)):
        return hit[1]
    prep = build()
    if len(_PREP_CACHE) >= _PREP_CACHE_MAX:
        _PREP_CACHE.pop(next(iter(_PREP_CACHE)))
    _PREP_CACHE[key] = (arrays, prep)
    return prep


def path_lengths_pallas(forest, X, interpret: bool = False) -> jax.Array:
    """Mean path lengths via the Pallas kernel. Rows are padded to the row
    block internally; pass ``interpret=True`` off-TPU."""
    X = jnp.asarray(X, jnp.float32)
    n = X.shape[0]
    pad = (-n) % _ROW_BLOCK
    if pad:
        X = jnp.pad(X, ((0, pad), (0, 0)))
    h = _height_of(forest.max_nodes)
    if isinstance(forest, StandardForest):

        def build_standard():
            return (
                jnp.asarray(forest.feature, jnp.float32),
                jnp.asarray(forest.threshold),
                _leaf_value_tables(forest.num_instances, h),
            )

        feature_f32, threshold, leaf_value = _cached_prep(forest, build_standard)
        out = _standard_pallas(X, feature_f32, threshold, leaf_value, interpret=interpret)
    else:
        F = X.shape[1]

        def build_extended():
            indices = np.asarray(forest.indices)
            weights = np.asarray(forest.weights)
            T, M, _ = indices.shape
            W = np.zeros((T, M, F), np.float32)
            t_ix, m_ix, k_ix = np.nonzero(indices >= 0)
            W[t_ix, m_ix, indices[t_ix, m_ix, k_ix]] += weights[t_ix, m_ix, k_ix]
            return (
                jnp.asarray(W),
                jnp.asarray(forest.offset),
                jnp.asarray((indices[..., 0] >= 0).astype(np.float32)),
                _leaf_value_tables(forest.num_instances, h),
            )

        W, offset, internal, leaf_value = _cached_prep(
            forest, build_extended, extra_key=(F,)
        )
        out = _extended_pallas(X, W, offset, internal, leaf_value, interpret=interpret)
    return out[:n]
