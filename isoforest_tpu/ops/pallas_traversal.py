"""Pallas TPU kernel for dense forest scoring.

Same gather-free algorithm as :mod:`.dense_traversal`, hand-blocked for the
TPU memory hierarchy: the grid is ``(row_blocks, trees)`` with trees minor,
so each row-block's accumulator stays resident in VMEM while the per-tree
node tables (a few KB each) stream HBM -> VMEM. Every instruction is a
full-width VPU op or (for the extended forest's hyperplane tests) an MXU
matmul; there is no data-dependent indexing anywhere.

Correctness is pinned against the XLA dense path in interpret mode (tests run
CPU-only); on TPU hardware select it via ``score_matrix(strategy="pallas")``
or ``ISOFOREST_TPU_STRATEGY=pallas``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU memory spaces are unavailable when lowering for CPU interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from ..utils.math import height_of as _height_of
from .tree_growth import StandardForest

_ROW_BLOCK = 1024
# Shared feature-count crossover (measured on a live v5e): below this,
# per-feature select passes beat the lane-padded one-hot contraction (which
# runs [C, 128] @ [128, M] regardless of true F). Imported so the dispatch
# boundary cannot drift between the XLA and Pallas paths (ADVICE r2):
# ``f_raw`` is a static kernel arg, so this stays a compile-time constant.
from .dense_traversal import _SELECT_MAX_FEATURES
# Mosaic tiles f32 as (8, 128) sublane x lane; node tables and the feature
# axis are padded to lane multiples so every block is natively tileable
# (511-wide tables and raw F were the round-1 hardware-compile risk).
_LANES = 128


def _pad_lanes(n: int) -> int:
    return max(_LANES, -(-n // _LANES) * _LANES)


@functools.lru_cache(maxsize=None)
def _concat_order(m: int) -> tuple:
    """Heap node index held by each table slot in the LEVEL-CONCAT layout.

    The kernel's level walk stores the children of a level as
    ``[all left children | all right children]`` rather than interleaved
    ``[L0, R0, L1, R1, ...]`` heap order: the interleave needs a
    ``stack(..., axis=2).reshape`` that Mosaic cannot lower (observed on
    hardware: ``tpu.reshape vector<1024x2x2xf32> -> vector<1024x4xf32>``
    "unsupported shape cast"), while the concat form is a plain lane-axis
    ``jnp.concatenate``. Within level ``l+1`` the left child of in-level
    parent ``p`` sits at in-level slot ``p`` and the right child at
    ``w + p``. All node tables are permuted into this layout host-side at
    prep time; scores are layout-invariant."""
    h = int(np.log2(m + 1)) - 1
    assert (1 << (h + 1)) - 1 == m, f"node table size {m} is not a full heap"
    order = [0]
    prev = [0]
    for _ in range(h):
        nxt = [2 * n + 1 for n in prev] + [2 * n + 2 for n in prev]
        order.extend(nxt)
        prev = nxt
    return tuple(order)


def _merged_value_heap(is_internal: np.ndarray, internal_value, num_instances, h: int):
    """[T, M] merged value plane in heap order: ``internal_value`` (threshold
    / hyperplane offset) at internal slots, the leaf path-length LUT
    ``depth + c(numInstances)`` at leaves, 0 at holes — the scoring_layout
    merge, built host-side for the kernel tables."""
    from ..utils.math import leaf_value_table

    return np.where(
        is_internal,
        np.asarray(internal_value, np.float32),
        leaf_value_table(num_instances, h),
    ).astype(np.float32)


def _pad_table(arr: np.ndarray, m_pad: int, fill: float) -> np.ndarray:
    """Permute a [T, M] heap-order node table into the level-concat layout
    (:func:`_concat_order`) and pad to [T, 1, m_pad] with ``fill``."""
    t, m = arr.shape
    out = np.full((t, m_pad), fill, arr.dtype)
    out[:, :m] = arr[:, list(_concat_order(m))]
    return out[:, None, :]


def _walk_levels(B, internal_f32, leaf_value, h: int):
    """Reach propagation on [C_blk, M] blocks — same recurrence as
    dense_traversal but over tables in the level-concat layout
    (:func:`_concat_order`): the next level's reach is a lane-axis concat,
    the one child-ordering Mosaic can lower."""
    C = B.shape[0]
    total = jnp.zeros((C,), jnp.float32)
    reach = jnp.ones((C, 1), jnp.float32)
    for level in range(h + 1):
        start = (1 << level) - 1
        width = 1 << level
        total = total + jnp.sum(reach * leaf_value[:, start : start + width], axis=1)
        if level < h:
            B_l = B[:, start : start + width]
            alive = reach * internal_f32[:, start : start + width]
            left = alive * (1.0 - B_l)
            right = alive * B_l
            reach = jnp.concatenate([left, right], axis=1)
    return total


def _bcast_rows(row, c: int, precision=None):
    """Materialize a [1, M] node-table row to [c, M] via a rank-1 MXU
    contraction. A plain ``row + zeros`` broadcast leaves the value in a
    sublane-broadcast layout that crashes Mosaic's layout inference when the
    walk later takes narrow lane slices of it (observed on hardware:
    ``Check failed: limits[i] <= dim(i) (128 vs. 1)``; a broadcasting
    multiply by a [c, 1] ones column hits the same class of crash in the
    *remote* compile helper even though the local chipless AOT pipeline
    accepts it — the helper runs a different Mosaic build, so only
    remote-proven formulations ship). ``precision``: the standard kernel
    passes HIGHEST so leaf/internal table values do not round through bf16
    mantissas (proven to compile remotely 2026-07-29); the EIF kernels keep
    the default — HIGHEST inside them crashes the remote helper, and they
    are the measured losers vs dense anyway (benchmarks/README.md)."""
    ones = jnp.ones((c, 1), jnp.float32)
    return jax.lax.dot_general(
        ones, row, (((1,), (0,)), ((), ())),
        precision=precision, preferred_element_type=jnp.float32,
    )


def _standard_kernel(h, T, f_raw, x_ref, feat_ref, val_ref, out_ref):
    t = pl.program_id(1)
    x = x_ref[...]  # [C_blk, F_pad]
    # node-table refs are [1, 1, M_pad] blocks (trailing two dims equal the
    # [T, 1, M_pad] array dims — a Mosaic block-shape requirement); drop the
    # leading tree axis. ``val`` is the merged value plane (threshold at
    # internal slots, leaf LUT at leaves, 0 at holes/pads) — the kernel
    # streams TWO node tables per tree instead of three.
    feature = feat_ref[0]  # [1, M_pad] int32 (feature id; -1 leaf/pad)
    val = val_ref[0]
    f_pad = x.shape[1]
    m_pad = feature.shape[1]
    c_blk = x.shape[0]
    if f_raw <= _SELECT_MAX_FEATURES:
        # Per-feature select chain (pure VPU), mirroring dense_traversal's
        # small-F dispatch. The one-hot contraction below runs over the
        # lane-PADDED F axis — [C, 128] @ [128, M] at HIGHEST precision is
        # ~42x the needed flops at F=3 and dominated the measured 1.04 s
        # pallas score at 1M rows; F masked passes over [C_blk, M_pad] are
        # O(F * C * M) VPU work with no padding amplification. (The round-1
        # worry about this loop was F=274 configs — those still take the
        # matmul branch.)
        xv = jnp.zeros((c_blk, m_pad), jnp.float32)
        for f in range(f_raw):
            xv = jnp.where(feature == f, x[:, f : f + 1], xv)
    else:
        # One-hot feature selection as a single MXU contraction (the
        # formulation dense_traversal.py uses for wide F).
        # sel[f, m] = 1 iff node m splits on feature f; padded slots match
        # no f. Mosaic requires integer iota, hence the int32 feature table.
        iota_f = jax.lax.broadcasted_iota(jnp.int32, (f_pad, m_pad), 0)
        sel = (iota_f == feature).astype(jnp.float32)  # [F_pad, M_pad]
        xv = jax.lax.dot_general(
            x, sel, (((1,), (0,)), ((), ())), precision=jax.lax.Precision.HIGHEST, preferred_element_type=jnp.float32
        )  # [C_blk, M_pad]
    # leaf/hole bits are garbage (val holds the LUT there) but the level
    # walk masks them with the internal plane, exactly like the XLA dense path
    B = (xv >= val).astype(jnp.float32)
    hp = jax.lax.Precision.HIGHEST
    internal_row = (feature >= 0).astype(jnp.float32)  # [1, M_pad]
    leaf_row = val * (1.0 - internal_row)  # LUT at leaves, 0 elsewhere
    internal = _bcast_rows(internal_row, c_blk, hp)
    pl_len = _walk_levels(B, internal, _bcast_rows(leaf_row, c_blk, hp), h)

    @pl.when(t == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += pl_len[:, None] / T


def _extended_kernel_sparse(
    h, T, x_ref, idx_ref, w_ref, val_ref, internal_ref, out_ref
):
    """EIF scoring from SPARSE hyperplane tables: densify in VMEM (k one-hot
    accumulation passes, pure VPU) instead of materialising [T, M_pad, F_pad]
    in HBM — at T=1000, F=274 the precomputed dense table cost ~786 MB; the
    sparse tables are ~2k/F of that. Used when k is small (the common sparse
    extension levels); large k dispatches to :func:`_extended_kernel_dense`
    where the HBM table is no bigger than the sparse form anyway.
    ``val`` is the merged value plane (offset | leaf LUT | 0), so each tree
    streams one fewer table than the pre-layout kernels."""
    t = pl.program_id(1)
    x = x_ref[...]  # [C_blk, F_pad]
    idx = idx_ref[0]  # [k, M_pad] sparse hyperplane coordinates (-1 pad)
    w = w_ref[0]  # [k, M_pad]
    f_pad = x.shape[1]
    m_pad = idx.shape[1]
    k = idx.shape[0]
    # Padded coordinates (-1) match no iota row, contributing zero weight.
    iota_f = jax.lax.broadcasted_iota(jnp.int32, (f_pad, m_pad), 0)
    w_dense = jnp.zeros((f_pad, m_pad), jnp.float32)
    for q in range(k):
        sel = (iota_f == idx[q][None, :]).astype(jnp.float32)  # [F_pad, M_pad]
        w_dense = w_dense + sel * w[q][None, :]
    # NOTE: default matmul precision (bf16 passes) — Precision.HIGHEST on
    # this contraction crashes the Mosaic compile helper on real hardware
    # (observed 2026-07-29: tpu_compile_helper exit 1; the standard kernel's
    # HIGHEST contraction compiles fine). The EIF pallas path is already the
    # measured loser vs dense (benchmarks/README.md) — kept compilable for
    # the record rather than bit-exact.
    dots = jax.lax.dot_general(
        x, w_dense, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [C_blk, M_pad] — MXU
    val = val_ref[0]
    B = (dots >= val).astype(jnp.float32)
    c_blk = dots.shape[0]
    internal_row = internal_ref[0]
    internal = _bcast_rows(internal_row, c_blk)
    pl_len = _walk_levels(
        B, internal, _bcast_rows(val * (1.0 - internal_row), c_blk), h
    )

    @pl.when(t == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += pl_len[:, None] / T


def _extended_kernel_dense(
    h, T, x_ref, w_ref, val_ref, internal_ref, out_ref
):
    """EIF scoring from a precomputed dense [T, M_pad, F_pad] table — for
    near-fully-extended forests, where sparse storage saves nothing and the
    in-kernel densify would redo k~F one-hot passes per row block."""
    t = pl.program_id(1)
    x = x_ref[...]  # [C_blk, F_pad]
    W = w_ref[0]  # [M_pad, F_pad]
    # default precision for the same Mosaic-compile reason as the sparse
    # EIF kernel above
    dots = jax.lax.dot_general(
        x, W, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [C_blk, M_pad] — MXU
    val = val_ref[0]
    B = (dots >= val).astype(jnp.float32)
    c_blk = dots.shape[0]
    internal_row = internal_ref[0]
    internal = _bcast_rows(internal_row, c_blk)
    pl_len = _walk_levels(
        B, internal, _bcast_rows(val * (1.0 - internal_row), c_blk), h
    )

    @pl.when(t == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += pl_len[:, None] / T


def _vmem_spec(block_shape, index_map):
    kw = {"memory_space": _VMEM} if _VMEM is not None else {}
    return pl.BlockSpec(block_shape, index_map, **kw)


@functools.partial(jax.jit, static_argnames=("h", "f_raw", "interpret"))
def _standard_pallas(X, feature, value, h, f_raw, interpret=False):
    C, Fp = X.shape
    T, _, Mp = value.shape
    grid = (C // _ROW_BLOCK, T)
    table = _vmem_spec((1, 1, Mp), lambda rb, t: (t, 0, 0))
    return pl.pallas_call(
        functools.partial(_standard_kernel, h, T, f_raw),
        grid=grid,
        in_specs=[
            _vmem_spec((_ROW_BLOCK, Fp), lambda rb, t: (rb, 0)),
            table,
            table,
        ],
        out_specs=_vmem_spec((_ROW_BLOCK, 1), lambda rb, t: (rb, 0)),
        out_shape=jax.ShapeDtypeStruct((C, 1), jnp.float32),
        interpret=interpret,
    )(X, feature, value)[:, 0]


# In-kernel densify beyond this many nonzero coordinates loses: the per-row-
# block one-hot passes approach the matmul's own cost, and sparse storage
# (2 * k entries/node) stops being smaller than the dense F_pad table.
_SPARSE_K_MAX = 32


@functools.partial(jax.jit, static_argnames=("h", "interpret"))
def _extended_pallas_sparse(
    X, indices, weights, value, internal, h, interpret=False
):
    C, Fp = X.shape
    T, _, Mp = value.shape
    k = indices.shape[1]
    grid = (C // _ROW_BLOCK, T)
    table = _vmem_spec((1, 1, Mp), lambda rb, t: (t, 0, 0))
    # [1, k, Mp] blocks: minor dim lane-aligned, k rides the sublane axis
    sparse = _vmem_spec((1, k, Mp), lambda rb, t: (t, 0, 0))
    return pl.pallas_call(
        functools.partial(_extended_kernel_sparse, h, T),
        grid=grid,
        in_specs=[
            _vmem_spec((_ROW_BLOCK, Fp), lambda rb, t: (rb, 0)),
            sparse,
            sparse,
            table,
            table,
        ],
        out_specs=_vmem_spec((_ROW_BLOCK, 1), lambda rb, t: (rb, 0)),
        out_shape=jax.ShapeDtypeStruct((C, 1), jnp.float32),
        interpret=interpret,
    )(X, indices, weights, value, internal)[:, 0]


@functools.partial(jax.jit, static_argnames=("h", "interpret"))
def _extended_pallas_dense(
    X, W_dense, value, internal, h, interpret=False
):
    C, Fp = X.shape
    T, _, Mp = value.shape
    grid = (C // _ROW_BLOCK, T)
    table = _vmem_spec((1, 1, Mp), lambda rb, t: (t, 0, 0))
    return pl.pallas_call(
        functools.partial(_extended_kernel_dense, h, T),
        grid=grid,
        in_specs=[
            _vmem_spec((_ROW_BLOCK, Fp), lambda rb, t: (rb, 0)),
            _vmem_spec((1, Mp, Fp), lambda rb, t: (t, 0, 0)),
            table,
            table,
        ],
        out_specs=_vmem_spec((_ROW_BLOCK, 1), lambda rb, t: (rb, 0)),
        out_shape=jax.ShapeDtypeStruct((C, 1), jnp.float32),
        interpret=interpret,
    )(X, W_dense, value, internal)[:, 0]


# The forest is immutable once trained/loaded, but the kernel needs host-side
# prep (padded node tables, leaf values; sparse [T, k, M_pad] or — above
# _SPARSE_K_MAX — dense [T, M_pad, F_pad] hyperplane tables for EIF). Cache
# prep per forest, keyed by the identities of ALL its arrays (a _replace of
# any single field must miss); holding strong references to the keyed arrays
# prevents id() reuse. Bounded FIFO.
_PREP_CACHE: dict = {}
_PREP_CACHE_MAX = 8


def _cached_prep(forest, build, extra_key=()):
    """``extra_key`` distinguishes preps that depend on call-site statics
    beyond the forest arrays (e.g. the dense EIF table's feature padding)."""
    arrays = tuple(forest)
    key = (tuple(id(a) for a in arrays), tuple(forest[0].shape), extra_key)
    hit = _PREP_CACHE.get(key)
    if hit is not None and all(a is b for a, b in zip(hit[0], arrays)):
        return hit[1]
    prep = build()
    if len(_PREP_CACHE) >= _PREP_CACHE_MAX:
        _PREP_CACHE.pop(next(iter(_PREP_CACHE)))
    _PREP_CACHE[key] = (arrays, prep)
    return prep


def standard_tables(forest, m_pad: int, h: int):
    """Kernel-layout node tables for a standard forest: ``(feature, value)``
    permuted/padded ``[T, 1, m_pad]`` — the finalized scoring layout's TWO
    tables (value = threshold at internal slots, leaf LUT at leaves) in the
    level-concat order, replacing the pre-layout feature/threshold/leaf
    triple. Single source for the production prep, the TPU-lowering tests,
    and the Mosaic machine-compile worker so they cannot diverge. Pads:
    feature -1 (no one-hot match, non-internal), value 0 (contributes 0 to
    every walk; the pad's go-right bit is masked by internal=0)."""
    feat_heap = np.asarray(forest.feature, np.int32)
    value_heap = _merged_value_heap(
        feat_heap >= 0, forest.threshold, forest.num_instances, h
    )
    return (
        jnp.asarray(_pad_table(feat_heap, m_pad, -1)),
        jnp.asarray(_pad_table(value_heap, m_pad, 0.0)),
    )


def extended_common_tables(forest, m_pad: int, h: int):
    """Kernel-layout ``(value, internal)`` tables shared by both extended
    kernels — value merges offset and leaf LUT (scoring_layout), same
    single-source rationale as :func:`standard_tables`."""
    indices = np.asarray(forest.indices)
    internal_heap = indices[..., 0] >= 0
    value_heap = _merged_value_heap(
        internal_heap, forest.offset, forest.num_instances, h
    )
    return (
        jnp.asarray(_pad_table(value_heap, m_pad, 0.0)),
        jnp.asarray(_pad_table(internal_heap.astype(np.float32), m_pad, 0.0)),
    )


def sparse_hyperplane_tables(forest, m_pad: int):
    """Node-axis-padded sparse hyperplane tables in the kernel layout
    ``[T, k, m_pad]`` (coordinates -1, weights 0 at padding) — shared by the
    production prep and the TPU-lowering tests so they cannot diverge."""
    indices = np.asarray(forest.indices)
    weights = np.asarray(forest.weights, np.float32)
    t_n, m, k = indices.shape
    order = list(_concat_order(m))
    idx_p = np.full((t_n, m_pad, k), -1, np.int32)
    idx_p[:, :m] = indices[:, order]
    w_p = np.zeros((t_n, m_pad, k), np.float32)
    w_p[:, :m] = weights[:, order]
    return (
        jnp.asarray(np.ascontiguousarray(idx_p.transpose(0, 2, 1))),
        jnp.asarray(np.ascontiguousarray(w_p.transpose(0, 2, 1))),
    )


def dense_hyperplane_table(forest, m_pad: int, f_pad: int):
    """Densified ``[T, m_pad, f_pad]`` hyperplane table for the large-k
    kernel. Duplicate coordinates accumulate (matching the dense XLA path's
    einsum; numpy fancy-index += would silently drop them)."""
    indices = np.asarray(forest.indices)
    order = list(_concat_order(indices.shape[1]))
    indices = indices[:, order]
    weights = np.asarray(forest.weights, np.float32)[:, order]
    t_n, m, k = indices.shape
    W = np.zeros((t_n, m_pad, f_pad), np.float32)
    t_ix, m_ix, k_ix = np.nonzero(indices >= 0)
    np.add.at(W, (t_ix, m_ix, indices[t_ix, m_ix, k_ix]), weights[t_ix, m_ix, k_ix])
    return jnp.asarray(W)


def path_lengths_pallas(forest, X, interpret: bool = False) -> jax.Array:
    """Mean path lengths via the Pallas kernel. Rows are padded to the row
    block and the node/feature axes to lane multiples internally; pass
    ``interpret=True`` off-TPU."""
    X = jnp.asarray(X, jnp.float32)
    n, F = X.shape
    f_pad = _pad_lanes(F)
    pad = (-n) % _ROW_BLOCK
    if pad or f_pad != F:
        X = jnp.pad(X, ((0, pad), (0, f_pad - F)))
    h = _height_of(forest.max_nodes)
    m_pad = _pad_lanes(forest.max_nodes)
    if isinstance(forest, StandardForest):

        def build_standard():
            return standard_tables(forest, m_pad, h)

        feature, value = _cached_prep(forest, build_standard)
        out = _standard_pallas(X, feature, value, h, F, interpret=interpret)
    else:

        k = forest.indices.shape[2]
        sparse = k <= _SPARSE_K_MAX

        def build_extended():
            common = extended_common_tables(forest, m_pad, h)
            if sparse:
                return sparse_hyperplane_tables(forest, m_pad) + common
            return (dense_hyperplane_table(forest, m_pad, f_pad),) + common

        prep = _cached_prep(
            forest, build_extended, extra_key=("sparse",) if sparse else ("dense", f_pad)
        )
        if sparse:
            idx_p, w_p, value, internal = prep
            out = _extended_pallas_sparse(
                X, idx_p, w_p, value, internal, h, interpret=interpret
            )
        else:
            W, value, internal = prep
            out = _extended_pallas_dense(
                X, W, value, internal, h, interpret=interpret
            )
    return out[:n]
