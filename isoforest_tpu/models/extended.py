"""Extended isolation forest Estimator / Model (random hyperplane splits).

Parity with ``extended/ExtendedIsolationForest.scala:40-136`` and
``extended/ExtendedIsolationForestModel.scala:37-175``: identical fit
orchestration to the standard estimator plus fit-time ``extensionLevel``
resolution (default ``numFeatures - 1``; the estimator itself is never
mutated — the resolved level is recorded on the model only,
ExtendedIsolationForest.scala:56-69,102).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.bagging import bagged_indices, feature_subsets, per_tree_keys
from ..ops.ext_growth import ExtendedForest, grow_extended_forest_fused
from ..utils import (
    ExtendedIsolationForestParams,
    UNKNOWN_TOTAL_NUM_FEATURES,
    extract_features,
    height_limit,
    logger,
    phase,
    resolve_extension_level,
    resolve_params,
)
from .isolation_forest import (
    _FIT_ROWS_TOTAL,
    _FIT_TREES_TOTAL,
    IsolationForestModel,
    _ParamSetters,
    _baseline_env_enabled,
    _blockwise_grow,
    _capture_fit_baseline,
    _compute_and_set_threshold,
    _fit_from_sample_impl,
    _fit_source_impl,
    _new_uid,
    _resolve_subsample_trees,
)

_REFERENCE_MODEL_CLASS = (
    "com.linkedin.relevance.isolationforest.extended.ExtendedIsolationForestModel"
)
_REFERENCE_ESTIMATOR_CLASS = (
    "com.linkedin.relevance.isolationforest.extended.ExtendedIsolationForest"
)


class ExtendedIsolationForest(_ParamSetters):
    """Estimator: ``fit(data) -> ExtendedIsolationForestModel``."""

    def __init__(
        self,
        params: Optional[ExtendedIsolationForestParams] = None,
        uid=None,
        **kw,
    ):
        self.params = (
            params if params is not None else ExtendedIsolationForestParams(**kw)
        )
        self.uid = uid or _new_uid("extended-isolation-forest")

    def set_extension_level(self, v: int):
        return self._set(extension_level=v)

    def fit(
        self,
        data,
        mesh=None,
        nonfinite: str = "warn",
        checkpoint_dir=None,
        checkpoint_every=None,
        resume: bool = False,
        baseline: bool = True,
        block_callback=None,
        subsample_trees=None,
    ) -> "ExtendedIsolationForestModel":
        """Train; same knobs as :meth:`IsolationForest.fit`, including the
        preemption-safe ``checkpoint_dir``/``checkpoint_every``/``resume``
        block-wise growth (docs/resilience.md §5), the drift-monitoring
        ``baseline`` capture (docs/observability.md §8) and the
        FastForest-style ``subsample_trees`` subbagging knob."""
        p = self.params
        if subsample_trees is not None:
            effective = _resolve_subsample_trees(subsample_trees, p.num_estimators)
            logger.info(
                "subsample_trees=%r: growing %d of %d trees",
                subsample_trees, effective, p.num_estimators,
            )
            p = p.replace(num_estimators=effective)
        X, _ = extract_features(data, p.features_col, nonfinite=nonfinite)
        total_rows, total_feats = int(X.shape[0]), int(X.shape[1])
        resolved = resolve_params(p, total_feats, total_rows)
        ext_level = resolve_extension_level(p.extension_level, resolved.num_features)
        logger.info(
            "resolved: numSamples=%d numFeatures=%d extensionLevel=%d",
            resolved.num_samples, resolved.num_features, ext_level,
        )

        h = height_limit(resolved.num_samples)
        key = jax.random.PRNGKey(np.uint32(p.random_seed & 0xFFFFFFFF))

        Xd = jnp.asarray(X, jnp.float32)
        fit_checkpoint = None
        with phase("extended_isolation_forest.fit.grow"):
            if checkpoint_dir is not None:
                from ..ops.ext_growth import grow_extended_forest_block

                if mesh is not None:
                    from ..parallel.sharded import sharded_grow_extended_forest

                    grow_block = lambda tk, bg, fx: sharded_grow_extended_forest(
                        mesh, tk, Xd, bg, fx, h, ext_level
                    )
                else:
                    grow_block = lambda tk, bg, fx: grow_extended_forest_block(
                        tk, Xd, bg, fx, height=h, extension_level=ext_level
                    )
                forest, fit_checkpoint = _blockwise_grow(
                    checkpoint_dir,
                    resume,
                    checkpoint_every,
                    key,
                    Xd,
                    kind="extended",
                    forest_cls=ExtendedForest,
                    grow_block=grow_block,
                    params=p,
                    resolved=resolved,
                    height=h,
                    extension_level=ext_level,
                    on_block=block_callback,
                )
            elif mesh is not None:
                from ..parallel.sharded import sharded_grow_extended_forest

                k_bag, k_feat, k_grow = jax.random.split(key, 3)
                bag = bagged_indices(
                    k_bag,
                    total_rows,
                    resolved.num_samples,
                    p.num_estimators,
                    p.bootstrap,
                )
                fidx = feature_subsets(
                    k_feat, total_feats, resolved.num_features, p.num_estimators
                )
                tree_keys = per_tree_keys(k_grow, p.num_estimators)
                forest = sharded_grow_extended_forest(
                    mesh, tree_keys, Xd, bag, fidx, h, ext_level
                )
            else:
                # single fused program — see grow_forest_fused's rationale
                forest = grow_extended_forest_fused(
                    key,
                    Xd,
                    num_samples=resolved.num_samples,
                    num_trees=p.num_estimators,
                    bootstrap=p.bootstrap,
                    num_features=resolved.num_features,
                    height=h,
                    extension_level=ext_level,
                )
            forest = jax.tree_util.tree_map(jax.block_until_ready, forest)

        _FIT_ROWS_TOTAL.inc(total_rows, model="extended")
        _FIT_TREES_TOTAL.inc(p.num_estimators, model="extended")
        model = ExtendedIsolationForestModel(
            forest=forest,
            params=p,
            num_samples=resolved.num_samples,
            num_features=resolved.num_features,
            extension_level=ext_level,
            total_num_features=total_feats,
        )
        model.fit_checkpoint = fit_checkpoint
        # finalize the packed scoring layout (offset + leaf LUT merged into
        # the value plane, hyperplanes inlined in the record) before the
        # threshold pass — same contract as the standard estimator
        model.finalize_scoring()
        _compute_and_set_threshold(model, Xd, mesh=mesh)
        if baseline and _baseline_env_enabled():
            _capture_fit_baseline(model, X)
        return model

    def fit_from_sample(
        self,
        X_sample,
        bag,
        *,
        checkpoint_dir=None,
        checkpoint_every=None,
        resume: bool = False,
        baseline: bool = True,
        nonfinite: str = "warn",
        sample_sha256=None,
        source_rows=None,
        block_callback=None,
    ) -> "ExtendedIsolationForestModel":
        """Fit from a pre-materialised sample — the EIF mirror of
        :meth:`IsolationForest.fit_from_sample` (docs/out_of_core.md)."""
        return _fit_from_sample_impl(
            self,
            X_sample,
            bag,
            extended=True,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            resume=resume,
            baseline=baseline,
            nonfinite=nonfinite,
            sample_sha256=sample_sha256,
            source_rows=source_rows,
            block_callback=block_callback,
        )

    def fit_source(
        self,
        source,
        *,
        chunk_rows=None,
        checkpoint_dir=None,
        checkpoint_every=None,
        resume: bool = False,
        baseline: bool = True,
        nonfinite: str = "warn",
        block_callback=None,
    ) -> "ExtendedIsolationForestModel":
        """Out-of-core fit from a sharded source — the EIF mirror of
        :meth:`IsolationForest.fit_source` (docs/out_of_core.md)."""
        return _fit_source_impl(
            self,
            source,
            extended=True,
            chunk_rows=chunk_rows,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            resume=resume,
            baseline=baseline,
            nonfinite=nonfinite,
            block_callback=block_callback,
        )

    def save(self, path: str, overwrite: bool = False) -> None:
        from ..io.persistence import save_estimator

        save_estimator(self, path, _REFERENCE_ESTIMATOR_CLASS, overwrite=overwrite)

    @classmethod
    def load(cls, path: str) -> "ExtendedIsolationForest":
        from ..io.persistence import load_estimator

        params, uid = load_estimator(
            path, ExtendedIsolationForestParams, _REFERENCE_ESTIMATOR_CLASS
        )
        return cls(params=params, uid=uid)


class ExtendedIsolationForestModel(IsolationForestModel):
    """Fitted EIF model. Scoring dispatches on the forest type (hyperplane
    traversal, ExtendedIsolationForestModel.scala:98-135) and consumes the
    inherited finalized scoring layout (:meth:`finalize_scoring` packs the
    ``1 + 2k``-float hyperplane records); only persistence and the recorded
    ``extension_level`` differ from the base model."""

    def __init__(
        self,
        forest: ExtendedForest,
        params: ExtendedIsolationForestParams,
        num_samples: int,
        num_features: int,
        extension_level: int,
        total_num_features: int = UNKNOWN_TOTAL_NUM_FEATURES,
        outlier_score_threshold: float = -1.0,
        uid: Optional[str] = None,
    ):
        super().__init__(
            forest=forest,
            params=params,
            num_samples=num_samples,
            num_features=num_features,
            total_num_features=total_num_features,
            outlier_score_threshold=outlier_score_threshold,
            uid=uid or _new_uid("extended-isolation-forest"),
        )
        self.extension_level = int(extension_level)

    def save(self, path: str, overwrite: bool = False) -> None:
        from ..io.persistence import save_extended_model

        save_extended_model(self, path, overwrite=overwrite)

    @classmethod
    def load(
        cls,
        path: str,
        verify="auto",
        on_corrupt: str = "raise",
        require_success: bool = True,
    ) -> "ExtendedIsolationForestModel":
        """Load with integrity verification; same resilience knobs as
        :meth:`IsolationForestModel.load` (docs/resilience.md)."""
        from ..io.persistence import load_extended_model

        return load_extended_model(
            path,
            verify=verify,
            on_corrupt=on_corrupt,
            require_success=require_success,
        )
