"""Standard isolation forest Estimator / Model.

API parity with the reference's spark.ml pair
(``IsolationForest.scala:25-125`` / ``IsolationForestModel.scala:37-192``):
same hyper-parameters, defaults, validators, fit orchestration
(``core/SharedTrainLogic.scala``) and scoring semantics — re-hosted on JAX.
``fit``/``transform`` accept an ``[N, F]`` array or a pandas DataFrame with a
vector-valued features column (the Dataset analogue); ``transform`` appends
``outlierScore`` and ``predictedLabel`` columns exactly like the reference's
``withColumn`` pipeline (IsolationForestModel.scala:142-148).
"""

from __future__ import annotations

import math
import os
import uuid
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.bagging import bagged_indices, feature_subsets, per_tree_keys
from ..ops.quantile import contamination_threshold, observed_contamination
from ..ops.traversal import score_matrix
from ..ops.tree_growth import StandardForest, grow_forest_fused
from ..telemetry.metrics import counter as _telemetry_counter
from ..telemetry.spans import span as _telemetry_span
from ..utils import (
    IsolationForestParams,
    UNKNOWN_TOTAL_NUM_FEATURES,
    check_non_finite,
    extract_features,
    height_limit,
    logger,
    phase,
    resolve_params,
    validate_feature_vector_size,
)

_REFERENCE_MODEL_CLASS = "com.linkedin.relevance.isolationforest.IsolationForestModel"
_REFERENCE_ESTIMATOR_CLASS = "com.linkedin.relevance.isolationforest.IsolationForest"

# Fit volume counters (docs/observability.md): labeled by model family so a
# mixed standard/EIF service can attribute training load.
_FIT_ROWS_TOTAL = _telemetry_counter(
    "isoforest_fit_rows_total",
    "Training rows consumed by fit(), by model family",
    labelnames=("model",),
)
_FIT_TREES_TOTAL = _telemetry_counter(
    "isoforest_fit_trees_total",
    "Trees grown by fit(), by model family",
    labelnames=("model",),
)


def _new_uid(prefix: str) -> str:
    return f"{prefix}_{uuid.uuid4().hex[:12]}"


# Scoring representations a model can prefer (persisted as the tolerated
# `scoringRepresentation` metadata extra): the exact f32 packed plane, or
# the rank-quantized q16 plane (ops/scoring_layout.pack_standard_q —
# decision-identical to f32 by construction, docs/scoring_layout.md).
SCORING_REPRESENTATIONS = ("f32", "q16")


def _resolve_subsample_trees(subsample_trees, num_estimators: int) -> int:
    """FastForest-style fit-time subbagging knob (arxiv 2004.02423): an int
    is an absolute tree count, a float in (0, 1] a fraction of
    ``numEstimators``. Returns the effective tree count (>= 1). Scoring
    normalisation rescales automatically — path lengths average over the
    grown trees, the same soundness argument as the dropped-tree degraded
    load (io/persistence._load_forest_tolerant)."""
    if isinstance(subsample_trees, bool) or not isinstance(
        subsample_trees, (int, float)
    ):
        raise ValueError(
            f"subsample_trees must be an int tree count or a float fraction "
            f"in (0, 1], got {subsample_trees!r}"
        )
    if isinstance(subsample_trees, int):
        count = subsample_trees
    else:
        if not 0.0 < subsample_trees <= 1.0:
            raise ValueError(
                f"fractional subsample_trees must be in (0, 1], got "
                f"{subsample_trees!r}"
            )
        count = int(round(subsample_trees * num_estimators))
    if not 1 <= count <= num_estimators:
        raise ValueError(
            f"subsample_trees resolves to {count} trees, outside "
            f"[1, numEstimators={num_estimators}]"
        )
    return count


# Fit-time drift-baseline capture (docs/observability.md §8): scored rows
# are capped so the capture stays a few percent of fit even at bench scale;
# the subsample is a deterministic stride (no RNG — checkpointed and plain
# fits must stay bitwise-identical).
_BASELINE_ENV = "ISOFOREST_TPU_BASELINE"
_BASELINE_MAX_ROWS = 65536


def _baseline_env_enabled() -> bool:
    return os.environ.get(_BASELINE_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def _capture_fit_baseline(model, X) -> None:
    """Capture the model's drift baseline from the training matrix: score a
    deterministic subsample and snapshot score + per-feature histograms
    (:func:`~isoforest_tpu.telemetry.monitor.capture_baseline`).

    Scoring is pinned to native (when available) or gather directly — not
    ``model.score``/``strategy="auto"`` — so the capture never takes a
    degradation rung of its own and never perturbs strategy-pinning tests.
    """
    from .. import native
    from ..ops.traversal import score_matrix as _score_matrix
    from ..telemetry.monitor import capture_baseline

    X = np.asarray(X, np.float32)
    n = int(X.shape[0])
    step = max(1, -(-n // _BASELINE_MAX_ROWS))
    sub = np.ascontiguousarray(X[::step])
    with _telemetry_span("fit.baseline", rows=int(sub.shape[0])):
        strategy = "native" if native.available() else "gather"
        scores = _score_matrix(
            model.forest,
            sub,
            model.num_samples,
            layout=model._scoring_layout,
            strategy=strategy,
        )
        model.baseline = capture_baseline(scores, sub, total_rows=n)


def _blockwise_grow(
    checkpoint_dir: str,
    resume: bool,
    checkpoint_every,
    key,
    Xd,
    *,
    kind: str,
    forest_cls,
    grow_block,
    params,
    resolved,
    height: int,
    extension_level=None,
    on_block=None,
    bag_override=None,
    sampler_sha256=None,
):
    """Preemption-safe growth shared by both estimators: grow the forest in
    checkpointed blocks of trees (docs/resilience.md §5).

    Bitwise identity with the uninterrupted fused fit rests on two
    invariants: (1) the key-split order ``(k_bag, k_feat, k_grow)`` matches
    :func:`~isoforest_tpu.ops.tree_growth.grow_forest_fused` exactly, and
    (2) the FULL-ensemble bag/feature/key tensors are derived once and
    *sliced* per block — the samplers' internal dispatch depends on the
    total tree count, so per-block re-derivation would change the bags.
    Per-tree growth streams are already block-partition-invariant
    (``fold_in(k_grow, tree_id)``; verified bitwise in
    tests/test_checkpoint.py).

    ``bag_override`` replaces the jitted bagging draw with precomputed bags
    (the out-of-core streamed sampler's, docs/out_of_core.md §3); the key
    split still happens so feature subsets and growth streams stay on the
    same (k_feat, k_grow) coordinates, and ``sampler_sha256`` joins the
    checkpoint fingerprint so a resume cannot mix samples.
    """
    from ..resilience import checkpoint as ckpt
    from ..resilience import faults

    num_trees = params.num_estimators
    block_trees = ckpt.resolve_block_size(checkpoint_every, num_trees)
    X_host = np.asarray(Xd)
    fingerprint = ckpt.fit_fingerprint(
        kind=kind,
        random_seed=params.random_seed,
        num_estimators=num_trees,
        bootstrap=params.bootstrap,
        num_samples=resolved.num_samples,
        num_features=resolved.num_features,
        height=height,
        total_rows=int(X_host.shape[0]),
        total_features=int(X_host.shape[1]),
        block_trees=block_trees,
        data_sha256=ckpt.data_fingerprint(X_host),
        extension_level=extension_level,
        sampler_sha256=sampler_sha256,
    )
    state = ckpt.FitCheckpoint(checkpoint_dir, fingerprint)
    state.begin(resume=resume)

    k_bag, k_feat, k_grow = jax.random.split(key, 3)
    if bag_override is not None:
        bag = jnp.asarray(bag_override, jnp.int32)
    else:
        bag = bagged_indices(
            k_bag,
            int(X_host.shape[0]),
            resolved.num_samples,
            num_trees,
            params.bootstrap,
        )
    fidx = feature_subsets(
        k_feat, int(X_host.shape[1]), resolved.num_features, num_trees
    )
    tree_keys = per_tree_keys(k_grow, num_trees)

    parts = []
    for index, start, stop in ckpt.block_ranges(num_trees, block_trees):
        arrays = state.load_block(index, start, stop)
        resumed = arrays is not None
        if arrays is None:
            with _telemetry_span("fit.grow_block", block=index, trees=stop - start):
                block = grow_block(
                    tree_keys[start:stop], bag[start:stop], fidx[start:stop]
                )
                block = jax.tree_util.tree_map(jax.block_until_ready, block)
                arrays = {
                    field: np.asarray(getattr(block, field))
                    for field in forest_cls._fields
                }
                state.seal_block(index, start, stop, arrays)
            # preemption seam: fires AFTER the seal, like a real kill
            # landing between blocks (tests/test_checkpoint.py)
            faults.check_fit_block(index)
        if on_block is not None:
            # progress hook consumed by the lifecycle manager: it observes
            # durable state only (the seal already happened), and a raise
            # here aborts the fit exactly like a between-block preemption
            on_block(index, start, stop, resumed)
        parts.append(arrays)
    logger.info(
        "checkpointed fit: %d/%d block(s) grown this session, %d resumed "
        "from %s",
        state.blocks_written,
        len(parts),
        state.blocks_loaded,
        checkpoint_dir,
    )
    forest = forest_cls(
        **{
            field: jnp.asarray(
                np.concatenate([part[field] for part in parts])
            )
            for field in forest_cls._fields
        }
    )
    return forest, state


def _require_absolute_max_samples(params) -> int:
    """Out-of-core fits can't resolve a fractional ``maxSamples`` — the
    stream length is unknown until the pass completes — so the param must be
    an absolute count (the reference default 256.0 qualifies)."""
    if params.max_samples <= 1.0:
        raise ValueError(
            f"out-of-core fit requires an absolute maxSamples (> 1), got "
            f"fraction {params.max_samples!r}; set max_samples to the "
            "per-tree sample count (e.g. 256)"
        )
    return int(math.floor(params.max_samples))


def _fit_from_sample_impl(
    est,
    X_sample,
    bag,
    *,
    extended: bool,
    checkpoint_dir=None,
    checkpoint_every=None,
    resume: bool = False,
    baseline: bool = True,
    nonfinite: str = "warn",
    sample_sha256=None,
    source_rows=None,
    block_callback=None,
):
    """Fit shared by both estimators from a pre-materialised sample: the
    union matrix ``X_sample [U, F]`` plus per-tree bags indexing into it
    (``[num_estimators, num_samples]``) — exactly what the streamed sampler
    (ops/bagging.StreamedBagger) emits. The bag replaces the jitted bagging
    draw; feature subsets and growth keys still come from the same
    ``(k_bag, k_feat, k_grow)`` split, so two fits given the same sample are
    bitwise-identical regardless of how the sample was produced or whether
    the growth was checkpointed."""
    p = est.params
    X = np.asarray(X_sample, dtype=np.float32)
    if X.ndim != 2 or X.shape[0] == 0:
        raise ValueError(f"sample matrix must be non-empty 2-D, got shape {X.shape}")
    bag = np.asarray(bag)
    if bag.ndim != 2:
        raise ValueError(f"bag must be [trees, samples], got shape {bag.shape}")
    if bag.shape[0] != p.num_estimators:
        raise ValueError(
            f"bag has {bag.shape[0]} trees but numEstimators={p.num_estimators}"
        )
    num_samples = _require_absolute_max_samples(p)
    if bag.shape[1] != num_samples:
        raise ValueError(
            f"bag has {bag.shape[1]} samples per tree but maxSamples "
            f"resolves to {num_samples}"
        )
    if bag.size and (int(bag.min()) < 0 or int(bag.max()) >= X.shape[0]):
        raise ValueError(
            f"bag indexes rows outside the sample matrix "
            f"[0, {X.shape[0]}) (min={int(bag.min())}, max={int(bag.max())})"
        )
    check_non_finite(X, nonfinite)
    U, F = int(X.shape[0]), int(X.shape[1])
    # max(U, num_samples) keeps resolve_params' small-dataset clamp from
    # shrinking num_samples below the bag width when the distinct-row union
    # is small (heavily overlapping bootstrap bags).
    resolved = resolve_params(p, F, max(U, num_samples))
    ext_level = None
    if extended:
        from ..utils import resolve_extension_level

        ext_level = resolve_extension_level(p.extension_level, resolved.num_features)
    h = height_limit(resolved.num_samples)
    key = jax.random.PRNGKey(np.uint32(p.random_seed & 0xFFFFFFFF))
    Xd = jnp.asarray(X, jnp.float32)
    if extended:
        from ..ops.ext_growth import ExtendedForest, grow_extended_forest_block

        forest_cls = ExtendedForest
        grow_block = lambda tk, bg, fx: grow_extended_forest_block(
            tk, Xd, bg, fx, height=h, extension_level=ext_level
        )
    else:
        from ..ops.tree_growth import grow_forest_block

        forest_cls = StandardForest
        grow_block = lambda tk, bg, fx: grow_forest_block(tk, Xd, bg, fx, height=h)

    kind = "extended" if extended else "standard"
    phase_name = (
        "extended_isolation_forest.fit.grow" if extended else "isolation_forest.fit.grow"
    )
    fit_checkpoint = None
    with phase(phase_name):
        if checkpoint_dir is not None:
            forest, fit_checkpoint = _blockwise_grow(
                checkpoint_dir,
                resume,
                checkpoint_every,
                key,
                Xd,
                kind=kind,
                forest_cls=forest_cls,
                grow_block=grow_block,
                params=p,
                resolved=resolved,
                height=h,
                extension_level=ext_level,
                on_block=block_callback,
                bag_override=bag,
                sampler_sha256=sample_sha256,
            )
        else:
            _, k_feat, k_grow = jax.random.split(key, 3)  # k_bag replaced by `bag`
            fidx = feature_subsets(k_feat, F, resolved.num_features, p.num_estimators)
            tree_keys = per_tree_keys(k_grow, p.num_estimators)
            forest = grow_block(tree_keys, jnp.asarray(bag, jnp.int32), fidx)
        forest = jax.tree_util.tree_map(jax.block_until_ready, forest)

    _FIT_ROWS_TOTAL.inc(int(source_rows) if source_rows else U, model=kind)
    _FIT_TREES_TOTAL.inc(p.num_estimators, model=kind)
    if extended:
        from .extended import ExtendedIsolationForestModel

        model = ExtendedIsolationForestModel(
            forest=forest,
            params=p,
            num_samples=resolved.num_samples,
            num_features=resolved.num_features,
            extension_level=ext_level,
            total_num_features=F,
        )
    else:
        model = IsolationForestModel(
            forest=forest,
            params=p,
            num_samples=resolved.num_samples,
            num_features=resolved.num_features,
            total_num_features=F,
        )
    model.fit_checkpoint = fit_checkpoint
    model.finalize_scoring()
    # contamination threshold estimated on the materialised sample (the only
    # rows on hand): a ~T*S-row quantile estimate — docs/out_of_core.md §3
    _compute_and_set_threshold(model, Xd)
    if baseline and _baseline_env_enabled():
        _capture_fit_baseline(model, X)
    return model


def _fit_source_impl(est, source, *, extended: bool, chunk_rows=None, **fit_kw):
    """One-pass out-of-core fit shared by both estimators: stream the source
    through the sampler, then fit from the materialised sample
    (docs/out_of_core.md)."""
    from ..io.source import open_source
    from ..ops.bagging import (
        StreamedBagger,
        materialise_bootstrap_sample,
        streamed_bootstrap_indices,
    )

    src = open_source(source)
    p = est.params
    num_samples = _require_absolute_max_samples(p)
    if p.bootstrap:
        # with replacement needs N up front (cheap for npy/avro/parquet
        # shard headers; one counting pass for CSV), then one data pass
        total = src.total_rows()
        idx = streamed_bootstrap_indices(
            p.random_seed, p.num_estimators, num_samples, total
        )
        sample = materialise_bootstrap_sample(
            src.iter_chunks(chunk_rows=chunk_rows), idx
        )
    else:
        bagger = StreamedBagger(p.random_seed, p.num_estimators, num_samples)
        for chunk in src.iter_chunks(chunk_rows=chunk_rows):
            bagger.consume(chunk.X)
        sample = bagger.finalize()
    logger.info(
        "streamed sample: %d distinct rows from a %d-row source "
        "(%d trees x %d samples)",
        sample.X.shape[0], sample.total_rows, p.num_estimators, num_samples,
    )
    return _fit_from_sample_impl(
        est,
        sample.X,
        sample.bag,
        extended=extended,
        sample_sha256=sample.sha256,
        source_rows=sample.total_rows,
        **fit_kw,
    )


class _ParamSetters:
    """Fluent setters mirroring the reference's Params traits
    (IsolationForestParamsBase.scala:8-110)."""

    params: IsolationForestParams

    def _set(self, **kw):
        self.params = self.params.replace(**kw)
        return self

    def set_num_estimators(self, v: int):
        return self._set(num_estimators=v)

    def set_max_samples(self, v: float):
        return self._set(max_samples=v)

    def set_contamination(self, v: float):
        return self._set(contamination=v)

    def set_contamination_error(self, v: float):
        return self._set(contamination_error=v)

    def set_max_features(self, v: float):
        return self._set(max_features=v)

    def set_bootstrap(self, v: bool):
        return self._set(bootstrap=v)

    def set_random_seed(self, v: int):
        return self._set(random_seed=v)

    def set_features_col(self, v: str):
        return self._set(features_col=v)

    def set_prediction_col(self, v: str):
        return self._set(prediction_col=v)

    def set_score_col(self, v: str):
        return self._set(score_col=v)


class IsolationForest(_ParamSetters):
    """Estimator: ``fit(data) -> IsolationForestModel`` (IsolationForest.scala:46-105)."""

    def __init__(self, params: Optional[IsolationForestParams] = None, uid=None, **kw):
        self.params = params if params is not None else IsolationForestParams(**kw)
        self.uid = uid or _new_uid("isolation-forest")

    def fit(
        self,
        data,
        mesh=None,
        nonfinite: str = "warn",
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        resume: bool = False,
        baseline: bool = True,
        block_callback=None,
        subsample_trees=None,
    ) -> "IsolationForestModel":
        """Train. With ``mesh`` (a `jax.sharding.Mesh` with a ``'trees'`` axis),
        tree growth is sharded across devices (SURVEY.md §2.4 tree parallelism);
        otherwise a single-device vmap over the tree axis.

        ``nonfinite`` is the NaN/inf input policy: ``"warn"`` (default,
        matching historical behaviour), ``"raise"``, or ``"allow"`` —
        non-finite features poison per-node min/max statistics during
        growth, so strict pipelines should pick ``"raise"``.

        ``checkpoint_dir`` turns on preemption-safe block-wise growth
        (docs/resilience.md §5): every ``checkpoint_every`` trees (default
        32) the completed block is sealed atomically under
        ``checkpoint_dir``, and a killed fit re-run with ``resume=True``
        continues from the last sealed block — producing a forest, scores
        and threshold **bitwise identical** to an uninterrupted fit. A
        config/data mismatch on resume raises
        :class:`~isoforest_tpu.resilience.CheckpointMismatchError`.

        ``baseline`` (default on; also gated by ``ISOFOREST_TPU_BASELINE``)
        captures the drift-monitoring baseline — training-score histogram +
        quantiles and per-feature stats from a capped deterministic
        subsample — persisted with the model as a ``_BASELINE.json``
        sidecar (docs/observability.md §8).

        ``block_callback`` (checkpointed fits only) is a progress hook
        called as ``callback(index, start, stop, resumed)`` after each tree
        block becomes durable (freshly sealed, or loaded from a previous
        session's seal) — the lifecycle manager uses it to emit
        ``retrain.block`` events live (docs/resilience.md §8).

        ``subsample_trees`` (FastForest-style subbagging, arxiv 2004.02423)
        grows only a subset of ``numEstimators`` trees — an int tree count
        or a float fraction in (0, 1] — trading a proportional fit-time cut
        for a small, bounded AUROC impact (pinned in
        tests/test_quality_gates.py). The fitted model records the reduced
        ensemble size, so scoring normalisation and persistence stay
        consistent."""
        p = self.params
        if subsample_trees is not None:
            effective = _resolve_subsample_trees(subsample_trees, p.num_estimators)
            logger.info(
                "subsample_trees=%r: growing %d of %d trees",
                subsample_trees, effective, p.num_estimators,
            )
            p = p.replace(num_estimators=effective)
        X, _ = extract_features(data, p.features_col, nonfinite=nonfinite)
        total_rows, total_feats = int(X.shape[0]), int(X.shape[1])
        resolved = resolve_params(p, total_feats, total_rows)
        logger.info(
            "resolved params: numSamples=%d numFeatures=%d (of %d rows x %d features)",
            resolved.num_samples, resolved.num_features, total_rows, total_feats,
        )

        h = height_limit(resolved.num_samples)
        key = jax.random.PRNGKey(np.uint32(p.random_seed & 0xFFFFFFFF))

        Xd = jnp.asarray(X, jnp.float32)
        fit_checkpoint = None
        with phase("isolation_forest.fit.grow"):
            if checkpoint_dir is not None:
                from ..ops.tree_growth import grow_forest_block

                if mesh is not None:
                    from ..parallel.sharded import sharded_grow_forest

                    grow_block = lambda tk, bg, fx: sharded_grow_forest(
                        mesh, tk, Xd, bg, fx, h
                    )
                else:
                    grow_block = lambda tk, bg, fx: grow_forest_block(
                        tk, Xd, bg, fx, height=h
                    )
                forest, fit_checkpoint = _blockwise_grow(
                    checkpoint_dir,
                    resume,
                    checkpoint_every,
                    key,
                    Xd,
                    kind="standard",
                    forest_cls=StandardForest,
                    grow_block=grow_block,
                    params=p,
                    resolved=resolved,
                    height=h,
                    on_block=block_callback,
                )
            elif mesh is not None:
                from ..parallel.sharded import sharded_grow_forest

                k_bag, k_feat, k_grow = jax.random.split(key, 3)
                bag = bagged_indices(
                    k_bag,
                    total_rows,
                    resolved.num_samples,
                    p.num_estimators,
                    p.bootstrap,
                )
                fidx = feature_subsets(
                    k_feat, total_feats, resolved.num_features, p.num_estimators
                )
                tree_keys = per_tree_keys(k_grow, p.num_estimators)
                forest = sharded_grow_forest(mesh, tree_keys, Xd, bag, fidx, h)
            else:
                # single fused program — one device dispatch instead of ~4
                # (bagging/subsets/keys/growth); key-split order inside is
                # identical, so the forest is stream-identical to the
                # sharded path's
                forest = grow_forest_fused(
                    key,
                    Xd,
                    num_samples=resolved.num_samples,
                    num_trees=p.num_estimators,
                    bootstrap=p.bootstrap,
                    num_features=resolved.num_features,
                    height=h,
                )
            forest = jax.tree_util.tree_map(jax.block_until_ready, forest)

        _FIT_ROWS_TOTAL.inc(total_rows, model="standard")
        _FIT_TREES_TOTAL.inc(p.num_estimators, model="standard")
        model = IsolationForestModel(
            forest=forest,
            params=p,
            num_samples=resolved.num_samples,
            num_features=resolved.num_features,
            total_num_features=total_feats,
        )
        model.fit_checkpoint = fit_checkpoint
        # finalize the packed scoring layout eagerly: the contamination
        # threshold pass below (and every later score) consumes it
        model.finalize_scoring()
        _compute_and_set_threshold(model, Xd, mesh=mesh)
        if baseline and _baseline_env_enabled():
            _capture_fit_baseline(model, X)
        return model

    def fit_from_sample(
        self,
        X_sample,
        bag,
        *,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        resume: bool = False,
        baseline: bool = True,
        nonfinite: str = "warn",
        sample_sha256: Optional[str] = None,
        source_rows: Optional[int] = None,
        block_callback=None,
    ) -> "IsolationForestModel":
        """Fit from a pre-materialised per-tree sample: ``X_sample`` is the
        ``[U, F]`` union of selected rows and ``bag`` the
        ``[numEstimators, numSamples]`` indices into it (what
        :class:`~isoforest_tpu.ops.bagging.StreamedBagger` emits). Growth,
        threshold and baseline are computed from the sample alone, so the
        result is independent of how (or from how many source rows) the
        sample was drawn — the bitwise contract behind :meth:`fit_source`.
        Supports the same ``checkpoint_dir``/``resume`` block-wise growth as
        :meth:`fit`."""
        return _fit_from_sample_impl(
            self,
            X_sample,
            bag,
            extended=False,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            resume=resume,
            baseline=baseline,
            nonfinite=nonfinite,
            sample_sha256=sample_sha256,
            source_rows=source_rows,
            block_callback=block_callback,
        )

    def fit_source(
        self,
        source,
        *,
        chunk_rows: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        resume: bool = False,
        baseline: bool = True,
        nonfinite: str = "warn",
        block_callback=None,
    ) -> "IsolationForestModel":
        """Out-of-core fit from a sharded on-disk source (a path / glob /
        :class:`~isoforest_tpu.io.source.ShardedSource`): one sequential
        bounded-memory pass streams the source through the one-pass sampler,
        then fits from the materialised sample (docs/out_of_core.md).
        Deterministic under ``random_seed`` and bitwise-invariant to
        ``chunk_rows`` and shard-size choices. Requires an absolute
        ``max_samples``."""
        return _fit_source_impl(
            self,
            source,
            extended=False,
            chunk_rows=chunk_rows,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            resume=resume,
            baseline=baseline,
            nonfinite=nonfinite,
            block_callback=block_callback,
        )

    # -- persistence (estimator: params-only metadata, IsolationForest.scala:114-125)
    def save(self, path: str, overwrite: bool = False) -> None:
        from ..io.persistence import save_estimator

        save_estimator(self, path, _REFERENCE_ESTIMATOR_CLASS, overwrite=overwrite)

    @classmethod
    def load(cls, path: str) -> "IsolationForest":
        from ..io.persistence import load_estimator

        params, uid = load_estimator(
            path, IsolationForestParams, _REFERENCE_ESTIMATOR_CLASS
        )
        return cls(params=params, uid=uid)


def _compute_and_set_threshold(model, Xd, mesh=None) -> None:
    """Contamination thresholding (SharedTrainLogic.scala:175-242):
    skip when contamination == 0 (threshold stays -1, all labels 0);
    else threshold = quantile(train scores, 1 - contamination) within
    ``contaminationError``, then verify observed contamination."""
    p = model.params
    if p.contamination == 0.0:
        return
    with phase("isolation_forest.fit.threshold"):
        # nonfinite policy already applied at fit's extract_features
        scores = model.score(np.asarray(Xd), mesh=mesh, nonfinite="allow")
        thr = contamination_threshold(scores, p.contamination, p.contamination_error)
        model.set_outlier_score_threshold(thr)
        observed = observed_contamination(scores, thr)
        verification_error = (
            p.contamination_error
            if p.contamination_error > 0
            else 0.01 * p.contamination
        )
        if abs(observed - p.contamination) > verification_error:
            logger.warning(
                "observed contamination %.6f deviates from requested %.6f by more "
                "than %.6f (SharedTrainLogic verification)",
                observed, p.contamination, verification_error,
            )


class IsolationForestModel:
    """Fitted model: broadcast-free scoring over the heap-tensor forest.

    Construction contract mirrors IsolationForestModel.scala:37-78: requires a
    non-empty forest and ``numSamples >= 2``; ``outlierScoreThreshold`` starts
    at ``-1`` (unset) and labels are all-zero until it is set (:142-148).
    """

    def __init__(
        self,
        forest: StandardForest,
        params: IsolationForestParams,
        num_samples: int,
        num_features: int,
        total_num_features: int = UNKNOWN_TOTAL_NUM_FEATURES,
        outlier_score_threshold: float = -1.0,
        uid: Optional[str] = None,
    ):
        if forest.num_trees < 1:
            raise ValueError("model requires a non-empty forest")
        if num_samples < 2:
            raise ValueError(f"numSamples must be >= 2, got {num_samples}")
        self.forest = forest
        self.params = params
        self.num_samples = int(num_samples)
        self.num_features = int(num_features)
        self.total_num_features = int(total_num_features)
        self.outlier_score_threshold = float(outlier_score_threshold)
        self.uid = uid or _new_uid("isolation-forest")
        # set by degraded (on_corrupt="drop") loads: which trees were lost
        # (resilience.LoadReport); None for fits and clean loads
        self.load_report = None
        # set by checkpointed fits (fit(checkpoint_dir=...)): the
        # resilience.FitCheckpoint with blocks_written/blocks_loaded;
        # None for plain fits and loads
        self.fit_checkpoint = None
        # drift-monitoring baseline (telemetry.monitor.Baseline): captured
        # by fit(), restored from the _BASELINE.json sidecar on load; None
        # for legacy directories and fit(baseline=False)
        self.baseline = None
        # streaming drift monitor attached by enable_monitoring(); every
        # score() folds into it while set
        self._monitor = None
        # packed scoring layout (ops.scoring_layout): built eagerly by
        # fit()/finalize_scoring(), lazily on first score for persisted
        # models — the on-disk format stays the reference Avro node arrays
        # and the layout is rebuilt from them after load
        self._scoring_layout = None
        # preferred serving representation ("f32" | "q16"): persisted as the
        # tolerated `scoringRepresentation` metadata extra and restored on
        # load, so a fleet that standardised on the quantized plane keeps it
        # across save/load without re-deciding per process. The on-disk node
        # table is always the exact f32 Avro form; q16 is rebuilt from it.
        self.scoring_representation = "f32"

    def set_outlier_score_threshold(self, value: float) -> "IsolationForestModel":
        """Manually override the threshold (IsolationForestModel.scala:86-95)."""
        if not (0.0 <= value <= 1.0 or value == -1.0):
            raise ValueError(
                f"outlierScoreThreshold must be in [0, 1] (or -1 = unset), got {value}"
            )
        self.outlier_score_threshold = float(value)
        return self

    # ------------------------------------------------------------------ #

    def set_scoring_representation(self, value: str) -> "IsolationForestModel":
        """Record the preferred serving representation (``"f32"`` default,
        or ``"q16"`` — the rank-quantized plane, decision-identical to f32).
        Persisted with the model and restored on load. ``"q16"`` requires
        the forest to pass the quantized capacity fence
        (:func:`~isoforest_tpu.ops.scoring_layout.quantized_eligible`);
        scoring with ``strategy="auto"`` still measures — the preference
        warms the quantized layout eagerly at :meth:`finalize_scoring` and
        travels with the model, it does not pin the kernel. Returns self."""
        if value not in SCORING_REPRESENTATIONS:
            raise ValueError(
                f"scoring representation must be one of "
                f"{'/'.join(SCORING_REPRESENTATIONS)}, got {value!r}"
            )
        if value == "q16":
            from ..ops.scoring_layout import quantized_unsupported_reason

            reason = quantized_unsupported_reason(self.forest)
            if reason is not None:
                raise ValueError(
                    f"this forest cannot take the q16 representation: {reason}"
                )
        self.scoring_representation = value
        if value == "q16":
            # release the exact f32 plane (rebuilt lazily if a non-q16
            # strategy runs) and warm the quantized one, so residency
            # accounting reflects the switch immediately
            self._scoring_layout = None
            from ..ops.scoring_layout import get_layout_q

            get_layout_q(self.forest)
        return self

    def finalize_scoring(self) -> "IsolationForestModel":
        """Build the finalized scoring layout (packed node records + leaf
        path-length LUT, :mod:`~isoforest_tpu.ops.scoring_layout`) once for
        this forest. ``fit`` calls this; loaded models hit it lazily on the
        first :meth:`score` — persistence round-trips through the reference
        Avro node arrays unchanged and rebuilds the layout here. Models
        preferring the ``"q16"`` representation warm ONLY the quantized
        plane: the exact f32 layout stays lazy (``score_matrix`` resolves
        it on demand if a non-q16 strategy actually runs), so a quantized
        tenant's resident bytes really are the compressed plane + shared
        tables (fleet residency accounting,
        :func:`~isoforest_tpu.fleet.registry.layout_nbytes`). Returns
        self."""
        from ..ops.scoring_layout import get_layout

        width = (
            self.total_num_features
            if self.total_num_features != UNKNOWN_TOTAL_NUM_FEATURES
            else None
        )
        with _telemetry_span("model.finalize_scoring", trees=self.forest.num_trees):
            if self.scoring_representation == "q16":
                from ..ops.scoring_layout import get_layout_q

                get_layout_q(self.forest)
            else:
                self._scoring_layout = get_layout(self.forest, num_features=width)
        return self

    def score(
        self,
        X,
        mesh=None,
        strict: bool = False,
        nonfinite: str = "warn",
        timeout_s: Optional[float] = None,
        strategy: str = "auto",
        chunk_size: Optional[int] = None,
        pipeline: Optional[bool] = None,
        fold_monitor: bool = True,
    ) -> np.ndarray:
        """Outlier scores ``2^(-E[h(x)]/c(n))`` for an ``[N, F]`` matrix.

        ``strict=True`` raises
        :class:`~isoforest_tpu.resilience.DegradationError` instead of
        silently falling back when the resolved scoring strategy cannot run
        (docs/resilience.md). ``nonfinite``: NaN/inf policy
        (``"warn"``/``"raise"``/``"allow"``). ``timeout_s`` arms the scoring
        watchdog (docs/resilience.md §6): a strategy that stalls past the
        deadline is abandoned and retried once on the portable gather
        kernel (rung ``scoring_timeout``; under ``strict=True`` the timeout
        raises instead). Local-strategy path only — mesh scoring runs the
        fused sharded program without a watchdog. ``strategy`` defaults to
        ``"auto"``, resolved by the measured autotuner (docs/autotune.md;
        the mesh path restricts it to the shard_map-jittable pair).
        ``chunk_size``/``pipeline`` forward to the streaming micro-batch
        executor (docs/pipeline.md): batches spanning multiple chunks
        double-buffer host→device transfer under compute, bitwise equal to
        single-shot scoring. ``fold_monitor=False`` skips the attached
        drift monitor's fold — the idempotent-replay path of a replicated
        deployment (docs/replication.md) re-scores a retried request
        without counting its rows twice; scores are unaffected."""
        X = np.asarray(X, np.float32)
        check_non_finite(X, nonfinite)
        validate_feature_vector_size(X.shape[1], self.total_num_features)
        with _telemetry_span("model.score", rows=int(X.shape[0])):
            if mesh is not None:
                from ..parallel.sharded import sharded_score

                scores = sharded_score(
                    mesh,
                    self.forest,
                    X,
                    self.num_samples,
                    score_strategy=strategy,
                    pipeline=pipeline,
                    chunk_rows=chunk_size,
                )
            else:
                if (
                    self._scoring_layout is None
                    and self.scoring_representation != "q16"
                ):
                    self.finalize_scoring()
                expected = (
                    self.total_num_features
                    if self.total_num_features != UNKNOWN_TOTAL_NUM_FEATURES
                    else None
                )
                scores = score_matrix(
                    self.forest,
                    X,
                    self.num_samples,
                    chunk_size=chunk_size,
                    strategy=strategy,
                    layout=self._scoring_layout,
                    strict=strict,
                    expected_features=expected,
                    timeout_s=timeout_s,
                    pipeline=pipeline,
                )
        monitor = self._monitor
        if monitor is not None and fold_monitor:
            # drift monitoring (docs/observability.md §8): fold the served
            # batch AFTER scoring so monitor cost never sits between the
            # caller and its scores on an alerting path
            monitor.observe(scores, X)
        return scores

    def degradations(self):
        """Structured degradation events recorded in this process (the
        unified ladder, docs/resilience.md): every scoring fallback plus any
        dropped-tree load. Model-specific load details live in
        ``self.load_report``."""
        from ..resilience import degradations as _degradations

        return _degradations()

    def diagnostics(self) -> dict:
        """Forest-structure diagnostics from the packed scoring layout
        (docs/observability.md §8): per-tree depth distribution, leaf-size
        histogram, feature split-usage counts, expected-vs-realised average
        path length and imbalance stats — plain JSON types, no Avro
        re-traversal."""
        from ..telemetry.diagnostics import forest_diagnostics

        return forest_diagnostics(self)

    def enable_monitoring(
        self,
        threshold: Optional[float] = None,
        **monitor_kwargs,
    ):
        """Attach a streaming drift monitor
        (:class:`~isoforest_tpu.telemetry.monitor.ScoreMonitor`): every
        subsequent :meth:`score` folds its batch into the monitor, which
        tracks PSI/KS of serving scores and input features against the
        fit-time baseline, exports the ``isoforest_*_drift_psi`` gauges and
        raises a ``drift_alert`` when the threshold is crossed (log-once;
        ``strict`` scoring is unaffected — scores stay exact). Returns the
        monitor; requires a baseline (fit with monitoring enabled, or a
        model dir carrying the ``_BASELINE.json`` sidecar)."""
        if self.baseline is None:
            raise ValueError(
                "this model has no drift baseline: it was loaded from a "
                "legacy directory (no _BASELINE.json sidecar) or fitted "
                "with baseline capture disabled — refit, or re-save from a "
                "fit with baseline=True, to enable monitoring"
            )
        from ..telemetry.monitor import ScoreMonitor

        kwargs = dict(monitor_kwargs)
        if threshold is not None:
            kwargs["threshold"] = threshold
        self._monitor = ScoreMonitor(self.baseline, **kwargs)
        return self._monitor

    def rebind_monitoring(self, baseline=None):
        """Re-arm the attached drift monitor against ``baseline`` (default:
        this model's own) via :meth:`ScoreMonitor.rebind`: folded counts and
        fired alerts are dropped and the edge-triggered ``drift.alert``
        re-arms, so a drift episode against the NEW baseline fires again
        instead of staying latched on the old one. The monitor *object*
        survives — operator handles from :meth:`enable_monitoring` stay
        valid across a lifecycle hot-swap (docs/resilience.md §8). Returns
        the monitor."""
        monitor = self._monitor
        if monitor is None:
            raise ValueError(
                "no drift monitor attached; call enable_monitoring() first"
            )
        target = baseline if baseline is not None else self.baseline
        if target is None:
            raise ValueError(
                "no baseline to rebind to: this model carries none and no "
                "explicit baseline was given"
            )
        monitor.rebind(target)
        return monitor

    def disable_monitoring(self) -> None:
        """Detach the drift monitor (its folded state is discarded)."""
        self._monitor = None

    def warmup(
        self,
        batch_sizes=(1024,),
        strategy: str = "auto",
        width: Optional[int] = None,
        mesh=None,
    ) -> "IsolationForestModel":
        """Pre-compile the scoring programs for the given batch sizes so
        latency-sensitive serving never pays XLA compilation on a live
        request. Returns self.

        Warm with the SAME configuration the serving path will use: the
        default ``strategy="auto"`` resolves identically here and in
        :meth:`score` (env var, else the per-platform default — the native
        C++ walker on CPU, whose per-forest prep this warms instead of an
        XLA program; dense on TPU), and pass ``mesh`` if serving scores
        through a mesh (the sharded program is compiled separately). Batch
        sizes dedupe to their power-of-two buckets, matching
        :func:`~isoforest_tpu.ops.traversal.score_matrix` bucketing. Legacy
        models with unknown ``totalNumFeatures`` must pass ``width`` (the
        serving input's feature count) explicitly.
        """
        if width is None:
            if self.total_num_features == UNKNOWN_TOTAL_NUM_FEATURES:
                raise ValueError(
                    "this model does not record totalNumFeatures (legacy); "
                    "pass width=<serving feature count> to warmup"
                )
            width = self.total_num_features
        from ..ops.traversal import batch_bucket

        buckets = sorted({batch_bucket(n) for n in batch_sizes})
        for bucket in buckets:
            dummy = np.zeros((bucket, max(width, 1)), np.float32)
            if mesh is not None:
                from ..parallel.sharded import sharded_score

                sharded_score(mesh, self.forest, dummy, self.num_samples)
            else:
                score_matrix(
                    self.forest, dummy, self.num_samples, strategy=strategy
                )
        return self

    def predict(self, scores: np.ndarray) -> np.ndarray:
        """Labels from scores: ``score >= threshold`` when a threshold is set,
        else all zeros (IsolationForestModel.scala:142-148)."""
        if self.outlier_score_threshold > 0:
            return (scores >= self.outlier_score_threshold).astype(np.float64)
        return np.zeros_like(scores, dtype=np.float64)

    def transform(self, data, mesh=None, nonfinite: str = "warn"):
        """Append score + label columns (IsolationForestModel.scala:116-151).

        DataFrame in -> DataFrame out (with ``scoreCol``/``predictionCol``
        appended); array in -> dict of column arrays.
        """
        p = self.params
        X, frame = extract_features(
            data,
            p.features_col,
            output_cols=(p.score_col, p.prediction_col),
            nonfinite=nonfinite,
        )
        scores = self.score(X, mesh=mesh, nonfinite="allow")  # checked above
        labels = self.predict(scores)
        if frame is not None:
            out = frame.copy()
            out[p.score_col] = scores.astype(np.float64)
            out[p.prediction_col] = labels
            return out
        return {p.score_col: scores.astype(np.float64), p.prediction_col: labels}

    # ------------------------------------------------------------------ #

    def save(self, path: str, overwrite: bool = False) -> None:
        """Persist in the reference's Avro + JSON-metadata layout
        (IsolationForestModelReadWrite.scala:210-249)."""
        from ..io.persistence import save_standard_model

        save_standard_model(self, path, overwrite=overwrite)

    @classmethod
    def load(
        cls,
        path: str,
        verify="auto",
        on_corrupt: str = "raise",
        require_success: bool = True,
    ) -> "IsolationForestModel":
        """Load with integrity verification (docs/resilience.md): ``verify``
        the ``_MANIFEST.json`` checksums (``"auto"``/``True``/``False``),
        ``on_corrupt`` in ``{"raise", "drop"}`` (drop salvages intact trees
        into a valid smaller forest and records ``model.load_report``), and
        ``require_success`` gates on the ``_SUCCESS`` seal markers."""
        from ..io.persistence import load_standard_model

        return load_standard_model(
            path,
            verify=verify,
            on_corrupt=on_corrupt,
            require_success=require_success,
        )
