from . import avro, outofcore, persistence, source

__all__ = ["avro", "outofcore", "persistence", "source"]
