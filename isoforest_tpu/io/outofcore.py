"""Out-of-core scoring: stream a sharded source, seal scores shard-by-shard.

:func:`score_source` is the scoring half of the out-of-core data plane
(docs/out_of_core.md): each source shard is scored chunk-by-chunk through the
model's streaming executor and its scores are sealed as one atomic part
directory (``part-00007/`` with ``scores.npy`` + ``part.json`` +
``_MANIFEST.json``) under the output sink. Because scoring is row-independent
and chunking is bitwise-neutral (docs/pipeline.md §2), each sealed part is a
deterministic function of (model, shard, strategy) — so a killed run re-run
with ``resume=True`` skips every intact sealed part and produces final output
bitwise-identical to an uninterrupted run. A ``fingerprint.json`` gate
(model sha + source shard identity + strategy) refuses resumes against a
different model, source, or scoring strategy — strategies are individually
deterministic but not mutually bitwise-equal, which is why the *requested*
strategy string is part of the identity (``"auto"`` included: its resolution
is device-local and stable within a box, and pinning e.g. ``"gather"``
makes the sink portable).

Memory model: one decoded chunk + one shard's score vector at a time — RSS
is bounded by ``O(chunk_rows * num_features + max_shard_rows)`` floats,
independent of the source's total size.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional

import numpy as np

from ..resilience import faults, manifest
from ..resilience.checkpoint import CheckpointMismatchError
from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _telemetry_counter
from ..utils import logger
from .persistence import _atomic_dir
from .source import ShardedSource, open_source

FINGERPRINT_NAME = "fingerprint.json"
SUMMARY_NAME = "_SUMMARY.json"
SINK_VERSION = 1

# Sealed per-shard score parts (docs/observability.md §3).
_SHARDS_SEALED_TOTAL = _telemetry_counter(
    "isoforest_score_source_shards_sealed_total",
    "Source shards whose scores were sealed by out-of-core scoring runs",
)


def _part_name(index: int) -> str:
    return f"part-{index:05d}"


def model_fingerprint(model) -> str:
    """sha256 over everything that determines a score: the forest's packed
    arrays, the ensemble normalisation constant, and the threshold."""
    h = hashlib.sha256()
    forest = model.forest
    for field in type(forest)._fields:
        arr = np.asarray(getattr(forest, field))
        h.update(field.encode())
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    h.update(
        repr(
            (
                int(model.num_samples),
                float(model.outlier_score_threshold),
            )
        ).encode()
    )
    return h.hexdigest()


def _sink_fingerprint(model, source: ShardedSource, strategy: str) -> dict:
    return {
        "sinkVersion": SINK_VERSION,
        "modelSha256": model_fingerprint(model),
        "strategy": str(strategy),
        "source": source.fingerprint(),
    }


def _write_json(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def _load_sealed_part(sink_dir: str, index: int, shard) -> Optional[np.ndarray]:
    """Return the sealed scores for shard ``index`` if the part is intact and
    matches the shard's identity, else None (re-score)."""
    part_dir = os.path.join(sink_dir, _part_name(index))
    if not os.path.isdir(part_dir) or not manifest.present(part_dir):
        return None
    if manifest.verify(part_dir):
        logger.warning(
            "out-of-core sink: sealed part %s failed manifest verification; "
            "re-scoring shard",
            part_dir,
        )
        return None
    try:
        with open(os.path.join(part_dir, "part.json")) as fh:
            meta = json.load(fh)
        if (
            meta.get("shardIndex") != index
            or meta.get("shardName") != shard.name
            or meta.get("sizeBytes") != shard.size_bytes
        ):
            return None
        return np.load(os.path.join(part_dir, "scores.npy"))
    except (OSError, ValueError, json.JSONDecodeError):
        return None


def score_source(
    model,
    source,
    sink_dir: str,
    *,
    chunk_rows: Optional[int] = None,
    strategy: str = "auto",
    pipeline: Optional[bool] = None,
    resume: bool = False,
) -> dict:
    """Score every row of ``source`` into ``sink_dir``, one sealed part per
    shard; returns a summary dict (also written as ``_SUMMARY.json``).

    ``resume=True`` re-attaches to an existing sink: the fingerprint gate
    must match (else :class:`CheckpointMismatchError`), intact sealed parts
    are skipped, and the final output is bitwise-identical to an
    uninterrupted run. ``resume=False`` on a non-empty sink refuses rather
    than silently reusing stale parts.
    """
    src = open_source(source) if not isinstance(source, ShardedSource) else source
    fingerprint = _sink_fingerprint(model, src, strategy)
    os.makedirs(sink_dir, exist_ok=True)
    fp_path = os.path.join(sink_dir, FINGERPRINT_NAME)
    if os.path.exists(fp_path):
        with open(fp_path) as fh:
            existing = json.load(fh)
        if existing != fingerprint:
            mismatched = sorted(
                k
                for k in set(existing) | set(fingerprint)
                if existing.get(k) != fingerprint.get(k)
            )
            raise CheckpointMismatchError(
                f"out-of-core sink {sink_dir!r} was written for a different "
                f"{'/'.join(mismatched)}; refusing to "
                f"{'resume' if resume else 'overwrite'} "
                "(use a fresh sink directory)",
                mismatched_fields=mismatched,
            )
        if not resume:
            sealed = [
                name
                for name in os.listdir(sink_dir)
                if name.startswith("part-")
                and manifest.present(os.path.join(sink_dir, name))
            ]
            if sealed:
                raise CheckpointMismatchError(
                    f"out-of-core sink {sink_dir!r} already holds "
                    f"{len(sealed)} sealed part(s); pass resume=True to "
                    "continue it or use a fresh sink directory",
                    mismatched_fields=["resume"],
                )
    else:
        _write_json(fp_path, fingerprint)

    t0 = time.perf_counter()
    record_event(
        "score_source.begin",
        sink=os.path.basename(os.path.normpath(sink_dir)),
        shards=src.num_shards,
        resume=bool(resume),
        strategy=str(strategy),
    )

    total_rows = 0
    sealed_now = 0
    skipped = 0
    shard_seconds = []
    for index, shard in enumerate(src.shards):
        if resume:
            scores = _load_sealed_part(sink_dir, index, shard)
            if scores is not None:
                total_rows += int(scores.shape[0])
                skipped += 1
                record_event(
                    "score_source.shard_skipped",
                    shard=index,
                    rows=int(scores.shape[0]),
                )
                continue
        t_shard = time.perf_counter()
        parts = []
        for chunk in src.iter_chunks(
            chunk_rows=chunk_rows, start_shard=index, stop_shard=index + 1
        ):
            parts.append(
                np.asarray(
                    model.score(
                        chunk.X,
                        strategy=strategy,
                        chunk_size=chunk_rows,
                        pipeline=pipeline,
                        nonfinite="allow",
                    )
                )
            )
        scores = (
            np.concatenate(parts) if len(parts) != 1 else parts[0]
        ) if parts else np.zeros((0,), dtype=np.float32)
        part_dir = os.path.join(sink_dir, _part_name(index))
        with _atomic_dir(part_dir, overwrite=True) as tmp:
            np.save(os.path.join(tmp, "scores.npy"), scores)
            with open(os.path.join(tmp, "part.json"), "w") as fh:
                json.dump(
                    {
                        "shardIndex": index,
                        "shardName": shard.name,
                        "sizeBytes": shard.size_bytes,
                        "rows": int(scores.shape[0]),
                    },
                    fh,
                    indent=1,
                    sort_keys=True,
                )
                fh.write("\n")
            manifest.write(tmp)
        elapsed = time.perf_counter() - t_shard
        shard_seconds.append(elapsed)
        total_rows += int(scores.shape[0])
        sealed_now += 1
        _SHARDS_SEALED_TOTAL.inc()
        record_event(
            "score_source.shard_sealed",
            shard=index,
            rows=int(scores.shape[0]),
            seconds=round(elapsed, 6),
        )
        # preemption seam: fires AFTER the seal, like a real kill landing
        # between shards (tests/test_out_of_core.py, CI smoke)
        faults.check_score_shard(index)

    seconds = time.perf_counter() - t0
    summary = {
        "shards": src.num_shards,
        "sealed": sealed_now,
        "skipped": skipped,
        "rows": total_rows,
        "seconds": round(seconds, 6),
        "rowsPerSecond": round(total_rows / seconds, 3) if seconds > 0 else None,
        "shardSecondsMean": (
            round(sum(shard_seconds) / len(shard_seconds), 6)
            if shard_seconds
            else None
        ),
        "strategy": str(strategy),
    }
    _write_json(os.path.join(sink_dir, SUMMARY_NAME), summary)
    record_event(
        "score_source.complete",
        rows=total_rows,
        sealed=sealed_now,
        skipped=skipped,
        seconds=round(seconds, 6),
    )
    logger.info(
        "out-of-core scoring: %d rows over %d shard(s) (%d sealed now, %d "
        "resumed) in %.3fs",
        total_rows, src.num_shards, sealed_now, skipped, seconds,
    )
    return summary


def read_scores(sink_dir: str, num_shards: Optional[int] = None) -> np.ndarray:
    """Concatenate the sealed per-shard scores of a completed sink in shard
    order. Raises if any expected part is missing or unsealed."""
    names = sorted(
        name
        for name in os.listdir(sink_dir)
        if name.startswith("part-") and os.path.isdir(os.path.join(sink_dir, name))
    )
    if num_shards is not None and len(names) != num_shards:
        raise FileNotFoundError(
            f"sink {sink_dir!r} holds {len(names)} part(s), expected {num_shards}"
        )
    if not names:
        raise FileNotFoundError(f"sink {sink_dir!r} holds no sealed parts")
    parts = []
    for name in names:
        part_dir = os.path.join(sink_dir, name)
        if not manifest.present(part_dir):
            raise FileNotFoundError(f"part {part_dir!r} is not sealed")
        issues = manifest.verify(part_dir)
        if issues:
            raise ValueError(f"part {part_dir!r} fails verification: {issues}")
        parts.append(np.load(os.path.join(part_dir, "scores.npy")))
    return np.concatenate(parts) if len(parts) > 1 else parts[0]
