"""Model persistence in the reference's exact on-disk layout.

Layout (IsolationForestModelReadWrite.scala:210-325 and
core/IsolationForestModelReadWriteUtils.scala:28-188):

    <path>/metadata/part-00000   single-line JSON: {class, timestamp,
                                 sparkVersion, uid, paramMap, <extras>}
    <path>/metadata/_SUCCESS
    <path>/data/part-00000-<uuid>-c000.avro   node table (one row per node)
    <path>/data/_SUCCESS

Node rows are ``(treeID, nodeData)`` with **pre-order** ids and ``-1`` null
sentinels (NodeData.build, IsolationForestModelReadWrite.scala:82-132;
extended variant ExtendedIsolationForestModelReadWrite.scala:59-67 with empty
arrays + 0.0 sentinels for leaves). The heap-tensor forest is converted to
pre-order on write and rebuilt on read, so models interoperate both ways with
the reference implementation and its ONNX converter, including the committed
Spark-era golden fixtures (snappy codec, loaded via :mod:`.avro`).

Legacy models without ``totalNumFeatures`` load with the ``-1`` sentinel and a
warning (IsolationForestModelReadWrite.scala:298-306).

Resilience additions (docs/resilience.md) on top of the reference layout:

* **Atomic writes** — every save materialises the full directory under a
  sibling temp name (``<path>.__tmp-<hex>``) and atomically renames it into
  place, so no reader can ever observe a partial model at ``<path>``; a
  killed writer leaves only the marked temp dir, which loads refuse and
  ``overwrite=True`` saves clean up.
* **Integrity manifest** — ``_MANIFEST.json`` (per-file size/CRC32/SHA-256,
  :mod:`isoforest_tpu.resilience.manifest`) written before the rename and
  verified on load; reference/Spark-written dirs without one load with a
  legacy warning.
* **Degraded loads** — ``on_corrupt="drop"`` salvages every intact tree
  from a corrupt directory, rebuilds a valid smaller forest (path-length
  normalisation rescales automatically) and reports exactly which trees
  were lost (``model.load_report``).

Observability addition (docs/observability.md §8): models fitted with
baseline capture persist a ``_BASELINE.json`` sidecar next to the Avro node
table — the training-score histogram + per-feature stats the drift monitor
compares serving traffic against. The sidecar is sealed by the same
``_MANIFEST.json``; directories without one (reference/Spark layouts, or
pre-monitoring saves) load with ``model.baseline = None`` and a warning.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import time
import uuid
from typing import List, Tuple

import numpy as np

from ..ops.ext_growth import ExtendedForest
from ..ops.tree_growth import StandardForest
from ..resilience import manifest as _manifest
from ..resilience.degradation import LoadReport, degrade
from ..utils import logger
from ..utils.params import ExtendedIsolationForestParams, IsolationForestParams
from ..utils.validation import UNKNOWN_TOTAL_NUM_FEATURES
from . import avro

SPARK_VERSION_STRING = "3.5.5"  # layout-compat version tag written to metadata

STANDARD_MODEL_CLASS = "com.linkedin.relevance.isolationforest.IsolationForestModel"
EXTENDED_MODEL_CLASS = (
    "com.linkedin.relevance.isolationforest.extended.ExtendedIsolationForestModel"
)
STANDARD_ESTIMATOR_CLASS = "com.linkedin.relevance.isolationforest.IsolationForest"
EXTENDED_ESTIMATOR_CLASS = (
    "com.linkedin.relevance.isolationforest.extended.ExtendedIsolationForest"
)

# Schemas matching what spark-avro emits for the reference's node tables.
STANDARD_SCHEMA = {
    "type": "record",
    "name": "topLevelRecord",
    "fields": [
        {"name": "treeID", "type": "int"},
        {
            "name": "nodeData",
            "type": [
                {
                    "type": "record",
                    "name": "nodeData",
                    "namespace": ".nodeData",
                    "fields": [
                        {"name": "id", "type": "int"},
                        {"name": "leftChild", "type": "int"},
                        {"name": "rightChild", "type": "int"},
                        {"name": "splitAttribute", "type": "int"},
                        {"name": "splitValue", "type": "double"},
                        {"name": "numInstances", "type": "long"},
                    ],
                },
                "null",
            ],
        },
    ],
}

EXTENDED_SCHEMA = {
    "type": "record",
    "name": "topLevelRecord",
    "fields": [
        {"name": "treeID", "type": "int"},
        {
            "name": "extendedNodeData",
            "type": [
                {
                    "type": "record",
                    "name": "extendedNodeData",
                    "namespace": "topLevelRecord",
                    "fields": [
                        {"name": "id", "type": "int"},
                        {"name": "leftChild", "type": "int"},
                        {"name": "rightChild", "type": "int"},
                        {"name": "indices", "type": [{"type": "array", "items": "int"}, "null"]},
                        {"name": "weights", "type": [{"type": "array", "items": "float"}, "null"]},
                        {"name": "offset", "type": "double"},
                        {"name": "numInstances", "type": "long"},
                    ],
                },
                "null",
            ],
        },
    ],
}


# --------------------------------------------------------------------------- #
# heap <-> pre-order conversion
# --------------------------------------------------------------------------- #


def standard_tree_to_records(feature, threshold, num_instances) -> List[dict]:
    """One tree's heap arrays -> pre-order NodeData dicts
    (sentinels per IsolationForestModelReadWrite.scala:36-67)."""
    records: List[dict] = []

    def walk(slot: int) -> int:
        my_id = len(records)
        records.append(None)  # reserve pre-order position
        if feature[slot] >= 0:
            left = walk(2 * slot + 1)
            right = walk(2 * slot + 2)
            records[my_id] = {
                "id": my_id,
                "leftChild": left,
                "rightChild": right,
                "splitAttribute": int(feature[slot]),
                "splitValue": float(threshold[slot]),
                "numInstances": -1,
            }
        else:
            records[my_id] = {
                "id": my_id,
                "leftChild": -1,
                "rightChild": -1,
                "splitAttribute": -1,
                "splitValue": 0.0,
                "numInstances": int(num_instances[slot]),
            }
        return my_id

    walk(0)
    return records


def extended_tree_to_records(indices, weights, offset, num_instances) -> List[dict]:
    """EIF heap arrays -> pre-order ExtendedNodeData dicts (leaf sentinels:
    empty arrays + 0.0, ExtendedIsolationForestModelReadWrite.scala:33-35)."""
    records: List[dict] = []

    def walk(slot: int) -> int:
        my_id = len(records)
        records.append(None)
        if indices[slot, 0] >= 0:
            left = walk(2 * slot + 1)
            right = walk(2 * slot + 2)
            valid = indices[slot] >= 0  # drop (-1, 0.0) padding entries
            records[my_id] = {
                "id": my_id,
                "leftChild": left,
                "rightChild": right,
                "indices": [int(v) for v in indices[slot][valid]],
                "weights": [float(v) for v in weights[slot][valid]],
                "offset": float(offset[slot]),
                "numInstances": -1,
            }
        else:
            records[my_id] = {
                "id": my_id,
                "leftChild": -1,
                "rightChild": -1,
                "indices": [],
                "weights": [],
                "offset": 0.0,
                "numInstances": int(num_instances[slot]),
            }
        return my_id

    walk(0)
    return records



def heap_preorder_columns(internal: np.ndarray):
    """Vectorised heap -> pre-order conversion for a whole forest.

    ``internal``: bool [T, M] (node at heap slot is internal). Returns
    ``(trees, slots, pre_id, left_id, right_id)`` — flat arrays over all
    existing nodes, ordered (tree, pre-order id), where ``left_id/right_id``
    are pre-order child ids (-1 at leaves). This replaces the recursive
    per-node Python walk of :func:`standard_tree_to_records` on the save
    fast path: pre-order ids satisfy ``id(left) = id + 1`` and
    ``id(right) = id + 1 + subtree_size(left)``, so subtree sizes (one
    reverse level sweep) and ids (one forward level sweep) vectorise over
    the whole [T, M] table.
    """
    t_n, m = internal.shape
    h = int(np.log2(m + 1)) - 1
    exists = np.zeros((t_n, m), bool)
    exists[:, 0] = True
    for level in range(h):
        start, width = (1 << level) - 1, 1 << level
        parent_int = exists[:, start : start + width] & internal[:, start : start + width]
        child = 2 * start + 1
        exists[:, child : child + 2 * width : 2] = parent_int
        exists[:, child + 1 : child + 1 + 2 * width : 2] = parent_int
    size = exists.astype(np.int64)
    for level in range(h - 1, -1, -1):
        start, width = (1 << level) - 1, 1 << level
        child = 2 * start + 1
        size[:, start : start + width] += (
            size[:, child : child + 2 * width : 2]
            + size[:, child + 1 : child + 1 + 2 * width : 2]
        ) * internal[:, start : start + width]
    pre_id = np.full((t_n, m), np.iinfo(np.int64).max, np.int64)
    pre_id[:, 0] = 0
    for level in range(h):
        start, width = (1 << level) - 1, 1 << level
        child = 2 * start + 1
        base = pre_id[:, start : start + width]
        left_sz = size[:, child : child + 2 * width : 2]
        pre_id[:, child : child + 2 * width : 2] = base + 1
        pre_id[:, child + 1 : child + 1 + 2 * width : 2] = base + 1 + left_sz
    pre_id = np.where(exists, pre_id, np.iinfo(np.int64).max)
    order = np.argsort(pre_id, axis=1, kind="stable")  # existing slots first
    counts = exists.sum(axis=1)
    keep = np.arange(m)[None, :] < counts[:, None]  # first count[t] of each row
    trees = np.repeat(np.arange(t_n, dtype=np.int32), counts)
    slots = order[keep]
    flat = (np.arange(t_n)[:, None] * m + order)[keep]  # (t, slot) flat index
    pre_flat = pre_id.reshape(-1)[flat].astype(np.int32)
    int_flat = internal.reshape(-1)[flat]
    left_slot = np.minimum(2 * (flat % m) + 1, m - 1)
    right_slot = np.minimum(2 * (flat % m) + 2, m - 1)
    base_flat = (flat // m) * m
    left_id = np.where(
        int_flat, pre_id.reshape(-1)[base_flat + left_slot], -1
    ).astype(np.int32)
    right_id = np.where(
        int_flat, pre_id.reshape(-1)[base_flat + right_slot], -1
    ).astype(np.int32)
    return trees, slots.astype(np.int32), pre_flat, left_id, right_id


# A tree of depth d occupies 2^(d+1)-1 heap slots. Reference-conformant trees
# have depth <= ceil(log2(maxSamples)) (IsolationTree.scala:60-61), so even
# maxSamples = 10^6 stays under 21. A corrupt or adversarial node table
# encoding a deep chain would otherwise force a 2^depth allocation.
_MAX_TREE_DEPTH = 24


def _check_depth(depth: int) -> None:
    if depth > _MAX_TREE_DEPTH:
        raise ValueError(
            f"refusing to materialise a tree of depth {depth} (> {_MAX_TREE_DEPTH}): "
            f"the implicit-heap layout would need 2^{depth + 1} slots; "
            "the node table is corrupt or not a valid isolation-forest model"
        )


def _assign_heap_slots(records: List[dict]) -> Tuple[dict, int]:
    """Pre-order records -> {node id: heap slot}; validates contiguous ids
    (the reference's buildTreeFromNodes contract,
    IsolationForestModelReadWrite.scala:179-205)."""
    by_id = {r["id"]: r for r in records}
    if sorted(by_id) != list(range(len(records))):
        raise ValueError("corrupt model data: node ids are not 0..N-1")
    slots: dict = {}
    max_depth = 0
    stack = [(0, 0, 0)]  # (node id, heap slot, depth)
    while stack:
        rid, slot, depth = stack.pop()
        _check_depth(depth)  # in-loop: terminates cycles and deep chains alike
        slots[rid] = slot
        max_depth = max(max_depth, depth)
        r = by_id[rid]
        if r["leftChild"] >= 0:
            stack.append((r["leftChild"], 2 * slot + 1, depth + 1))
            stack.append((r["rightChild"], 2 * slot + 2, depth + 1))
    return slots, max_depth


def records_to_standard_forest(
    trees: List[List[dict]], threshold_dtype=np.float32
) -> StandardForest:
    """``threshold_dtype=np.float64`` preserves the reference's Double split
    values exactly (inspection / golden-structure checks); compute uses f32."""
    depths = []
    slot_maps = []
    for records in trees:
        slots, depth = _assign_heap_slots(records)
        slot_maps.append(slots)
        depths.append(depth)
    height = max(depths) if depths else 0
    _check_depth(height)
    M = 2 ** (height + 1) - 1
    T = len(trees)
    feature = np.full((T, M), -1, np.int32)
    threshold = np.zeros((T, M), threshold_dtype)
    num_instances = np.full((T, M), -1, np.int32)
    for t, records in enumerate(trees):
        slots = slot_maps[t]
        for r in records:
            slot = slots[r["id"]]
            if r["leftChild"] >= 0:
                feature[t, slot] = r["splitAttribute"]
                threshold[t, slot] = r["splitValue"]
            else:
                num_instances[t, slot] = r["numInstances"]
    return StandardForest(
        feature=feature, threshold=threshold, num_instances=num_instances
    )


def records_to_extended_forest(
    trees: List[List[dict]], offset_dtype=np.float32
) -> ExtendedForest:
    depths = []
    slot_maps = []
    k = 1
    for records in trees:
        slots, depth = _assign_heap_slots(records)
        slot_maps.append(slots)
        depths.append(depth)
        for r in records:
            if r["leftChild"] >= 0:
                k = max(k, len(r["indices"]))
    height = max(depths) if depths else 0
    _check_depth(height)
    M = 2 ** (height + 1) - 1
    T = len(trees)
    indices = np.full((T, M, k), -1, np.int32)
    weights = np.zeros((T, M, k), np.float32)
    offset = np.zeros((T, M), offset_dtype)
    num_instances = np.full((T, M), -1, np.int32)
    for t, records in enumerate(trees):
        slots = slot_maps[t]
        for r in records:
            slot = slots[r["id"]]
            if r["leftChild"] >= 0:
                nk = len(r["indices"])
                indices[t, slot, :nk] = r["indices"]
                weights[t, slot, :nk] = r["weights"]
                offset[t, slot] = r["offset"]
            else:
                num_instances[t, slot] = r["numInstances"]
    return ExtendedForest(
        indices=indices, weights=weights, offset=offset, num_instances=num_instances
    )


# --------------------------------------------------------------------------- #
# directory layout helpers
# --------------------------------------------------------------------------- #


# Temp-dir marker for atomic writes. A save builds the COMPLETE directory
# (metadata, data, _SUCCESS markers, manifest) under <path>.__tmp-<hex> and
# renames it into place in one os.rename — readers either see the old
# model, no model, or the fully sealed new one, never a partial dir.
_TMP_MARKER = ".__tmp-"


def _is_partial_dir(path: str) -> bool:
    return _TMP_MARKER in os.path.basename(os.path.normpath(path))


def _clean_stale_partials(path: str) -> None:
    """Remove leftover temp dirs of killed writers for ``path``."""
    target = os.path.abspath(os.path.normpath(path))
    parent, base = os.path.split(target)
    if not os.path.isdir(parent):
        return
    for name in os.listdir(parent):
        if name.startswith(base + _TMP_MARKER):
            stale = os.path.join(parent, name)
            logger.warning(
                "removing stale partial write %s (left by an interrupted save)",
                stale,
            )
            shutil.rmtree(stale, ignore_errors=True)


def _begin_atomic_dir(path: str, overwrite: bool) -> str:
    """Start an atomic directory write; returns the empty temp dir. Same
    parent as ``path`` so the final rename stays on one filesystem. Also
    the primitive under fit-checkpoint block seals
    (:mod:`isoforest_tpu.resilience.checkpoint`), so it creates no
    model-layout subdirs itself — writers lay out their own content."""
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(
            f"path {path} already exists; pass overwrite=True to replace"
        )
    if overwrite:
        _clean_stale_partials(path)
    tmp = f"{os.path.normpath(path)}{_TMP_MARKER}{uuid.uuid4().hex[:12]}"
    os.makedirs(tmp)
    return tmp


def _commit_atomic_dir(tmp: str, path: str, overwrite: bool) -> None:
    """Seal the temp dir (manifest last) and rename it into place."""
    _manifest.write(tmp)
    if os.path.exists(path):
        if not overwrite:  # re-check: another writer may have landed first
            raise FileExistsError(
                f"path {path} already exists; pass overwrite=True to replace"
            )
        shutil.rmtree(path)
    os.rename(tmp, path)


@contextlib.contextmanager
def _atomic_dir(path: str, overwrite: bool):
    """``with _atomic_dir(path, overwrite) as tmp: <write into tmp>`` —
    commits on success, removes the temp dir on any failure so an aborted
    save leaves the target untouched and no litter behind (a hard kill can
    still leave the marked temp dir; loads refuse it and the next
    ``overwrite=True`` save sweeps it)."""
    tmp = _begin_atomic_dir(path, overwrite)
    try:
        yield tmp
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _commit_atomic_dir(tmp, path, overwrite)


def _write_metadata(path: str, metadata: dict) -> None:
    os.makedirs(os.path.join(path, "metadata"), exist_ok=True)
    with open(os.path.join(path, "metadata", "part-00000"), "w") as fh:
        fh.write(json.dumps(metadata, separators=(",", ":")))
        fh.write("\n")
    open(os.path.join(path, "metadata", "_SUCCESS"), "w").close()


def _read_metadata(path: str) -> dict:
    # first line of the metadata file (loadMetadata,
    # core/IsolationForestModelReadWriteUtils.scala:97-104)
    meta_dir = os.path.join(path, "metadata")
    part = os.path.join(meta_dir, "part-00000")
    if not os.path.exists(part):
        parts = sorted(
            f for f in os.listdir(meta_dir) if f.startswith("part-")
        )
        if not parts:
            raise FileNotFoundError(f"no metadata part files under {meta_dir}")
        part = os.path.join(meta_dir, parts[0])
    with open(part) as fh:
        return json.loads(fh.readline())


def load_model(path: str, **load_kwargs):
    """Load a saved model directory as the right model class, dispatched on
    the metadata ``class`` field (standard vs extended) — the one loader
    every operational entry point (CLI, serving, lifecycle resume) shares.
    ``load_kwargs`` forward to the class ``load`` (``verify``,
    ``on_corrupt``, ``require_success``)."""
    from ..models import ExtendedIsolationForestModel, IsolationForestModel

    cls = (
        ExtendedIsolationForestModel
        if _read_metadata(path).get("class") == EXTENDED_MODEL_CLASS
        else IsolationForestModel
    )
    return cls.load(path, **load_kwargs)


def _data_part_path(path: str) -> str:
    """Spark-layout framing shared by both save paths: data dir + single
    part file; caller writes it, then :func:`_mark_success` seals it."""
    data_dir = os.path.join(path, "data")
    os.makedirs(data_dir, exist_ok=True)
    return os.path.join(data_dir, f"part-00000-{uuid.uuid4()}-c000.avro")


def _mark_success(path: str) -> None:
    open(os.path.join(path, "data", "_SUCCESS"), "w").close()


def _write_data(path: str, schema: dict, records: List[dict]) -> None:
    avro.write_container(_data_part_path(path), schema, records)
    _mark_success(path)


def _read_data(path: str) -> List[dict]:
    data_dir = os.path.join(path, "data")
    records: List[dict] = []
    for fname in sorted(os.listdir(data_dir)):
        if fname.endswith(".avro"):
            _, recs = avro.read_container(os.path.join(data_dir, fname))
            records.extend(recs)
    if not records:
        raise FileNotFoundError(f"no avro data files under {data_dir}")
    return records


# --------------------------------------------------------------------------- #
# load preconditions + degraded (tolerant) load path
# --------------------------------------------------------------------------- #


def _check_model_dir(path: str, require_success: bool, expect_data: bool = True) -> None:
    """Refuse partial writes and unsealed directories before reading a byte."""
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no model directory at {path}")
    if _is_partial_dir(path):
        raise ValueError(
            f"{path} is a partial write left by an interrupted save (temp "
            f"marker {_TMP_MARKER!r} in its name): the writer died before "
            "the atomic rename, so its contents are not trustworthy. Delete "
            "it and re-save; a save(..., overwrite=True) to the real path "
            "cleans such leftovers automatically"
        )
    if require_success:
        wanted = ["metadata/_SUCCESS"] + (["data/_SUCCESS"] if expect_data else [])
        missing = [
            m for m in wanted if not os.path.exists(os.path.join(path, *m.split("/")))
        ]
        if missing:
            raise ValueError(
                f"{path} is not a sealed model directory (missing "
                f"{', '.join(missing)}): the writer never finished, or the "
                "markers were stripped. Pass require_success=False to load "
                "it anyway"
            )


def _verify_manifest(path: str, verify, on_corrupt: str) -> List[str]:
    """Manifest gate for loads. Returns the data-file issues a
    ``on_corrupt="drop"`` load may tolerate; everything else raises.

    ``verify``: ``"auto"`` (default — verify when a manifest is present,
    legacy warning when absent), ``True`` (require one), ``False`` (skip)."""
    if verify is False:
        return []
    if verify not in ("auto", True):
        raise ValueError(f"verify must be 'auto', True or False, got {verify!r}")
    if not _manifest.present(path):
        if verify is True:
            raise ValueError(
                f"{path} has no {_manifest.MANIFEST_NAME} but verify=True "
                "was requested; re-save with this library or pass "
                "verify='auto' to accept legacy/Spark-written layouts"
            )
        logger.warning(
            "model directory %s has no %s (legacy/Spark-written layout); "
            "integrity verification skipped",
            path,
            _manifest.MANIFEST_NAME,
        )
        return []
    issues = _manifest.verify(path)
    if not issues:
        return []
    data_issues = [i for i in issues if i.startswith("data/")]
    fatal = [i for i in issues if not i.startswith("data/")]
    if fatal or on_corrupt != "drop":
        raise ValueError(
            f"model directory {path} failed manifest verification: "
            + "; ".join(issues)
            + ". The directory is corrupt; restore it from source, or pass "
            "on_corrupt='drop' to salvage the intact trees (data files only)"
        )
    return data_issues


def _read_data_tolerant(path: str):
    """Best-effort record read for degraded loads: per-file, per-block,
    per-record error containment. Returns ``(records, issues)``."""
    data_dir = os.path.join(path, "data")
    records: List[dict] = []
    issues: List[str] = []
    fnames = (
        sorted(f for f in os.listdir(data_dir) if f.endswith(".avro"))
        if os.path.isdir(data_dir)
        else []
    )
    if not fnames:
        issues.append("data/: no avro part files")
        return records, issues
    for fname in fnames:
        fpath = os.path.join(data_dir, fname)
        try:
            schema, blocks, file_issues = avro.read_blocks_tolerant(fpath)
        except Exception as exc:
            issues.append(f"{fname}: unreadable container ({exc})")
            continue
        issues.extend(file_issues)
        for bi, (count, body) in enumerate(blocks):
            # each record costs >= 2 bytes (treeID varint + union index), so
            # a count beyond the body length is corruption, not data — and
            # bounding it here keeps a flipped varint from driving a huge
            # decode loop
            if count <= 0 or count > len(body):
                issues.append(
                    f"{fname} block {bi}: implausible record count {count}"
                )
                continue
            reader = avro._Reader(body)
            block_records: List[dict] = []
            try:
                for _ in range(count):
                    block_records.append(avro.decode_value(schema, reader))
                if reader.pos != len(body):
                    raise ValueError(
                        f"{len(body) - reader.pos} undecoded trailing bytes"
                    )
            except Exception as exc:
                issues.append(f"{fname} block {bi}: corrupt records ({exc})")
                continue
            records.extend(block_records)
    return records, issues


_I32_MIN, _I32_MAX = -(2**31), 2**31 - 1


def _tree_records_sane(records: List[dict], kind: str, max_k) -> None:
    """Value-level sanity for one salvaged tree: every field must fit the
    forest tensors (int32 ids/instances, finite floats, bounded hyperplane
    width) — a tree that parses as Avro can still carry poisoned values."""
    for r in records:
        for field in ("id", "leftChild", "rightChild"):
            v = r[field]
            if not isinstance(v, int) or not _I32_MIN <= v <= _I32_MAX:
                raise ValueError(f"{field}={v!r} out of range")
        if not _I32_MIN <= r["numInstances"] <= _I32_MAX:
            raise ValueError(f"numInstances={r['numInstances']!r} out of range")
        internal = r["leftChild"] >= 0
        if kind == "standard":
            if internal and not np.isfinite(r["splitValue"]):
                raise ValueError(f"non-finite splitValue {r['splitValue']!r}")
            if internal and not 0 <= r["splitAttribute"] <= _I32_MAX:
                raise ValueError(f"splitAttribute={r['splitAttribute']!r} invalid")
        else:
            if not np.isfinite(r["offset"]):
                raise ValueError(f"non-finite offset {r['offset']!r}")
            idx, w = r["indices"], r["weights"]
            if len(idx) != len(w):
                raise ValueError("indices/weights length mismatch")
            if max_k is not None and len(idx) > max_k:
                raise ValueError(
                    f"hyperplane width {len(idx)} exceeds the model's "
                    f"feature count {max_k}"
                )
            if internal and (
                any(not 0 <= i <= _I32_MAX for i in idx)
                or any(not np.isfinite(v) for v in w)
            ):
                raise ValueError("corrupt hyperplane coordinates")


def _salvage_trees(records: List[dict], payload_field: str, kind: str, expected, max_k):
    """Group records by treeID, keeping only trees that fully validate
    (contiguous pre-order ids, bounded depth, sane values). Returns
    ``({tree_id: sorted records}, issues)``."""
    trees: dict = {}
    malformed = 0
    for rec in records:
        try:
            tid = rec["treeID"]
            payload = rec[payload_field]
            if payload is None:
                raise ValueError("null node payload")
            if not isinstance(tid, int) or tid < 0 or tid > _I32_MAX:
                raise ValueError(f"bad treeID {tid!r}")
            if expected is not None and tid >= expected:
                raise ValueError(f"phantom treeID {tid} >= numEstimators")
            trees.setdefault(tid, []).append(payload)
        except Exception:
            malformed += 1
    issues = [f"{malformed} malformed node records discarded"] if malformed else []
    good: dict = {}
    for tid in sorted(trees):
        recs = sorted(trees[tid], key=lambda r: r.get("id", -1))
        try:
            _tree_records_sane(recs, kind, max_k)
            _assign_heap_slots(recs)
        except Exception as exc:
            # repr, not str: a KeyError('999') from a dangling child pointer
            # stringifies to just "999"
            issues.append(f"tree {tid}: {exc!r}")
            continue
        good[tid] = recs
    return good, issues


def _load_forest_tolerant(
    path: str, payload_field: str, kind: str, to_forest, expected, max_k, pre_issues
):
    """The ``on_corrupt="drop"`` load path: salvage intact trees, rebuild a
    valid smaller forest, and report exactly what was lost.

    The rebuilt forest's scoring normalisation rescales automatically —
    path lengths average over ``forest.num_trees`` (the survivors), so the
    score ``2^(-E[h]/c(n))`` stays well-formed at the reduced ensemble size
    (the bounded-quality-impact degraded mode of FastForest, arxiv
    2004.02423).
    """
    records, issues = _read_data_tolerant(path)
    issues = list(pre_issues) + issues
    good, tree_issues = _salvage_trees(records, payload_field, kind, expected, max_k)
    issues += tree_issues
    kept_ids = sorted(good)
    if not kept_ids:
        raise ValueError(
            f"no usable tree data under {path} even with on_corrupt='drop': "
            + "; ".join(issues[:10])
        )
    if expected is not None:
        dropped = tuple(sorted(set(range(expected)) - set(kept_ids)))
    else:
        dropped = ()
    forest = to_forest([good[t] for t in kept_ids])
    if dropped or issues:
        degrade(
            "dropped_trees",
            f"{expected if expected is not None else '?'}-tree forest",
            f"{len(kept_ids)}-tree forest",
            detail=(
                f"loaded {path} in degraded mode: kept {len(kept_ids)} trees"
                + (
                    f", dropped tree ids {list(dropped)}"
                    if dropped
                    else ""
                )
                + (f"; issues: {'; '.join(issues[:5])}" if issues else "")
                + " — scoring normalisation rescales to the surviving trees"
            ),
        )
    report = LoadReport(
        path=path,
        expected_trees=expected,
        kept_trees=len(kept_ids),
        dropped_tree_ids=dropped,
        issues=tuple(issues),
    )
    return forest, report


# --------------------------------------------------------------------------- #
# native columnar load fast path
# --------------------------------------------------------------------------- #


def _preorder_slots(is_internal_list: List[bool]) -> Tuple[List[int], int]:
    """Heap slots for a tree's nodes given their pre-order internal flags.

    Pre-order with contiguous ids makes child lookup unnecessary: walk the
    sequence with an explicit slot stack (left child visited immediately
    after its parent). Returns (slots, max_depth)."""
    slots = [0] * len(is_internal_list)
    stack = [0]
    max_slot = 0
    slot_cap = (1 << (_MAX_TREE_DEPTH + 2)) - 1  # in-loop depth enforcement
    for i, internal in enumerate(is_internal_list):
        slot = stack.pop()
        if slot > slot_cap:
            _check_depth(_MAX_TREE_DEPTH + 1)
        slots[i] = slot
        if slot > max_slot:
            max_slot = slot
        if internal:
            stack.append(2 * slot + 2)  # right pops after the left subtree
            stack.append(2 * slot + 1)
    if stack:
        raise ValueError("corrupt model data: pre-order walk did not consume tree")
    depth = 0
    while (1 << (depth + 1)) - 1 <= max_slot:
        depth += 1
    return slots, depth


def _native_node_columns(path: str, kind: str):
    """Decode the node table into numpy columns via the C++ accelerator;
    None when the native library is unavailable. ``kind``: 'standard' |
    'extended'."""
    from .. import native

    if not native.available():
        return None
    data_dir = os.path.join(path, "data")
    col_parts = []
    flat_parts = []
    for fname in sorted(os.listdir(data_dir)):
        if not fname.endswith(".avro"):
            continue
        _, blocks = avro.read_blocks(os.path.join(data_dir, fname))
        for count, body in blocks:
            if kind == "standard":
                cols = native.decode_standard_block(body, count)
                col_parts.append(cols)
            else:
                cols, flat_idx, flat_w, lens = native.decode_extended_block(body, count)
                cols = dict(cols)
                cols["_hyper_len"] = lens
                col_parts.append(cols)
                flat_parts.append((flat_idx, flat_w))
    if not col_parts:
        raise FileNotFoundError(f"no avro data files under {data_dir}")
    merged = {
        k: np.concatenate([c[k] for c in col_parts]) for k in col_parts[0]
    }
    if np.any(merged["id"] == -2):
        raise ValueError("corrupt model data: null nodeData rows")
    if kind == "extended":
        merged["_flat_indices"] = np.concatenate([f for f, _ in flat_parts])
        merged["_flat_weights"] = np.concatenate([w for _, w in flat_parts])
    return merged


def _column_tree_ranges(tree_id: np.ndarray, node_id: np.ndarray):
    """Sort columns by (treeID, id); validate contiguity; return sorted order
    and per-tree [start, end) ranges."""
    order = np.lexsort((node_id, tree_id))
    tid = tree_id[order]
    nid = node_id[order]
    tree_ids = np.unique(tid)
    if not np.array_equal(tree_ids, np.arange(len(tree_ids))):
        raise ValueError("corrupt model data: treeIDs are not contiguous 0..T-1")
    starts = np.searchsorted(tid, np.arange(len(tree_ids) + 1))
    for t in range(len(tree_ids)):
        s, e = starts[t], starts[t + 1]
        if not np.array_equal(nid[s:e], np.arange(e - s)):
            raise ValueError("corrupt model data: node ids are not 0..N-1")
    return order, starts


def columns_to_standard_forest(cols, threshold_dtype=np.float32) -> StandardForest:
    order, starts = _column_tree_ranges(cols["treeID"], cols["id"])
    lc = cols["leftChild"][order]
    sa = cols["splitAttribute"][order]
    sv = cols["splitValue"][order]
    ni = cols["numInstances"][order]
    T = len(starts) - 1
    internal = (lc >= 0).tolist()
    all_slots = np.empty(len(lc), np.int64)
    height = 0
    for t in range(T):
        s, e = starts[t], starts[t + 1]
        slots, depth = _preorder_slots(internal[s:e])
        all_slots[s:e] = slots
        height = max(height, depth)
    _check_depth(height)
    M = 2 ** (height + 1) - 1
    feature = np.full((T, M), -1, np.int32)
    threshold = np.zeros((T, M), threshold_dtype)
    num_instances = np.full((T, M), -1, np.int32)
    tree_of = np.repeat(np.arange(T), np.diff(starts))
    is_int = lc >= 0
    feature[tree_of[is_int], all_slots[is_int]] = sa[is_int]
    threshold[tree_of[is_int], all_slots[is_int]] = sv[is_int]
    num_instances[tree_of[~is_int], all_slots[~is_int]] = ni[~is_int]
    return StandardForest(
        feature=feature, threshold=threshold, num_instances=num_instances
    )


def columns_to_extended_forest(cols, offset_dtype=np.float32) -> ExtendedForest:
    order, starts = _column_tree_ranges(cols["treeID"], cols["id"])
    lc = cols["leftChild"][order]
    off = cols["offset"][order]
    ni = cols["numInstances"][order]
    lens = cols["_hyper_len"][order]
    # flat hyperplane buffers are in original record order
    flat_starts = np.zeros(len(lc) + 1, np.int64)
    np.cumsum(cols["_hyper_len"], out=flat_starts[1:])
    T = len(starts) - 1
    internal = (lc >= 0).tolist()
    all_slots = np.empty(len(lc), np.int64)
    height = 0
    for t in range(T):
        s, e = starts[t], starts[t + 1]
        slots, depth = _preorder_slots(internal[s:e])
        all_slots[s:e] = slots
        height = max(height, depth)
    _check_depth(height)
    M = 2 ** (height + 1) - 1
    k = int(lens.max()) if len(lens) else 1
    k = max(k, 1)
    indices = np.full((T, M, k), -1, np.int32)
    weights = np.zeros((T, M, k), np.float32)
    offset = np.zeros((T, M), offset_dtype)
    num_instances = np.full((T, M), -1, np.int32)
    tree_of = np.repeat(np.arange(T), np.diff(starts))
    flat_idx = cols["_flat_indices"]
    flat_w = cols["_flat_weights"]
    for pos in range(len(lc)):
        orig = order[pos]
        t = tree_of[pos]
        slot = all_slots[pos]
        if lc[pos] >= 0:
            n_k = int(cols["_hyper_len"][orig])
            fs = flat_starts[orig]
            indices[t, slot, :n_k] = flat_idx[fs : fs + n_k]
            weights[t, slot, :n_k] = flat_w[fs : fs + n_k]
            offset[t, slot] = off[pos]
        else:
            num_instances[t, slot] = ni[pos]
    return ExtendedForest(
        indices=indices, weights=weights, offset=offset, num_instances=num_instances
    )


def _group_trees(records: List[dict], payload_field: str) -> List[List[dict]]:
    """groupByKey(treeID) + sortByKey equivalent
    (IsolationForestModelReadWrite.scala:282-288)."""
    trees: dict = {}
    for rec in records:
        trees.setdefault(rec["treeID"], []).append(rec[payload_field])
    tree_ids = sorted(trees)
    if tree_ids != list(range(len(tree_ids))):
        raise ValueError("corrupt model data: treeIDs are not contiguous 0..T-1")
    return [sorted(trees[t], key=lambda r: r["id"]) for t in tree_ids]


def _check_class(metadata: dict, expected: str) -> None:
    cls = metadata.get("class")
    if cls != expected:
        raise ValueError(
            f"metadata class mismatch: expected {expected}, found {cls}"
        )


# --------------------------------------------------------------------------- #
# model save / load
# --------------------------------------------------------------------------- #


def _model_metadata(model, class_name: str) -> dict:
    meta = {
        "class": class_name,
        "timestamp": int(time.time() * 1000),
        "sparkVersion": SPARK_VERSION_STRING,
        "uid": model.uid,
        "paramMap": model.params.to_param_map(),
        # extras (IsolationForestModelReadWrite.scala:220-224)
        "outlierScoreThreshold": model.outlier_score_threshold
        if model.outlier_score_threshold >= 0
        else -1.0,
        "numSamples": model.num_samples,
        "numFeatures": model.num_features,
        "totalNumFeatures": model.total_num_features,
    }
    # tolerated extra: the preferred serving representation ("f32" | "q16",
    # docs/scoring_layout.md). The node table itself is ALWAYS the exact f32
    # Avro form — readers that don't know the key (the reference, older
    # versions of this library) ignore it and lose nothing but the warm-up
    # preference.
    representation = getattr(model, "scoring_representation", "f32")
    if representation != "f32":
        meta["scoringRepresentation"] = representation
    return meta


def _write_data_raw(path: str, schema: dict, body: bytes, count: int) -> None:
    avro.write_container_raw(_data_part_path(path), schema, [(count, body)])
    _mark_success(path)


def _write_baseline(model, tmp: str) -> None:
    """Persist the drift baseline as a manifest-sealed sidecar (written
    inside the atomic temp dir, so it is covered by the same
    ``_MANIFEST.json`` and ``os.rename`` as the node table)."""
    from ..telemetry.monitor import BASELINE_NAME

    baseline = getattr(model, "baseline", None)
    if baseline is not None:
        baseline.save(os.path.join(tmp, BASELINE_NAME))


def _read_baseline(path: str):
    """Load the ``_BASELINE.json`` sidecar; None (with a warning) when the
    directory predates monitoring or was written by the reference."""
    from ..telemetry.monitor import BASELINE_NAME, Baseline

    sidecar = os.path.join(path, BASELINE_NAME)
    if not os.path.exists(sidecar):
        logger.warning(
            "model directory %s has no %s sidecar (legacy/reference layout "
            "or a fit with baseline capture disabled): drift monitoring is "
            "unavailable for this model until it is refitted",
            path,
            BASELINE_NAME,
        )
        return None
    try:
        return Baseline.load(sidecar)
    except Exception as exc:
        logger.warning(
            "ignoring unreadable baseline sidecar %s (%s): drift monitoring "
            "unavailable for this model",
            sidecar,
            exc,
        )
        return None


def _fast_standard_body(forest):
    """Vectorised pre-order + native columnar encode; None if unavailable."""
    from .. import native

    if not native.available():
        return None
    feature = np.asarray(forest.feature)
    threshold = np.asarray(forest.threshold)
    num_instances = np.asarray(forest.num_instances)
    m = feature.shape[1]
    trees, slots, pre, left, right = heap_preorder_columns(feature >= 0)
    flat = trees.astype(np.int64) * m + slots
    attr = feature.reshape(-1)[flat]
    is_int = attr >= 0
    # leaf sentinels per IsolationForestModelReadWrite.scala:36-67
    val = np.where(is_int, threshold.reshape(-1)[flat].astype(np.float64), 0.0)
    ni = np.where(is_int, -1, num_instances.reshape(-1)[flat]).astype(np.int64)
    body = native.encode_standard_records(trees, pre, left, right, attr, val, ni)
    if body is None:
        return None
    return body, len(trees)


def save_standard_model(model, path: str, overwrite: bool = False) -> None:
    with _atomic_dir(path, overwrite) as tmp:
        _write_metadata(tmp, _model_metadata(model, STANDARD_MODEL_CLASS))
        _write_baseline(model, tmp)
        fast = _fast_standard_body(model.forest)
        if fast is not None:
            _write_data_raw(tmp, STANDARD_SCHEMA, *fast)
        else:
            feature = np.asarray(model.forest.feature)
            threshold = np.asarray(model.forest.threshold)
            num_instances = np.asarray(model.forest.num_instances)
            records = []
            for t in range(model.forest.num_trees):
                for node in standard_tree_to_records(
                    feature[t], threshold[t], num_instances[t]
                ):
                    records.append({"treeID": t, "nodeData": node})
            _write_data(tmp, STANDARD_SCHEMA, records)
    logger.info(
        "saved IsolationForestModel (%d trees) to %s%s",
        model.forest.num_trees,
        path,
        " (native encoder)" if fast is not None else "",
    )


def _fast_extended_body(forest):
    """EIF variant of :func:`_fast_standard_body`."""
    from .. import native

    if not native.available():
        return None
    indices = np.asarray(forest.indices)
    weights = np.asarray(forest.weights)
    offset = np.asarray(forest.offset)
    num_instances = np.asarray(forest.num_instances)
    t_n, m, k = indices.shape
    trees, slots, pre, left, right = heap_preorder_columns(indices[:, :, 0] >= 0)
    flat = trees.astype(np.int64) * m + slots
    idx_rows = indices.reshape(-1, k)[flat]  # [n, k]
    w_rows = weights.reshape(-1, k)[flat]
    valid = idx_rows >= 0
    hyper_len = valid.sum(axis=1).astype(np.int32)
    flat_idx = idx_rows[valid].astype(np.int32)
    flat_w = w_rows[valid].astype(np.float32)
    is_int = idx_rows[:, 0] >= 0
    off = np.where(is_int, offset.reshape(-1)[flat].astype(np.float64), 0.0)
    ni = np.where(is_int, -1, num_instances.reshape(-1)[flat]).astype(np.int64)
    body = native.encode_extended_records(
        trees, pre, left, right, off, ni, hyper_len, flat_idx, flat_w
    )
    if body is None:
        return None
    return body, len(trees)


def save_extended_model(model, path: str, overwrite: bool = False) -> None:
    with _atomic_dir(path, overwrite) as tmp:
        meta = _model_metadata(model, EXTENDED_MODEL_CLASS)
        # resolved extensionLevel always persists on the model (even when the
        # estimator left it unset — ExtendedIsolationForest.scala:102)
        meta["paramMap"]["extensionLevel"] = int(model.extension_level)
        _write_metadata(tmp, meta)
        _write_baseline(model, tmp)
        fast = _fast_extended_body(model.forest)
        if fast is not None:
            _write_data_raw(tmp, EXTENDED_SCHEMA, *fast)
        else:
            indices = np.asarray(model.forest.indices)
            weights = np.asarray(model.forest.weights)
            offset = np.asarray(model.forest.offset)
            num_instances = np.asarray(model.forest.num_instances)
            records = []
            for t in range(model.forest.num_trees):
                for node in extended_tree_to_records(
                    indices[t], weights[t], offset[t], num_instances[t]
                ):
                    records.append({"treeID": t, "extendedNodeData": node})
            _write_data(tmp, EXTENDED_SCHEMA, records)
    logger.info(
        "saved ExtendedIsolationForestModel (%d trees) to %s%s",
        model.forest.num_trees,
        path,
        " (native encoder)" if fast is not None else "",
    )


def _load_common(
    path: str,
    expected_class: str,
    verify="auto",
    on_corrupt: str = "raise",
    require_success: bool = True,
):
    if on_corrupt not in ("raise", "drop"):
        raise ValueError(
            f"on_corrupt must be 'raise' or 'drop', got {on_corrupt!r}"
        )
    _check_model_dir(path, require_success)
    data_issues = _verify_manifest(path, verify, on_corrupt)
    metadata = _read_metadata(path)
    _check_class(metadata, expected_class)
    if "totalNumFeatures" in metadata:
        total_num_features = int(metadata["totalNumFeatures"])
    else:
        # legacy fallback (IsolationForestModelReadWrite.scala:298-306)
        logger.warning(
            "loading legacy model without totalNumFeatures; feature-width "
            "validation disabled (sentinel -1)"
        )
        total_num_features = UNKNOWN_TOTAL_NUM_FEATURES
    return metadata, total_num_features, data_issues


def _restore_representation(model, metadata: dict) -> None:
    """Restore the persisted ``scoringRepresentation`` extra (absent or
    unknown values fall back to the exact "f32" default — a forest edited
    on disk, or one salvaged smaller by a degraded load, may no longer pass
    the q16 capacity fence, and the representation is a preference, never a
    correctness input)."""
    representation = metadata.get("scoringRepresentation", "f32")
    if representation == "f32":
        return
    try:
        model.set_scoring_representation(representation)
    except ValueError as exc:
        logger.warning(
            "ignoring persisted scoringRepresentation=%r: %s",
            representation,
            exc,
        )


def _expected_trees(metadata: dict):
    try:
        n = int(metadata["paramMap"]["numEstimators"])
        return n if n > 0 else None
    except (KeyError, TypeError, ValueError):
        return None


def load_standard_model(
    path: str,
    verify="auto",
    on_corrupt: str = "raise",
    require_success: bool = True,
):
    """Load a standard model. ``verify``/``on_corrupt``/``require_success``
    are the resilience knobs (docs/resilience.md): manifest verification
    mode, corrupt-tree policy (``"raise"`` | ``"drop"``), and the
    ``_SUCCESS``-marker gate."""
    from ..models.isolation_forest import IsolationForestModel

    metadata, total_num_features, data_issues = _load_common(
        path, STANDARD_MODEL_CLASS, verify, on_corrupt, require_success
    )
    params = IsolationForestParams.from_param_map(metadata["paramMap"])
    load_report = None
    if on_corrupt == "drop":
        # tolerant pure-Python path: per-block + per-tree error containment
        # (the native columnar decoder is all-or-nothing by design)
        forest, load_report = _load_forest_tolerant(
            path,
            "nodeData",
            "standard",
            records_to_standard_forest,
            _expected_trees(metadata),
            None,
            data_issues,
        )
    else:
        try:  # native columnar fast path (~5x on 1000-tree models)
            cols = _native_node_columns(path, "standard")
        except (ImportError, OSError):
            cols = None
        if cols is not None:
            forest = columns_to_standard_forest(cols)
        else:
            trees = _group_trees(_read_data(path), "nodeData")
            forest = records_to_standard_forest(trees)
    model = IsolationForestModel(
        forest=forest,
        params=params,
        num_samples=int(metadata["numSamples"]),
        num_features=int(metadata["numFeatures"]),
        total_num_features=total_num_features,
        uid=metadata.get("uid"),
    )
    model.load_report = load_report
    model.baseline = _read_baseline(path)
    _restore_representation(model, metadata)
    threshold = float(metadata.get("outlierScoreThreshold", -1.0))
    if threshold >= 0:
        model.set_outlier_score_threshold(threshold)
    return model


def load_extended_model(
    path: str,
    verify="auto",
    on_corrupt: str = "raise",
    require_success: bool = True,
):
    from ..models.extended import ExtendedIsolationForestModel

    metadata, total_num_features, data_issues = _load_common(
        path, EXTENDED_MODEL_CLASS, verify, on_corrupt, require_success
    )
    params = ExtendedIsolationForestParams.from_param_map(metadata["paramMap"])
    load_report = None
    if on_corrupt == "drop":
        # bound salvaged hyperplane width by the recorded feature count so a
        # corrupt array length cannot force a huge [T, M, k] allocation
        max_k = int(metadata.get("numFeatures", 0)) or None
        forest, load_report = _load_forest_tolerant(
            path,
            "extendedNodeData",
            "extended",
            records_to_extended_forest,
            _expected_trees(metadata),
            max_k,
            data_issues,
        )
    else:
        try:
            cols = _native_node_columns(path, "extended")
        except (ImportError, OSError):
            cols = None
        if cols is not None:
            forest = columns_to_extended_forest(cols)
        else:
            trees = _group_trees(_read_data(path), "extendedNodeData")
            forest = records_to_extended_forest(trees)
    model = ExtendedIsolationForestModel(
        forest=forest,
        params=params,
        num_samples=int(metadata["numSamples"]),
        num_features=int(metadata["numFeatures"]),
        extension_level=int(params.extension_level)
        if params.extension_level is not None
        else forest.k - 1,
        total_num_features=total_num_features,
        uid=metadata.get("uid"),
    )
    model.load_report = load_report
    model.baseline = _read_baseline(path)
    _restore_representation(model, metadata)
    threshold = float(metadata.get("outlierScoreThreshold", -1.0))
    if threshold >= 0:
        model.set_outlier_score_threshold(threshold)
    return model


# --------------------------------------------------------------------------- #
# estimator save / load (params-only metadata, IsolationForest.scala:114-125)
# --------------------------------------------------------------------------- #


def save_estimator(estimator, path: str, class_name: str, overwrite: bool = False) -> None:
    with _atomic_dir(path, overwrite) as tmp:
        metadata = {
            "class": class_name,
            "timestamp": int(time.time() * 1000),
            "sparkVersion": SPARK_VERSION_STRING,
            "uid": estimator.uid,
            "paramMap": estimator.params.to_param_map(),
        }
        _write_metadata(tmp, metadata)


def load_estimator(
    path: str, params_cls, expected_class: str, require_success: bool = True
):
    _check_model_dir(path, require_success, expect_data=False)
    _verify_manifest(path, "auto", "raise")
    metadata = _read_metadata(path)
    _check_class(metadata, expected_class)
    params = params_cls.from_param_map(metadata["paramMap"])
    return params, metadata.get("uid")
