"""Sharded on-disk data sources — the out-of-core data plane's input side.

The reference reads training data as a Spark ``Dataset`` partitioned across
executors; the single-process analogue is a :class:`ShardedSource`: an ordered
list of shard files (CSV / NPY / Avro / Parquet), addressed by a directory, a
glob, or a single file, streamed through a bounded-memory chunk iterator.
Shard order is the sorted file-name order and global row indices are assigned
sequentially across that order, so two passes over the same source enumerate
byte-identical ``(global_row, features)`` pairs — the invariant the streamed
bagging sampler (ops/bagging.StreamedBagger) and the resumable scoring sink
(io/outofcore.score_source) both build their determinism on.

Memory model (docs/out_of_core.md §4): ``iter_chunks`` holds at most one
decoded chunk (``chunk_rows`` rows) plus, for Avro, one shard's compressed
container bytes; nothing is ever concatenated across shards, so RSS is
bounded by ``O(chunk_rows * num_features)`` regardless of source size.

Formats:

* ``.csv``  — textual rows, parsed exactly like the CLI's ``np.loadtxt``
  path (``delimiter=","``, ``#`` comments, blank lines skipped).
* ``.npy``  — 2-D float arrays, memory-mapped; row counts come from the
  header, so counting a shard costs a stat + 128 bytes.
* ``.avro`` — container files written by :func:`write_avro_shard` (records
  ``{"features": [...]}`` or ``{"features": [...], "label": ...}``); the
  per-block record counts in the container give row counts without decoding.
* ``.parquet`` — gated on ``pyarrow`` being importable; absent installs get
  a clear error naming the dependency instead of an ImportError mid-stream.

``labeled=True`` treats the last column (CSV/NPY) or the ``label`` field
(Avro/Parquet) as a label, excluded from features — the same convention as
``python -m isoforest_tpu --labeled``.
"""

from __future__ import annotations

import glob as _glob
import io as _io
import os
from dataclasses import dataclass, field
from typing import Iterator, List, NamedTuple, Optional, Sequence

import numpy as np

from ..telemetry.metrics import counter as _telemetry_counter
from . import avro as _avro

# Rows streamed out of sharded sources, by shard format
# (docs/observability.md §3).
_SOURCE_ROWS_TOTAL = _telemetry_counter(
    "isoforest_source_rows_total",
    "Rows streamed from sharded on-disk sources, by shard format",
    labelnames=("format",),
)

#: Recognised shard file extensions -> format names.
SHARD_FORMATS = {
    ".csv": "csv",
    ".npy": "npy",
    ".avro": "avro",
    ".parquet": "parquet",
}

#: Default rows per streamed chunk — large enough to amortise per-chunk
#: dispatch, small enough that a chunk of f32 features stays a few dozen MB.
DEFAULT_CHUNK_ROWS = 1 << 16


class SourceFormatError(ValueError):
    """A shard has an unrecognised or unavailable format."""


class SourceChunk(NamedTuple):
    """One decoded chunk of a sequential pass.

    ``global_start`` is the absolute row index of ``X[0]`` across the whole
    source (shard order x row order) — the coordinate the streamed sampler
    keys on. ``y`` is ``None`` for unlabeled sources.
    """

    X: np.ndarray
    y: Optional[np.ndarray]
    shard_index: int
    global_start: int


def _parquet_module():
    try:
        import pyarrow.parquet as pq  # type: ignore
    except ImportError as exc:  # pragma: no cover - exercised via gate test
        raise SourceFormatError(
            "parquet shards require pyarrow, which is not installed; "
            "convert the source to .npy/.csv/.avro shards or install pyarrow"
        ) from exc
    return pq


@dataclass
class Shard:
    """One shard file: path + format + size, with a lazily-counted row count."""

    path: str
    format: str
    size_bytes: int
    _rows: Optional[int] = field(default=None, repr=False)

    @property
    def name(self) -> str:
        return os.path.basename(self.path)

    def count_rows(self) -> int:
        """Row count, computed as cheaply as the format allows (npy header /
        avro block counts / parquet metadata; CSV pays a line-counting pass).
        Cached after the first call."""
        if self._rows is None:
            self._rows = _count_rows(self)
        return self._rows


def _count_rows(shard: Shard) -> int:
    if shard.format == "npy":
        with open(shard.path, "rb") as fh:
            version = np.lib.format.read_magic(fh)
            shape, _, _ = np.lib.format._read_array_header(fh, version)
        return int(shape[0]) if shape else 0
    if shard.format == "csv":
        rows = 0
        with open(shard.path, "r") as fh:
            for line in fh:
                line = line.strip()
                if line and not line.startswith("#"):
                    rows += 1
        return rows
    if shard.format == "avro":
        _, blocks = _avro.read_blocks(shard.path)
        return int(sum(count for count, _ in blocks))
    if shard.format == "parquet":
        pq = _parquet_module()
        return int(pq.ParquetFile(shard.path).metadata.num_rows)
    raise SourceFormatError(f"unknown shard format {shard.format!r}")


def _rows_from_records(records: Sequence[dict], labeled: bool):
    X = np.asarray([r["features"] for r in records], dtype=np.float32)
    if X.ndim != 2:
        X = X.reshape(len(records), -1)
    if labeled:
        y = np.asarray(
            [float(r.get("label", 0.0)) for r in records], dtype=np.float32
        )
        return X, y
    return X, None


def _split_label(data: np.ndarray, labeled: bool):
    data = np.asarray(data, dtype=np.float32)
    if data.ndim != 2:
        data = data.reshape(data.shape[0], -1) if data.size else data.reshape(0, 1)
    if labeled:
        if data.shape[1] < 2:
            raise ValueError(
                f"labeled source needs >= 2 columns (features + label), "
                f"got {data.shape[1]}"
            )
        return np.ascontiguousarray(data[:, :-1]), np.ascontiguousarray(data[:, -1])
    return data, None


def _iter_shard_csv(shard: Shard, labeled: bool, chunk_rows: int):
    buf: list = []
    with open(shard.path, "r") as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            buf.append(line)
            if len(buf) >= chunk_rows:
                data = np.loadtxt(
                    _io.StringIO("\n".join(buf)), delimiter=",", ndmin=2
                )
                buf.clear()
                yield _split_label(data, labeled)
        if buf:
            data = np.loadtxt(_io.StringIO("\n".join(buf)), delimiter=",", ndmin=2)
            yield _split_label(data, labeled)


def _iter_shard_npy(shard: Shard, labeled: bool, chunk_rows: int):
    mm = np.load(shard.path, mmap_mode="r")
    if mm.ndim != 2:
        raise SourceFormatError(
            f"npy shard {shard.name} must be 2-D, got shape {mm.shape}"
        )
    for start in range(0, mm.shape[0], chunk_rows):
        yield _split_label(np.array(mm[start : start + chunk_rows]), labeled)


def _iter_shard_avro(shard: Shard, labeled: bool, chunk_rows: int):
    schema, blocks = _avro.read_blocks(shard.path)
    reader_schema = _avro._normalise(schema)
    buf: list = []
    for count, payload in blocks:
        reader = _avro._Reader(payload)
        for _ in range(count):
            buf.append(_avro.decode_value(reader_schema, reader))
            if len(buf) >= chunk_rows:
                yield _rows_from_records(buf, labeled)
                buf = []
    if buf:
        yield _rows_from_records(buf, labeled)


def _iter_shard_parquet(shard: Shard, labeled: bool, chunk_rows: int):
    pq = _parquet_module()
    pf = pq.ParquetFile(shard.path)
    for batch in pf.iter_batches(batch_size=chunk_rows):
        cols = batch.schema.names
        if "features" in cols:
            X = np.asarray(batch.column("features").to_pylist(), dtype=np.float32)
            if labeled:
                y = np.asarray(batch.column("label").to_pylist(), dtype=np.float32)
                yield X, y
            else:
                yield X, None
        else:
            data = np.column_stack(
                [np.asarray(batch.column(c), dtype=np.float32) for c in cols]
            )
            yield _split_label(data, labeled)


_SHARD_ITERATORS = {
    "csv": _iter_shard_csv,
    "npy": _iter_shard_npy,
    "avro": _iter_shard_avro,
    "parquet": _iter_shard_parquet,
}


class ShardedSource:
    """An ordered, re-iterable set of on-disk shards.

    Construction resolves and *sorts* the shard list once; every pass
    (``iter_chunks``) enumerates the same rows in the same global order.
    """

    def __init__(self, shards: Sequence[Shard], labeled: bool = False):
        if not shards:
            raise ValueError("source matched no shard files")
        self.shards: List[Shard] = list(shards)
        self.labeled = bool(labeled)
        self._num_features: Optional[int] = None

    # -- metadata ----------------------------------------------------------

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    def shard_rows(self) -> List[int]:
        """Per-shard row counts (cheap for npy/avro/parquet; one counting
        pass per CSV shard, cached)."""
        return [s.count_rows() for s in self.shards]

    def total_rows(self) -> int:
        return sum(self.shard_rows())

    def num_features(self) -> int:
        """Feature width, resolved by peeking at the first chunk of the
        first shard (cached)."""
        if self._num_features is None:
            for chunk in self.iter_chunks(chunk_rows=1):
                self._num_features = int(chunk.X.shape[1])
                break
            else:  # pragma: no cover - empty shards
                raise ValueError("source has no rows")
        return self._num_features

    def fingerprint(self) -> dict:
        """Identity of the source for resume gating: shard names + sizes +
        the labeled flag. Deliberately excludes chunk_rows (chunking is
        bitwise-neutral, docs/pipeline.md §2) and absolute paths (a moved
        source directory stays resumable)."""
        return {
            "shards": [
                {"name": s.name, "format": s.format, "sizeBytes": s.size_bytes}
                for s in self.shards
            ],
            "labeled": self.labeled,
        }

    # -- streaming ---------------------------------------------------------

    def iter_chunks(
        self,
        chunk_rows: Optional[int] = None,
        start_shard: int = 0,
        stop_shard: Optional[int] = None,
    ) -> Iterator[SourceChunk]:
        """Sequential bounded-memory pass: yields :class:`SourceChunk` with
        absolute ``global_start`` row coordinates. ``start_shard`` /
        ``stop_shard`` restrict the pass to a shard range (resume / per-shard
        scoring) while keeping global coordinates — skipped leading shards
        are counted, not decoded."""
        chunk_rows = int(chunk_rows or DEFAULT_CHUNK_ROWS)
        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be > 0, got {chunk_rows}")
        stop = self.num_shards if stop_shard is None else min(stop_shard, self.num_shards)
        global_row = sum(s.count_rows() for s in self.shards[:start_shard])
        for index in range(start_shard, stop):
            shard = self.shards[index]
            shard_rows = 0
            for X, y in _SHARD_ITERATORS[shard.format](shard, self.labeled, chunk_rows):
                if X.shape[0] == 0:
                    continue
                if self._num_features is None:
                    self._num_features = int(X.shape[1])
                _SOURCE_ROWS_TOTAL.inc(X.shape[0], format=shard.format)
                yield SourceChunk(X, y, index, global_row)
                global_row += X.shape[0]
                shard_rows += X.shape[0]
            if shard._rows is None:
                shard._rows = shard_rows
            elif shard._rows != shard_rows:
                raise ValueError(
                    f"shard {shard.name} row count changed mid-run "
                    f"({shard._rows} -> {shard_rows}); source must be immutable"
                )

    def read_all(self, chunk_rows: Optional[int] = None):
        """Materialise the whole source as ``(X, y)`` — the compatibility
        path for CLI commands that need the full matrix (fit --input,
        telemetry, autotune). Still reads chunk-by-chunk, so peak transient
        memory is one chunk above the final matrix."""
        xs, ys = [], []
        for chunk in self.iter_chunks(chunk_rows=chunk_rows):
            xs.append(chunk.X)
            if chunk.y is not None:
                ys.append(chunk.y)
        if not xs:
            raise ValueError("source has no rows")
        X = np.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
        y = (np.concatenate(ys, axis=0) if len(ys) > 1 else ys[0]) if ys else None
        return X, y


def _shard_from_path(path: str) -> Shard:
    ext = os.path.splitext(path)[1].lower()
    fmt = SHARD_FORMATS.get(ext)
    if fmt is None:
        raise SourceFormatError(
            f"unrecognised shard extension {ext!r} for {path!r} "
            f"(expected one of {sorted(SHARD_FORMATS)})"
        )
    return Shard(path=path, format=fmt, size_bytes=os.path.getsize(path))


def open_source(
    spec: str, labeled: bool = False, formats: Optional[Sequence[str]] = None
) -> ShardedSource:
    """Open ``spec`` as a sharded source.

    ``spec`` may be a directory (every recognised shard file inside, sorted
    by name), a glob pattern (``shards/part-*.npy``), or a single file.
    ``formats`` optionally restricts which extensions are picked up from a
    directory (ignored for explicit globs/files).
    """
    if isinstance(spec, ShardedSource):
        return spec
    paths: List[str]
    if os.path.isdir(spec):
        wanted = set(formats) if formats else set(SHARD_FORMATS.values())
        paths = sorted(
            os.path.join(spec, name)
            for name in os.listdir(spec)
            if os.path.isfile(os.path.join(spec, name))
            and SHARD_FORMATS.get(os.path.splitext(name)[1].lower()) in wanted
        )
        if not paths:
            raise FileNotFoundError(
                f"directory {spec!r} contains no shard files "
                f"({sorted(SHARD_FORMATS)})"
            )
    elif os.path.isfile(spec):
        # single explicit file: unknown extensions default to CSV (the
        # historical CLI contract — `--input data.txt` parsed as CSV)
        ext = os.path.splitext(spec)[1].lower()
        if ext not in SHARD_FORMATS:
            return ShardedSource(
                [Shard(path=spec, format="csv", size_bytes=os.path.getsize(spec))],
                labeled=labeled,
            )
        paths = [spec]
    else:
        paths = sorted(_glob.glob(spec))
        if not paths:
            raise FileNotFoundError(f"source {spec!r} matched no files")
    return ShardedSource([_shard_from_path(p) for p in paths], labeled=labeled)


# -- shard writers (synthetic sources, tests, bench) -----------------------


def write_csv_shard(path: str, X: np.ndarray, y: Optional[np.ndarray] = None) -> None:
    X = np.asarray(X, dtype=np.float32)
    data = X if y is None else np.column_stack([X, np.asarray(y, dtype=np.float32)])
    np.savetxt(path, data, delimiter=",", fmt="%.9g")


def write_npy_shard(path: str, X: np.ndarray, y: Optional[np.ndarray] = None) -> None:
    X = np.asarray(X, dtype=np.float32)
    data = X if y is None else np.column_stack([X, np.asarray(y, dtype=np.float32)])
    np.save(path, data)


def write_avro_shard(path: str, X: np.ndarray, y: Optional[np.ndarray] = None) -> None:
    """Write an Avro container shard with ``{"features": [...]}`` records
    (plus ``"label"`` when ``y`` is given) via the pure-python codec."""
    X = np.asarray(X, dtype=np.float32)
    fields = [
        {"name": "features", "type": {"type": "array", "items": "float"}}
    ]
    if y is not None:
        fields.append({"name": "label", "type": "float"})
        y = np.asarray(y, dtype=np.float32)
        records = [
            {"features": row.tolist(), "label": float(lab)}
            for row, lab in zip(X, y)
        ]
    else:
        records = [{"features": row.tolist()} for row in X]
    schema = {"type": "record", "name": "Row", "fields": fields}
    _avro.write_container(path, schema, records)
