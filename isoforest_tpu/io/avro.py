"""Minimal pure-Python Avro object-container codec (+ snappy block decoder).

The reference persists models as Spark-written Avro container files
(``IsolationForestModelReadWrite.scala:238-249``); its committed golden
fixtures use the ``snappy`` codec and the schemas captured in
:mod:`.persistence`. The base image has neither ``avro`` nor ``fastavro`` nor
``python-snappy``, so this module implements the subset of the Avro 1.x spec
the model layout needs, from the wire format up:

  * primitives: null, boolean, int/long (zigzag varint), float, double,
    string, bytes;
  * complex: record, array, map, union;
  * container framing: magic ``Obj\\x01``, file-metadata map, 16-byte sync
    marker, record blocks;
  * codecs: ``null`` and ``deflate`` for read+write, ``snappy`` read-only
    (enough to load every fixture Spark ever wrote for this model family).

This is a clean-room implementation against the Avro specification; no code
is derived from the reference repository.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Dict, Iterable, List, Tuple

MAGIC = b"Obj\x01"
SYNC_SIZE = 16


# --------------------------------------------------------------------------- #
# snappy (read-only)
# --------------------------------------------------------------------------- #


def snappy_decompress(data: bytes) -> bytes:
    """Decode a raw snappy block (the format Avro's snappy codec wraps)."""
    pos = 0
    # uncompressed length varint
    expected = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        expected |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:  # literal
            length = tag >> 2
            if length >= 60:
                extra = length - 59
                length = int.from_bytes(data[pos : pos + extra], "little")
                pos += extra
            length += 1
            out += data[pos : pos + length]
            pos += length
        else:
            if kind == 1:  # copy, 1-byte offset
                length = ((tag >> 2) & 0x07) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:  # copy, 2-byte offset
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:  # copy, 4-byte offset
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            if offset == 0:
                raise ValueError("corrupt snappy stream: zero copy offset")
            start = len(out) - offset
            if start < 0:
                raise ValueError("corrupt snappy stream: offset before start")
            for _ in range(length):  # copies may overlap — byte-by-byte
                out.append(out[start])
                start += 1
    if len(out) != expected:
        raise ValueError(
            f"snappy length mismatch: expected {expected}, got {len(out)}"
        )
    return bytes(out)


# --------------------------------------------------------------------------- #
# primitive binary codec
# --------------------------------------------------------------------------- #


def encode_long(value: int) -> bytes:
    out = bytearray()
    zz = (value << 1) ^ (value >> 63) if value >= 0 else ((-value) << 1) - 1
    # encode unsigned varint of zigzag
    n = zz
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            break
    return bytes(out)


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def read_long(self) -> int:
        result = 0
        shift = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (result >> 1) ^ -(result & 1)

    def read_bytes(self) -> bytes:
        n = self.read_long()
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def read_raw(self, n: int) -> bytes:
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out


# --------------------------------------------------------------------------- #
# schema-driven encode / decode
# --------------------------------------------------------------------------- #


def _normalise(schema: Any) -> Any:
    """Accept schema JSON strings or already-parsed dict/list forms."""
    if isinstance(schema, str) and (schema.startswith("{") or schema.startswith("[")):
        return json.loads(schema)
    return schema


def encode_value(schema: Any, value: Any, out: bytearray) -> None:
    schema = _normalise(schema)
    if isinstance(schema, list):  # union: pick first branch matching None-ness
        if value is None:
            for i, branch in enumerate(schema):
                if branch == "null":
                    out += encode_long(i)
                    return
            raise ValueError("union has no null branch for None value")
        for i, branch in enumerate(schema):
            if branch != "null":
                out += encode_long(i)
                encode_value(branch, value, out)
                return
        raise ValueError("union has no non-null branch")
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for field in schema["fields"]:
                encode_value(field["type"], value[field["name"]], out)
            return
        if t == "array":
            items = list(value)
            if items:
                out += encode_long(len(items))
                for item in items:
                    encode_value(schema["items"], item, out)
            out += encode_long(0)
            return
        if t == "map":
            entries = dict(value)
            if entries:
                out += encode_long(len(entries))
                for k, v in entries.items():
                    kb = k.encode()
                    out += encode_long(len(kb))
                    out += kb
                    encode_value(schema["values"], v, out)
            out += encode_long(0)
            return
        t_inner = t  # e.g. {"type": "int"}
        return encode_value(t_inner, value, out)
    # primitive name
    if schema == "null":
        return
    if schema == "boolean":
        out.append(1 if value else 0)
        return
    if schema in ("int", "long"):
        out += encode_long(int(value))
        return
    if schema == "float":
        out += struct.pack("<f", float(value))
        return
    if schema == "double":
        out += struct.pack("<d", float(value))
        return
    if schema in ("string", "bytes"):
        data = value.encode() if isinstance(value, str) else bytes(value)
        out += encode_long(len(data))
        out += data
        return
    raise ValueError(f"unsupported Avro schema: {schema!r}")


def decode_value(schema: Any, reader: _Reader) -> Any:
    schema = _normalise(schema)
    if isinstance(schema, list):
        idx = reader.read_long()
        return decode_value(schema[idx], reader)
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {
                f["name"]: decode_value(f["type"], reader) for f in schema["fields"]
            }
        if t == "array":
            items: List[Any] = []
            while True:
                count = reader.read_long()
                if count == 0:
                    break
                if count < 0:
                    reader.read_long()  # block byte size — unused
                    count = -count
                for _ in range(count):
                    items.append(decode_value(schema["items"], reader))
            return items
        if t == "map":
            entries: Dict[str, Any] = {}
            while True:
                count = reader.read_long()
                if count == 0:
                    break
                if count < 0:
                    reader.read_long()
                    count = -count
                for _ in range(count):
                    key = reader.read_bytes().decode()
                    entries[key] = decode_value(schema["values"], reader)
            return entries
        return decode_value(t, reader)
    if schema == "null":
        return None
    if schema == "boolean":
        return reader.read_raw(1) != b"\x00"
    if schema in ("int", "long"):
        return reader.read_long()
    if schema == "float":
        return struct.unpack("<f", reader.read_raw(4))[0]
    if schema == "double":
        return struct.unpack("<d", reader.read_raw(8))[0]
    if schema == "string":
        return reader.read_bytes().decode()
    if schema == "bytes":
        return reader.read_bytes()
    raise ValueError(f"unsupported Avro schema: {schema!r}")


# --------------------------------------------------------------------------- #
# object container files
# --------------------------------------------------------------------------- #


def _write_header(fh, schema_json: str, codec: str, sync: bytes) -> None:
    header = bytearray()
    header += MAGIC
    meta = {"avro.schema": schema_json.encode(), "avro.codec": codec.encode()}
    header += encode_long(len(meta))
    for k, v in meta.items():
        kb = k.encode()
        header += encode_long(len(kb))
        header += kb
        header += encode_long(len(v))
        header += v
    header += encode_long(0)
    header += sync
    fh.write(bytes(header))


def _compress_block(payload: bytes, codec: str, level: int = 9) -> bytes:
    if codec == "deflate":
        comp = zlib.compressobj(level, zlib.DEFLATED, -15)
        return comp.compress(payload) + comp.flush()
    if codec != "null":
        raise ValueError(f"unsupported write codec {codec!r}")
    return payload


def write_container_raw(
    path: str,
    schema: Any,
    blocks: Iterable[tuple],
    codec: str = "deflate",
    level: int = 1,
) -> None:
    """Write an Avro object-container file from pre-encoded block bodies.

    ``blocks`` yields ``(record_count, plaintext_body_bytes)`` — the
    write-side twin of :func:`read_blocks`, used by the native columnar
    encoders (record encoding happens in C, container framing here).
    Defaults to fast deflate (``level=1``): the save fast path trades a
    slightly larger file for wall-clock.
    """
    schema_json = schema if isinstance(schema, str) else json.dumps(schema)
    sync = os.urandom(SYNC_SIZE)
    with open(path, "wb") as fh:
        _write_header(fh, schema_json, codec, sync)
        for count, body in blocks:
            if not count:
                continue
            payload = _compress_block(body, codec, level)
            fh.write(encode_long(count))
            fh.write(encode_long(len(payload)))
            fh.write(payload)
            fh.write(sync)


def write_container(
    path: str,
    schema: Any,
    records: Iterable[dict],
    codec: str = "deflate",
    block_records: int = 4096,
) -> None:
    """Write an Avro object-container file (single writer, blocked)."""
    schema_json = schema if isinstance(schema, str) else json.dumps(schema)
    sync = os.urandom(SYNC_SIZE)
    with open(path, "wb") as fh:
        _write_header(fh, schema_json, codec, sync)

        parsed = _normalise(schema_json)
        batch: List[dict] = []

        def flush(batch: List[dict]) -> None:
            if not batch:
                return
            body = bytearray()
            for rec in batch:
                encode_value(parsed, rec, body)
            payload = _compress_block(bytes(body), codec)
            fh.write(encode_long(len(batch)))
            fh.write(encode_long(len(payload)))
            fh.write(payload)
            fh.write(sync)

        for rec in records:
            batch.append(rec)
            if len(batch) >= block_records:
                flush(batch)
                batch = []
        flush(batch)


def _read_container_header(path: str):
    """Shared container-header parse -> (reader positioned at the first
    block, full file bytes, schema, codec, sync marker)."""
    data = open(path, "rb").read()
    from ..resilience import faults

    data = faults.filter_read_bytes(path, data)  # fault-injection seam
    if data[:4] != MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    reader = _Reader(data, 4)
    meta: Dict[str, bytes] = {}
    while True:
        count = reader.read_long()
        if count == 0:
            break
        if count < 0:
            reader.read_long()
            count = -count
        for _ in range(count):
            key = reader.read_bytes().decode()
            meta[key] = reader.read_bytes()
    sync = reader.read_raw(SYNC_SIZE)
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    return reader, data, schema, codec, sync


def _decode_block(path: str, data: bytes, reader: _Reader, codec: str):
    """Read + decompress one block at the reader's position -> (count, body).
    Raises on any corruption (bad codec stream, CRC, framing)."""
    count = reader.read_long()
    size = reader.read_long()
    if size < 0 or size > len(data) - reader.pos:
        raise ValueError(f"{path}: block size {size} exceeds remaining file")
    block = reader.read_raw(size)
    if codec == "deflate":
        block = zlib.decompress(block, -15)
    elif codec == "snappy":
        payload = block[:-4]  # trailing 4-byte CRC32 (BE) of plaintext
        decoded = None
        try:  # native fast path (isoforest_tpu/native), pure-Python fallback
            from .. import native as _native

            decoded = _native.snappy_decompress(payload)
        except ImportError:  # pragma: no cover
            decoded = None
        block = decoded if decoded is not None else snappy_decompress(payload)
        crc = struct.unpack(">I", data[reader.pos - 4 : reader.pos])[0]
        if zlib.crc32(block) & 0xFFFFFFFF != crc:
            raise ValueError(f"{path}: snappy block CRC mismatch")
    elif codec != "null":
        raise ValueError(f"unsupported read codec {codec!r}")
    return count, block


def read_blocks(path: str) -> Tuple[Any, List[Tuple[int, bytes]]]:
    """Read an Avro container -> (parsed schema, [(record_count, plaintext
    block body)]). Codec (null/deflate/snappy) handled here; record decoding
    is the caller's choice (generic :func:`decode_value`, or the native
    columnar decoders in :mod:`isoforest_tpu.native`)."""
    reader, data, schema, codec, sync = _read_container_header(path)
    blocks: List[Tuple[int, bytes]] = []
    n = len(data)
    while reader.pos < n:
        blocks.append(_decode_block(path, data, reader, codec))
        if reader.read_raw(SYNC_SIZE) != sync:
            raise ValueError(f"{path}: sync marker mismatch")
    return schema, blocks


def read_blocks_tolerant(path: str):
    """Best-effort variant of :func:`read_blocks` for degraded loads
    (``on_corrupt="drop"``): a corrupt block is skipped and reported rather
    than failing the file; a sync-marker mismatch after a bad block means
    the framing can no longer be trusted, so reading stops there. Returns
    ``(schema, blocks, issues)`` — callers decide what the lost blocks
    mean."""
    reader, data, schema, codec, sync = _read_container_header(path)
    blocks: List[Tuple[int, bytes]] = []
    issues: List[str] = []
    n = len(data)
    index = 0
    while reader.pos < n:
        try:
            block = _decode_block(path, data, reader, codec)
        except Exception as exc:
            issues.append(f"{os.path.basename(path)} block {index}: {exc}")
            break  # size/offset no longer trustworthy; later syncs are noise
        marker = reader.read_raw(SYNC_SIZE)
        if marker != sync:
            issues.append(
                f"{os.path.basename(path)} block {index}: sync marker "
                "mismatch (truncated or shifted frame); discarding the "
                "block and the remainder of the file"
            )
            break
        blocks.append(block)
        index += 1
    return schema, blocks, issues


def read_container(path: str) -> Tuple[Any, List[dict]]:
    """Read an Avro object-container file -> (parsed schema, records)."""
    schema, blocks = read_blocks(path)
    records: List[dict] = []
    for count, block in blocks:
        block_reader = _Reader(block)
        for _ in range(count):
            records.append(decode_value(schema, block_reader))
    return schema, records
