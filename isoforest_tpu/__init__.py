"""isoforest_tpu — a TPU-native isolation-forest framework.

Capability parity with linkedin/isolation-forest (standard + extended
isolation forests, Estimator/Model API, reference-layout persistence, ONNX
export), re-designed for TPU: fixed-shape heap-tensor forests, jit/vmap
level-synchronous tree growth, batched gather traversal, and tree/row
sharding over a `jax.sharding.Mesh`.
"""

__version__ = "0.6.0"

from . import lifecycle, ops, parallel, resilience, serving, telemetry, utils  # noqa: F401
from .models import (
    ExtendedIsolationForest,
    ExtendedIsolationForestModel,
    IsolationForest,
    IsolationForestModel,
)

__all__ = [
    "lifecycle",
    "ops",
    "parallel",
    "resilience",
    "telemetry",
    "utils",
    "__version__",
    "ExtendedIsolationForest",
    "ExtendedIsolationForestModel",
    "IsolationForest",
    "IsolationForestModel",
]
