"""ScoringService: the online scoring path behind ``POST /score``.

Glues the pieces every prior PR built into one request path
(docs/serving.md):

* scoring goes through a :class:`~isoforest_tpu.lifecycle.ModelManager`
  when one is attached — live traffic feeds the drift monitor and the
  recent-data reservoir, hot-swaps stay transparent to in-flight requests
  (each flush scores on one complete model reference), and a restarted
  process resumes from ``CURRENT.json``; a baseline-less model serves
  bare, with a warning, through ``model.score`` directly;
* requests coalesce in a :class:`~.coalescer.MicroBatchCoalescer` sized to
  the autotuner's batch buckets; ``score_timeout_s`` arms the scoring
  watchdog so a stalled kernel degrades (ladder rung ``scoring_timeout``)
  instead of stalling the queue;
* :meth:`prewarm` resolves the strategy winner table and compiles the
  scoring programs for the configured buckets at startup (ROADMAP item 4
  follow-on) so the first coalesced flush never pays a probe or an XLA
  compile, and emits one ``serving.warmup`` event naming the buckets.

:func:`serve_model` is the one-call assembly the ``serve`` subcommand (and
tests) use: load → manage (resume) → mount → prewarm → handle.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..telemetry.events import record_event
from ..telemetry.spans import set_span_attrs
from ..utils.logging import logger
from .coalescer import MicroBatchCoalescer, ServingError


@dataclasses.dataclass
class ServingConfig:
    """Knobs of the coalescing policy and the backpressure ladder
    (docs/serving.md). ``batch_rows`` should be a
    :func:`~isoforest_tpu.ops.traversal.batch_bucket` size — flushes then
    land exactly on the pre-warmed, autotuned compiled shapes."""

    batch_rows: int = 1024
    linger_ms: float = 2.0
    max_queue_rows: int = 8192
    queue_deadline_ms: float = 2000.0
    request_timeout_s: float = 30.0
    score_timeout_s: Optional[float] = None
    # answered idempotency keys remembered per service (LRU): a router
    # retry replaying one of them re-scores WITHOUT re-folding the drift
    # monitor/reservoir (docs/replication.md)
    idempotency_capacity: int = 4096
    # priority class for the autopilot's shed rung (docs/autopilot.md):
    # under sustained overload, tenants with lower weight are refused
    # (typed 429 + Retry-After) before higher-weight neighbors. The
    # highest weight class attached to a controller is never shed.
    weight: float = 1.0


class ShedError(ServingError):
    """Admission refused by the overload autopilot's shed rung: this
    tenant's weight class is temporarily browned out so higher-priority
    traffic keeps its SLO (HTTP 429 — retriable; ``Retry-After`` carries
    the controller's recovery-window estimate, docs/autopilot.md)."""

    status = 429


class ScoringService:
    """One model lineage's online scoring front: admission-controlled,
    coalesced, lifecycle-aware. Construct with EITHER ``manager`` (the
    lifecycle path) or ``model`` (bare). ``clock``/``start`` forward to the
    coalescer (tests: fake clock, threadless :meth:`~.coalescer
    .MicroBatchCoalescer.pump`)."""

    def __init__(
        self,
        model=None,
        manager=None,
        config: Optional[ServingConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        start: bool = True,
        model_id: Optional[str] = None,
    ) -> None:
        if (model is None) == (manager is None):
            raise ValueError("pass exactly one of model= or manager=")
        self._bare_model = model
        self.manager = manager
        # fleet tenant identity (docs/fleet.md): the registry constructs
        # one service per tenant; None keeps the single-model deployments
        # every prior PR built byte-identical
        self.model_id = None if model_id is None else str(model_id)
        self.config = config or ServingConfig()
        from ..ops.traversal import batch_bucket

        # largest pre-warmed compiled batch shape; flushes beyond it stream
        # through the micro-batch executor in bucket-sized chunks instead
        # of compiling (and synchronously uploading) one oversized program
        # (docs/pipeline.md) — prewarm() raises it to the largest bucket
        self._max_warm_bucket = batch_bucket(self.config.batch_rows)
        self.coalescer = MicroBatchCoalescer(
            self._score_batch,
            max_batch_rows=self.config.batch_rows,
            max_linger_s=self.config.linger_ms / 1e3,
            max_queue_rows=self.config.max_queue_rows,
            queue_deadline_s=self.config.queue_deadline_ms / 1e3,
            clock=clock,
            start=start,
        )
        # idempotency keys this service already ANSWERED (LRU set): the
        # replicated tier's retry dedup (docs/replication.md). A key lands
        # here only after its scores were computed and folded — a retry
        # whose first attempt died before scoring replays the normal path.
        self._idempotency_lock = threading.Lock()
        self._idempotency_seen: "collections.OrderedDict[str, None]" = (
            collections.OrderedDict()
        )
        # autopilot brownout state (docs/autopilot.md). Reads/writes are
        # single attribute assignments (GIL-atomic); the controller owns
        # transitions, the request path only reads.
        self._shed = False
        self._shed_retry_after_s: Optional[float] = None
        # (subsample_fraction or None, force_q16) when the quality rung is
        # engaged; None = full-fidelity scoring
        self._quality: Optional[Tuple[Optional[float], bool]] = None
        # cache of the sliced brownout subforest keyed by the source
        # forest's identity + fraction (rebuilt across hot-swaps)
        self._subforest_cache: Optional[Tuple[int, int, object]] = None
        self.started_unix_s = time.time()

    # ------------------------------------------------------------------ #

    @property
    def model(self):
        """The CURRENT active model (post any hot-swap)."""
        return self.manager.model if self.manager is not None else self._bare_model

    # ------------------------------------------------------------------ #
    # autopilot brownout knobs (docs/autopilot.md)
    # ------------------------------------------------------------------ #

    @property
    def shed(self) -> bool:
        return self._shed

    def set_shed(
        self, active: bool, retry_after_s: Optional[float] = None
    ) -> None:
        """Engage/lift the shed rung for this tenant. While active every
        admission is refused with :class:`ShedError` (429) before touching
        the queue; ``retry_after_s`` is the controller's estimate of when
        the rung may lift (the response's ``Retry-After``)."""
        self._shed_retry_after_s = retry_after_s if active else None
        self._shed = bool(active)

    def check_admission(self) -> None:
        """Admission gate ahead of the coalescer: raises :class:`ShedError`
        while this tenant's weight class is browned out. Called by every
        request entry point (HTTP handler, fleet registry, :meth:`score`)."""
        if self._shed:
            exc = ShedError(
                f"tenant {self.model_id or 'default'} "
                f"(weight={self.config.weight:g}) is shed by the overload "
                "autopilot; retry after the brownout lifts"
            )
            exc.retry_after_s = self._shed_retry_after_s
            raise exc

    @property
    def quality(self) -> Optional[dict]:
        """The active quality degradation, or None at full fidelity."""
        q = self._quality
        if q is None:
            return None
        return {"subsample_trees": q[0], "q16": q[1]}

    def set_quality(
        self,
        subsample_trees: Optional[float] = None,
        force_q16: bool = False,
    ) -> None:
        """Engage/lift the quality rung: score every subsequent flush on
        the first ``subsample_trees`` fraction of the active forest and/or
        the q16 quantized plane. ``set_quality()`` with no arguments
        restores full fidelity. The degradation is never silent: responses
        carry a ``degraded`` field and the flush span is annotated."""
        if subsample_trees is not None:
            f = float(subsample_trees)
            if not 0.0 < f <= 1.0:
                raise ValueError(
                    f"subsample_trees must be in (0, 1], got {f:g}"
                )
            if f == 1.0:
                subsample_trees = None
            else:
                subsample_trees = f
        if subsample_trees is None and not force_q16:
            self._quality = None
            self._subforest_cache = None
            return
        self._quality = (subsample_trees, bool(force_q16))

    def _degraded_forest(self, model, fraction: Optional[float]):
        """The brownout subforest: the FIRST ``fraction`` of the trees
        (FastForest, arxiv 2004.02423 — trees are i.i.d., so a prefix is
        an unbiased subsample and ``score_matrix`` renormalizes the path
        length to the surviving tree count automatically). Cached per
        (source forest, tree count) so repeated flushes reuse one array
        identity — the packed-layout cache stays warm across flushes."""
        forest = model.forest
        if fraction is None:
            return forest
        total = int(forest.feature.shape[0])
        keep = max(1, int(total * fraction))
        if keep >= total:
            return forest
        cache = self._subforest_cache
        if cache is not None and cache[0] == id(forest) and cache[1] == keep:
            return cache[2]
        sub = type(forest)(*(leaf[:keep] for leaf in forest))
        self._subforest_cache = (id(forest), keep, sub)
        return sub

    def _score_quality_degraded(self, X: np.ndarray) -> np.ndarray:
        """One coalesced flush under the autopilot's quality rung: a
        point-in-time reference of the active model scored through
        :func:`~isoforest_tpu.ops.traversal.score_matrix` on the sliced
        subforest and/or the q16 plane. Deliberately bypasses the manager
        fold — degraded scores must not feed the drift baseline (they
        would read as artificial drift) nor the retrain reservoir."""
        from ..ops.traversal import score_matrix

        fraction, force_q16 = self._quality or (None, False)
        manager = self.manager
        model = manager.model if manager is not None else self._bare_model
        generation = manager.generation if manager is not None else 0
        forest = self._degraded_forest(model, fraction)
        kwargs = {}
        if int(X.shape[0]) > self._max_warm_bucket:
            kwargs = {"chunk_size": self._max_warm_bucket, "pipeline": True}
        scores = score_matrix(
            forest,
            X,
            model.num_samples,
            strategy="q16" if force_q16 else "auto",
            expected_features=int(model.total_num_features),
            timeout_s=self.config.score_timeout_s,
            **kwargs,
        )
        set_span_attrs(
            model_id=self.model_id,
            generation=generation,
            degraded="quality",
            subsample_trees=fraction if fraction is not None else 1.0,
            q16=force_q16,
        )
        return np.asarray(scores)

    def _score_batch(self, X: np.ndarray) -> np.ndarray:
        """One coalesced flush: a single scoring call on one complete model
        reference. Through the manager the flush also feeds the drift
        monitor + reservoir and may trigger the retrain loop.

        A flush larger than the largest pre-warmed bucket (a single
        oversized request draining alone — e.g. a 1M-row CSV POST) streams
        through the micro-batch executor in pre-warmed-bucket-sized chunks
        (docs/pipeline.md): H2D overlaps compute, no oversized XLA program
        is compiled on a live request, and the flusher returns to the
        queue sooner. Scores are bitwise identical; the 429/503 admission
        ladder is untouched (it runs at submit time, before scoring)."""
        if self._quality is not None:
            return self._score_quality_degraded(X)
        timeout_s = self.config.score_timeout_s
        kwargs = {}
        if int(X.shape[0]) > self._max_warm_bucket:
            kwargs = {"chunk_size": self._max_warm_bucket, "pipeline": True}
        # annotate the enclosing serving.flush span with WHICH model served
        # this flush — the cross-thread link test pins scores to generations.
        # The generation must be the one the score call pinned under the
        # manager lock: reading manager.generation here separately races a
        # concurrent hot-swap (new scores tagged with the old number).
        if self.manager is not None:
            scores, generation = self.manager.score(
                X, timeout_s=timeout_s, return_generation=True, **kwargs
            )
            set_span_attrs(model_id=self.model_id, generation=generation)
            return scores
        set_span_attrs(model_id=self.model_id, generation=0)
        return self._bare_model.score(X, timeout_s=timeout_s, **kwargs)

    def score(self, rows: np.ndarray) -> np.ndarray:
        """Blocking request-side score: enqueue, coalesce, demultiplex.
        Raises the :mod:`.coalescer` admission/timeout errors (the HTTP
        layer maps them to 429/503)."""
        self.check_admission()
        pending = self.coalescer.submit(rows)
        return self.coalescer.result(
            pending, timeout_s=self.config.request_timeout_s
        )

    def predict(self, scores: np.ndarray) -> np.ndarray:
        return self.model.predict(scores)

    # ------------------------------------------------------------------ #
    # idempotent replay (docs/replication.md)
    # ------------------------------------------------------------------ #

    def idempotency_seen(self, key: str) -> bool:
        """True when ``key`` was already answered by this service — the
        retried request must take :meth:`score_replay`, not fold again."""
        with self._idempotency_lock:
            if key in self._idempotency_seen:
                self._idempotency_seen.move_to_end(key)
                return True
            return False

    def record_idempotency(self, key: Optional[str]) -> None:
        """Remember an ANSWERED key (bounded LRU). Called after scoring
        succeeded — a request that died before its flush never lands here,
        so its retry folds normally (it was never counted)."""
        if not key:
            return
        with self._idempotency_lock:
            self._idempotency_seen[key] = None
            self._idempotency_seen.move_to_end(key)
            while len(self._idempotency_seen) > self.config.idempotency_capacity:
                self._idempotency_seen.popitem(last=False)

    def score_replay(self, rows: np.ndarray) -> Tuple[np.ndarray, Optional[int]]:
        """``(scores, generation)`` for a replayed idempotent request:
        scores directly on the active model WITHOUT folding the drift
        monitor, the reservoir or the retrain trigger — the first attempt
        already counted these rows. Bitwise identical to the coalesced
        path (coalesced == direct ``model.score`` is the serving tier's
        standing parity guarantee, docs/serving.md)."""
        rows = np.asarray(rows, np.float32)
        timeout_s = self.config.score_timeout_s
        kwargs = {}
        if int(rows.shape[0]) > self._max_warm_bucket:
            kwargs = {"chunk_size": self._max_warm_bucket, "pipeline": True}
        if self.manager is not None:
            return self.manager.score(
                rows,
                timeout_s=timeout_s,
                return_generation=True,
                fold=False,
                **kwargs,
            )
        scores = self._bare_model.score(
            rows, timeout_s=timeout_s, fold_monitor=False, **kwargs
        )
        return scores, None

    # ------------------------------------------------------------------ #

    def prewarm(self, batch_sizes: Sequence[int] = ()) -> List[dict]:
        """Resolve the autotuner's winner and compile the scoring program
        for each batch bucket BEFORE traffic arrives, so no live flush pays
        a cold probe or an XLA compile (docs/autotune.md; the ``autotune
        --warm`` machinery applied to serving's own buckets). Emits exactly
        one ``serving.warmup`` event naming the warmed buckets and the
        resolved strategies; returns the per-bucket decisions."""
        from ..ops.traversal import batch_bucket
        from ..telemetry import resources
        from .. import tuning

        model = self.model
        sizes = set(int(b) for b in batch_sizes)
        sizes.add(self.config.batch_rows)
        buckets = sorted({batch_bucket(b) for b in sizes if b >= 1})
        width = max(int(model.total_num_features), 1)
        decisions = []
        # prewarm IS the warmup phase: every compile here attributes to
        # serving.prewarm and ticks phase=warmup even when a later
        # re-warm runs after mark_steady() (docs/observability.md §10)
        with resources.warmup_scope(), resources.compile_scope(
            "serving.prewarm", key=",".join(str(b) for b in buckets)
        ):
            for bucket in buckets:
                dummy = np.zeros((bucket, width), np.float32)
                d = tuning.resolve_decision(
                    model.forest, dummy, model.num_samples, site="serving.prewarm"
                )
                decisions.append(
                    {
                        "bucket": bucket,
                        "strategy": d.strategy,
                        "source": d.source,
                        "key": d.key,
                    }
                )
            model.warmup(batch_sizes=buckets)
        if buckets:
            self._max_warm_bucket = max(buckets)
        record_event(
            "serving.warmup",
            buckets=",".join(str(b) for b in buckets),
            strategies=json.dumps(
                {str(d["bucket"]): d["strategy"] for d in decisions},
                sort_keys=True,
            ),
        )
        logger.info(
            "serving: pre-warmed %d batch bucket(s): %s",
            len(buckets),
            ", ".join(f"{d['bucket']}->{d['strategy']}" for d in decisions),
        )
        return decisions

    def state(self) -> dict:
        """Operator-facing service state (plain JSON types), merged into
        ``/healthz`` alongside the lifecycle section."""
        doc = {
            "model_id": self.model_id,
            # live coalescer policy, not the construction-time config —
            # the autopilot's rung 1 reconfigures these on the fly
            "batch_rows": self.coalescer.max_batch_rows,
            "linger_ms": self.coalescer.max_linger_s * 1e3,
            "max_queue_rows": self.config.max_queue_rows,
            "queue_deadline_ms": self.config.queue_deadline_ms,
            "queue_rows": self.coalescer.pending_rows,
            "generation": (
                self.manager.generation if self.manager is not None else None
            ),
            "lifecycle": self.manager is not None,
            "weight": self.config.weight,
            "shed": self._shed,
            "quality": self.quality,
        }
        return doc

    def close(self) -> None:
        """Drain the coalescer; the manager (if any) is left to its owner."""
        self.coalescer.close(drain=True)


class ServingHandle:
    """A running ``/score`` deployment: HTTP server + service (+ manager).
    ``close()`` tears the stack down in dependency order; usable as a
    context manager."""

    def __init__(self, server, service: ScoringService, manager=None) -> None:
        self.server = server
        self.service = service
        self.manager = manager

    @property
    def url(self) -> str:
        return self.server.url

    def __enter__(self) -> "ServingHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        self.service.close()
        if self.manager is not None:
            self.manager.close()
        self.server.stop()


def serve_model(
    model_dir: str,
    *,
    port: int = 0,
    host: str = "127.0.0.1",
    config: Optional[ServingConfig] = None,
    lifecycle: bool = True,
    work_dir: Optional[str] = None,
    warm_batch_sizes: Sequence[int] = (1,),
    manager_kwargs: Optional[dict] = None,
) -> ServingHandle:
    """Assemble the full online scoring stack over a saved model dir:

    1. load the model (class-dispatched);
    2. wrap it in a :class:`~isoforest_tpu.lifecycle.ModelManager` when it
       carries a drift baseline (resuming from ``work_dir/CURRENT.json`` if
       a sealed generation exists — a restarted process picks up the last
       swapped model, not the seed); a baseline-less model serves bare with
       a warning;
    3. start the telemetry HTTP server and mount ``POST /score`` on it;
    4. pre-warm the autotuner winner table + compiled programs for the
       serving batch buckets.

    Returns the :class:`ServingHandle`.
    """
    from ..io.persistence import load_model
    from ..telemetry.events import record_event as _event
    from ..telemetry.http import serve as _telemetry_serve
    from .http import mount

    config = config or ServingConfig()
    model = load_model(model_dir)
    manager = None
    if lifecycle and model.baseline is not None:
        from ..lifecycle import ModelManager

        manager = ModelManager(
            model,
            work_dir=work_dir or model_dir + ".lifecycle",
            **(manager_kwargs or {}),
        )
    elif lifecycle:
        logger.warning(
            "serving: %s has no _BASELINE.json sidecar — serving WITHOUT "
            "the lifecycle manager (no drift-triggered retraining); refit "
            "and re-save to enable it",
            model_dir,
        )
    service = ScoringService(
        model=None if manager is not None else model,
        manager=manager,
        config=config,
    )
    server = _telemetry_serve(port=port, host=host)
    mount(server, service)
    service.prewarm(warm_batch_sizes)
    # warmed shapes are now compiled: any compile a live request triggers
    # from here on ticks isoforest_compiles_total{phase="steady"} — the
    # recompile-storm anomaly signal CI gates at zero
    from ..telemetry.resources import mark_steady

    mark_steady()
    _event(
        "serving.start",
        port=server.port,
        model=model_dir,
        generation=manager.generation if manager is not None else 0,
        lifecycle=manager is not None,
    )
    return ServingHandle(server, service, manager)
