"""``POST /score``: the wire protocol over the telemetry HTTP server.

Mounted onto the existing :class:`~isoforest_tpu.telemetry.http
.MetricsServer` (one daemon serves ``/metrics``, ``/healthz``,
``/snapshot`` AND scores — a deployment is one port, one process). Wire
schema (docs/serving.md):

* ``Content-Type: application/json`` —
  ``{"row": [f, ...]}`` (single row) or ``{"rows": [[f, ...], ...]}``
  (batch). Response: ``{"scores": [...], "predictions": [...],
  "rows": n, "generation": g, "flush_rows": m, "flush_requests": k}``
  (``flush_*`` report the coalesced flush the request rode in — a load
  generator verifies coalescing from them).
* ``Content-Type: text/csv`` (or a ``?format=csv`` query) — body is CSV
  feature rows; response is a CSV column ``outlierScore``.

Status codes are the backpressure ladder, never a hang: 400 malformed
payload, 429 admission queue full (retry with backoff), 503 queue stale /
request timeout / shutting down, 500 scoring error. End-to-end request
latency (parse → queue → coalesced score → encode) lands in the
``isoforest_serving_request_seconds`` histogram — the p50/p95/p99 the load
generator reports come from the server's own series, not client clocks —
and every response ticks ``isoforest_serving_responses_total{code=}``.

Tracing (docs/observability.md §9): every request runs inside a
``serving.request`` root span. An inbound ``X-Isoforest-Trace`` header
(sanitised: ``[A-Za-z0-9._-]``, ≤64 chars) is adopted as the request's
trace id — a client can stamp its own id and fetch the server-side trace
with ``GET /trace?trace_id=`` later — and the response always echoes the
effective trace id in the same header. The span records where the latency
went (``queue_wait_s``) and which coalesced flush served it
(``flush_trace_id``/``flush_span_id`` attrs, resolvable to the flush's own
trace with the strategy + per-chunk pipeline spans under it).
"""

from __future__ import annotations

import io
import json
import math
import re
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..telemetry.metrics import counter as _counter
from ..telemetry.metrics import exponential_buckets, histogram as _histogram
from ..telemetry.spans import TraceContext, span, with_context
from .coalescer import ServingError

SCORE_PATH = "/score"
RELOAD_PATH = "/reload"

TRACE_HEADER = "X-Isoforest-Trace"
# one scoring request's identity across router retries
# (docs/replication.md): a replica that already ANSWERED this key re-scores
# without re-folding the drift monitor — retried flushes never double-count
IDEMPOTENCY_HEADER = "X-Isoforest-Idempotency-Key"
# accepted inbound trace ids: our own hex ids plus dotted/dashed client
# ids; anything else (header injection, oversized junk) is ignored and the
# server mints its own id instead
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

# ~1.3x-geometric bounds, 50 us .. ~0.65 s: a warm coalesced 1-row request
# through a cold full-bucket flush all resolve (same shape the old
# serving-latency microbench used, so round-to-round numbers compare)
_REQUEST_SECONDS = _histogram(
    "isoforest_serving_request_seconds",
    "End-to-end /score request latency (parse + queue wait + coalesced "
    "scoring + encode)",
    buckets=exponential_buckets(50e-6, 1.3, 36),
)
_RESPONSES = _counter(
    "isoforest_serving_responses_total",
    "/score responses by HTTP status code",
    labelnames=("code",),
)


class _BadRequest(ValueError):
    """Payload the endpoint refuses with a 400 and a reason."""


def _parse_json(body: bytes) -> Tuple[np.ndarray, bool]:
    """(rows, single?) from a JSON body; raises :class:`_BadRequest` with
    an actionable message on any malformed shape."""
    try:
        doc = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise _BadRequest(f"body is not valid JSON: {exc}") from None
    if not isinstance(doc, dict) or ("row" in doc) == ("rows" in doc):
        raise _BadRequest(
            'JSON body must be an object with exactly one of "row" '
            '(single feature vector) or "rows" (list of feature vectors)'
        )
    single = "row" in doc
    payload = [doc["row"]] if single else doc["rows"]
    try:
        rows = np.asarray(payload, dtype=np.float32)
    except (TypeError, ValueError) as exc:
        raise _BadRequest(f"feature values are not numeric: {exc}") from None
    if rows.ndim != 2 or rows.shape[0] < 1 or rows.shape[1] < 1:
        raise _BadRequest(
            f'"{"row" if single else "rows"}" must parse to a non-empty '
            f"[N, F] matrix, got shape {tuple(rows.shape)}"
        )
    return rows, single


def _parse_csv(body: bytes) -> np.ndarray:
    if not body.strip():
        raise _BadRequest("CSV body contains no rows")
    try:
        rows = np.loadtxt(
            io.StringIO(body.decode("utf-8")),
            delimiter=",",
            comments="#",
            ndmin=2,
        ).astype(np.float32)
    except (UnicodeDecodeError, ValueError) as exc:
        raise _BadRequest(f"body is not parseable CSV: {exc}") from None
    if rows.size == 0:
        raise _BadRequest("CSV body contains no rows")
    return rows


def inbound_trace_id(headers) -> Optional[str]:
    """The sanitised client-supplied trace id, or None (absent/invalid)."""
    raw = headers.get(TRACE_HEADER) if headers is not None else None
    if raw and _TRACE_ID_RE.match(raw):
        return raw
    return None


def inbound_idempotency_key(headers) -> Optional[str]:
    """The sanitised ``X-Isoforest-Idempotency-Key``, or None (same
    alphabet as trace ids: junk is ignored rather than indexed)."""
    raw = headers.get(IDEMPOTENCY_HEADER) if headers is not None else None
    if raw and _TRACE_ID_RE.match(raw):
        return raw
    return None


def handle_score(
    service, body: bytes, headers, query: str = ""
) -> Tuple[int, str, str, Dict[str, str]]:
    """One ``/score`` request → ``(status, content_type, body, headers)``.
    Pure function of the payload + service so the status mapping is
    unit-testable without a socket. The returned headers always carry the
    request's effective trace id (module doc)."""
    inbound = inbound_trace_id(headers)
    ctx = TraceContext(inbound) if inbound else None
    with with_context(ctx):
        with span("serving.request", path=SCORE_PATH) as sp:
            status, content_type, payload, extra = _respond(
                service, body, headers, query, sp
            )
            sp.set_attrs(status=status)
            trace_id = sp.trace_id or inbound
    resp_headers = dict(extra)
    if trace_id:
        resp_headers[TRACE_HEADER] = trace_id
    return status, content_type, payload, resp_headers


def _respond(
    service, body: bytes, headers, query: str, sp
) -> Tuple[int, str, str, Dict[str, str]]:
    t0 = time.perf_counter()
    content_type = (headers.get("Content-Type") or "").lower()
    csv = "csv" in content_type or "format=csv" in (query or "")
    try:
        try:
            rows = _parse_csv(body) if csv else None
            single = False
            if rows is None:
                rows, single = _parse_json(body)
        except _BadRequest as exc:
            return _finish(t0, 400, _error_body(400, str(exc)))
        sp.set_attrs(rows=int(rows.shape[0]))
        try:
            # the autopilot's shed rung refuses this tenant BEFORE any
            # queue or replay work — a typed 429 with Retry-After
            service.check_admission()
        except ServingError as exc:
            return _finish(
                t0,
                exc.status,
                _error_body(exc.status, str(exc)),
                retry_after_s=exc.retry_after_s,
            )
        idem_key = inbound_idempotency_key(headers)
        if idem_key is not None and service.idempotency_seen(idem_key):
            # a router retry of a request this replica ALREADY answered
            # (the first response died on the wire): re-score fold-free —
            # bitwise the same scores, the drift monitor counts the rows
            # once (docs/replication.md)
            try:
                scores, generation = service.score_replay(rows)
            except Exception as exc:
                return _finish(t0, 500, _error_body(500, repr(exc)))
            sp.set_attrs(idempotent_replay=True)
            if csv:
                out = "outlierScore\n" + "".join(
                    f"{float(s)!r}\n" for s in scores
                )
                return _finish(t0, 200, out, "text/csv; charset=utf-8")
            doc = {
                "scores": [float(s) for s in scores],
                "predictions": [float(p) for p in service.predict(scores)],
                "rows": int(rows.shape[0]),
                "single": single,
                "generation": generation,
                "flush_rows": int(rows.shape[0]),
                "flush_requests": 1,
                "replayed": True,
            }
            return _finish(t0, 200, json.dumps(doc) + "\n")
        try:
            pending = service.coalescer.submit(rows)
            scores = service.coalescer.result(
                pending, timeout_s=service.config.request_timeout_s
            )
        except ServingError as exc:
            return _finish(
                t0,
                exc.status,
                _error_body(exc.status, str(exc)),
                retry_after_s=exc.retry_after_s,
            )
        except Exception as exc:  # scoring failure: typed 500, never a hang
            return _finish(t0, 500, _error_body(500, repr(exc)))
        # the flush folded these rows: remember the key BEFORE the response
        # hits the wire, so a retry after a torn write replays fold-free
        service.record_idempotency(idem_key)
        # where the latency went + which flush served us: the request trace
        # names its flush (a DIFFERENT trace, reachable via the flush
        # span's link back to this request — docs/observability.md §9)
        sp.set_attrs(
            queue_wait_s=round(pending.queue_wait_s, 6),
            flush_trace_id=(
                pending.flush_ctx.trace_id if pending.flush_ctx else None
            ),
            flush_span_id=(
                pending.flush_ctx.span_id if pending.flush_ctx else None
            ),
        )
        if csv:
            out = "outlierScore\n" + "".join(
                f"{float(s)!r}\n" for s in scores
            )
            return _finish(t0, 200, out, "text/csv; charset=utf-8")
        predictions = service.predict(scores)
        doc = {
            "scores": [float(s) for s in scores],
            "predictions": [float(p) for p in predictions],
            "rows": int(rows.shape[0]),
            "single": single,
            "generation": (
                service.manager.generation if service.manager is not None else None
            ),
            "flush_rows": pending.flush_rows,
            "flush_requests": pending.flush_requests,
        }
        quality = service.quality
        if quality is not None:
            # quality loss is never silent (docs/autopilot.md): a flush
            # scored on the sliced/q16 brownout path says so on the wire
            doc["degraded"] = quality
        return _finish(t0, 200, json.dumps(doc) + "\n")
    except Exception as exc:  # encoder/accounting bug: still a typed 500
        return _finish(t0, 500, _error_body(500, repr(exc)))


def _error_body(status: int, message: str) -> str:
    return json.dumps({"error": message, "status": status}) + "\n"


def retry_after_headers(
    status: int, retry_after_s: Optional[float] = None
) -> Dict[str, str]:
    """The ``Retry-After`` header for a backpressure response: every
    429/503 carries one (integer seconds, >= 1) so clients back off for a
    server-grounded interval — the raiser's queue-drain estimate when it
    provided one (``ServingError.retry_after_s``), else a 1 s floor.
    Non-backpressure statuses get no header."""
    if status not in (429, 503):
        return {}
    seconds = 1 if retry_after_s is None else max(1, math.ceil(retry_after_s))
    return {"Retry-After": str(int(seconds))}


def _finish(
    t0: float,
    status: int,
    body: str,
    content_type: str = "application/json",
    retry_after_s: Optional[float] = None,
) -> Tuple[int, str, str, Dict[str, str]]:
    _REQUEST_SECONDS.observe(time.perf_counter() - t0)
    _RESPONSES.inc(code=status)
    return status, content_type, body, retry_after_headers(status, retry_after_s)


def handle_reload(service, body: bytes, headers, query: str = ""):
    """``POST /reload`` — adopt a newer generation another process swapped
    into the shared work dir (``CURRENT.json``), the per-replica leg of a
    rolling model push (docs/replication.md). Always 200 with the
    post-reload state; a lifecycle-less deployment reports
    ``lifecycle: false`` and reloads nothing."""
    manager = service.manager
    if manager is None:
        doc = {"reloaded": False, "lifecycle": False, "generation": None}
        return 200, "application/json", json.dumps(doc) + "\n"
    try:
        changed = manager.refresh_from_current()
    except Exception as exc:  # a torn push must not kill the route
        return 500, "application/json", _error_body(500, repr(exc))
    doc = {
        "reloaded": bool(changed),
        "lifecycle": True,
        "generation": manager.generation,
    }
    return 200, "application/json", json.dumps(doc) + "\n"


def mount(server, service) -> None:
    """Register ``POST /score`` (+ ``POST /reload``) on a running
    :class:`~isoforest_tpu.telemetry.http.MetricsServer` and add the
    service's state to its ``/healthz`` payload."""
    server.register_post(
        SCORE_PATH,
        lambda body, headers, query="": handle_score(service, body, headers, query),
    )
    server.register_post(
        RELOAD_PATH,
        lambda body, headers, query="": handle_reload(service, body, headers, query),
    )
    server.serving_state = service.state  # picked up by health()
    server.is_replica = True  # arm the replica chaos seams on this server


def unmount(server) -> None:
    server.unregister_post(SCORE_PATH)
    server.unregister_post(RELOAD_PATH)
    server.serving_state = None
    server.is_replica = False
