"""Dynamic micro-batch coalescing: many requests, one traversal.

The forest-inference throughput lesson (FastForest, arxiv 2004.02423; our
own packed-layout + autotuner measurements) is that traversal work wants to
be batched to the memory system's sweet spot — a 1-row score and a
1024-row score cost nearly the same once the batch is padded to its
power-of-two bucket (``ops/traversal.batch_bucket``) and the per-call
overhead (Python dispatch, per-strategy prep, XLA program entry) is paid.
An online endpoint that scores each request alone therefore throws away
almost the entire batch budget.

:class:`MicroBatchCoalescer` recovers it: concurrent requests enqueue their
rows into one shared buffer; a flusher drains the buffer into a single
scoring call when either

* the pending row count reaches ``max_batch_rows`` (the configured
  per-bucket sweet spot — ``serve`` pre-warms exactly these buckets), or
* the OLDEST queued request has lingered ``max_linger_s`` (the tail-latency
  bound: a lone 2 a.m. request never waits for company longer than the
  linger),

whichever comes first, then demultiplexes the score vector back to the
waiting requests by row offset. Requests are never split across flushes —
each waiter's rows travel together, so its scores come from exactly one
model reference (the no-torn-batch guarantee the lifecycle hot-swap test
leans on).

Admission control keeps overload failure crisp instead of degenerate:

* a request that would push the buffer past ``max_queue_rows`` is refused
  immediately with :class:`QueueFullError` (HTTP 429 — the client should
  back off and retry);
* once the oldest queued request is older than ``queue_deadline_s`` the
  service is not keeping up at all and new work is refused with
  :class:`QueueStaleError` (HTTP 503 — the client should go elsewhere);
* a waiter whose own result does not arrive within its wait budget gets
  :class:`RequestTimeoutError` (503) rather than a hang.

``clock`` is injectable and ``start=False`` runs the coalescer without its
flusher thread (tests drive flushes via :meth:`pump` on a
:class:`~isoforest_tpu.resilience.faults.FakeClock` — the whole size/linger
policy is provable with zero real sleeps). Metrics:
``isoforest_serving_queue_depth`` (gauge, rows waiting),
``isoforest_serving_batch_rows`` (histogram, rows per flush),
``isoforest_serving_coalesced_requests_total`` (counter, requests scored
per flush) and ``isoforest_serving_flushes_total{cause=size|linger|close}``.
Schema table in ``docs/serving.md``.

Tracing (docs/observability.md §9): :meth:`submit` captures the caller's
:func:`~isoforest_tpu.telemetry.current_context` — the request's root span
— and the flush wraps scoring in a ``serving.flush`` span that *links*
every captured context (one flush, many requests; links, not parentage,
because the flush belongs to the flusher thread's own trace). Each served
request gets its measured queue wait and the flush span's identity back on
the pending handle, so the HTTP layer can report where the latency went.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..telemetry.metrics import counter as _counter, gauge as _gauge
from ..telemetry.metrics import histogram as _histogram
from ..telemetry.spans import current_context as _current_context
from ..telemetry.spans import span as _span

_QUEUE_DEPTH = _gauge(
    "isoforest_serving_queue_depth",
    "Rows currently waiting in the micro-batch coalescer buffer",
)
_BATCH_ROWS = _histogram(
    "isoforest_serving_batch_rows",
    "Rows per coalesced scoring flush",
    buckets=tuple(float(1 << i) for i in range(17)),  # 1 .. 65536
)
_COALESCED = _counter(
    "isoforest_serving_coalesced_requests_total",
    "Requests whose rows were scored via a coalesced flush "
    "(incremented by the request count of every flush)",
)
_FLUSHES = _counter(
    "isoforest_serving_flushes_total",
    "Coalesced scoring flushes by trigger "
    "(size = buffer reached max_batch_rows; linger = oldest request hit "
    "the max-linger deadline; close = drain at shutdown)",
    labelnames=("cause",),
)


class ServingError(Exception):
    """Base class for serving-layer refusals; ``status`` is the HTTP code
    the endpoint maps the error to (docs/serving.md backpressure table).
    ``retry_after_s`` is the server's drain estimate — every 429/503
    response carries it as an integer ``Retry-After`` header so clients
    back off for a grounded interval instead of guessing."""

    status = 500
    retry_after_s: Optional[float] = None


class QueueFullError(ServingError):
    """Admission refused: the request would overflow ``max_queue_rows``
    (HTTP 429 — retriable after backoff)."""

    status = 429


class QueueStaleError(ServingError):
    """Admission refused: the oldest queued request has aged past
    ``queue_deadline_s`` — the service is not draining (HTTP 503)."""

    status = 503


class RequestTimeoutError(ServingError):
    """The caller's wait budget expired before its flush completed
    (HTTP 503)."""

    status = 503


class CoalescerClosedError(ServingError):
    """Submitted after :meth:`MicroBatchCoalescer.close` (HTTP 503)."""

    status = 503


class _Pending:
    """One enqueued request: its rows, arrival time, and the slot its
    flush fills in. ``flush_rows``/``flush_requests`` record the flush it
    rode in (surfaced in the HTTP response so a load generator can verify
    coalescing actually happened)."""

    __slots__ = (
        "rows",
        "enqueued_at",
        "event",
        "scores",
        "error",
        "flush_rows",
        "flush_requests",
        "ctx",
        "queue_wait_s",
        "flush_ctx",
    )

    def __init__(self, rows: np.ndarray, enqueued_at: float, ctx=None) -> None:
        self.rows = rows
        self.enqueued_at = enqueued_at
        self.event = threading.Event()
        self.scores: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.flush_rows = 0
        self.flush_requests = 0
        # trace handoff: the submitter's span context (linked by the flush
        # span), the measured enqueue->drain wait, and the flush span's own
        # context (reported back so the request trace names its flush)
        self.ctx = ctx
        self.queue_wait_s = 0.0
        self.flush_ctx = None


class MicroBatchCoalescer:
    """Shared request buffer with size-or-linger flushing (module doc).

    ``score_fn(X) -> scores`` is called once per flush with the
    concatenated ``[N, F]`` rows of every drained request — in serving it
    is ``manager.score`` (so coalesced traffic feeds the drift monitor and
    recent-data reservoir, and hot-swaps stay transparent) with
    ``timeout_s`` arming the scoring watchdog + degradation ladder.
    """

    def __init__(
        self,
        score_fn: Callable[[np.ndarray], np.ndarray],
        *,
        max_batch_rows: int = 1024,
        max_linger_s: float = 0.002,
        max_queue_rows: int = 8192,
        queue_deadline_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        start: bool = True,
    ) -> None:
        if max_batch_rows < 1:
            raise ValueError(f"max_batch_rows must be >= 1, got {max_batch_rows}")
        if max_queue_rows < max_batch_rows:
            raise ValueError(
                f"max_queue_rows ({max_queue_rows}) must be >= max_batch_rows "
                f"({max_batch_rows}) or the size trigger can never fire"
            )
        if max_linger_s < 0 or queue_deadline_s <= 0:
            raise ValueError(
                "max_linger_s must be >= 0 and queue_deadline_s > 0"
            )
        self._score_fn = score_fn
        self.max_batch_rows = int(max_batch_rows)
        self.max_linger_s = float(max_linger_s)
        self.max_queue_rows = int(max_queue_rows)
        self.queue_deadline_s = float(queue_deadline_s)
        self._clock = clock
        self._cond = threading.Condition()
        self._queue: List[_Pending] = []
        self._pending_rows = 0
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="isoforest-coalescer"
            )
            self._thread.start()

    # ------------------------------------------------------------------ #
    # request side
    # ------------------------------------------------------------------ #

    def submit(self, rows: np.ndarray) -> _Pending:
        """Enqueue one request's rows; returns the pending handle to pass
        to :meth:`result`. Raises the admission-control errors documented
        on the module instead of ever blocking the caller on a full or
        stalled buffer."""
        rows = np.asarray(rows, np.float32)
        if rows.ndim != 2 or rows.shape[0] < 1:
            raise ValueError(
                f"submit expects a non-empty [N, F] row matrix, got shape "
                f"{rows.shape}"
            )
        n = int(rows.shape[0])
        with self._cond:
            if self._closed:
                raise CoalescerClosedError("the coalescer is shut down")
            now = self._clock()
            if self._queue:
                age = now - self._queue[0].enqueued_at
                if age > self.queue_deadline_s:
                    exc: ServingError = QueueStaleError(
                        f"oldest queued request is {age:.3f}s old "
                        f"(> queue_deadline_s={self.queue_deadline_s:g}); "
                        "the scoring backend is not draining the queue"
                    )
                    exc.retry_after_s = self.queue_deadline_s
                    raise exc
            if self._pending_rows + n > self.max_queue_rows:
                exc = QueueFullError(
                    f"{n} rows would overflow the admission queue "
                    f"({self._pending_rows}/{self.max_queue_rows} rows "
                    "pending); back off and retry"
                )
                exc.retry_after_s = self._drain_estimate_s_locked()
                raise exc
            pending = _Pending(rows, now, ctx=_current_context())
            self._queue.append(pending)
            self._pending_rows += n
            _QUEUE_DEPTH.set(self._pending_rows)
            self._cond.notify_all()
        return pending

    def result(
        self, pending: _Pending, timeout_s: Optional[float] = None
    ) -> np.ndarray:
        """Block until ``pending``'s flush completes; returns its scores or
        re-raises the flush's error. A wait past ``timeout_s`` raises
        :class:`RequestTimeoutError` (the flush may still complete later;
        its result is discarded)."""
        if not pending.event.wait(timeout_s):
            raise RequestTimeoutError(
                f"no result within {timeout_s:g}s (queue wait + scoring)"
            )
        if pending.error is not None:
            raise pending.error
        assert pending.scores is not None
        return pending.scores

    def score(self, rows: np.ndarray, timeout_s: Optional[float] = None) -> np.ndarray:
        """Convenience: :meth:`submit` + :meth:`result`."""
        return self.result(self.submit(rows), timeout_s=timeout_s)

    # ------------------------------------------------------------------ #
    # flush side
    # ------------------------------------------------------------------ #

    @property
    def pending_rows(self) -> int:
        with self._cond:
            return self._pending_rows

    def _drain_estimate_s_locked(self) -> float:
        """Rough time to drain the current backlog: flushes needed at the
        configured batch size, each paced by the linger window (floored so
        a zero-linger coalescer still advertises a sane backoff). Caller
        holds the lock; feeds the ``Retry-After`` header on 429s."""
        flushes = max(1, -(-self._pending_rows // self.max_batch_rows))
        return flushes * max(self.max_linger_s, 0.05)

    def reconfigure(
        self,
        *,
        max_batch_rows: Optional[int] = None,
        max_linger_s: Optional[float] = None,
    ) -> dict:
        """Adjust the flush policy on a LIVE coalescer (the autopilot's
        rung-1 knob, docs/autopilot.md). Takes effect under the condition
        lock so in-flight submits/flushes see one consistent policy: queued
        requests are never lost, split, or double-drained across the
        change — the next ``_due_locked`` simply evaluates the new
        thresholds. Returns the policy that was in force BEFORE the change
        so the caller can revert. Same validation as the constructor."""
        with self._cond:
            previous = {
                "max_batch_rows": self.max_batch_rows,
                "max_linger_s": self.max_linger_s,
            }
            new_batch = (
                self.max_batch_rows
                if max_batch_rows is None
                else int(max_batch_rows)
            )
            new_linger = (
                self.max_linger_s if max_linger_s is None else float(max_linger_s)
            )
            if new_batch < 1:
                raise ValueError(f"max_batch_rows must be >= 1, got {new_batch}")
            if self.max_queue_rows < new_batch:
                raise ValueError(
                    f"max_batch_rows ({new_batch}) must stay <= max_queue_rows "
                    f"({self.max_queue_rows}) or the size trigger can never fire"
                )
            if new_linger < 0:
                raise ValueError(f"max_linger_s must be >= 0, got {new_linger}")
            self.max_batch_rows = new_batch
            self.max_linger_s = new_linger
            # wake the flusher: the new policy may make a waiting batch due
            # (shorter linger) or let it keep filling (wider batch)
            self._cond.notify_all()
        return previous

    def _due_locked(self) -> Tuple[List[_Pending], Optional[str]]:
        """(batch, cause) when a flush is due, else ([], None). Caller
        holds the lock. Never splits a request: drains whole waiters from
        the front until the NEXT one would exceed ``max_batch_rows`` (a
        single oversize request drains alone — ``score_fn`` chunks
        internally)."""
        if not self._queue:
            return [], None
        if self._closed:
            cause = "close"
        elif self._pending_rows >= self.max_batch_rows:
            cause = "size"
        elif self._clock() - self._queue[0].enqueued_at >= self.max_linger_s:
            cause = "linger"
        else:
            return [], None
        batch: List[_Pending] = []
        rows = 0
        while self._queue:
            head = self._queue[0]
            n = int(head.rows.shape[0])
            if batch and rows + n > self.max_batch_rows:
                break
            batch.append(self._queue.pop(0))
            rows += n
        self._pending_rows -= rows
        _QUEUE_DEPTH.set(self._pending_rows)
        return batch, cause

    def _wait_s_locked(self) -> Optional[float]:
        """How long the flusher may sleep before the next linger deadline
        (None = until notified). Caller holds the lock."""
        if not self._queue:
            return None
        due = self._queue[0].enqueued_at + self.max_linger_s - self._clock()
        return max(due, 0.0)

    def _flush(self, batch: List[_Pending], cause: str) -> None:
        offsets = np.cumsum([0] + [int(p.rows.shape[0]) for p in batch])
        total = int(offsets[-1])
        X = batch[0].rows if len(batch) == 1 else np.concatenate(
            [p.rows for p in batch], axis=0
        )
        drained_at = self._clock()
        for p in batch:
            p.queue_wait_s = max(drained_at - p.enqueued_at, 0.0)
        # one flush serves many requests on this (flusher) thread: the span
        # LINKS each request's captured context instead of parenting it
        with _span(
            "serving.flush",
            links=[p.ctx for p in batch],
            cause=cause,
            rows=total,
            requests=len(batch),
        ) as fsp:
            flush_ctx = fsp.context
            for p in batch:
                p.flush_ctx = flush_ctx
            try:
                scores = np.asarray(self._score_fn(X))
                if scores.shape[0] != total:
                    raise ValueError(
                        f"score_fn returned {scores.shape[0]} scores for "
                        f"{total} rows"
                    )
            except BaseException as exc:  # every waiter learns the same fate
                fsp.set_attrs(error=type(exc).__name__)
                for p in batch:
                    p.error = exc
                    p.event.set()
                _FLUSHES.inc(cause=cause)
                return
            _BATCH_ROWS.observe(float(total))
            _COALESCED.inc(len(batch))
            _FLUSHES.inc(cause=cause)
            for i, p in enumerate(batch):
                p.scores = scores[offsets[i] : offsets[i + 1]]
                p.flush_rows = total
                p.flush_requests = len(batch)
                p.event.set()

    def pump(self) -> int:
        """Run at most one due flush on the CALLER's thread; returns the
        number of requests flushed (0 = nothing due). The threadless test
        mode: with ``start=False`` and an injected fake clock, the
        size/linger/backpressure policy is exercised deterministically."""
        with self._cond:
            batch, cause = self._due_locked()
        if not batch:
            return 0
        self._flush(batch, cause)
        return len(batch)

    def _run(self) -> None:
        while True:
            with self._cond:
                while True:
                    batch, cause = self._due_locked()
                    if batch:
                        break
                    if self._closed:
                        return
                    self._cond.wait(self._wait_s_locked())
            self._flush(batch, cause)

    def close(self, drain: bool = True) -> None:
        """Stop accepting work. ``drain=True`` flushes whatever is queued
        (cause ``close``) so no waiter is stranded; ``drain=False`` fails
        the stragglers with :class:`CoalescerClosedError`. Idempotent."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            if not drain:
                for p in self._queue:
                    p.error = CoalescerClosedError("coalescer closed")
                    p.event.set()
                self._queue.clear()
                self._pending_rows = 0
                _QUEUE_DEPTH.set(0)
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        elif drain:
            while self.pump():
                pass
