"""Online scoring service: a real ``POST /score`` with dynamic
micro-batch coalescing (ROADMAP item 1, docs/serving.md).

Everything the prior PRs built toward "serves heavy traffic" meets the
wire here: concurrent requests coalesce into micro-batches sized to the
autotuner's sweet-spot buckets (:mod:`.coalescer`), score ONCE through the
lifecycle manager (drift monitoring, reservoir, transparent hot-swaps) with
the watchdog/degradation ladder bounding tail latency, and demultiplex back
to their waiters (:mod:`.service`), behind the existing telemetry HTTP
daemon (:mod:`.http`) with a crisp backpressure ladder: 429 on queue
overflow, 503 on a stale queue or timeout — never a hang, never a torn
batch.

Start one with ``python -m isoforest_tpu serve <model_dir> --port N`` or
:func:`serve_model`; load-test with ``tools/serving_latency.py``.
"""

from .coalescer import (
    CoalescerClosedError,
    MicroBatchCoalescer,
    QueueFullError,
    QueueStaleError,
    RequestTimeoutError,
    ServingError,
)
from .http import SCORE_PATH, handle_score, mount, unmount
from .service import (
    ScoringService,
    ServingConfig,
    ServingHandle,
    ShedError,
    serve_model,
)

__all__ = [
    "SCORE_PATH",
    "CoalescerClosedError",
    "MicroBatchCoalescer",
    "QueueFullError",
    "QueueStaleError",
    "RequestTimeoutError",
    "ScoringService",
    "ServingConfig",
    "ServingError",
    "ServingHandle",
    "ShedError",
    "handle_score",
    "mount",
    "serve_model",
    "unmount",
]
