"""Unified degradation ladder.

Before this module every fallback in the scoring stack kept its own
module-level ``_warned_*`` boolean: four in ``ops/traversal.py``, one in
``parallel/sharded.py`` — five ad-hoc once-flags, none queryable, none
visible to ``bench.py`` or a serving operator. The reference library at
least funnels its partial/legacy tolerance through explicit log lines
(IsolationForestModelReadWrite.scala:298-306); at serving scale that is the
minimum bar: a fallback must be *observable*, not just survivable.

Here every fallback goes through :func:`degrade`:

* the event is recorded in a process-wide :class:`DegradationReport`
  (queryable via :func:`degradations` / ``model.degradations()``, dumped by
  ``bench.py``), with a per-reason occurrence count;
* the warning is logged exactly once per reason (until
  :func:`reset_degradations`), preserving the old once-flag contract;
* under ``strict=True`` (``score_matrix(strict=True)``) the fallback
  RAISES :class:`DegradationError` instead — serving stacks that pin a
  strategy for latency SLOs must fail loudly, never silently run a
  different kernel.

Each rung's trigger and parity guarantee is documented in :data:`LADDER`
and prose-form in ``docs/resilience.md``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _counter
from ..utils.logging import logger

_DEGRADATIONS_TOTAL = _counter(
    "isoforest_degradations_total",
    "Degradation-ladder rungs taken, by reason (docs/resilience.md)",
    labelnames=("reason",),
)

# The documented ladder: reason -> (parity guarantee) — one row per rung.
# degrade() refuses unknown reasons so a typo cannot create an untracked,
# undocumented rung. Keep this table in sync with docs/resilience.md.
LADDER: Dict[str, str] = {
    # scoring-strategy rungs (ops/traversal.py)
    "native_unavailable": (
        "native -> gather: scores agree to f32 tolerance; EIF exact ties may "
        "route differently (PARITY.md bounded deviation class)"
    ),
    "walk_off_tpu": (
        "walk -> gather off-TPU: bit-identical to an explicit gather run "
        "(the gather kernel IS what executes)"
    ),
    "walk_unsupported": (
        "walk -> dense (wide-k hyperplanes or VMEM-oversized tables): "
        "bit-identical to an explicit dense run"
    ),
    "eif_pallas_fence": (
        "pallas -> dense for extended forests on real TPU: dense keeps "
        "HIGHEST-precision hyperplane contractions; the fenced kernel would "
        "run bf16-mantissa matmuls (measured up to 0.24 path-length error)"
    ),
    "q16_unsupported": (
        "q16 -> gather for forests outside the quantized fences "
        "(scoring_layout.quantized_unsupported_reason): bit-identical to an "
        "explicit gather run; an ELIGIBLE q16 run is itself bitwise-equal "
        "to its f32 traversal family, so this rung only ever changes speed"
    ),
    "env_strategy_unknown": (
        "unrecognised ISOFOREST_TPU_STRATEGY pin -> per-backend default: "
        "scores are the default strategy's, within cross-strategy f32 "
        "tolerance of any valid pin"
    ),
    # autotuner rung (tuning/autotuner.py, docs/autotune.md)
    "autotune_probe_failed": (
        "strategy='auto' probe produced no measurement over the eligible "
        "strategies -> static per-backend preference table: the fallback is "
        "a fully supported strategy (scores within cross-strategy f32 "
        "tolerance of any tuned pick), so — like drift_alert — this rung is "
        "deliberately strict-exempt; the decision is mirrored as an "
        "autotune.decision event with source='fallback'"
    ),
    # shard_map rung (parallel/sharded.py)
    "shard_pin_ineligible": (
        "ineligible ISOFOREST_TPU_STRATEGY pin inside shard_map -> "
        "per-platform jittable default (gather/dense): scores within "
        "cross-strategy f32 tolerance"
    ),
    # watchdog rung (ops/traversal.py, score_matrix(timeout_s=...))
    "scoring_timeout": (
        "strategy missed its watchdog deadline -> one retry on the portable "
        "gather kernel (the stalled program is abandoned to its daemon "
        "thread): scores are gather's, within cross-strategy f32 tolerance; "
        "a gather run that itself times out raises WatchdogTimeout"
    ),
    # streaming-executor rung (ops/streaming.py, docs/pipeline.md)
    "pipeline_fallback": (
        "committed async device_put unavailable for the streaming "
        "micro-batch executor -> synchronous per-chunk upload: scores are "
        "BITWISE identical (every scoring formulation is row-independent; "
        "only the H2D/compute overlap is lost), so — like drift_alert — "
        "this rung is deliberately strict-exempt"
    ),
    # model-observability rung (telemetry/monitor.py, ScoreMonitor)
    "drift_alert": (
        "serving traffic drifted past the configured PSI threshold vs the "
        "training baseline: scores are still computed exactly (no kernel "
        "change) — the rung flags model-quality risk, not a compute "
        "fallback, so strict scoring is deliberately unaffected "
        "(docs/observability.md §8)"
    ),
    # fleet-registry rungs (fleet/registry.py, docs/fleet.md)
    "fleet_load_failed": (
        "a tenant's lazy (re)load from its sealed model dir failed -> that "
        "tenant's request is refused with a typed 503 (ModelLoadError) and "
        "the registry retries the load on its next request; every OTHER "
        "tenant's scoring path is untouched (per-tenant isolation), so no "
        "score is ever computed from a partially loaded model"
    ),
    "fleet_evict_under_load": (
        "residency-budget pressure (or an injected fault) evicted a tenant "
        "that still had in-flight requests -> the eviction drains the "
        "tenant's coalescer first, so every in-flight flush completes on "
        "its point-in-time model reference with BITWISE-exact scores; only "
        "subsequent requests pay the re-load from the sealed gen dir — "
        "like drift_alert, this rung flags an operational event, not a "
        "compute fallback, so it is deliberately strict-exempt"
    ),
    # overload-autopilot rungs (autopilot/controller.py, docs/autopilot.md)
    "autopilot_widen_batch": (
        "sustained queue pressure -> the controller widens the live "
        "coalescer's max_linger_s/max_batch_rows toward the "
        "throughput-optimal bucket: scores stay BITWISE identical (batch "
        "composition never affects a row's score — the serving tier's "
        "standing parity guarantee); only per-request latency trades "
        "against throughput, and the original policy is restored "
        "rung-by-rung on recovery"
    ),
    "autopilot_shed_low_weight": (
        "queue pressure persists at the widened batch policy -> tenants "
        "below the fleet's highest ServingConfig.weight class are refused "
        "with a typed 429 (ShedError) + Retry-After; surviving tenants' "
        "scores remain BITWISE identical and their admission ladder is "
        "untouched — shed traffic is refused crisply, never half-served"
    ),
    "autopilot_quality_degrade": (
        "queue pressure persists after shedding -> scoring drops to the "
        "q16 quantized plane and/or a subsample_trees prefix of the "
        "forest (FastForest, arxiv 2004.02423): path-length normalisation "
        "rescales to the surviving tree count automatically, an ELIGIBLE "
        "q16 run is bitwise-equal to its f32 traversal family, and the "
        "response/flush span say 'degraded' — quality loss is reported, "
        "never silent; full fidelity returns on recovery"
    ),
    # load-time rung (io/persistence.py, on_corrupt='drop')
    "dropped_trees": (
        "corrupt trees dropped at load -> valid smaller forest: path-length "
        "normalisation rescales to the surviving tree count automatically "
        "(score = 2^(-mean_h/c(n)) over kept trees); ensemble quality "
        "degrades gracefully with lost trees (FastForest, arxiv 2004.02423)"
    ),
}


class DegradationError(RuntimeError):
    """A fallback was required but ``strict=True`` forbids silent fallback."""


@dataclasses.dataclass
class DegradationEvent:
    """One recorded fallback: which rung, what it replaced, how often."""

    reason: str
    from_: str
    to: str
    detail: str
    count: int = 1
    first_unix_s: float = 0.0
    last_unix_s: float = 0.0

    def as_dict(self) -> dict:
        return {
            "reason": self.reason,
            "from": self.from_,
            "to": self.to,
            "detail": self.detail,
            "count": self.count,
        }


@dataclasses.dataclass(frozen=True)
class LoadReport:
    """Outcome of an ``on_corrupt="drop"`` model load: exactly which trees
    were lost and why. Attached to the loaded model as ``model.load_report``
    (None for clean strict loads)."""

    path: str
    expected_trees: Optional[int]
    kept_trees: int
    dropped_tree_ids: Tuple[int, ...]
    issues: Tuple[str, ...]

    def as_dict(self) -> dict:
        return {
            "path": self.path,
            "expected_trees": self.expected_trees,
            "kept_trees": self.kept_trees,
            "dropped_tree_ids": list(self.dropped_tree_ids),
            "issues": list(self.issues),
        }


class DegradationReport:
    """Registry of degradation events; one process-wide instance backs
    :func:`degrade`. Thread-safe (serving stacks score from worker pools)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: Dict[str, DegradationEvent] = {}

    def record(self, reason: str, from_: str, to: str, detail: str) -> bool:
        """Record one occurrence; returns True when this is the first
        occurrence since the last reset (i.e. the warning should log)."""
        now = time.time()
        with self._lock:
            ev = self._events.get(reason)
            if ev is None:
                self._events[reason] = DegradationEvent(
                    reason, from_, to, detail, 1, now, now
                )
                return True
            ev.count += 1
            ev.last_unix_s = now
            ev.detail = detail
            return False

    def events(self) -> List[DegradationEvent]:
        with self._lock:
            return [dataclasses.replace(ev) for ev in self._events.values()]

    def count(self, reason: str) -> int:
        with self._lock:
            ev = self._events.get(reason)
            return ev.count if ev else 0

    def reset(self, reason: Optional[str] = None) -> None:
        with self._lock:
            if reason is None:
                self._events.clear()
            else:
                self._events.pop(reason, None)


_REPORT = DegradationReport()


def degradation_report() -> DegradationReport:
    """The process-wide registry instance."""
    return _REPORT


def degradations() -> List[DegradationEvent]:
    """Snapshot of every degradation recorded since process start / reset."""
    return _REPORT.events()


def reset_degradations(reason: Optional[str] = None) -> None:
    """Clear recorded events (all, or one reason) — re-arms the log-once
    warning for the cleared rungs. Intended for tests and long-lived
    operators that sample-and-clear."""
    _REPORT.reset(reason)


def degrade(
    reason: str,
    from_: str,
    to: str,
    detail: str = "",
    strict: bool = False,
) -> str:
    """Take one rung down the ladder; returns ``to`` for assignment style
    ``strategy = degrade(...)``.

    Logs the detail once per ``reason`` (until reset), records a structured
    event every time, and raises :class:`DegradationError` instead when
    ``strict`` — the caller must not fall back in that case.
    """
    if reason not in LADDER:
        raise ValueError(
            f"unknown degradation reason {reason!r}; known rungs: "
            f"{', '.join(sorted(LADDER))} (add new rungs to "
            "resilience.degradation.LADDER and docs/resilience.md)"
        )
    if strict:
        raise DegradationError(
            f"strict mode forbids the {reason!r} fallback ({from_} -> {to}): "
            f"{detail or LADDER[reason]}"
        )
    first = _REPORT.record(reason, from_, to, detail)
    # every fallback is one timeline event + one counter tick, so a single
    # telemetry.snapshot() shows WHEN each rung fired relative to retries,
    # checkpoint seals and watchdog timeouts — model.degradations() remains
    # the aggregated per-reason view of the same facts (and stays exact
    # even when telemetry is disabled or the bounded timeline wraps)
    _DEGRADATIONS_TOTAL.inc(reason=reason)
    record_event(
        "degradation",
        reason=reason,
        **{"from": from_, "to": to},
        detail=detail or LADDER[reason],
    )
    if first:
        logger.warning(
            "degraded [%s] %s -> %s: %s", reason, from_, to, detail or LADDER[reason]
        )
    return to
