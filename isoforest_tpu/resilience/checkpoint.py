"""Block-wise fit checkpointing: preemption-safe training with bitwise resume.

The reference survives executor loss because Spark re-runs a lost
partition's single tree for free (``SharedTrainLogic.scala`` trains one
tree per partition under task retry). The fused JAX fit is all-or-nothing:
a preemption at tree 990 of 1000 loses the whole fit. This module restores
the reference's property at a coarser, TPU-friendly granularity — the fit
grows the forest in *blocks* of trees and seals each completed block
durably, so a killed fit resumes from the last sealed block.

The resume is **bitwise-identical**, not merely statistically equivalent,
because every per-tree random stream is independently derivable: tree ``t``
grows from ``fold_in(k_grow, t)`` and draws its bag/feature subset from
vmapped per-tree streams (``ops/bagging.py``), so growing trees
``[a, b)`` in any session, on any block partition, on one device or a
mesh, produces the same arrays (the determinism argument FastForest,
arXiv:2004.02423, leans on for subsampled ensembles). The fit driver
computes the FULL-ensemble bag/feature/key tensors once and slices per
block — the samplers' internal dispatch depends on the total tree count,
so slicing (never re-deriving at block size) is what keeps blocks bitwise
equal to the uninterrupted fused program.

On-disk layout (all seals atomic via the persistence temp-dir + rename
machinery, each block carrying a ``_MANIFEST.json`` checksum manifest):

    <checkpoint_dir>/
      fingerprint.json          # config/RNG/data fingerprint, written first
      block-00000/
        arrays.npz              # the block's forest tensors
        block.json              # {blockIndex, treeStart, treeStop, fingerprintSha256}
        _MANIFEST.json          # per-file size/CRC32/SHA-256 (resilience.manifest)
      block-00001/ ...

Resume rules (``fit(..., resume=True)``):

* the stored fingerprint must match the current fit's exactly — any
  mismatch (different seed, config, or training data) refuses with a
  :class:`CheckpointMismatchError` naming the differing fields;
* a sealed, manifest-verified block with matching ``block.json`` is
  loaded; anything else (torn write, corrupt npz, stale temp dir, wrong
  range) is logged and **re-grown** — regrowth is always safe because
  blocks are deterministic;
* ``resume=False`` against a directory that already holds sealed blocks
  refuses (never silently clobber another fit's progress).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _counter
from ..utils.logging import logger
from . import manifest as _manifest

_BLOCKS_SEALED_TOTAL = _counter(
    "isoforest_checkpoint_blocks_sealed_total",
    "Checkpointed-fit tree blocks sealed durably this process",
)
_BLOCKS_RESUMED_TOTAL = _counter(
    "isoforest_checkpoint_blocks_resumed_total",
    "Checkpointed-fit tree blocks loaded from a previous session's seals",
)

CHECKPOINT_VERSION = 1
FINGERPRINT_NAME = "fingerprint.json"
_BLOCK_PREFIX = "block-"
_ARRAYS_NAME = "arrays.npz"
_BLOCK_META_NAME = "block.json"

# default trees per block: at the reference-default 100-tree ensemble this
# is 4 seals — small enough that a preemption loses <= 32 trees of work,
# large enough that seal I/O stays well under 5% of fit time (bench.py
# reports checkpoint_overhead_s against the plain fit)
DEFAULT_BLOCK_TREES = 32


class CheckpointMismatchError(ValueError):
    """The checkpoint on disk was written by a different fit configuration
    (or different training data) than the resume attempt. Carries
    ``mismatched_fields``."""

    def __init__(self, message: str, mismatched_fields: Tuple[str, ...] = ()) -> None:
        super().__init__(message)
        self.mismatched_fields = tuple(mismatched_fields)


def resolve_block_size(checkpoint_every: Optional[int], num_trees: int) -> int:
    """Trees per block: ``checkpoint_every`` clamped to the ensemble, or the
    default."""
    if checkpoint_every is None:
        return min(num_trees, DEFAULT_BLOCK_TREES)
    block = int(checkpoint_every)
    if block < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
    return min(block, num_trees)


def data_fingerprint(X: np.ndarray) -> str:
    """Bounded-cost content fingerprint of the training matrix: shape,
    dtype, a <=64k-element stride sample and both edges. Cheap at any N
    while catching the realistic wrong-resume mistakes (different dataset,
    different preprocessing, truncated load)."""
    x = np.ascontiguousarray(X)
    digest = hashlib.sha256()
    digest.update(repr((x.shape, str(x.dtype))).encode())
    flat = x.reshape(-1)
    if flat.size:
        stride = max(1, flat.size // 65536)
        digest.update(np.ascontiguousarray(flat[::stride]).tobytes())
        digest.update(flat[:64].tobytes())
        digest.update(flat[-64:].tobytes())
    return digest.hexdigest()


def fit_fingerprint(
    *,
    kind: str,
    random_seed: int,
    num_estimators: int,
    bootstrap: bool,
    num_samples: int,
    num_features: int,
    height: int,
    total_rows: int,
    total_features: int,
    block_trees: int,
    data_sha256: str,
    extension_level: Optional[int] = None,
    sampler_sha256: Optional[str] = None,
) -> Dict[str, object]:
    """Everything that determines the grown forest's bits (plus the block
    partition): a resumed fit must agree on every field or the resumed
    forest could silently differ from the uninterrupted one.

    ``sampler_sha256`` is set only by the out-of-core fit path (the streamed
    sampler's sample-content hash, docs/out_of_core.md §3); it is added to
    the fingerprint *conditionally* so checkpoints written before the field
    existed keep resuming byte-for-byte."""
    out = {
        "checkpointVersion": CHECKPOINT_VERSION,
        "kind": kind,
        "randomSeed": int(random_seed),
        "numEstimators": int(num_estimators),
        "bootstrap": bool(bootstrap),
        "numSamples": int(num_samples),
        "numFeatures": int(num_features),
        "height": int(height),
        "totalRows": int(total_rows),
        "totalFeatures": int(total_features),
        "blockTrees": int(block_trees),
        "extensionLevel": None if extension_level is None else int(extension_level),
        "dataSha256": str(data_sha256),
    }
    if sampler_sha256 is not None:
        out["samplerSha256"] = str(sampler_sha256)
    return out


def _fingerprint_sha(fingerprint: Dict[str, object]) -> str:
    return hashlib.sha256(
        json.dumps(fingerprint, sort_keys=True).encode()
    ).hexdigest()


class FitCheckpoint:
    """One fit's checkpoint directory: fingerprint gate + sealed tree blocks.

    Lifecycle: construct with the current fit's fingerprint, :meth:`begin`
    (validates/initialises the directory), then per block either
    :meth:`load_block` (returns the sealed arrays or None) or grow +
    :meth:`seal_block`. ``blocks_written`` counts seals this session —
    ``bench.py`` reports it alongside ``checkpoint_overhead_s``.
    """

    def __init__(self, directory: str, fingerprint: Dict[str, object]) -> None:
        self.directory = str(directory)
        self.fingerprint = dict(fingerprint)
        self.sha = _fingerprint_sha(self.fingerprint)
        self.blocks_written = 0
        self.blocks_loaded = 0

    # ------------------------------------------------------------------ #

    def _block_path(self, index: int) -> str:
        return os.path.join(self.directory, f"{_BLOCK_PREFIX}{index:05d}")

    def _sealed_block_names(self) -> List[str]:
        if not os.path.isdir(self.directory):
            return []
        return sorted(
            name
            for name in os.listdir(self.directory)
            if name.startswith(_BLOCK_PREFIX)
            and os.path.isdir(os.path.join(self.directory, name))
            and ".__tmp-" not in name
        )

    def begin(self, resume: bool) -> None:
        """Validate or initialise the checkpoint directory.

        A stored fingerprint must match the current fit bit for bit;
        otherwise :class:`CheckpointMismatchError` lists the differing
        fields (the actionable half: fix the config/data, resume into a
        fresh directory, or delete this one). ``resume=False`` refuses a
        directory that already holds sealed blocks."""
        os.makedirs(self.directory, exist_ok=True)
        fp_path = os.path.join(self.directory, FINGERPRINT_NAME)
        sealed = self._sealed_block_names()
        if os.path.exists(fp_path):
            try:
                with open(fp_path) as fh:
                    on_disk = json.load(fh)
            except (OSError, ValueError) as exc:
                raise CheckpointMismatchError(
                    f"checkpoint fingerprint {fp_path} is unreadable ({exc}); "
                    "the checkpoint directory is corrupt — delete it and "
                    "re-run the fit"
                ) from exc
            if on_disk != self.fingerprint:
                fields = tuple(
                    sorted(
                        k
                        for k in set(on_disk) | set(self.fingerprint)
                        if on_disk.get(k) != self.fingerprint.get(k)
                    )
                )
                raise CheckpointMismatchError(
                    f"checkpoint at {self.directory} was written by a "
                    "different fit configuration; refusing to resume "
                    "(a mismatched resume would silently produce a "
                    "different forest). Mismatched fields: "
                    + ", ".join(
                        f"{k}: checkpoint={on_disk.get(k)!r} vs "
                        f"current={self.fingerprint.get(k)!r}"
                        for k in fields
                    )
                    + ". Fix the config/data, point checkpoint_dir at a "
                    "fresh directory, or delete the stale checkpoint",
                    mismatched_fields=fields,
                )
            if not resume and sealed:
                raise CheckpointMismatchError(
                    f"checkpoint_dir {self.directory} already holds "
                    f"{len(sealed)} sealed block(s) from a previous fit; "
                    "pass resume=True to continue it, or delete the "
                    "directory to start over"
                )
        else:
            if sealed:
                raise CheckpointMismatchError(
                    f"checkpoint_dir {self.directory} holds sealed blocks "
                    "but no fingerprint — the directory is corrupt or not a "
                    "fit checkpoint; delete it and re-run the fit"
                )
            tmp = f"{fp_path}.tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(self.fingerprint, fh, indent=1, sort_keys=True)
                fh.write("\n")
            os.replace(tmp, fp_path)
        record_event(
            "checkpoint.begin",
            directory=self.directory,
            resume=bool(resume),
            sealed_blocks=len(sealed),
        )

    # ------------------------------------------------------------------ #

    def load_block(
        self, index: int, start: int, stop: int
    ) -> Optional[Dict[str, np.ndarray]]:
        """The sealed arrays for block ``index`` covering trees
        ``[start, stop)``, or None when absent/unverifiable (the caller
        re-grows — always safe, blocks are deterministic)."""
        path = self._block_path(index)
        if not os.path.isdir(path):
            return None
        issues: List[str] = []
        if not _manifest.present(path):
            issues.append("no manifest (unsealed block)")
        else:
            issues.extend(_manifest.verify(path))
        meta = None
        if not issues:
            try:
                with open(os.path.join(path, _BLOCK_META_NAME)) as fh:
                    meta = json.load(fh)
            except (OSError, ValueError) as exc:
                issues.append(f"unreadable {_BLOCK_META_NAME} ({exc})")
        if meta is not None:
            want = {
                "blockIndex": index,
                "treeStart": start,
                "treeStop": stop,
                "fingerprintSha256": self.sha,
            }
            for key, value in want.items():
                if meta.get(key) != value:
                    issues.append(
                        f"{_BLOCK_META_NAME}: {key} is {meta.get(key)!r}, "
                        f"expected {value!r}"
                    )
        arrays: Optional[Dict[str, np.ndarray]] = None
        if not issues:
            try:
                with np.load(os.path.join(path, _ARRAYS_NAME)) as npz:
                    arrays = {name: npz[name] for name in npz.files}
            except Exception as exc:
                issues.append(f"unreadable {_ARRAYS_NAME} ({exc})")
        if issues:
            record_event(
                "checkpoint.block_regrown",
                index=index,
                start=start,
                stop=stop,
                issues="; ".join(issues),
            )
            logger.warning(
                "checkpoint block %s is unusable (%s); re-growing trees "
                "[%d, %d) — deterministic streams make regrowth lossless",
                path,
                "; ".join(issues),
                start,
                stop,
            )
            return None
        self.blocks_loaded += 1
        _BLOCKS_RESUMED_TOTAL.inc()
        record_event(
            "checkpoint.block_resumed", index=index, start=start, stop=stop
        )
        return arrays

    def seal_block(
        self, index: int, start: int, stop: int, arrays: Dict[str, np.ndarray]
    ) -> None:
        """Atomically persist one completed block: full content under a
        temp dir, ``_MANIFEST.json`` checksums, one ``os.rename``. A kill
        at any point leaves either the previous state or the sealed block —
        never a partial one (the marked temp dir a hard kill can leave is
        swept by the next seal and ignored by :meth:`load_block`)."""
        from ..io.persistence import _atomic_dir

        path = self._block_path(index)
        with _atomic_dir(path, overwrite=True) as tmp:
            np.savez(os.path.join(tmp, _ARRAYS_NAME), **arrays)
            with open(os.path.join(tmp, _BLOCK_META_NAME), "w") as fh:
                json.dump(
                    {
                        "checkpointVersion": CHECKPOINT_VERSION,
                        "blockIndex": int(index),
                        "treeStart": int(start),
                        "treeStop": int(stop),
                        "fingerprintSha256": self.sha,
                    },
                    fh,
                    indent=1,
                    sort_keys=True,
                )
                fh.write("\n")
        self.blocks_written += 1
        _BLOCKS_SEALED_TOTAL.inc()
        record_event(
            "checkpoint.block_sealed", index=index, start=start, stop=stop
        )


def block_ranges(num_trees: int, block_trees: int) -> List[Tuple[int, int, int]]:
    """``[(block index, tree start, tree stop), ...]`` covering the ensemble."""
    out = []
    for index, start in enumerate(range(0, num_trees, block_trees)):
        out.append((index, start, min(num_trees, start + block_trees)))
    return out
