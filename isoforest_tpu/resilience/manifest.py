"""Checksummed model-directory manifest (``_MANIFEST.json``).

The reference seals directories with an empty ``_SUCCESS`` marker — proof a
writer *finished*, but not that the bytes on disk today are the bytes it
wrote (bit rot, torn replication, a truncating copy). Every save here
additionally emits a manifest with per-file size + CRC32 + SHA-256 and a
schema version, written inside the temp directory *before* the atomic
rename, so a directory either carries a complete self-describing manifest
or does not exist under its final name at all.

Empty marker files (``_SUCCESS``) are deliberately excluded: they carry no
content to checksum, and excluding them lets ``require_success=False``
loads of deliberately unsealed directories still verify content integrity.

Verification is read-side cheap (one streaming pass per file; model dirs
are typically a few MB) and runs before any Avro parsing, so a corrupt
part file is reported by *name and digest*, not as a decoder backtrace.
"""

from __future__ import annotations

import hashlib
import json
import os
import zlib
from typing import Dict, List

MANIFEST_NAME = "_MANIFEST.json"
MANIFEST_VERSION = 1

# files that exist only as presence markers — no content to verify
_MARKER_NAMES = frozenset({"_SUCCESS"})


def _digests(path: str) -> Dict[str, object]:
    sha = hashlib.sha256()
    crc = 0
    size = 0
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            sha.update(chunk)
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return {
        "size": size,
        "crc32": f"{crc & 0xFFFFFFFF:08x}",
        "sha256": sha.hexdigest(),
    }


def build(root: str) -> dict:
    """Manifest dict for every content file under ``root`` (recursive),
    keyed by /-separated relative path."""
    files: Dict[str, Dict[str, object]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            if name == MANIFEST_NAME or name in _MARKER_NAMES:
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            files[rel] = _digests(full)
    return {"manifestVersion": MANIFEST_VERSION, "files": files}


def write(root: str) -> str:
    """Build and write ``root/_MANIFEST.json``; returns its path."""
    path = os.path.join(root, MANIFEST_NAME)
    with open(path, "w") as fh:
        json.dump(build(root), fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


def present(root: str) -> bool:
    return os.path.exists(os.path.join(root, MANIFEST_NAME))


def verify(root: str) -> List[str]:
    """Verify ``root`` against its manifest; returns a list of mismatch
    descriptions (empty = intact). Raises if the manifest itself is missing
    or unparseable — callers decide legacy tolerance via :func:`present`."""
    mpath = os.path.join(root, MANIFEST_NAME)
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except FileNotFoundError:
        raise
    except Exception as exc:
        return [f"{MANIFEST_NAME}: unparseable ({exc})"]
    issues: List[str] = []
    version = manifest.get("manifestVersion")
    if version != MANIFEST_VERSION:
        issues.append(
            f"{MANIFEST_NAME}: manifestVersion {version!r} != supported "
            f"{MANIFEST_VERSION} (written by an incompatible library version)"
        )
        return issues
    files = manifest.get("files")
    if not isinstance(files, dict):
        return [f"{MANIFEST_NAME}: malformed 'files' table"]
    for rel, want in sorted(files.items()):
        full = os.path.join(root, *rel.split("/"))
        if not os.path.isfile(full):
            issues.append(f"{rel}: listed in manifest but missing on disk")
            continue
        got = _digests(full)
        for field in ("size", "crc32", "sha256"):
            if got[field] != want.get(field):
                issues.append(
                    f"{rel}: {field} mismatch (manifest {want.get(field)!r}, "
                    f"on disk {got[field]!r})"
                )
                break
    # a part file the loader would consume but the writer never manifested
    # is itself an integrity violation (e.g. an injected extra .avro)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            if name == MANIFEST_NAME or name in _MARKER_NAMES:
                continue
            rel = os.path.relpath(os.path.join(dirpath, name), root).replace(
                os.sep, "/"
            )
            if rel not in files:
                issues.append(f"{rel}: on disk but not in manifest")
    return issues
