"""Fault-injection harness.

Production code consults this module at four narrow seams (each a no-op
single dict lookup when no fault is armed):

* ``io.avro.read_blocks`` -> :func:`filter_read_bytes` — ``corrupt_avro``
  (flip a byte of a data part file on read) and ``truncate_data`` (read a
  truncated prefix, the torn-download case);
* ``native.get_library`` -> :func:`native_hidden` — ``hide_native`` makes
  the C++ extension report unavailable (missing ``.so`` / no toolchain);
* ``ops.traversal.score_matrix`` -> :func:`check_strategy` —
  ``raise_strategy=<name>`` makes the named strategy raise
  :class:`FaultInjectedError` at dispatch, proving kernel failures
  propagate loudly instead of silently hopping rungs;
* checkpointed fit (``models/*.fit(checkpoint_dir=...)``) ->
  :func:`check_fit_block` — ``kill_fit_after_block=<k>`` aborts the fit
  immediately after block ``k`` seals (the preemption-mid-fit case the
  resume path exists for);
* out-of-core scoring (``io/outofcore.score_source``) ->
  :func:`check_score_shard` — ``kill_score_after_shard=<k>`` aborts the
  scoring run immediately after shard ``k``'s scores seal (the
  preemption-mid-scoring case ``resume=True`` exists for);
* ``parallel.mesh.initialize_distributed`` ->
  :func:`take_distributed_init_failure` — ``fail_distributed_init=<n>``
  makes the first ``n`` bring-up attempts raise (coordinator not up yet /
  port race), proving the retry/backoff schedule end to end;
* the model lifecycle manager (``lifecycle/manager.py``) -> four seams:
  :func:`take_retrain_kill` — ``kill_retrain_after_block=<k>`` aborts the
  background refit once, immediately after refit block ``k`` seals (the
  preemption-mid-retrain case; one-shot, so the manager's retry/resume
  loop proves the recovery rather than dying again);
  :func:`candidate_corrupted` — ``corrupt_candidate`` poisons the refit
  candidate's float plane before validation (the torn-refit case the
  gates exist to catch); :func:`check_validation` — ``fail_validation``
  forces every validation gate run to fail while armed (rollback drill);
  :func:`check_swap` — ``fail_swap`` raises mid-swap, after the candidate
  is durably saved but before it reaches the scoring path (the
  crash-between-save-and-flip case rollback must survive);
* the model fleet registry (``fleet/registry.py``) -> two seams:
  :func:`check_fleet_load` — ``fail_fleet_load[=<model_id>]`` makes the
  named tenant's (or any) lazy load raise, proving one tenant's broken
  artifacts refuse with a typed 503 while the rest of the fleet serves;
  :func:`evict_during_score` — ``evict_during_score`` forces an eviction
  immediately after a request enqueues, proving in-flight flushes finish
  on their point-in-time service reference (docs/fleet.md);
* scoring execution (``ops.traversal.score_matrix``) and the multihost
  worker body -> :func:`maybe_slow_collective` — ``slow_collective`` (all
  strategies), ``slow_collective=<seconds>`` (stall cap) or
  ``slow_collective=<strategy>`` (stall only that strategy) simulates a
  hung kernel/collective; the stall polls its own arming so exiting
  :func:`inject` releases any abandoned watchdog thread promptly;
* the replicated serving tier (docs/replication.md) -> three seams:
  :func:`take_replica_kill` — ``kill_replica_during_score[=<n>|exit]``
  kills the replica on a scoring request: the HTTP layer severs the
  connection without a response (``=<n>``: the n-th request from now;
  ``exit`` hard-exits the process for subprocess drills; ONE-SHOT like
  :func:`take_retrain_kill` — the router's retry proves the recovery);
  :func:`maybe_wedge_healthz` — ``wedge_replica_healthz[=<seconds>]``
  stalls ``GET /healthz`` while armed (the wedged-but-listening replica
  the router's probe timeout must eject, and re-admit on disarm);
  :func:`push_stalled` — ``stall_current_json_push`` freezes the router's
  rolling-push watcher (no ``CURRENT.json`` generation propagates while
  armed; disarming resumes exactly where it stopped).

:class:`FakeClock` is the injectable time source the retry/watchdog tests
drive: deterministic ``now``/``sleep`` so every backoff schedule and
deadline is provable with zero real sleeps in tier-1.

Faults arm two ways: the :func:`inject` context manager (scoped, stackable,
test-friendly) or the ``ISOFOREST_TPU_FAULTS`` environment variable
(comma-separated ``name`` or ``name=value`` items, e.g.
``ISOFOREST_TPU_FAULTS="corrupt_avro=200,hide_native"``) so subprocesses —
CI's ASan sweep, ``tools/asan/corrupt_models.py`` — can arm faults without
code changes.

:func:`corrupt_file_on_disk` / :func:`truncate_file_on_disk` are the
*persistent* variants (mutate the file once) used to exercise the manifest
CRC layer, which by design cannot see read-time corruption.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable, Dict, List, Optional, Union

FAULTS_ENV = "ISOFOREST_TPU_FAULTS"

KNOWN_FAULTS = frozenset(
    {
        "corrupt_avro",
        "truncate_data",
        "hide_native",
        "raise_strategy",
        "kill_fit_after_block",
        "kill_score_after_shard",
        "kill_retrain_after_block",
        "corrupt_candidate",
        "fail_validation",
        "fail_swap",
        "fail_distributed_init",
        "slow_collective",
        "break_pipeline_stage",
        "fail_fleet_load",
        "evict_during_score",
        "kill_replica_during_score",
        "wedge_replica_healthz",
        "stall_current_json_push",
    }
)

FaultValue = Union[bool, int, str]


class FaultInjectedError(RuntimeError):
    """Raised by an armed ``raise_strategy`` fault at strategy dispatch."""


_STACK: List[Dict[str, FaultValue]] = []


def _parse_env() -> Dict[str, FaultValue]:
    spec = os.environ.get(FAULTS_ENV, "")
    out: Dict[str, FaultValue] = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, value = item.partition("=")
        out[name.strip()] = value.strip() if value else True
    return out


@contextlib.contextmanager
def inject(**faults: FaultValue):
    """Arm the given faults for the dynamic extent of the block::

        with faults.inject(corrupt_avro=True, hide_native=True):
            model = IsolationForestModel.load(path)   # sees the faults
    """
    unknown = set(faults) - KNOWN_FAULTS
    if unknown:
        raise ValueError(
            f"unknown fault(s) {sorted(unknown)}; known: {sorted(KNOWN_FAULTS)}"
        )
    _STACK.append(dict(faults))
    try:
        yield
    finally:
        _STACK.pop()


def get(name: str) -> Optional[FaultValue]:
    """Active value for a fault: innermost :func:`inject` frame wins, then
    the ``ISOFOREST_TPU_FAULTS`` environment; None when unarmed."""
    for frame in reversed(_STACK):
        if name in frame:
            return frame[name]
    return _parse_env().get(name)


def active(name: str) -> bool:
    value = get(name)
    return value is not None and value is not False


# --------------------------------------------------------------------------- #
# seams consulted by production code
# --------------------------------------------------------------------------- #


def _flip_at(data: bytes, offset: int) -> bytes:
    offset = max(0, min(offset, len(data) - 1))
    out = bytearray(data)
    out[offset] ^= 0x5A  # nonzero, so the byte always changes
    return bytes(out)


def filter_read_bytes(path: str, data: bytes) -> bytes:
    """Apply read-time data-file faults to freshly read container bytes.
    Targets ``.avro`` part files only — metadata corruption is a different
    failure class with its own (always-fatal) handling."""
    if not _STACK and FAULTS_ENV not in os.environ:
        return data  # fast path: nothing armed anywhere
    if not os.path.basename(path).endswith(".avro") or not data:
        return data
    corrupt = get("corrupt_avro")
    if corrupt is not None and corrupt is not False:
        # default lands ~3/4 in, well past the container header and inside
        # the (usually single) record block
        offset = int(corrupt) if str(corrupt).isdigit() else (len(data) * 3) // 4
        data = _flip_at(data, offset)
    truncate = get("truncate_data")
    if truncate is not None and truncate is not False:
        keep = int(truncate) if str(truncate).isdigit() else len(data) // 2
        data = data[: max(1, min(keep, len(data)))]
    return data


def native_hidden() -> bool:
    """True when the ``hide_native`` fault is armed — the native extension
    must report unavailable without touching its build/bind cache."""
    return active("hide_native")


def check_strategy(strategy: str) -> None:
    """Raise :class:`FaultInjectedError` when ``raise_strategy`` names the
    strategy about to execute."""
    target = get("raise_strategy")
    if target is not None and str(target) == strategy:
        raise FaultInjectedError(
            f"injected fault: scoring strategy {strategy!r} forced to raise "
            f"(raise_strategy={target!r})"
        )


def check_fit_block(block_index: int) -> None:
    """Raise :class:`FaultInjectedError` when ``kill_fit_after_block`` names
    the block that just SEALED — the checkpointed-fit preemption seam. The
    block's checkpoint is already durable when this fires, exactly like a
    real preemption landing between seal and the next block's growth."""
    value = get("kill_fit_after_block")
    if value is None or value is False:
        return
    if int(value) == int(block_index):
        raise FaultInjectedError(
            f"injected fault: fit killed after sealing block {block_index} "
            f"(kill_fit_after_block={value!r}) — resume with "
            "fit(..., resume=True)"
        )


def check_score_shard(shard_index: int) -> None:
    """Raise :class:`FaultInjectedError` when ``kill_score_after_shard``
    names the source shard whose scores just SEALED — the out-of-core
    scoring preemption seam (io/outofcore.score_source). Like
    :func:`check_fit_block` it fires after the seal, so the durable state is
    exactly what a real kill landing between shards would leave behind;
    ``score_source(..., resume=True)`` must then skip every sealed shard and
    produce bitwise-identical final output (docs/out_of_core.md §5)."""
    value = get("kill_score_after_shard")
    if value is None or value is False:
        return
    if int(value) == int(shard_index):
        raise FaultInjectedError(
            f"injected fault: scoring killed after sealing shard {shard_index} "
            f"(kill_score_after_shard={value!r}) — resume with "
            "score_source(..., resume=True)"
        )


def take_retrain_kill(block_index: int) -> None:
    """Consume a ``kill_retrain_after_block`` token when it names the refit
    block that just sealed. ONE-SHOT, unlike :func:`check_fit_block`: a real
    preemption does not recur deterministically on every retry, and the
    lifecycle manager's retry/resume loop is exactly what the seam exists to
    prove — a recurring kill would only prove the retry budget exhausts.
    Frame-armed values disarm in place; the env form consumes once per
    process."""
    for frame in reversed(_STACK):
        if "kill_retrain_after_block" in frame:
            value = frame["kill_retrain_after_block"]
            if value is None or value is False:
                # consumed (or never-armed) frame: fall through to any outer
                # armed frame — stacked injects model back-to-back kills
                continue
            if int(value) == int(block_index):
                frame["kill_retrain_after_block"] = False
                raise FaultInjectedError(
                    "injected fault: background refit killed after sealing "
                    f"block {block_index} (kill_retrain_after_block={value!r})"
                    " — the sealed blocks resume on the next attempt"
                )
            return
    global _ENV_RETRAIN_KILL_CONSUMED
    value = _parse_env().get("kill_retrain_after_block")
    if value is None or value is False or _ENV_RETRAIN_KILL_CONSUMED:
        return
    if int(value) == int(block_index):
        _ENV_RETRAIN_KILL_CONSUMED = True
        raise FaultInjectedError(
            "injected fault: background refit killed after sealing block "
            f"{block_index} (kill_retrain_after_block={value!r})"
        )


_ENV_RETRAIN_KILL_CONSUMED = False


def candidate_corrupted() -> bool:
    """True while ``corrupt_candidate`` is armed — the lifecycle manager
    then poisons the refit candidate's float plane before validation, so
    the gates (not luck) decide whether garbage reaches the scoring path."""
    return active("corrupt_candidate")


def check_validation() -> None:
    """Raise :class:`FaultInjectedError` while ``fail_validation`` is armed
    — forces the candidate-validation gates to fail (the rollback drill)."""
    if active("fail_validation"):
        raise FaultInjectedError(
            "injected fault: candidate validation forced to fail "
            "(fail_validation) — the manager must roll back to the incumbent"
        )


def check_swap() -> None:
    """Raise :class:`FaultInjectedError` while ``fail_swap`` is armed — a
    mid-swap fault landing after the candidate's durable save but before
    the in-memory flip; the incumbent must keep serving."""
    if active("fail_swap"):
        raise FaultInjectedError(
            "injected fault: model hot-swap forced to fail mid-swap "
            "(fail_swap) — rolling back to the incumbent"
        )


def check_fleet_load(model_id: str) -> None:
    """Raise :class:`FaultInjectedError` while ``fail_fleet_load`` is armed
    (optionally ``fail_fleet_load=<model_id>`` to fail only that tenant's
    lazy load) — the fleet registry must refuse that tenant's request with
    a typed 503 (``fleet_load_failed`` rung) while every other tenant keeps
    serving, and retry the load on the tenant's next request."""
    value = get("fail_fleet_load")
    if value is None or value is False:
        return
    if value is True or str(value) == str(model_id):
        raise FaultInjectedError(
            f"injected fault: fleet lazy load of model {model_id!r} forced "
            f"to fail (fail_fleet_load={value!r})"
        )


def evict_during_score() -> bool:
    """True while ``evict_during_score`` is armed — the fleet registry then
    evicts the tenant right after a request enqueues, proving the waiter's
    in-flight flush finishes on its point-in-time service reference
    (drained, bitwise-exact scores) and only subsequent requests pay the
    re-load (``fleet_evict_under_load`` rung)."""
    return active("evict_during_score")


# env-armed fail_distributed_init consumes across calls within the process
# (subprocess workers re-read the env fresh, matching a real flaky bring-up)
_ENV_DIST_INIT_CONSUMED = 0


def take_distributed_init_failure() -> None:
    """Consume one ``fail_distributed_init`` token; raises
    :class:`FaultInjectedError` while tokens remain (the first-N-attempts
    bring-up failure), then becomes a no-op. Frame-armed values decrement in
    place so nested :func:`inject` scopes stay independent."""
    for frame in reversed(_STACK):
        if "fail_distributed_init" in frame:
            value = frame["fail_distributed_init"]
            if value is False:
                return
            remaining = int(value)
            if remaining > 0:
                frame["fail_distributed_init"] = remaining - 1
                raise FaultInjectedError(
                    "injected fault: distributed bring-up attempt failed "
                    f"({remaining - 1} injected failure(s) remaining)"
                )
            return
    value = _parse_env().get("fail_distributed_init")
    if value is None or value is False:
        return
    total = int(value) if str(value).isdigit() else 1
    global _ENV_DIST_INIT_CONSUMED
    if _ENV_DIST_INIT_CONSUMED < total:
        _ENV_DIST_INIT_CONSUMED += 1
        raise FaultInjectedError(
            "injected fault: distributed bring-up attempt failed "
            f"({total - _ENV_DIST_INIT_CONSUMED} injected failure(s) remaining)"
        )


def maybe_slow_collective(
    strategy: Optional[str] = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
) -> None:
    """Stall while ``slow_collective`` is armed — the hung-kernel /
    hung-DCN-collective simulation the watchdogs exist to bound.

    Value forms: ``True`` (stall any caller, 30 s cap), a number (stall any
    caller, that many seconds), or a strategy name (stall only when
    ``strategy`` matches, 30 s cap). The stall re-checks its own arming
    every 10 ms, so a test exiting :func:`inject` releases the abandoned
    watchdog thread promptly instead of leaking a sleeping thread for the
    full cap."""
    value = get("slow_collective")
    if value is None or value is False:
        return
    limit = 30.0
    if not isinstance(value, bool):
        try:
            limit = float(value)
        except (TypeError, ValueError):
            # strategy-named stall: only the matching caller stalls
            if strategy is None or str(value) != strategy:
                return
    start = clock()
    while active("slow_collective") and clock() - start < limit:
        sleep(0.01)


def take_replica_kill() -> Optional[str]:
    """Consume a ``kill_replica_during_score`` token at the replica HTTP
    layer's scoring dispatch; returns what the kill should look like:
    ``"sever"`` (close the connection without a response — the client sees
    a torn wire, exactly what a SIGKILL mid-request looks like from the
    router's side) or ``"exit"`` (hard-exit the process, the subprocess
    drill), or ``None`` (no kill). Value forms: ``True``/``1`` sever the
    next scoring request, ``<n>`` the n-th from now (the countdown
    decrements in place), ``"exit"`` hard-exits on the next one. ONE-SHOT
    like :func:`take_retrain_kill`: a real replica death does not recur on
    the retried request, and the router's retry-on-another-replica path is
    exactly what the seam exists to prove."""
    for frame in reversed(_STACK):
        if "kill_replica_during_score" in frame:
            value = frame["kill_replica_during_score"]
            if value is None or value is False:
                continue  # consumed frame: fall through to any outer one
            if isinstance(value, str) and not value.isdigit():
                frame["kill_replica_during_score"] = False
                return "exit" if value == "exit" else "sever"
            remaining = int(value)
            if remaining <= 1:
                frame["kill_replica_during_score"] = False
                return "sever"
            frame["kill_replica_during_score"] = remaining - 1
            return None
    global _ENV_REPLICA_KILL_STATE
    if _ENV_REPLICA_KILL_STATE == "consumed":
        return None
    value = _parse_env().get("kill_replica_during_score")
    if value is None or value is False:
        return None
    if isinstance(value, str) and not value.isdigit():
        _ENV_REPLICA_KILL_STATE = "consumed"
        return "exit" if value == "exit" else "sever"
    remaining = (
        int(value)
        if _ENV_REPLICA_KILL_STATE is None
        else int(_ENV_REPLICA_KILL_STATE)
    )
    if remaining <= 1:
        _ENV_REPLICA_KILL_STATE = "consumed"
        return "sever"
    _ENV_REPLICA_KILL_STATE = remaining - 1
    return None


# env-armed countdown state: None (untouched), an int (requests left), or
# "consumed" (the one-shot fired)
_ENV_REPLICA_KILL_STATE: Optional[FaultValue] = None


def maybe_wedge_healthz(
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
) -> None:
    """Stall while ``wedge_replica_healthz`` is armed — the replica whose
    process is alive (socket accepts) but whose health answer never comes,
    the case a router probe TIMEOUT (not a connect failure) must eject.
    Value forms: ``True`` (30 s cap) or a number (that many seconds). Like
    :func:`maybe_slow_collective`, the stall re-checks its own arming every
    10 ms so exiting :func:`inject` releases the wedged handler thread
    promptly."""
    value = get("wedge_replica_healthz")
    if value is None or value is False:
        return
    limit = 30.0
    if not isinstance(value, bool):
        try:
            limit = float(value)
        except (TypeError, ValueError):
            pass
    start = clock()
    while active("wedge_replica_healthz") and clock() - start < limit:
        sleep(0.01)


def push_stalled() -> bool:
    """True while ``stall_current_json_push`` is armed — the router's
    rolling-push watcher then makes NO propagation progress (no replica
    learns of a new ``CURRENT.json`` generation), proving in-flight
    requests keep answering bitwise old-generation scores until the stall
    clears and the push converges (docs/replication.md)."""
    return active("stall_current_json_push")


class FakeClock:
    """Deterministic injectable clock: ``now``/``sleep`` advance virtual
    time only, and every requested sleep is recorded — the retry/watchdog
    schedules are proven against it with zero real sleeps in tier-1."""

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)
        self.sleeps: List[float] = []

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self.sleeps.append(float(seconds))
        self._now += float(seconds)

    def advance(self, seconds: float) -> None:
        self._now += float(seconds)


# --------------------------------------------------------------------------- #
# on-disk mutation helpers (tests / corrupt-corpus generation)
# --------------------------------------------------------------------------- #


def corrupt_file_on_disk(path: str, offset: Optional[int] = None) -> int:
    """Flip one byte of ``path`` in place; returns the offset flipped.
    Unlike the read-time fault this survives the process — it is what the
    manifest CRC layer exists to catch."""
    data = open(path, "rb").read()
    if not data:
        raise ValueError(f"cannot corrupt empty file {path}")
    if offset is None:
        offset = (len(data) * 3) // 4
    mutated = _flip_at(data, offset)
    with open(path, "wb") as fh:
        fh.write(mutated)
    return max(0, min(offset, len(data) - 1))


def truncate_file_on_disk(path: str, keep: Optional[int] = None) -> int:
    """Truncate ``path`` in place (default: half); returns the kept size."""
    size = os.path.getsize(path)
    if keep is None:
        keep = size // 2
    keep = max(1, min(keep, size))
    with open(path, "rb+") as fh:
        fh.truncate(keep)
    return keep
