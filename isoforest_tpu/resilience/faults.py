"""Fault-injection harness.

Production code consults this module at four narrow seams (each a no-op
single dict lookup when no fault is armed):

* ``io.avro.read_blocks`` -> :func:`filter_read_bytes` — ``corrupt_avro``
  (flip a byte of a data part file on read) and ``truncate_data`` (read a
  truncated prefix, the torn-download case);
* ``native.get_library`` -> :func:`native_hidden` — ``hide_native`` makes
  the C++ extension report unavailable (missing ``.so`` / no toolchain);
* ``ops.traversal.score_matrix`` -> :func:`check_strategy` —
  ``raise_strategy=<name>`` makes the named strategy raise
  :class:`FaultInjectedError` at dispatch, proving kernel failures
  propagate loudly instead of silently hopping rungs.

Faults arm two ways: the :func:`inject` context manager (scoped, stackable,
test-friendly) or the ``ISOFOREST_TPU_FAULTS`` environment variable
(comma-separated ``name`` or ``name=value`` items, e.g.
``ISOFOREST_TPU_FAULTS="corrupt_avro=200,hide_native"``) so subprocesses —
CI's ASan sweep, ``tools/asan/corrupt_models.py`` — can arm faults without
code changes.

:func:`corrupt_file_on_disk` / :func:`truncate_file_on_disk` are the
*persistent* variants (mutate the file once) used to exercise the manifest
CRC layer, which by design cannot see read-time corruption.
"""

from __future__ import annotations

import contextlib
import os
from typing import Dict, List, Optional, Union

FAULTS_ENV = "ISOFOREST_TPU_FAULTS"

KNOWN_FAULTS = frozenset(
    {"corrupt_avro", "truncate_data", "hide_native", "raise_strategy"}
)

FaultValue = Union[bool, int, str]


class FaultInjectedError(RuntimeError):
    """Raised by an armed ``raise_strategy`` fault at strategy dispatch."""


_STACK: List[Dict[str, FaultValue]] = []


def _parse_env() -> Dict[str, FaultValue]:
    spec = os.environ.get(FAULTS_ENV, "")
    out: Dict[str, FaultValue] = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, value = item.partition("=")
        out[name.strip()] = value.strip() if value else True
    return out


@contextlib.contextmanager
def inject(**faults: FaultValue):
    """Arm the given faults for the dynamic extent of the block::

        with faults.inject(corrupt_avro=True, hide_native=True):
            model = IsolationForestModel.load(path)   # sees the faults
    """
    unknown = set(faults) - KNOWN_FAULTS
    if unknown:
        raise ValueError(
            f"unknown fault(s) {sorted(unknown)}; known: {sorted(KNOWN_FAULTS)}"
        )
    _STACK.append(dict(faults))
    try:
        yield
    finally:
        _STACK.pop()


def get(name: str) -> Optional[FaultValue]:
    """Active value for a fault: innermost :func:`inject` frame wins, then
    the ``ISOFOREST_TPU_FAULTS`` environment; None when unarmed."""
    for frame in reversed(_STACK):
        if name in frame:
            return frame[name]
    return _parse_env().get(name)


def active(name: str) -> bool:
    value = get(name)
    return value is not None and value is not False


# --------------------------------------------------------------------------- #
# seams consulted by production code
# --------------------------------------------------------------------------- #


def _flip_at(data: bytes, offset: int) -> bytes:
    offset = max(0, min(offset, len(data) - 1))
    out = bytearray(data)
    out[offset] ^= 0x5A  # nonzero, so the byte always changes
    return bytes(out)


def filter_read_bytes(path: str, data: bytes) -> bytes:
    """Apply read-time data-file faults to freshly read container bytes.
    Targets ``.avro`` part files only — metadata corruption is a different
    failure class with its own (always-fatal) handling."""
    if not _STACK and FAULTS_ENV not in os.environ:
        return data  # fast path: nothing armed anywhere
    if not os.path.basename(path).endswith(".avro") or not data:
        return data
    corrupt = get("corrupt_avro")
    if corrupt is not None and corrupt is not False:
        # default lands ~3/4 in, well past the container header and inside
        # the (usually single) record block
        offset = int(corrupt) if str(corrupt).isdigit() else (len(data) * 3) // 4
        data = _flip_at(data, offset)
    truncate = get("truncate_data")
    if truncate is not None and truncate is not False:
        keep = int(truncate) if str(truncate).isdigit() else len(data) // 2
        data = data[: max(1, min(keep, len(data)))]
    return data


def native_hidden() -> bool:
    """True when the ``hide_native`` fault is armed — the native extension
    must report unavailable without touching its build/bind cache."""
    return active("hide_native")


def check_strategy(strategy: str) -> None:
    """Raise :class:`FaultInjectedError` when ``raise_strategy`` names the
    strategy about to execute."""
    target = get("raise_strategy")
    if target is not None and str(target) == strategy:
        raise FaultInjectedError(
            f"injected fault: scoring strategy {strategy!r} forced to raise "
            f"(raise_strategy={target!r})"
        )


# --------------------------------------------------------------------------- #
# on-disk mutation helpers (tests / corrupt-corpus generation)
# --------------------------------------------------------------------------- #


def corrupt_file_on_disk(path: str, offset: Optional[int] = None) -> int:
    """Flip one byte of ``path`` in place; returns the offset flipped.
    Unlike the read-time fault this survives the process — it is what the
    manifest CRC layer exists to catch."""
    data = open(path, "rb").read()
    if not data:
        raise ValueError(f"cannot corrupt empty file {path}")
    if offset is None:
        offset = (len(data) * 3) // 4
    mutated = _flip_at(data, offset)
    with open(path, "wb") as fh:
        fh.write(mutated)
    return max(0, min(offset, len(data) - 1))


def truncate_file_on_disk(path: str, keep: Optional[int] = None) -> int:
    """Truncate ``path`` in place (default: half); returns the kept size."""
    size = os.path.getsize(path)
    if keep is None:
        keep = size // 2
    keep = max(1, min(keep, size))
    with open(path, "rb+") as fh:
        fh.truncate(keep)
    return keep
