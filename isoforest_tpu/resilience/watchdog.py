"""Deadline watchdogs: bounded execution for code that can hang, not just fail.

The retry layer (:mod:`.retry`) handles operations that *raise*; this module
handles the nastier class that simply never returns — a DCN collective
waiting on a dead peer, a native walker wedged in a pathological input, a
Pallas kernel stuck in compilation. Python cannot cancel such work, so the
watchdog runs it in a daemon worker thread and *abandons* it at the
deadline: the stalled thread keeps whatever it was doing (it dies with the
process), while the caller gets a typed :class:`WatchdogTimeout` promptly
and can take a different path — the scoring dispatch retries on the
portable gather kernel through the degradation ladder
(``score_matrix(timeout_s=...)``, rung ``scoring_timeout``), and the
multihost worker converts it into a
:class:`~isoforest_tpu.resilience.retry.DistributedTimeoutError` carrying
per-peer heartbeat diagnostics.

Heartbeats are the companion primitive: each multihost process runs a
:class:`HeartbeatWriter` (a background thread re-writing a small JSON file
every ``interval_s``), and on timeout any survivor reads the whole
directory back with :func:`peer_heartbeat_ages` — so the error names the
peer that went quiet and for how long, instead of reporting only "my own
deadline passed".
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional

from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _counter, gauge as _gauge

_WATCHDOG_TIMEOUTS_TOTAL = _counter(
    "isoforest_watchdog_timeouts_total",
    "Watchdog deadlines that fired (the watched work was abandoned)",
)
_PEER_HEARTBEAT_AGE = _gauge(
    "isoforest_peer_heartbeat_age_seconds",
    "Seconds since each multihost peer's last heartbeat, at last read "
    "(inf = unreadable/torn heartbeat file)",
    labelnames=("peer",),
)


class WatchdogTimeout(RuntimeError):
    """The watched operation did not finish inside its deadline."""

    def __init__(self, message: str, *, deadline_s: Optional[float] = None) -> None:
        super().__init__(message)
        self.deadline_s = deadline_s


# threads whose deadline fired and were left behind; an interpreter exiting
# while one is inside native/XLA code can abort in C++, so tests drain them
# with join_abandoned() after releasing whatever stalled them
_abandoned: list = []
_abandoned_lock = threading.Lock()


def join_abandoned(timeout_s: float = 5.0) -> int:
    """Join previously abandoned watchdog threads (test teardown hygiene);
    returns how many are still alive after ``timeout_s``. Release the stall
    first (e.g. exit the ``slow_collective`` inject scope) or they cannot
    finish."""
    deadline = time.monotonic() + timeout_s
    with _abandoned_lock:
        threads = list(_abandoned)
    for worker in threads:
        worker.join(timeout=max(0.0, deadline - time.monotonic()))
    alive = [w for w in threads if w.is_alive()]
    with _abandoned_lock:
        _abandoned[:] = alive
    return len(alive)


def run_with_deadline(
    fn: Callable[[], object],
    timeout_s: float,
    *,
    describe: str = "operation",
    on_timeout: Optional[Callable[[], str]] = None,
):
    """Run ``fn()`` with a hard wall-clock deadline; returns its result,
    re-raises its exception, or raises :class:`WatchdogTimeout`.

    The work runs in a daemon thread. On timeout the thread is ABANDONED —
    Python has no thread cancellation — so use this only around operations
    whose stalled continuation is harmless (a wedged kernel, a blocked
    collective) and where the caller falls back to a different code path.
    ``on_timeout`` supplies extra diagnostics (e.g. peer heartbeat ages)
    for the error message at the moment the deadline fires.
    """
    if timeout_s <= 0:
        raise ValueError(f"timeout_s must be positive, got {timeout_s}")
    outcome: dict = {}
    done = threading.Event()

    def target() -> None:
        try:
            outcome["value"] = fn()
        except BaseException as exc:  # re-raised in the caller below
            outcome["error"] = exc
        finally:
            done.set()

    worker = threading.Thread(
        target=target, daemon=True, name=f"isoforest-watchdog[{describe}]"
    )
    worker.start()
    if not done.wait(timeout_s):
        with _abandoned_lock:
            _abandoned.append(worker)
        detail = ""
        if on_timeout is not None:
            try:
                detail = on_timeout()
            except Exception as exc:
                detail = f"(diagnostics unavailable: {exc!r})"
        _WATCHDOG_TIMEOUTS_TOTAL.inc()
        record_event(
            "watchdog.timeout",
            describe=describe,
            deadline_s=timeout_s,
            detail=detail,
        )
        raise WatchdogTimeout(
            f"{describe} exceeded its {timeout_s:g}s deadline; the stalled "
            "worker thread was abandoned" + (f" [{detail}]" if detail else ""),
            deadline_s=timeout_s,
        )
    if "error" in outcome:
        raise outcome["error"]
    return outcome["value"]


# --------------------------------------------------------------------------- #
# peer heartbeats (multihost liveness diagnostics)
# --------------------------------------------------------------------------- #

_HEARTBEAT_PREFIX = "heartbeat-"


class HeartbeatWriter:
    """Background thread re-writing ``<dir>/heartbeat-<name>.json`` every
    ``interval_s`` with a wall-clock timestamp — one per multihost process,
    so survivors can tell a dead peer from a slow one. Writes are
    tmp-file + ``os.replace`` so a reader never sees a torn JSON."""

    def __init__(
        self,
        directory: str,
        name: str,
        interval_s: float = 1.0,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.path = os.path.join(directory, f"{_HEARTBEAT_PREFIX}{name}.json")
        self.name = str(name)
        self.interval_s = float(interval_s)
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        """Write one heartbeat now (also called by the background loop)."""
        payload = {"name": self.name, "pid": os.getpid(), "time": self._clock()}
        tmp = f"{self.path}.tmp-{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, self.path)

    def start(self) -> "HeartbeatWriter":
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.beat()  # first beat synchronously: peers see us immediately
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"isoforest-heartbeat[{self.name}]"
        )
        self._thread.start()
        record_event(
            "heartbeat.start", peer=self.name, interval_s=self.interval_s
        )
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except OSError:  # a full/vanished disk must not kill the worker
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s)
            record_event("heartbeat.stop", peer=self.name)


def peer_heartbeat_ages(
    directory: str, clock: Callable[[], float] = time.time
) -> Dict[str, float]:
    """``{peer name: seconds since its last heartbeat}`` for every heartbeat
    file under ``directory``; unreadable/torn files report ``inf`` (a peer
    that died mid-write is still a dead peer)."""
    ages: Dict[str, float] = {}
    if not os.path.isdir(directory):
        return ages
    for fname in sorted(os.listdir(directory)):
        if not fname.startswith(_HEARTBEAT_PREFIX) or not fname.endswith(".json"):
            continue
        name = fname[len(_HEARTBEAT_PREFIX) : -len(".json")]
        try:
            with open(os.path.join(directory, fname)) as fh:
                payload = json.load(fh)
            ages[name] = max(0.0, clock() - float(payload["time"]))
        except (OSError, ValueError, KeyError, TypeError):
            ages[name] = float("inf")
    for name, age in ages.items():
        _PEER_HEARTBEAT_AGE.set(age, peer=name)
    return ages


def format_heartbeat_ages(ages: Dict[str, float], stale_after_s: float) -> str:
    """Human summary for timeout diagnostics: flags peers whose last beat is
    older than ``stale_after_s`` as likely dead."""
    if not ages:
        return "no peer heartbeats found"
    parts = []
    for name in sorted(ages):
        age = ages[name]
        flag = " (LIKELY DEAD)" if age > stale_after_s else ""
        parts.append(f"peer {name}: last heartbeat {age:.1f}s ago{flag}")
    return ", ".join(parts)
