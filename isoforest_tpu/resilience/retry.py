"""Retry with exponential backoff, bounded jitter and a hard deadline.

The reference gets transient-failure tolerance for free: Spark re-runs a
lost partition's task (one tree per partition, ``SharedTrainLogic.scala``)
under its task-retry machinery. The JAX runtime has no such layer — a
failed ``jax.distributed.initialize`` (coordinator not up yet, port race,
transient DNS) or a flaky DCN bring-up simply raises, and at pod scale the
first attempt failing is the *common* case, not the exception. This module
is the missing retry layer, built for provability:

* **deterministic jitter** — delays come from a seeded ``random.Random``,
  so a test (or an incident postmortem) can reproduce the exact schedule;
* **injectable clock/sleep** — every time source is a parameter, so the
  whole schedule (backoff growth, jitter bounds, deadline exhaustion) is
  provable with :class:`~isoforest_tpu.resilience.faults.FakeClock` and
  zero real sleeps in tier-1;
* **typed exhaustion** — callers get :class:`RetryError` (attempts,
  elapsed, last exception) rather than the bare final error, and the
  distributed wrappers re-type that as :class:`DistributedTimeoutError`
  with peer diagnostics (``parallel/mesh.py``, ``tests/multihost_worker.py``).

Backoff is the standard capped exponential: attempt ``a`` sleeps
``min(max_delay_s, base_delay_s * multiplier**a) * (1 + jitter*(2u-1))``
with ``u ~ U[0,1)``, i.e. the jittered delay stays within ``±jitter`` of
the deterministic curve. ``deadline_s`` bounds the *whole* operation: a
retry that could not complete its sleep before the deadline is not
attempted at all — the caller learns about exhaustion ``delay`` seconds
sooner and with the budget honestly reported.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, List, Optional, Tuple

from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _counter
from ..utils.logging import logger

_RETRY_ATTEMPTS_TOTAL = _counter(
    "isoforest_retry_attempts_total",
    "Failed attempts seen by retry_call, by outcome (retried vs exhausted)",
    labelnames=("outcome",),
)


class RetryError(RuntimeError):
    """An operation failed through every allowed attempt (or its deadline).

    Carries the schedule's outcome for diagnostics: ``attempts`` made,
    ``elapsed_s`` since the first attempt started, and ``last_exception``.
    """

    def __init__(
        self,
        message: str,
        *,
        attempts: int = 0,
        elapsed_s: float = 0.0,
        last_exception: Optional[BaseException] = None,
    ) -> None:
        super().__init__(message)
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.last_exception = last_exception


class DistributedTimeoutError(RuntimeError):
    """A distributed peer or collective missed its deadline.

    The typed replacement for the two silent failure modes of the multihost
    path: an indefinite hang inside ``jax.distributed.initialize`` / a DCN
    collective (a dead peer never answers), and a bring-up that fails every
    retry. ``diagnostics`` carries whatever the detecting layer knows —
    per-peer heartbeat ages, attempt counts, the coordinator address — so
    the operator learns *which* peer died, not just that something did.
    """

    def __init__(
        self,
        message: str,
        *,
        elapsed_s: Optional[float] = None,
        deadline_s: Optional[float] = None,
        diagnostics: Tuple[str, ...] = (),
    ) -> None:
        if diagnostics:
            message = message + " [" + "; ".join(diagnostics) + "]"
        super().__init__(message)
        self.elapsed_s = elapsed_s
        self.deadline_s = deadline_s
        self.diagnostics = tuple(diagnostics)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff schedule.

    ``jitter`` is a fraction: each delay is scaled by ``1 + jitter*(2u-1)``
    (``u ~ U[0,1)``), keeping it within ``±jitter`` of the deterministic
    curve — enough to de-synchronise a pod's workers hammering one
    coordinator, small enough to keep the schedule predictable.
    ``deadline_s`` bounds the whole operation (None = attempts-only).
    """

    max_attempts: int = 5
    base_delay_s: float = 0.5
    multiplier: float = 2.0
    max_delay_s: float = 30.0
    jitter: float = 0.1
    deadline_s: Optional[float] = None

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be non-negative")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def delay(self, attempt: int, u: float = 0.5) -> float:
        """Jittered sleep after failed attempt ``attempt`` (0-based).
        ``u in [0, 1)``; the default midpoint gives the deterministic curve."""
        base = min(self.max_delay_s, self.base_delay_s * self.multiplier**attempt)
        return base * (1.0 + self.jitter * (2.0 * u - 1.0))


def backoff_schedule(
    policy: RetryPolicy, attempts: Optional[int] = None, seed: int = 0
) -> List[float]:
    """The exact delays :func:`retry_call` would sleep for this policy and
    seed — a reproducible preview for tests and capacity planning."""
    rng = random.Random(seed)
    n = (policy.max_attempts - 1) if attempts is None else attempts
    return [policy.delay(a, rng.random()) for a in range(n)]


def retry_call(
    fn: Callable[[], object],
    *,
    policy: Optional[RetryPolicy] = None,
    retry_on: tuple = (Exception,),
    describe: str = "operation",
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    seed: int = 0,
):
    """Call ``fn`` under ``policy``; returns its result or raises
    :class:`RetryError`.

    Only ``retry_on`` exceptions are retried — everything else (including
    ``KeyboardInterrupt``/``SystemExit``, which are not ``Exception``
    subclasses) propagates immediately. ``clock``/``sleep`` are injectable
    so schedules are provable without real time passing; ``seed`` fixes the
    jitter stream (:func:`backoff_schedule` with the same seed previews it).
    """
    policy = policy or RetryPolicy()
    rng = random.Random(seed)
    start = clock()
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except retry_on as exc:
            elapsed = clock() - start
            if attempt == policy.max_attempts - 1:
                _RETRY_ATTEMPTS_TOTAL.inc(outcome="exhausted")
                record_event(
                    "retry.exhausted",
                    describe=describe,
                    attempts=attempt + 1,
                    elapsed_s=round(elapsed, 4),
                    error=repr(exc),
                )
                raise RetryError(
                    f"{describe} failed after {attempt + 1} attempt(s) over "
                    f"{elapsed:.2f}s; last error: {exc!r}",
                    attempts=attempt + 1,
                    elapsed_s=elapsed,
                    last_exception=exc,
                ) from exc
            delay = policy.delay(attempt, rng.random())
            if (
                policy.deadline_s is not None
                and elapsed + delay > policy.deadline_s
            ):
                _RETRY_ATTEMPTS_TOTAL.inc(outcome="exhausted")
                record_event(
                    "retry.exhausted",
                    describe=describe,
                    attempts=attempt + 1,
                    elapsed_s=round(elapsed, 4),
                    deadline_s=policy.deadline_s,
                    error=repr(exc),
                )
                raise RetryError(
                    f"{describe} abandoned after {attempt + 1} attempt(s): "
                    f"the next retry (+{delay:.2f}s backoff) would exceed the "
                    f"{policy.deadline_s:.2f}s deadline ({elapsed:.2f}s "
                    f"elapsed); last error: {exc!r}",
                    attempts=attempt + 1,
                    elapsed_s=elapsed,
                    last_exception=exc,
                ) from exc
            _RETRY_ATTEMPTS_TOTAL.inc(outcome="retried")
            record_event(
                "retry.attempt",
                describe=describe,
                attempt=attempt + 1,
                max_attempts=policy.max_attempts,
                delay_s=round(delay, 4),
                error=repr(exc),
            )
            logger.warning(
                "%s attempt %d/%d failed (%r); retrying in %.2fs",
                describe,
                attempt + 1,
                policy.max_attempts,
                exc,
                delay,
            )
            sleep(delay)
    raise AssertionError("unreachable: loop either returns or raises")
