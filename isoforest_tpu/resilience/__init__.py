"""Resilience layer: degradation ladder, fault injection, model integrity.

The ROADMAP north star is serving heavy traffic; at that scale a torn write,
a corrupt Avro block, a missing native ``.so`` or a fenced kernel must degrade
predictably and observably — not crash or silently change behaviour. Three
coordinated pieces:

* :mod:`.degradation` — the unified degradation ladder. Every runtime
  fallback (native→gather, walk→gather/dense, the EIF Pallas precision
  fence, shard_map-ineligible strategy pins, dropped-tree loads) routes
  through one :func:`degrade` call that logs once, records a structured
  event, and raises :class:`DegradationError` under ``strict=True``.
* :mod:`.manifest` — ``_MANIFEST.json`` written atomically with every model
  directory: per-file size + CRC32 + SHA-256 so loads verify integrity
  before parsing a byte of Avro.
* :mod:`.faults` — fault-injection harness (context manager +
  ``ISOFOREST_TPU_FAULTS`` env hook) that can corrupt Avro bytes on read,
  truncate data part files, hide the native extension, and force a named
  scoring strategy to raise — used by ``tests/test_resilience.py`` to prove
  every failure path lands on its documented rung.

The ladder itself (every rung, trigger, and parity guarantee) is documented
in ``docs/resilience.md``.
"""

from . import faults, manifest
from .degradation import (
    LADDER,
    DegradationError,
    DegradationEvent,
    DegradationReport,
    LoadReport,
    degradation_report,
    degradations,
    degrade,
    reset_degradations,
)

__all__ = [
    "faults",
    "manifest",
    "LADDER",
    "DegradationError",
    "DegradationEvent",
    "DegradationReport",
    "LoadReport",
    "degradation_report",
    "degradations",
    "degrade",
    "reset_degradations",
]
