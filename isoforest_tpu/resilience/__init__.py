"""Resilience layer: degradation ladder, fault injection, model integrity.

The ROADMAP north star is serving heavy traffic; at that scale a torn write,
a corrupt Avro block, a missing native ``.so`` or a fenced kernel must degrade
predictably and observably — not crash or silently change behaviour. Three
coordinated pieces:

* :mod:`.degradation` — the unified degradation ladder. Every runtime
  fallback (native→gather, walk→gather/dense, the EIF Pallas precision
  fence, shard_map-ineligible strategy pins, dropped-tree loads) routes
  through one :func:`degrade` call that logs once, records a structured
  event, and raises :class:`DegradationError` under ``strict=True``.
* :mod:`.manifest` — ``_MANIFEST.json`` written atomically with every model
  directory: per-file size + CRC32 + SHA-256 so loads verify integrity
  before parsing a byte of Avro.
* :mod:`.faults` — fault-injection harness (context manager +
  ``ISOFOREST_TPU_FAULTS`` env hook) that can corrupt Avro bytes on read,
  truncate data part files, hide the native extension, force a named
  scoring strategy to raise, kill a checkpointed fit after a chosen block,
  fail the first N distributed bring-up attempts, and stall a
  kernel/collective — used by ``tests/test_resilience.py`` /
  ``tests/test_checkpoint.py`` to prove every failure path lands on its
  documented rung.
* :mod:`.checkpoint` — block-wise fit checkpointing: a killed fit resumes
  from the last atomically sealed tree block and yields a bitwise-identical
  forest (``fit(checkpoint_dir=..., resume=True)``).
* :mod:`.retry` — capped exponential backoff with deterministic jitter,
  injectable clock/sleep and a hard deadline; typed
  :class:`DistributedTimeoutError` for the multihost path.
* :mod:`.watchdog` — deadline watchdogs for code that hangs rather than
  raises (stalled kernels, dead-peer collectives), plus the peer-heartbeat
  files multihost timeout diagnostics read.

The ladder itself (every rung, trigger, and parity guarantee) is documented
in ``docs/resilience.md``.
"""

from . import checkpoint, faults, manifest, retry, watchdog
from .checkpoint import CheckpointMismatchError, FitCheckpoint
from .degradation import (
    LADDER,
    DegradationError,
    DegradationEvent,
    DegradationReport,
    LoadReport,
    degradation_report,
    degradations,
    degrade,
    reset_degradations,
)
from .retry import DistributedTimeoutError, RetryError, RetryPolicy, retry_call
from .watchdog import WatchdogTimeout

__all__ = [
    "checkpoint",
    "faults",
    "manifest",
    "retry",
    "watchdog",
    "LADDER",
    "CheckpointMismatchError",
    "DegradationError",
    "DegradationEvent",
    "DegradationReport",
    "DistributedTimeoutError",
    "FitCheckpoint",
    "LoadReport",
    "RetryError",
    "RetryPolicy",
    "WatchdogTimeout",
    "degradation_report",
    "degradations",
    "degrade",
    "reset_degradations",
    "retry_call",
]
