"""Streaming engine: online anomaly detection over event-time streams.

The reference is a batch Spark estimator, but its anti-abuse use case is a
stream: scores must stay fresh as traffic drifts. This package closes the
loop the north-star names (ROADMAP item 6) — an unbounded, append-only
source of timestamped rows flows through

* :mod:`.sources` — tail a shard directory / CSV file, accept a TCP line
  protocol, or wrap any in-process generator, all yielding
  :class:`~isoforest_tpu.stream.sources.StreamBatch` (event times +
  features + optional labels);
* :mod:`.engine` — :class:`StreamEngine`: event-time tumbling/sliding
  windows under a watermark with bounded allowed lateness, bounded-lag
  scoring through the serving micro-batch coalescer, per-window folds into
  the lifecycle manager's (decay) reservoir, and window-cadenced
  retrain/validate/swap so sliding-mode refresh is the steady state, not a
  drift-triggered exception.

Windowing model, decay-reservoir math and the event/metric tables:
``docs/streaming.md``. CLI: ``python -m isoforest_tpu stream``.
"""

from .engine import StreamConfig, StreamEngine
from .sources import StreamBatch, generator_source, socket_source, tail_source

__all__ = [
    "StreamBatch",
    "StreamConfig",
    "StreamEngine",
    "generator_source",
    "socket_source",
    "tail_source",
]
