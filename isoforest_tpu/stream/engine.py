"""StreamEngine: event-time windows, a watermark, and the steady-state
retrain loop.

The engine turns the lifecycle manager's drift-*exception* path into the
streaming *rule* (ROADMAP item 6): rows arrive stamped with the time they
HAPPENED (event time), get scored with bounded lag through the serving
micro-batch coalescer, and fold into the retrain window only when their
**pane** seals under the watermark — so the (decay) reservoir weighs rows
by event time even when the transport delivers them out of order.

Windowing model (docs/streaming.md §2–3):

* Windows are ``[m * slide_s, m * slide_s + window_s)``; ``slide_s``
  defaults to ``window_s`` (tumbling) and must divide ``window_s``
  (sliding = overlapping windows sharing panes).
* The **watermark** is ``max(event_ts seen) - lateness_s`` — a pure
  function of the data, never of the wall clock: a stalled stream freezes
  the watermark (tests pin this), and a replayed historical file sweeps it
  through the past at replay speed.
* A **pane** (one ``slide_s``-wide stripe) seals when the watermark passes
  its end: its rows fold into the manager's reservoir exactly once,
  stamped with their event times (``stream.fold``). A **window** closes
  when the watermark passes ITS end: the aggregate over its panes is
  emitted (``stream.window_closed``), and every ``retrain_every``-th
  non-empty close drives ``ModelManager.retrain`` — sliding-mode
  retrain/validate/swap as the steady state (``stream.retrain`` /
  ``stream.swap``).
* A row arriving with ``event_ts`` already behind the watermark is
  **late**: it is still scored (the caller gets an answer) but never
  folded — counted in ``isoforest_stream_late_rows_total`` and routed to
  a typed ``stream.late`` event, never silently dropped.

Scoring reuses :class:`~isoforest_tpu.serving.coalescer.MicroBatchCoalescer`
unchanged — each source batch is submitted under a ``stream.ingest`` span
(the flush span links it, so a stream row's causal path reconstructs
exactly like an HTTP request's, docs/observability.md §9) and its
submit→result wall time lands in ``isoforest_stream_lag_seconds``: the
bounded-lag proof. ``threaded=False`` runs the coalescer flusher-less and
the engine never blocks — tests drive the whole loop on a FakeClock with
zero real sleeps.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..lifecycle.manager import OUTCOME_SWAPPED, ModelManager
from ..lifecycle.window import DecayReservoir
from ..serving.coalescer import MicroBatchCoalescer
from ..telemetry.events import record_event
from ..telemetry.metrics import counter as _counter, gauge as _gauge
from ..telemetry.metrics import histogram as _histogram
from ..telemetry.spans import span as _span
from ..utils.logging import logger
from .sources import StreamBatch

_ROWS_TOTAL = _counter(
    "isoforest_stream_rows_total",
    "Rows ingested (scored) by the streaming engine, late rows included",
)
_LATE_ROWS_TOTAL = _counter(
    "isoforest_stream_late_rows_total",
    "Rows that arrived behind the watermark (scored, routed to a "
    "stream.late event, excluded from window folds)",
)
_WINDOWS_CLOSED_TOTAL = _counter(
    "isoforest_stream_windows_closed_total",
    "Event-time windows closed by the watermark (empty windows included)",
)
_WATERMARK_LAG = _gauge(
    "isoforest_stream_watermark_lag_seconds",
    "Wall clock minus the event-time watermark at the last ingest — how far "
    "behind 'now' the stream's complete prefix is (large and shrinking "
    "during a historical replay; growing when the stream stalls)",
)
_FRESHNESS = _gauge(
    "isoforest_window_freshness_seconds",
    "Seconds of wall time since the newest window pane was folded into the "
    "retrain reservoir — the staleness companion to the drift gauges "
    "(isoforest_score_drift_psi drifting while this grows means the model "
    "is judged against a window nobody is refreshing)",
)
_LAG_SECONDS = _histogram(
    "isoforest_stream_lag_seconds",
    "Bounded-lag proof: wall seconds from a stream batch's coalescer "
    "submit to its scores arriving (queue wait + coalesced flush)",
)


def _peak_rss_bytes() -> int:
    """Process peak RSS (ru_maxrss is KB on Linux, bytes on macOS) — the
    flat-memory proof the stream soak pins per window close."""
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(peak if sys.platform == "darwin" else peak * 1024)
    except Exception:  # pragma: no cover - exotic platforms
        return 0


@dataclass
class StreamConfig:
    """Engine knobs; every time quantity is in seconds.

    ``window_s``/``slide_s``/``lateness_s`` define the event-time geometry
    (``slide_s=None`` = tumbling). ``retrain_every`` is the window-close
    cadence of the steady-state retrain loop (non-empty closes only).
    ``batch_rows``/``linger_s``/``max_queue_rows``/``queue_deadline_s``
    forward to the micro-batch coalescer; ``max_pending`` bounds how many
    source batches may be in flight before ingest blocks on the oldest
    (the lag bound, in batches). ``threaded=False`` is the deterministic
    test mode: no flusher thread, the engine pumps, nothing sleeps.
    """

    window_s: float = 60.0
    slide_s: Optional[float] = None
    lateness_s: float = 0.0
    retrain_every: int = 1
    batch_rows: int = 1024
    linger_s: float = 0.002
    max_queue_rows: int = 65536
    queue_deadline_s: float = 60.0
    max_pending: int = 8
    result_timeout_s: float = 300.0
    wait_retrain: bool = True
    threaded: bool = True

    def __post_init__(self) -> None:
        if not (self.window_s > 0):
            raise ValueError(f"window_s must be > 0, got {self.window_s}")
        if self.slide_s is None:
            self.slide_s = float(self.window_s)
        if not (0 < self.slide_s <= self.window_s):
            raise ValueError(
                f"slide_s must be in (0, window_s], got {self.slide_s}"
            )
        panes = self.window_s / self.slide_s
        if abs(panes - round(panes)) > 1e-9:
            raise ValueError(
                f"window_s ({self.window_s}) must be a whole multiple of "
                f"slide_s ({self.slide_s}); got {panes:.6f} panes per window"
            )
        if self.lateness_s < 0:
            raise ValueError(f"lateness_s must be >= 0, got {self.lateness_s}")
        if self.retrain_every < 1:
            raise ValueError(f"retrain_every must be >= 1, got {self.retrain_every}")

    @property
    def panes_per_window(self) -> int:
        return int(round(self.window_s / self.slide_s))


class _Pane:
    """Buffered on-time rows of one ``slide_s`` stripe, pre-seal."""

    __slots__ = ("xs", "ys", "tss", "score_sum", "anomalies", "labeled")

    def __init__(self) -> None:
        self.xs: List[np.ndarray] = []
        self.ys: List[np.ndarray] = []
        self.tss: List[np.ndarray] = []
        self.score_sum = 0.0
        self.anomalies = 0
        self.labeled = True


class StreamEngine:
    """Online anomaly detection over an event-time stream (module doc).

    ``manager`` is a :class:`~isoforest_tpu.lifecycle.ModelManager` —
    usually constructed with ``auto_retrain=False`` (the engine's window
    cadence drives retrains, not the drift debounce; both can coexist) and
    ``reservoir="decay"`` so the fold stream's event stamps matter.
    ``clock`` is the wall clock (injectable: FakeClock in tests); event
    time only ever comes from the data.
    """

    def __init__(
        self,
        manager: ModelManager,
        config: Optional[StreamConfig] = None,
        *,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.manager = manager
        self.config = config or StreamConfig()
        self._clock = clock
        self._decay = isinstance(manager.reservoir, DecayReservoir)
        self.coalescer = MicroBatchCoalescer(
            self._score_flush,
            max_batch_rows=self.config.batch_rows,
            max_linger_s=self.config.linger_s,
            max_queue_rows=self.config.max_queue_rows,
            queue_deadline_s=self.config.queue_deadline_s,
            clock=clock,
            start=self.config.threaded,
        )
        # event-time state: all -inf until the first row lands
        self._watermark = float("-inf")
        self._max_event_ts = float("-inf")
        self._cursor: Optional[float] = None  # next window end to close
        self._max_pane_end = float("-inf")  # bound for the +inf final sweep
        self._panes: Dict[int, _Pane] = {}
        self._sealed: Dict[int, dict] = {}  # pane stats until last window closes
        self._in_flight: List[Tuple[StreamBatch, object, float]] = []
        self._windows_since_retrain = 0
        self._last_fold_wall: Optional[float] = None
        self._finished = False
        # summary counters
        self.rows = 0
        self.late_rows = 0
        self.windows_closed = 0
        self.empty_windows = 0
        self.folded_rows = 0
        self.swaps = 0
        self.retrain_outcomes: Dict[str, int] = {}
        self.rss_trajectory: List[Tuple[float, int]] = []

    # ------------------------------------------------------------------ #
    # scoring path
    # ------------------------------------------------------------------ #

    def _score_flush(self, X: np.ndarray) -> np.ndarray:
        # the coalescer's score_fn: drift monitor folds, reservoir does NOT
        # — rows enter the window only when their pane seals, stamped with
        # event time (module doc)
        return self.manager.score(X, fold_reservoir=False)

    def process(self, batch: StreamBatch) -> None:
        """Submit one source batch for scoring and ingest every completed
        one. Never blocks in threadless mode; in threaded mode blocks only
        when more than ``max_pending`` batches are in flight (the lag
        bound)."""
        if self._finished:
            raise RuntimeError("StreamEngine.finish() already ran")
        if batch.rows == 0:
            return
        if batch.ts.shape[0] != batch.X.shape[0]:
            raise ValueError(
                f"batch has {batch.ts.shape[0]} timestamps for "
                f"{batch.X.shape[0]} rows"
            )
        with _span("stream.ingest", rows=batch.rows):
            pending = self.coalescer.submit(batch.X)
        self._in_flight.append((batch, pending, self._clock()))
        self.drain(block=len(self._in_flight) > self.config.max_pending)

    def drain(self, block: bool = False) -> int:
        """Ingest completed in-flight batches, in submission order (the
        watermark is order-sensitive). Returns how many were ingested."""
        done = 0
        while self._in_flight:
            batch, pending, submitted = self._in_flight[0]
            if not pending.event.is_set():
                if not self.config.threaded:
                    self.coalescer.pump()
                if not pending.event.is_set():
                    if not (block and self.config.threaded):
                        break
            scores = self.coalescer.result(
                pending, timeout_s=self.config.result_timeout_s
            )
            self._in_flight.pop(0)
            self._ingest(batch, scores, self._clock() - submitted)
            done += 1
            block = len(self._in_flight) > self.config.max_pending
        return done

    # ------------------------------------------------------------------ #
    # event-time bookkeeping
    # ------------------------------------------------------------------ #

    def _ingest(self, batch: StreamBatch, scores: np.ndarray, lag_s: float) -> None:
        cfg = self.config
        n = batch.rows
        self.rows += n
        _ROWS_TOTAL.inc(n)
        _LAG_SECONDS.observe(max(lag_s, 0.0))
        threshold = getattr(self.manager.model, "outlier_score_threshold", None)
        late = batch.ts < self._watermark
        n_late = int(late.sum())
        if n_late:
            self.late_rows += n_late
            _LATE_ROWS_TOTAL.inc(n_late)
            record_event(
                "stream.late",
                rows=n_late,
                watermark=self._watermark,
                min_ts=float(batch.ts[late].min()),
                max_ts=float(batch.ts[late].max()),
            )
        ontime = ~late
        if ontime.any():
            ts = batch.ts[ontime]
            X = batch.X[ontime]
            y = batch.y[ontime] if batch.y is not None else None
            s = np.asarray(scores)[ontime]
            pane_ids = np.floor(ts / cfg.slide_s).astype(np.int64)
            for pid in np.unique(pane_ids):
                rows = pane_ids == pid
                pane = self._panes.get(int(pid))
                if pane is None:
                    pane = self._panes[int(pid)] = _Pane()
                    self._max_pane_end = max(
                        self._max_pane_end,
                        (float(pid) + cfg.panes_per_window) * cfg.slide_s,
                    )
                pane.xs.append(X[rows])
                pane.tss.append(ts[rows])
                if y is None:
                    pane.labeled = False
                elif pane.labeled:
                    pane.ys.append(y[rows])
                pane.score_sum += float(s[rows].sum())
                if threshold is not None:
                    pane.anomalies += int((s[rows] > threshold).sum())
            if self._cursor is None:
                first = float(ts.min())
                self._cursor = (math.floor(first / cfg.slide_s) + 1) * cfg.slide_s
            self._max_event_ts = max(self._max_event_ts, float(ts.max()))
            self._watermark = self._max_event_ts - cfg.lateness_s
        _WATERMARK_LAG.set(self._clock() - self._watermark)
        if self._last_fold_wall is not None:
            _FRESHNESS.set(self._clock() - self._last_fold_wall)
        self._advance()

    def _advance(self) -> None:
        cfg = self.config
        for pid in sorted(self._panes):
            if (pid + 1) * cfg.slide_s <= self._watermark:
                self._seal_pane(pid)
        while (
            self._cursor is not None
            and self._watermark >= self._cursor
            and self._cursor <= self._max_pane_end
        ):
            end = self._cursor
            self._cursor = end + cfg.slide_s
            self._close_window(end)

    def _seal_pane(self, pid: int) -> None:
        cfg = self.config
        pane = self._panes.pop(pid)
        X = np.concatenate(pane.xs)
        ts = np.concatenate(pane.tss)
        y = np.concatenate(pane.ys) if (pane.labeled and pane.ys) else None
        if self._decay:
            self.manager.reservoir.fold(X, y, event_ts=ts)
        else:
            self.manager.reservoir.fold(X, y)
        self.folded_rows += int(X.shape[0])
        self._last_fold_wall = self._clock()
        _FRESHNESS.set(0.0)
        record_event(
            "stream.fold",
            pane_start=pid * cfg.slide_s,
            pane_end=(pid + 1) * cfg.slide_s,
            rows=int(X.shape[0]),
            labeled=y is not None,
            reservoir_rows=self.manager.reservoir.rows,
        )
        self._sealed[pid] = {
            "rows": int(X.shape[0]),
            "anomalies": pane.anomalies,
            "score_sum": pane.score_sum,
        }

    def _close_window(self, end: float) -> None:
        cfg = self.config
        end_pid = int(round(end / cfg.slide_s))
        pids = range(end_pid - cfg.panes_per_window, end_pid)
        stats = [self._sealed[p] for p in pids if p in self._sealed]
        rows = sum(s["rows"] for s in stats)
        anomalies = sum(s["anomalies"] for s in stats)
        score_sum = sum(s["score_sum"] for s in stats)
        self.windows_closed += 1
        _WINDOWS_CLOSED_TOTAL.inc()
        if rows == 0:
            self.empty_windows += 1
        record_event(
            "stream.window_closed",
            start=end - cfg.window_s,
            end=end,
            rows=rows,
            anomalies=anomalies,
            mean_score=(score_sum / rows) if rows else None,
            watermark=self._watermark,
            reservoir_rows=self.manager.reservoir.rows,
        )
        self.rss_trajectory.append((end, _peak_rss_bytes()))
        # a pane is spent once its LAST containing window has closed
        for pid in [p for p in self._sealed if (p + cfg.panes_per_window) * cfg.slide_s <= end]:
            del self._sealed[pid]
        if rows > 0:
            self._windows_since_retrain += 1
            if self._windows_since_retrain >= cfg.retrain_every:
                self._maybe_retrain(end)

    # ------------------------------------------------------------------ #
    # steady-state retrain loop
    # ------------------------------------------------------------------ #

    def _maybe_retrain(self, window_end: float) -> None:
        manager = self.manager
        if manager.retrain_in_progress:
            return  # a background retrain is still running: retry next close
        if manager.reservoir.rows < manager.min_window_rows:
            logger.info(
                "stream: window closed at %.1f but the reservoir holds %d "
                "rows (< min_window_rows=%d); retrain deferred",
                window_end,
                manager.reservoir.rows,
                manager.min_window_rows,
            )
            return
        self._windows_since_retrain = 0
        with _span("stream.retrain", window_end=window_end):
            outcome = manager.retrain(
                reason="window_close", wait=self.config.wait_retrain
            )
        if outcome is None:
            return
        self.retrain_outcomes[outcome] = self.retrain_outcomes.get(outcome, 0) + 1
        record_event(
            "stream.retrain",
            window_end=window_end,
            outcome=outcome,
            generation=manager.generation,
        )
        if outcome == OUTCOME_SWAPPED:
            self.swaps += 1
            record_event(
                "stream.swap",
                window_end=window_end,
                generation=manager.generation,
                path=manager.model_path,
                reservoir_rows=manager.reservoir.rows,
            )

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #

    def run(self, source: Iterable[StreamBatch], max_rows: Optional[int] = None) -> dict:
        """Consume ``source`` to exhaustion (or ``max_rows``), then
        :meth:`finish`. Returns the summary dict."""
        record_event(
            "stream.start",
            window_s=self.config.window_s,
            slide_s=self.config.slide_s,
            lateness_s=self.config.lateness_s,
            retrain_every=self.config.retrain_every,
            mode=self.manager.mode,
            reservoir=self.manager.reservoir_mode,
        )
        for batch in source:
            self.process(batch)
            if max_rows is not None and self.rows + sum(
                b.rows for b, _, _ in self._in_flight
            ) >= max_rows:
                break
        return self.finish()

    def finish(self) -> dict:
        """Drain in-flight scoring, advance the watermark past every pane
        (end-of-stream closes all windows), emit ``stream.stop`` and return
        the summary. Idempotent."""
        if self._finished:
            return self.state()
        self._finished = True
        self.coalescer.close(drain=True)
        while self._in_flight:
            batch, pending, submitted = self._in_flight.pop(0)
            scores = self.coalescer.result(pending, timeout_s=self.config.result_timeout_s)
            self._ingest(batch, scores, self._clock() - submitted)
        if math.isfinite(self._max_event_ts):
            self._watermark = float("inf")
            self._advance()
            self._watermark = self._max_event_ts - self.config.lateness_s
        summary = self.state()
        record_event(
            "stream.stop",
            rows=self.rows,
            late_rows=self.late_rows,
            windows_closed=self.windows_closed,
            swaps=self.swaps,
            generation=self.manager.generation,
        )
        return summary

    def close(self) -> None:
        """Tear down without the end-of-stream watermark sweep (buffered
        panes stay unfolded): the abandon path. :meth:`finish` is the
        graceful one."""
        self._finished = True
        self.coalescer.close(drain=False)

    def freshness_seconds(self) -> Optional[float]:
        """Wall seconds since the newest pane fold (None = nothing folded)."""
        if self._last_fold_wall is None:
            return None
        return self._clock() - self._last_fold_wall

    @property
    def watermark(self) -> float:
        return self._watermark

    def state(self) -> dict:
        """Operator-facing summary (plain JSON types)."""
        lag = _LAG_SECONDS.summary()
        return {
            "rows": self.rows,
            "late_rows": self.late_rows,
            "folded_rows": self.folded_rows,
            "windows_closed": self.windows_closed,
            "empty_windows": self.empty_windows,
            "swaps": self.swaps,
            "retrain_outcomes": dict(self.retrain_outcomes),
            "generation": self.manager.generation,
            "watermark": None if not math.isfinite(self._watermark) else self._watermark,
            "max_event_ts": (
                None if not math.isfinite(self._max_event_ts) else self._max_event_ts
            ),
            "window_s": self.config.window_s,
            "slide_s": self.config.slide_s,
            "lateness_s": self.config.lateness_s,
            "freshness_seconds": self.freshness_seconds(),
            "lag_p99_s": lag.get("p99"),
            "reservoir_rows": self.manager.reservoir.rows,
            "reservoir": self.manager.reservoir_mode,
            "rss_trajectory": [
                {"window_end": e, "peak_rss_bytes": b} for e, b in self.rss_trajectory
            ],
            "peak_rss_bytes": _peak_rss_bytes(),
        }
