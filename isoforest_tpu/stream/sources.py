"""Append-only stream sources: file tail, TCP line protocol, generators.

Every source yields :class:`StreamBatch` — event timestamps (float64 unix
seconds: the windowing coordinate, never truncated to float32), a float32
feature matrix, and optional labels. The wire/row convention everywhere is

    event_ts, f1, ..., fn[, label]

— the FIRST column is the event time, and ``labeled=True`` treats the LAST
column as the label (the same trailing-label convention as the batch CLI).

``tail_source`` rides the ``io/source.py`` shard abstraction: a directory
(or glob) of ``.csv``/``.npy`` shards is streamed in sorted-name order, and
in ``follow`` mode the tail then polls for shard files that were not there
before — the append-only contract is "new shards appear" (write-complete
then rename, like the out-of-core sinks), never "old shards mutate". A
single CSV file tails line-by-line instead, picking up appended rows. The
poll ``sleep`` is injectable so tests drive the tail on a FakeClock with
zero real sleeps.

``socket_source`` binds a ThreadingTCPServer speaking one CSV row per line
(the ``python -m isoforest_tpu stream --source tcp://HOST:PORT`` transport);
``generator_source`` adapts any in-process iterable (bench, examples,
tests).
"""

from __future__ import annotations

import io as _io
import os
import queue
import socketserver
import threading
import time
from typing import Callable, Iterable, Iterator, List, NamedTuple, Optional, Tuple

import numpy as np

from ..io.source import SHARD_FORMATS, open_source


class StreamBatch(NamedTuple):
    """One decoded slice of the stream: per-row event times (float64 unix
    seconds), features (float32 ``[N, F]``), optional labels."""

    ts: np.ndarray
    X: np.ndarray
    y: Optional[np.ndarray]

    @property
    def rows(self) -> int:
        return int(self.X.shape[0])


def split_timed(data: np.ndarray, labeled: bool) -> StreamBatch:
    """Split a raw ``[N, 1 + F (+1)]`` float64 matrix into
    ``(event_ts, features, label?)`` per the first/last-column convention."""
    data = np.asarray(data, np.float64)
    if data.ndim != 2:
        data = data.reshape(data.shape[0], -1) if data.size else data.reshape(0, 2)
    min_cols = 3 if labeled else 2
    if data.shape[1] < min_cols:
        raise ValueError(
            f"timed rows need >= {min_cols} columns "
            f"(event_ts + features{' + label' if labeled else ''}); "
            f"got {data.shape[1]}"
        )
    ts = np.ascontiguousarray(data[:, 0])
    if labeled:
        X = np.ascontiguousarray(data[:, 1:-1], dtype=np.float32)
        y = np.ascontiguousarray(data[:, -1])
        return StreamBatch(ts, X, y)
    return StreamBatch(ts, np.ascontiguousarray(data[:, 1:], dtype=np.float32), None)


def parse_lines(lines: List[str], labeled: bool) -> StreamBatch:
    """Parse buffered CSV lines (blank/comment lines already skipped) into
    one batch — float64 end-to-end so unix-epoch event times keep
    sub-second resolution."""
    data = np.loadtxt(_io.StringIO("\n".join(lines)), delimiter=",", ndmin=2)
    return split_timed(data, labeled)


def generator_source(
    batches: Iterable, labeled: bool = False
) -> Iterator[StreamBatch]:
    """Adapt an in-process iterable: items may be :class:`StreamBatch`,
    ``(ts, X)`` / ``(ts, X, y)`` tuples, or raw timed matrices (first
    column = event time, ``labeled`` applies the trailing-label split)."""
    for item in batches:
        if isinstance(item, StreamBatch):
            yield item
        elif isinstance(item, tuple) and len(item) in (2, 3):
            ts, X = item[0], item[1]
            y = item[2] if len(item) == 3 else None
            ts = np.asarray(ts, np.float64).reshape(-1)
            X = np.asarray(X, np.float32)
            yield StreamBatch(ts, X, None if y is None else np.asarray(y, np.float64))
        else:
            yield split_timed(np.asarray(item), labeled)


# --------------------------------------------------------------------------- #
# file tail
# --------------------------------------------------------------------------- #


def _iter_timed_shard(path: str, fmt: str, labeled: bool, chunk_rows: int):
    """Chunked float64 pass over one shard. Only the textual and npy formats
    are tailed — they preserve the float64 event-time column; avro/parquet
    shards decode features as float32 and would truncate unix timestamps."""
    if fmt == "csv":
        buf: List[str] = []
        with open(path, "r") as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                buf.append(line)
                if len(buf) >= chunk_rows:
                    yield parse_lines(buf, labeled)
                    buf.clear()
            if buf:
                yield parse_lines(buf, labeled)
    elif fmt == "npy":
        mm = np.load(path, mmap_mode="r")
        if mm.ndim != 2:
            raise ValueError(f"npy shard {path!r} must be 2-D, got shape {mm.shape}")
        for start in range(0, mm.shape[0], chunk_rows):
            yield split_timed(np.array(mm[start : start + chunk_rows]), labeled)
    else:
        raise ValueError(
            f"stream tailing supports .csv/.npy shards; {path!r} is {fmt!r} "
            "(float32 record formats would truncate the event-time column)"
        )


def _resolve_shards(spec: str) -> List[Tuple[str, str]]:
    """Sorted ``(path, format)`` pairs currently matching ``spec`` (a
    directory or glob). An empty/absent directory resolves to [] — in
    follow mode the very first shard may not exist yet."""
    try:
        source = open_source(spec)
    except FileNotFoundError:
        return []
    return [(s.path, s.format) for s in source.shards]


def tail_source(
    spec: str,
    labeled: bool = False,
    *,
    follow: bool = False,
    poll_s: float = 0.25,
    chunk_rows: int = 4096,
    sleep: Callable[[float], None] = time.sleep,
    stop: Optional[Callable[[], bool]] = None,
) -> Iterator[StreamBatch]:
    """Tail ``spec`` as an append-only timed stream.

    * directory / glob — stream every current ``.csv``/``.npy`` shard in
      sorted-name order, then (``follow=True``) poll every ``poll_s`` for
      shards that appeared since and stream those; a shard is read exactly
      once, so producers must write-then-rename complete files.
    * single file — parse as CSV and, in follow mode, keep reading rows
      appended past the last EOF (the classic ``tail -f``).

    ``stop()`` (checked between batches and polls) ends a follow tail;
    without ``follow`` the iterator ends at the current end of the data.
    """
    if os.path.isfile(spec) and SHARD_FORMATS.get(
        os.path.splitext(spec)[1].lower(), "csv"
    ) == "csv":
        yield from _tail_csv_file(
            spec, labeled, follow=follow, poll_s=poll_s, chunk_rows=chunk_rows,
            sleep=sleep, stop=stop,
        )
        return
    if not follow:
        # a one-shot replay of a missing/empty source is an operator error,
        # not a zero-row stream; only a follow tail may start before its
        # first shard exists
        open_source(spec)
    seen = set()
    while True:
        new = [(p, f) for p, f in _resolve_shards(spec) if p not in seen]
        for path, fmt in new:
            seen.add(path)
            for batch in _iter_timed_shard(path, fmt, labeled, chunk_rows):
                if batch.rows:
                    yield batch
                if stop is not None and stop():
                    return
        if not follow or (stop is not None and stop()):
            return
        if not new:
            sleep(poll_s)


def _tail_csv_file(
    path: str,
    labeled: bool,
    *,
    follow: bool,
    poll_s: float,
    chunk_rows: int,
    sleep: Callable[[float], None],
    stop: Optional[Callable[[], bool]],
) -> Iterator[StreamBatch]:
    buf: List[str] = []
    partial = ""
    position = 0
    while True:
        with open(path, "r") as fh:
            fh.seek(position)
            text = fh.read()
            position = fh.tell()
        lines = (partial + text).split("\n")
        # the final element is "" after a complete line, else a fragment a
        # producer is mid-append on: hold it until its newline lands
        partial = lines.pop()
        for line in lines:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            buf.append(line)
            if len(buf) >= chunk_rows:
                yield parse_lines(buf, labeled)
                buf.clear()
                if stop is not None and stop():
                    return
        if buf:
            yield parse_lines(buf, labeled)
            buf.clear()
        if not follow or (stop is not None and stop()):
            if not follow and partial.strip() and not partial.startswith("#"):
                yield parse_lines([partial.strip()], labeled)
            return
        sleep(poll_s)


# --------------------------------------------------------------------------- #
# TCP line protocol
# --------------------------------------------------------------------------- #


class _LineHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:  # one connection: CSV rows, one per line
        for raw in self.rfile:
            line = raw.decode("utf-8", "replace").strip()
            if line and not line.startswith("#"):
                self.server.lines.put(line)  # type: ignore[attr-defined]


class SocketFeed:
    """A bound TCP line-protocol listener plus its batch iterator.

    ``batches()`` drains complete rows into :class:`StreamBatch` chunks —
    a batch closes at ``chunk_rows`` rows or after ``idle_s`` with no new
    line (so a trickle still flows with bounded latency). ``stop()`` (or
    an external ``should_stop`` callable turning True) shuts the listener
    and ends the iterator once the queue is drained.
    """

    def __init__(
        self,
        port: int,
        host: str = "127.0.0.1",
        *,
        labeled: bool = False,
        chunk_rows: int = 1024,
        idle_s: float = 0.25,
        should_stop: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.labeled = bool(labeled)
        self.chunk_rows = int(chunk_rows)
        self.idle_s = float(idle_s)
        self._should_stop = should_stop
        self._stopped = threading.Event()
        self.server = socketserver.ThreadingTCPServer(
            (host, int(port)), _LineHandler, bind_and_activate=True
        )
        self.server.daemon_threads = True
        self.server.lines = queue.Queue()  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self.server.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True,
            name="isoforest-stream-listener",
        )
        self._thread.start()
        self.address = self.server.server_address[:2]

    @property
    def port(self) -> int:
        return int(self.address[1])

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            self.server.shutdown()
            self.server.server_close()

    def _done(self) -> bool:
        return self._stopped.is_set() or (
            self._should_stop is not None and self._should_stop()
        )

    def batches(self) -> Iterator[StreamBatch]:
        lines: "queue.Queue[str]" = self.server.lines  # type: ignore[attr-defined]
        buf: List[str] = []
        while True:
            try:
                buf.append(lines.get(timeout=self.idle_s))
                if len(buf) < self.chunk_rows:
                    continue
            except queue.Empty:
                if self._done() and lines.empty():
                    break
            if buf:
                yield parse_lines(buf, self.labeled)
                buf = []
        if buf:
            yield parse_lines(buf, self.labeled)
        self.stop()


def socket_source(
    port: int,
    host: str = "127.0.0.1",
    *,
    labeled: bool = False,
    chunk_rows: int = 1024,
    idle_s: float = 0.25,
    should_stop: Optional[Callable[[], bool]] = None,
) -> SocketFeed:
    """Bind the TCP line-protocol listener; iterate ``feed.batches()``."""
    return SocketFeed(
        port,
        host,
        labeled=labeled,
        chunk_rows=chunk_rows,
        idle_s=idle_s,
        should_stop=should_stop,
    )
