"""Tier-wide telemetry federation: merge per-process views into one answer.

Since the replicated tier (docs/replication.md) every serving process —
the router and each replica — keeps its own in-memory telemetry planes.
This module is the pure merge layer the router's federated endpoints
(``GET /metrics``, ``/snapshot``, ``/trace``, ``/traces/recent``,
``/debug/bundle`` mounted by ``replication.router.mount_router``) sit on:
it takes *named* per-source documents (the ``/snapshot`` / ``/trace``
payloads each process already serves) and produces one tier document.

Merge semantics (docs/observability.md §11):

* **counters** sum per label set;
* **histograms** bucket-sum per label set — the ``le`` edges must be
  identical across sources, a mismatch is a typed
  :class:`BucketMismatchError`, never a silent drop;
* **gauges** are not summable (the tier's "outstanding requests" is not
  one number, it is one number per process) — every series gains a
  ``{replica="<source>"}`` label instead;
* **events** interleave by ``unix_s`` with a ``source`` label;
* **traces** stitch across processes: the router's ``router.request``
  span and the replica's ``serving.request`` span share a trace id via
  ``X-Isoforest-Trace``, so :func:`federated_chrome` renders every source
  as its own Perfetto ``pid`` lane and draws flow arrows across the
  process boundary.

All refusals are typed subclasses of :class:`FederationError` (duplicate
source names, conflicting metric types/labels, mismatched bucket edges) —
the HTTP layer maps them to a structured error body, so a malformed tier
can never masquerade as a healthy one. Partial answers are the caller's
job: the router fans out, collects what it can, and reports the rest in
``missing_replicas`` (this module never talks to the network).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .export import _escape_label_value, _format_labels, _format_value  # noqa: F401

_LabelKey = Tuple[Tuple[str, str], ...]


class FederationError(ValueError):
    """Base for typed merge refusals; ``code`` keys the HTTP error body."""

    code = "federation_error"


class DuplicateSourceError(FederationError):
    """Two sources claim the same name — a merge would double-count."""

    code = "duplicate_source"


class MetricTypeConflictError(FederationError):
    """One metric name, conflicting types or label schemas across sources."""

    code = "metric_type_conflict"


class BucketMismatchError(FederationError):
    """One histogram, different ``le`` edges across sources — bucket-wise
    sums would be meaningless, so the merge refuses loudly."""

    code = "bucket_mismatch"


def error_payload(exc: FederationError) -> dict:
    """The structured body federated endpoints return on refusal."""
    return {"error": exc.code, "detail": str(exc)}


def _check_source_names(sources: Sequence[Tuple[str, object]]) -> List[str]:
    names = [str(name) for name, _doc in sources]
    seen = set()
    for name in names:
        if name in seen:
            raise DuplicateSourceError(
                f"duplicate source name {name!r}: every replica must federate "
                "under a unique name"
            )
        seen.add(name)
    return names


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _bucket_edges(series: dict) -> Tuple[str, ...]:
    return tuple(str(bound) for bound, _count in series.get("buckets", ()))


# --------------------------------------------------------------------------- #
# metrics
# --------------------------------------------------------------------------- #


def merge_metrics(
    sources: Sequence[Tuple[str, Dict[str, dict]]]
) -> Dict[str, dict]:
    """Merge per-source registry snapshots (``{name: snapshot-dict}`` as
    produced by ``metrics.registry().snapshot()``) into one document of the
    same shape. Counters sum, histograms bucket-sum (identical edges
    enforced), gauges gain a ``replica`` label. Raises a typed
    :class:`FederationError` subclass on any conflict."""
    _check_source_names(sources)
    ordered_names: List[str] = []
    seen_names = set()
    for source, metrics_doc in sources:
        for metric_name in metrics_doc or {}:
            if metric_name not in seen_names:
                seen_names.add(metric_name)
                ordered_names.append(metric_name)

    out: Dict[str, dict] = {}
    for metric_name in sorted(ordered_names):
        present = [
            (source, (metrics_doc or {})[metric_name])
            for source, metrics_doc in sources
            if metric_name in (metrics_doc or {})
        ]
        types = {snap.get("type") for _s, snap in present}
        if len(types) > 1:
            raise MetricTypeConflictError(
                f"metric {metric_name!r} has conflicting types across "
                f"sources: {sorted(t for t in types if t)}"
            )
        mtype = next(iter(types))
        label_schemas = {tuple(snap.get("labelnames", ())) for _s, snap in present}
        if len(label_schemas) > 1:
            raise MetricTypeConflictError(
                f"metric {metric_name!r} has conflicting label schemas "
                f"across sources: {sorted(label_schemas)}"
            )
        labelnames = list(next(iter(label_schemas)))
        help_text = next(
            (snap.get("help") for _s, snap in present if snap.get("help")), ""
        )
        if mtype == "counter":
            out[metric_name] = _merge_counter(
                metric_name, mtype, help_text, labelnames, present
            )
        elif mtype == "gauge":
            out[metric_name] = _merge_gauge(
                metric_name, help_text, labelnames, present
            )
        elif mtype == "histogram":
            out[metric_name] = _merge_histogram(
                metric_name, help_text, labelnames, present
            )
        else:
            raise MetricTypeConflictError(
                f"metric {metric_name!r} has unknown type {mtype!r}"
            )
    return out


def _merge_counter(name, mtype, help_text, labelnames, present) -> dict:
    totals: Dict[_LabelKey, float] = {}
    for _source, snap in present:
        for series in snap.get("series", ()):
            key = _label_key(series.get("labels", {}))
            totals[key] = totals.get(key, 0) + series.get("value", 0)
    return {
        "type": mtype,
        "help": help_text,
        "labelnames": labelnames,
        "series": [
            {"labels": dict(key), "value": totals[key]}
            for key in sorted(totals)
        ],
    }


def _merge_gauge(name, help_text, labelnames, present) -> dict:
    series_out = []
    for source, snap in present:
        for series in snap.get("series", ()):
            labels = dict(series.get("labels", {}))
            # a gauge that ALREADY speaks per-replica (the router's own
            # isoforest_tier_missing_replicas) keeps its label — the
            # source tag must never clobber it
            labels.setdefault("replica", source)
            series_out.append(
                {"labels": labels, "value": series.get("value", 0)}
            )
    series_out.sort(key=lambda s: _label_key(s["labels"]))
    if "replica" not in labelnames:
        labelnames = [*labelnames, "replica"]
    return {
        "type": "gauge",
        "help": help_text,
        "labelnames": list(labelnames),
        "series": series_out,
    }


def _merge_histogram(name, help_text, labelnames, present) -> dict:
    edges: Optional[Tuple[str, ...]] = None
    edge_owner = None
    acc: Dict[_LabelKey, dict] = {}
    for source, snap in present:
        for series in snap.get("series", ()):
            series_edges = _bucket_edges(series)
            if edges is None:
                edges, edge_owner = series_edges, source
            elif series_edges != edges:
                raise BucketMismatchError(
                    f"histogram {name!r} bucket edges differ between "
                    f"source {edge_owner!r} ({list(edges)}) and source "
                    f"{source!r} ({list(series_edges)})"
                )
            key = _label_key(series.get("labels", {}))
            slot = acc.get(key)
            if slot is None:
                slot = acc[key] = {
                    "labels": dict(series.get("labels", {})),
                    "count": 0,
                    "sum": 0.0,
                    "min": None,
                    "max": None,
                    "counts": [0] * len(series_edges),
                }
            slot["count"] += series.get("count", 0)
            slot["sum"] += series.get("sum", 0.0)
            for stat, pick in (("min", min), ("max", max)):
                value = series.get(stat)
                if value is not None:
                    slot[stat] = (
                        value if slot[stat] is None else pick(slot[stat], value)
                    )
            for i, (_bound, count) in enumerate(series.get("buckets", ())):
                slot["counts"][i] += count
    series_out = []
    for key in sorted(acc):
        slot = acc[key]
        series_out.append(
            {
                "labels": slot["labels"],
                "count": slot["count"],
                "sum": slot["sum"],
                "min": slot["min"],
                "max": slot["max"],
                "buckets": [
                    [bound, slot["counts"][i]]
                    for i, bound in enumerate(edges or ())
                ],
            }
        )
    return {
        "type": "histogram",
        "help": help_text,
        "labelnames": labelnames,
        "series": series_out,
    }


def metrics_to_prometheus(metrics_doc: Dict[str, dict]) -> str:
    """Render a plain registry-snapshot document (local or merged) in the
    Prometheus text exposition format — the same output shape as
    ``export.to_prometheus``, but working from data instead of live metric
    objects, so a merged tier document renders identically."""
    lines: List[str] = []
    for name in metrics_doc:
        snap = metrics_doc[name]
        if snap.get("help"):
            lines.append(f"# HELP {name} {snap['help']}")
        lines.append(f"# TYPE {name} {snap['type']}")
        for series in snap.get("series", ()):
            labels = series.get("labels", {})
            if snap["type"] == "histogram":
                cumulative = 0
                for bound, count in series.get("buckets", ()):
                    cumulative += count
                    le = bound if bound == "+Inf" else _format_value(float(bound))
                    lines.append(
                        f"{name}_bucket"
                        f"{_format_labels(labels, (('le', le),))} {cumulative}"
                    )
                lines.append(
                    f"{name}_sum{_format_labels(labels)} "
                    f"{_format_value(series.get('sum', 0.0))}"
                )
                lines.append(
                    f"{name}_count{_format_labels(labels)} "
                    f"{series.get('count', 0)}"
                )
            else:
                lines.append(
                    f"{name}{_format_labels(labels)} "
                    f"{_format_value(series.get('value', 0))}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------------------------------- #
# events + snapshots
# --------------------------------------------------------------------------- #


def merge_events(
    sources: Sequence[Tuple[str, Iterable[dict]]]
) -> List[dict]:
    """Interleave per-source event timelines by timestamp, each event
    tagged with its ``source``. Ties break on (source, seq) so the merged
    order is deterministic across calls."""
    _check_source_names(sources)
    merged: List[dict] = []
    for source, events in sources:
        for event in events or ():
            merged.append({**event, "source": source})
    merged.sort(
        key=lambda e: (e.get("unix_s", 0.0), e.get("source", ""), e.get("seq", 0))
    )
    return merged


def merge_snapshots(
    sources: Sequence[Tuple[str, dict]],
    missing_replicas: Sequence[str] = (),
) -> dict:
    """Merge per-source ``telemetry.snapshot()`` documents into one tier
    snapshot. The ``metrics`` section keeps the exact registry-snapshot
    shape (tools that read a single process's snapshot — e.g.
    ``tools/serving_latency.py`` — work unchanged against the merged one);
    events interleave with ``source`` labels; per-source trace-ring stats
    are kept under ``traces.sources``. ``missing_replicas`` names fanned-
    out sources that could not answer — a partial answer is explicit,
    never silent."""
    names = _check_source_names(sources)
    merged_metrics = merge_metrics(
        [(name, doc.get("metrics", {})) for name, doc in sources]
    )
    events = merge_events(
        [(name, doc.get("events", ())) for name, doc in sources]
    )
    return {
        "federated": True,
        "sources": names,
        "missing_replicas": sorted(missing_replicas),
        "telemetry_enabled": any(
            doc.get("telemetry_enabled", False) for _n, doc in sources
        ),
        "generated_unix_s": max(
            [doc.get("generated_unix_s", 0.0) for _n, doc in sources],
            default=0.0,
        ),
        "metrics": merged_metrics,
        "events": events,
        "events_dropped": sum(
            doc.get("events_dropped", 0) for _n, doc in sources
        ),
        "traces": {
            "sources": {name: doc.get("traces") for name, doc in sources}
        },
    }


def merge_recent_traces(
    sources: Sequence[Tuple[str, Iterable[dict]]],
    limit: int = 20,
    missing_replicas: Sequence[str] = (),
) -> dict:
    """Merge per-source ``recent_traces`` summaries, newest first, each
    tagged with its ``source``."""
    _check_source_names(sources)
    merged: List[dict] = []
    for source, summaries in sources:
        for summary in summaries or ():
            merged.append({**summary, "source": source})
    merged.sort(
        key=lambda t: (-(t.get("start_unix_s") or 0.0), t.get("source", ""))
    )
    if limit:
        merged = merged[: max(0, int(limit))]
    return {
        "federated": True,
        "traces": merged,
        "missing_replicas": sorted(missing_replicas),
    }


# --------------------------------------------------------------------------- #
# traces: cross-process stitching
# --------------------------------------------------------------------------- #


def flatten_trace_doc(trace: dict) -> List[dict]:
    """Every span dict one ``get_trace``-shaped document carries, including
    link-adjacent traces merged in under ``linked``."""
    out = list(trace.get("spans", ()))
    for adj in trace.get("linked", ()):
        out.extend(adj.get("spans", ()))
    return out


def federated_trace_spans(
    sources: Sequence[Tuple[str, dict]],
    trace_id: str,
    missing_replicas: Sequence[str] = (),
) -> dict:
    """Merge per-source trace documents for one trace id into a flat
    ``spans`` view: each span tagged with its ``source``, de-duplicated by
    span id (sources sharing a process — or a proxy echoing a replica's
    spans — must not double-report), ordered by start time."""
    _check_source_names(sources)
    seen = set()
    spans_out: List[dict] = []
    per_source: Dict[str, dict] = {}
    for source, doc in sources:
        per_source[source] = doc
        for span in flatten_trace_doc(doc):
            span_id = span.get("span_id")
            if span_id and span_id in seen:
                continue
            if span_id:
                seen.add(span_id)
            spans_out.append({**span, "source": source})
    spans_out.sort(key=lambda s: (s.get("start_unix_s") or 0.0, s.get("span_id") or ""))
    return {
        "federated": True,
        "trace_id": trace_id,
        "sources": per_source,
        "missing_replicas": sorted(missing_replicas),
        "spans": spans_out,
    }


def federated_chrome(
    sources: Sequence[Tuple[str, List[dict]]],
    trace_id: Optional[str] = None,
    missing_replicas: Sequence[str] = (),
) -> dict:
    """Stitch per-source span lists into ONE Chrome trace-event document:
    each source gets its own ``pid`` lane (named by ``process_name``
    metadata — "router", replica names, journal spool names), spans keep
    their per-thread ``tid`` lanes inside it, in-process span links render
    as flow arrows exactly like ``export.to_chrome_trace``, and one extra
    arrow family crosses the process boundary: every ``router.request``
    span flows into each *other-source* root span sharing its trace id
    (the replica's ``serving.request`` adopted via ``X-Isoforest-Trace``),
    so Perfetto draws the request hop router-lane → replica-lane."""
    _check_source_names(sources)
    events_out: List[dict] = []
    by_span_id: Dict[str, dict] = {}
    all_docs: List[Tuple[str, dict]] = []
    for pid, (source, span_docs) in enumerate(sources, start=1):
        events_out.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": source},
            }
        )
        tids: Dict[str, int] = {}
        for doc in span_docs or ():
            span_id = doc.get("span_id")
            if span_id and span_id in by_span_id:
                continue  # de-dup: a span lives in its first source's lane
            thread = str(doc.get("thread") or "main")
            if thread not in tids:
                tids[thread] = len(tids) + 1
                events_out.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": tids[thread],
                        "args": {"name": thread},
                    }
                )
            args = {
                "trace_id": doc.get("trace_id"),
                "span_id": span_id,
                "parent_id": doc.get("parent_id"),
                "source": source,
            }
            args.update(doc.get("attrs") or {})
            event = {
                "name": doc["name"],
                "cat": "span",
                "ph": "X",
                "ts": float(doc.get("start_unix_s") or 0.0) * 1e6,
                "dur": max(float(doc.get("wall_s") or 0.0) * 1e6, 1.0),
                "pid": pid,
                "tid": tids[thread],
                "args": args,
            }
            events_out.append(event)
            if span_id:
                by_span_id[span_id] = event
            all_docs.append((source, doc))
    # in-process flow arrows: declared span links (request -> flush)
    for source, doc in all_docs:
        sink = by_span_id.get(doc.get("span_id") or "")
        if sink is None:
            continue
        for target_trace, target_span in doc.get("links") or ():
            origin = by_span_id.get(target_span or "")
            if origin is None:
                continue
            flow_id = str(target_span)
            events_out.append(
                {
                    "name": "coalesce", "cat": "link", "ph": "s",
                    "id": flow_id, "ts": origin["ts"],
                    "pid": origin["pid"], "tid": origin["tid"],
                    "args": {"trace_id": target_trace},
                }
            )
            events_out.append(
                {
                    "name": "coalesce", "cat": "link", "ph": "f", "bp": "e",
                    "id": flow_id, "ts": sink["ts"],
                    "pid": sink["pid"], "tid": sink["tid"],
                    "args": {"trace_id": doc.get("trace_id")},
                }
            )
    # cross-process flow arrows: router.request -> other-source roots
    # sharing the trace id (the hop X-Isoforest-Trace carried on the wire)
    for source, doc in all_docs:
        if doc.get("name") != "router.request":
            continue
        origin = by_span_id.get(doc.get("span_id") or "")
        if origin is None:
            continue
        for other_source, other in all_docs:
            if (
                other_source == source
                or other.get("parent_id") is not None
                or other.get("trace_id") != doc.get("trace_id")
                or other.get("span_id") == doc.get("span_id")
            ):
                continue
            sink = by_span_id.get(other.get("span_id") or "")
            if sink is None:
                continue
            flow_id = f"xproc-{other.get('span_id')}"
            events_out.append(
                {
                    "name": "route", "cat": "xproc", "ph": "s",
                    "id": flow_id, "ts": origin["ts"],
                    "pid": origin["pid"], "tid": origin["tid"],
                    "args": {"trace_id": doc.get("trace_id")},
                }
            )
            events_out.append(
                {
                    "name": "route", "cat": "xproc", "ph": "f", "bp": "e",
                    "id": flow_id, "ts": sink["ts"],
                    "pid": sink["pid"], "tid": sink["tid"],
                    "args": {"trace_id": other.get("trace_id")},
                }
            )
    return {
        "traceEvents": events_out,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": trace_id,
            "federated": True,
            "sources": [name for name, _docs in sources],
            "missing_replicas": sorted(missing_replicas),
            "producer": "isoforest_tpu.telemetry",
        },
    }
