"""Forest structure diagnostics from the finalized scoring layout.

``model.diagnostics()`` answers the operator questions the score stream
cannot: how deep did the trees actually grow, how large are the leaves,
which features do the trees split on (the split-axis inductive bias of
arXiv:2505.12825 — a feature the forest never splits on contributes nothing
to isolation), and how far the realised average path length sits from the
``c(n)`` the score normalisation assumes.

Everything derives from the in-memory packed node tables
(:mod:`~isoforest_tpu.ops.scoring_layout`) plus the heap-tensor
``num_instances`` plane — never from a re-traversal of the raw Avro
records. In particular the *actual* average path length reads the packed
value plane directly: at leaf slots it already holds ``depth + c(n_leaf)``
(the leaf LUT), so the instance-weighted mean over leaves is exactly the
expected path length of a training point — one vectorised reduction over
``[T, M]``.

The same numbers export as gauges via :func:`publish_gauges` (the CLI's
``diagnose --format prometheus`` and anything scraping ``/metrics`` after a
``diagnose`` run); schema in ``docs/observability.md`` §8.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .metrics import gauge as _gauge

_FOREST_TREES = _gauge(
    "isoforest_forest_trees", "Trees in the diagnosed forest"
)
_FOREST_TREE_DEPTH = _gauge(
    "isoforest_forest_tree_depth",
    "Per-tree max leaf depth of the diagnosed forest, by aggregate stat",
    labelnames=("stat",),
)
_FOREST_LEAF_SIZE = _gauge(
    "isoforest_forest_leaf_size",
    "Leaf numInstances of the diagnosed forest, by aggregate stat",
    labelnames=("stat",),
)
_FOREST_AVG_PATH_LENGTH = _gauge(
    "isoforest_forest_avg_path_length",
    "Expected c(numSamples) vs realised instance-weighted average path "
    "length of the diagnosed forest",
    labelnames=("kind",),
)
_FOREST_SPLIT_USAGE = _gauge(
    "isoforest_forest_feature_split_usage",
    "Internal-node split count per feature id in the diagnosed forest",
    labelnames=("feature",),
)


def _slot_depth_vector(max_nodes: int) -> np.ndarray:
    # lazy import: scoring_layout pulls the jax ops chain, which itself
    # imports telemetry during package bring-up
    from ..ops.scoring_layout import _slot_depths

    return np.asarray(_slot_depths(max_nodes))


def forest_diagnostics(model) -> dict:
    """Structure diagnostics for a fitted/loaded model, as plain JSON types.

    Keys: ``model``/``num_trees``/``max_nodes``/``num_samples``/
    ``height_limit``, ``nodes`` (internal/leaf/slot counts + occupancy),
    ``tree_depth`` (per-tree max leaf depth: min/max/mean + histogram),
    ``leaf_size`` (min/max/mean + power-of-two histogram), ``leaf_depth``
    (instance-weighted mean/std), ``feature_split_usage`` (feature id →
    internal-split count; EIF counts every hyperplane coordinate),
    ``path_length`` (expected ``c(n)`` vs realised weighted mean, per-tree
    min/max, ratio) and ``imbalance`` (depth spread + height utilisation).
    """
    from ..ops.scoring_layout import PackedStandardLayout, get_layout
    from ..utils.math import avg_path_length, height_of

    if model._scoring_layout is None:
        model.finalize_scoring()
    layout = model._scoring_layout
    if layout is None:
        # q16-preference models keep the exact f32 layout lazy (it is not
        # part of their resident working set); diagnostics read the exact
        # planes, so resolve them through the shared cache here
        layout = get_layout(model.forest)
    forest = model.forest
    ni = np.asarray(forest.num_instances)
    num_trees, max_nodes = ni.shape
    leaf = ni >= 0
    standard = isinstance(layout, PackedStandardLayout)
    if standard:
        feat = np.asarray(layout.feature, np.int64)
        internal = feat >= 0
        usage = np.bincount(feat[internal]) if internal.any() else np.zeros(0, np.int64)
    else:
        k = layout.k
        # hyperplane coordinate ids live bitcast into the packed record's
        # float lanes; .view() is the host-side inverse bitcast
        ids = np.ascontiguousarray(
            np.asarray(layout.packed, np.float32)[..., 1 : 1 + k]
        ).view(np.int32)
        internal = ids[..., 0] >= 0
        used = ids[internal].reshape(-1)
        used = used[used >= 0]
        usage = np.bincount(used) if used.size else np.zeros(0, np.int64)

    depths = _slot_depth_vector(max_nodes)  # f32 [M], static heap levels
    value = np.asarray(layout.value, np.float64)  # leaf slots: depth + c(n)

    # instance-weighted leaf statistics; per tree, leaf weights sum to the
    # bag size, so the weighted mean of the leaf LUT IS the realised average
    # path length of a training point through that tree
    w = np.where(leaf, ni, 0).astype(np.float64)
    wsum = np.maximum(w.sum(axis=1), 1.0)
    actual_pl = (w * np.where(leaf, value, 0.0)).sum(axis=1) / wsum
    d = np.broadcast_to(depths, (num_trees, max_nodes)).astype(np.float64)
    mean_leaf_depth = (w * np.where(leaf, d, 0.0)).sum(axis=1) / wsum
    mean_leaf_depth_sq = (w * np.where(leaf, d, 0.0) ** 2).sum(axis=1) / wsum
    leaf_depth_std = np.sqrt(
        np.maximum(mean_leaf_depth_sq - mean_leaf_depth**2, 0.0)
    )

    leaf_d = np.where(leaf, d, -np.inf)
    tree_depth_max = leaf_d.max(axis=1)
    tree_depth_min = np.where(leaf, d, np.inf).min(axis=1)
    depth_hist: Dict[str, int] = {}
    for depth_value in tree_depth_max:
        key = str(int(depth_value))
        depth_hist[key] = depth_hist.get(key, 0) + 1

    sizes = ni[leaf].astype(np.int64)
    size_bucket = np.floor(np.log2(np.maximum(sizes, 1))).astype(np.int64)
    size_hist = {
        f"{1 << int(b)}-{(1 << (int(b) + 1)) - 1}": int(c)
        for b, c in zip(*np.unique(size_bucket, return_counts=True))
    }

    expected = float(np.asarray(avg_path_length(model.num_samples)))
    height = height_of(max_nodes)
    internal_count = int(internal.sum())
    leaf_count = int(leaf.sum())
    return {
        "model": "standard" if standard else "extended",
        "num_trees": int(num_trees),
        "max_nodes": int(max_nodes),
        "num_samples": int(model.num_samples),
        "height_limit": int(height),
        "nodes": {
            "internal": internal_count,
            "leaves": leaf_count,
            "slots": int(num_trees * max_nodes),
            "occupancy": round(
                (internal_count + leaf_count) / float(num_trees * max_nodes), 6
            ),
        },
        "tree_depth": {
            "min": int(tree_depth_max.min()),
            "max": int(tree_depth_max.max()),
            "mean": round(float(tree_depth_max.mean()), 4),
            "histogram": {k: depth_hist[k] for k in sorted(depth_hist, key=int)},
        },
        "leaf_depth": {
            "weighted_mean": round(float(mean_leaf_depth.mean()), 4),
            "weighted_std": round(float(leaf_depth_std.mean()), 4),
        },
        "leaf_size": {
            "min": int(sizes.min()),
            "max": int(sizes.max()),
            "mean": round(float(sizes.mean()), 4),
            "histogram": size_hist,
        },
        "feature_split_usage": {
            str(i): int(c) for i, c in enumerate(usage) if c
        },
        "path_length": {
            "expected": round(expected, 6),
            "actual_mean": round(float(actual_pl.mean()), 6),
            "actual_min": round(float(actual_pl.min()), 6),
            "actual_max": round(float(actual_pl.max()), 6),
            "ratio_actual_to_expected": round(
                float(actual_pl.mean()) / expected, 6
            )
            if expected > 0
            else None,
        },
        "imbalance": {
            "depth_spread_mean": round(
                float((tree_depth_max - tree_depth_min).mean()), 4
            ),
            "leaf_depth_std_mean": round(float(leaf_depth_std.mean()), 4),
            "height_utilisation": round(
                float(tree_depth_max.mean()) / height, 4
            )
            if height > 0
            else None,
        },
    }


def publish_gauges(diag: dict) -> None:
    """Mirror a :func:`forest_diagnostics` result onto the metrics registry
    (``isoforest_forest_*`` gauges) so ``/metrics`` scrapes and the CLI's
    Prometheus format carry the structural health numbers too."""
    _FOREST_TREES.set(diag["num_trees"])
    for stat in ("min", "max", "mean"):
        _FOREST_TREE_DEPTH.set(diag["tree_depth"][stat], stat=stat)
        _FOREST_LEAF_SIZE.set(diag["leaf_size"][stat], stat=stat)
    _FOREST_AVG_PATH_LENGTH.set(diag["path_length"]["expected"], kind="expected")
    _FOREST_AVG_PATH_LENGTH.set(
        diag["path_length"]["actual_mean"], kind="actual"
    )
    for feature, count in diag["feature_split_usage"].items():
        _FOREST_SPLIT_USAGE.set(count, feature=feature)
