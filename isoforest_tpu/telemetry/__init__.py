"""Telemetry subsystem: structured spans, metrics registry, event timeline.

The observability substrate for every layer of the package (ISSUE 4 /
ROADMAP serving north star): the reference only logs at phase boundaries
via Spark's ``Logging`` mixin; here a single ``telemetry.snapshot()``
explains a whole run — which phases ran and for how long (spans), how much
work flowed through which kernel (metrics), and every operational incident
in causal order (events: degradation rungs, retries, watchdog timeouts,
checkpoint seals/resumes, distributed bring-up attempts).

Eight coordinated pieces, stdlib-only (plus one jax.monitoring hook):

* :mod:`.spans` — nestable, thread-safe span tracer with wall/process time,
  optional ``jax.profiler.TraceAnnotation`` pass-through, and request-scoped
  trace context (deterministic ``trace_id``/``span_id``, cross-thread span
  links, a bounded trace ring with a slow-request capture policy);
* :mod:`.metrics` — process-wide registry of counters, gauges and
  fixed-bucket histograms with p50/p95/p99 summaries;
* :mod:`.events` — one ordered, timestamped, bounded event timeline;
* :mod:`.export` — JSON snapshot + Prometheus text exposition, wired into
  ``bench.py`` and ``python -m isoforest_tpu telemetry``;
* :mod:`.monitor` — MODEL observability (ISSUE 5): training-baseline
  capture at fit, and streaming PSI/KS drift of serving scores and input
  features against it, with the ``drift_alert`` degradation rung;
* :mod:`.diagnostics` — forest-structure diagnostics (depths, leaf sizes,
  split-feature usage, realised vs expected path length) computed from the
  packed scoring layout;
* :mod:`.http` — a stdlib HTTP daemon serving ``/metrics`` (Prometheus),
  ``/healthz`` (heartbeat liveness), ``/snapshot`` (JSON), ``/trace`` +
  ``/traces/recent`` (Perfetto-loadable request traces), ``/debug/bundle``
  (the flight-recorder artifact), started via :func:`serve` or
  ``ISOFOREST_TPU_METRICS_PORT``;
* :mod:`.resources` — the resource observability plane (docs/observability
  .md §10): XLA compile accounting via a ``jax.monitoring`` listener with
  ``compile_scope`` attribution and a warmup/steady phase, host-staging and
  resident-plane memory watermarks, and the ``build_bundle`` flight
  recorder behind ``GET /debug/bundle`` /
  ``python -m isoforest_tpu debug-bundle``.

Telemetry is ON by default and near-zero cost when disabled
(``ISOFOREST_TPU_TELEMETRY=0`` or :func:`disable`; the enabled-vs-disabled
scoring overhead is gated at 3% in CI via ``tools/bench_smoke.py``).
Span/metric/event names and schemas are documented in
``docs/observability.md``.
"""

from ._state import disable, enable, enabled
from .diagnostics import forest_diagnostics, publish_gauges
from .events import (
    Event,
    EventTimeline,
    get_events,
    record_event,
    set_event_sink,
    timeline,
)
from .export import (
    parse_prometheus,
    reset,
    snapshot,
    snapshot_json,
    to_chrome_trace,
    to_chrome_trace_json,
    to_prometheus,
)
from .federation import (
    BucketMismatchError,
    DuplicateSourceError,
    FederationError,
    MetricTypeConflictError,
    federated_chrome,
    federated_trace_spans,
    merge_events,
    merge_metrics,
    merge_recent_traces,
    merge_snapshots,
    metrics_to_prometheus,
)
from .http import MetricsServer, active_server, maybe_serve_from_env, serve
from .journal import (
    Journal,
    activate_journal,
    active_journal,
    deactivate_journal,
    list_spools,
    read_spool,
)
from .metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    exponential_buckets,
    gauge,
    histogram,
    registry,
)
from .monitor import (
    Baseline,
    ScoreMonitor,
    StreamBaseline,
    capture_baseline,
    ks,
    psi,
)
from .resources import (
    BUNDLE_SCHEMA,
    BUNDLE_SECTIONS,
    build_bundle,
    compile_counts,
    compile_log,
    compile_scope,
    compile_seconds_total,
    disable_resources,
    enable_resources,
    mark_steady,
    mark_warmup,
    memory_watermarks,
    model_plane_bytes,
    note_host_staging,
    peak_host_staging_bytes,
    reset_resources,
    resident_plane_bytes,
    resources_enabled,
    warmup_scope,
    write_bundle,
)
from .spans import (
    SpanRecord,
    TraceContext,
    current_context,
    current_span_name,
    get_trace,
    recent_traces,
    reset_traces,
    seed_trace_ids,
    set_span_attrs,
    set_trace_commit_sink,
    set_trace_policy,
    span,
    trace_stats,
    with_context,
)
from .spans import records as span_records
from .spans import summary as span_summary

__all__ = [
    "BUNDLE_SCHEMA",
    "BUNDLE_SECTIONS",
    "BucketMismatchError",
    "DEFAULT_LATENCY_BUCKETS",
    "Baseline",
    "Counter",
    "DuplicateSourceError",
    "Event",
    "EventTimeline",
    "FederationError",
    "Gauge",
    "Histogram",
    "Journal",
    "MetricTypeConflictError",
    "MetricsRegistry",
    "MetricsServer",
    "ScoreMonitor",
    "SpanRecord",
    "StreamBaseline",
    "TraceContext",
    "activate_journal",
    "active_journal",
    "active_server",
    "build_bundle",
    "capture_baseline",
    "compile_counts",
    "compile_log",
    "compile_scope",
    "compile_seconds_total",
    "counter",
    "current_context",
    "current_span_name",
    "deactivate_journal",
    "disable",
    "disable_resources",
    "enable",
    "enable_resources",
    "enabled",
    "exponential_buckets",
    "federated_chrome",
    "federated_trace_spans",
    "forest_diagnostics",
    "gauge",
    "get_events",
    "get_trace",
    "histogram",
    "ks",
    "list_spools",
    "mark_steady",
    "mark_warmup",
    "maybe_serve_from_env",
    "memory_watermarks",
    "merge_events",
    "merge_metrics",
    "merge_recent_traces",
    "merge_snapshots",
    "metrics_to_prometheus",
    "model_plane_bytes",
    "note_host_staging",
    "parse_prometheus",
    "peak_host_staging_bytes",
    "psi",
    "publish_gauges",
    "read_spool",
    "recent_traces",
    "record_event",
    "registry",
    "reset",
    "reset_resources",
    "reset_traces",
    "resident_plane_bytes",
    "resources_enabled",
    "seed_trace_ids",
    "serve",
    "set_event_sink",
    "set_span_attrs",
    "set_trace_commit_sink",
    "set_trace_policy",
    "snapshot",
    "snapshot_json",
    "span",
    "span_records",
    "span_summary",
    "timeline",
    "to_chrome_trace",
    "to_chrome_trace_json",
    "to_prometheus",
    "trace_stats",
    "warmup_scope",
    "with_context",
    "write_bundle",
]

# live /metrics endpoint opt-in: exporting ISOFOREST_TPU_METRICS_PORT makes
# any process that imports the package serve its telemetry without a single
# code change (docs/observability.md §8)
maybe_serve_from_env()

# crash-durable flight recorder opt-in: exporting ISOFOREST_TPU_JOURNAL_DIR
# spools every event and committed trace to disk the same zero-code way
# (docs/observability.md §12)
from .journal import maybe_activate_from_env as _maybe_activate_journal

_maybe_activate_journal()
