"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Stdlib-only (no prometheus_client in the image) but Prometheus-shaped so
:mod:`.export` can emit standard text exposition: counters end in
``_total``, histograms keep cumulative ``le`` bucket semantics (a value
lands in the first bucket whose upper bound is ``>= value``), and every
metric carries a fixed ``labelnames`` tuple with per-label-set series.

Histograms additionally track exact ``min``/``max`` per series and derive
p50/p95/p99 summaries by linear interpolation inside the matched bucket
(clamped to the observed min/max, so a wide final bucket cannot report a
quantile beyond any real observation) — the summary ``tools/serving_latency.py``
reports and ``docs/observability.md`` documents.

Everything is thread-safe: serving stacks score from worker pools and the
resilience watchdogs record from abandoned daemon threads. When telemetry
is disabled (:mod:`._state`) every mutator returns immediately; readers
(snapshots, summaries) always work so an operator can inspect what was
recorded before the flag flipped.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import _state

# Default buckets for wall-clock durations: 100 us .. 60 s, roughly
# 2.5x steps — wide enough for both a 1-row serving score and a 1M-row
# bulk pass, small enough that exposition stays readable.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def exponential_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """``count`` geometric bucket bounds from ``start``: finer-grained
    alternatives to :data:`DEFAULT_LATENCY_BUCKETS` (the serving-latency
    tool uses ~1.3x steps so p99 resolves to ~30% relative error)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError(
            f"need start > 0, factor > 1, count >= 1; got {start}, {factor}, {count}"
        )
    return tuple(start * factor**i for i in range(count))


def _check_labels(labelnames: Tuple[str, ...], labels: Dict[str, object]) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"label mismatch: metric declares {list(labelnames)}, "
            f"call supplied {sorted(labels)}"
        )
    return tuple(str(labels[name]) for name in labelnames)


class _Metric:
    """Shared per-metric machinery: name/help/labelnames + series dict."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str]) -> None:
        self.name = str(name)
        self.help = str(help)
        self.labelnames = tuple(str(n) for n in labelnames)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], object] = {}

    def _clear(self) -> None:
        with self._lock:
            self._series.clear()

    def series_labels(self) -> List[Dict[str, str]]:
        with self._lock:
            return [
                dict(zip(self.labelnames, key)) for key in sorted(self._series)
            ]


class Counter(_Metric):
    """Monotonically increasing count; ``inc(amount, **labels)``."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: object) -> None:
        if not _state.enabled():
            return
        if amount < 0:
            raise ValueError(f"counters only go up; got inc({amount})")
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: object) -> float:
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def snapshot(self) -> dict:
        with self._lock:
            series = [
                {"labels": dict(zip(self.labelnames, key)), "value": value}
                for key, value in sorted(self._series.items())
            ]
        return {
            "type": self.kind,
            "help": self.help,
            "labelnames": list(self.labelnames),
            "series": series,
        }


class Gauge(_Metric):
    """Point-in-time value; ``set``/``inc``/``dec``."""

    kind = "gauge"

    def set(self, value: float, **labels: object) -> None:
        if not _state.enabled():
            return
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: object) -> None:
        if not _state.enabled():
            return
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: object) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: object) -> float:
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    snapshot = Counter.snapshot


class _HistSeries:
    __slots__ = ("bucket_counts", "count", "sum", "min", "max")

    def __init__(self, num_buckets: int) -> None:
        self.bucket_counts = [0] * num_buckets
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None


class Histogram(_Metric):
    """Fixed-bucket histogram with Prometheus ``le`` semantics plus exact
    min/max per series. ``buckets`` are the finite upper bounds; a final
    ``+Inf`` bucket is implicit."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
    ) -> None:
        super().__init__(name, help, labelnames)
        bounds = tuple(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram needs at least one finite bucket bound")
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError(f"bucket bounds must strictly increase: {bounds}")
        if math.isinf(bounds[-1]):
            bounds = bounds[:-1]  # +Inf is implicit
        self.buckets = bounds

    def observe(self, value: float, **labels: object) -> None:
        if not _state.enabled():
            return
        value = float(value)
        key = _check_labels(self.labelnames, labels)
        # first index whose bound >= value == the `le` bucket; past the last
        # finite bound lands in the implicit +Inf slot
        index = bisect.bisect_left(self.buckets, value)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistSeries(len(self.buckets) + 1)
            series.bucket_counts[index] += 1
            series.count += 1
            series.sum += value
            series.min = value if series.min is None else min(series.min, value)
            series.max = value if series.max is None else max(series.max, value)

    def _get(self, labels: Dict[str, object]) -> Optional[_HistSeries]:
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            return self._series.get(key)

    def quantile(self, q: float, **labels: object) -> float:
        """Bucket-interpolated quantile in ``[0, 1]``; 0.0 when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        series = self._get(labels)
        if series is None or series.count == 0:
            return 0.0
        target = q * series.count
        cumulative = 0.0
        lower = 0.0
        for bound, in_bucket in zip(
            self.buckets + (math.inf,), series.bucket_counts
        ):
            previous = cumulative
            cumulative += in_bucket
            if cumulative >= target and in_bucket > 0:
                if math.isinf(bound):
                    estimate = lower
                else:
                    estimate = lower + (bound - lower) * (
                        (target - previous) / in_bucket
                    )
                break
            if not math.isinf(bound):
                lower = bound
        else:  # pragma: no cover - loop always breaks once cumulative==count
            estimate = lower
        # a wide bucket must not report a value outside anything observed
        return min(max(estimate, series.min), series.max)

    def summary(self, **labels: object) -> dict:
        """``{count, sum, min, max, p50, p95, p99}`` for one series."""
        series = self._get(labels)
        if series is None or series.count == 0:
            return {
                "count": 0, "sum": 0.0, "min": None, "max": None,
                "p50": None, "p95": None, "p99": None,
            }
        return {
            "count": series.count,
            "sum": series.sum,
            "min": series.min,
            "max": series.max,
            "p50": self.quantile(0.50, **labels),
            "p95": self.quantile(0.95, **labels),
            "p99": self.quantile(0.99, **labels),
        }

    def snapshot(self) -> dict:
        bounds = [*self.buckets, math.inf]
        with self._lock:
            series = [
                {
                    "labels": dict(zip(self.labelnames, key)),
                    "count": s.count,
                    "sum": s.sum,
                    "min": s.min,
                    "max": s.max,
                    # per-bucket (non-cumulative) counts; export derives the
                    # cumulative `le` form. +Inf serialises as "+Inf".
                    "buckets": [
                        ["+Inf" if math.isinf(b) else b, c]
                        for b, c in zip(bounds, s.bucket_counts)
                    ],
                }
                for key, s in sorted(self._series.items())
            ]
        return {
            "type": self.kind,
            "help": self.help,
            "labelnames": list(self.labelnames),
            "series": series,
        }


class MetricsRegistry:
    """Get-or-create registry; one process-wide instance backs the module
    helpers. Re-registering a name with a different type/labelnames/buckets
    raises — a silent shape change would corrupt every existing series."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, labelnames, **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.labelnames}"
                    )
                if (
                    isinstance(existing, Histogram)
                    and "buckets" in kw
                    and kw["buckets"] is not None
                    and existing.buckets
                    != tuple(float(b) for b in kw["buckets"])
                ):
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {existing.buckets}"
                    )
                return existing
            metric = cls(name, help, labelnames, **{k: v for k, v in kw.items() if v is not None})
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        if buckets is not None:
            buckets = tuple(float(b) for b in buckets)
            if buckets and math.isinf(buckets[-1]):
                buckets = buckets[:-1]  # +Inf is implicit, as in Histogram
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[name] for name in sorted(self._metrics)]

    def snapshot(self) -> Dict[str, dict]:
        return {m.name: m.snapshot() for m in self.metrics()}

    def reset(self) -> None:
        """Clear every series IN PLACE — metric objects cached at module
        scope by instrumented code stay registered and usable."""
        for metric in self.metrics():
            metric._clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry instance."""
    return _REGISTRY


def counter(name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
    return _REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
    return _REGISTRY.gauge(name, help, labelnames)


def histogram(
    name: str,
    help: str = "",
    labelnames: Sequence[str] = (),
    buckets: Optional[Iterable[float]] = None,
) -> Histogram:
    return _REGISTRY.histogram(name, help, labelnames, buckets)


def reset_metrics() -> None:
    _REGISTRY.reset()
