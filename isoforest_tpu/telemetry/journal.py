"""Crash-durable flight recorder: a per-process append-only NDJSON spool.

Every in-memory telemetry plane — the trace ring, the event timeline, the
metrics registry — dies with its process. For a single serve that is an
acceptable trade (the debug bundle is one curl away), but the replicated
tier's whole point is that processes die: a SIGKILLed replica takes its
entire telemetry history to the grave exactly when an operator most needs
it. The journal closes that gap the way Spark's persistent event log does
for executors: when ``--journal-dir`` / ``ISOFOREST_TPU_JOURNAL_DIR`` is
set, every recorded event (degradation rungs included — they flow through
``record_event``) and every committed trace is *also* appended to an
on-disk NDJSON spool, so the tier ``/debug/bundle`` can read a dead
replica's last moments off disk (docs/observability.md §12).

Spool layout — one directory per process under the shared journal root::

    <journal_dir>/<name>/segment-00000.ndjson
    <journal_dir>/<name>/segment-00001.ndjson      # rotated by size
    ...

Each line is one JSON record: ``{"type": "open", ...}`` when a segment
starts, ``{"type": "event", "seq", "unix_s", "kind", ...}`` per timeline
event, ``{"type": "trace", "trace": {...}}`` per committed trace (the full
trace-ring entry: root, spans, links). Writes are flushed per record (a
kill -9 loses at most the record being written) and fsynced every
``fsync_every`` records (machine-crash durability is a knob, not a tax);
segments rotate at ``max_segment_bytes`` and the oldest are deleted past
``max_segments`` so a spool is size-bounded like every other telemetry
plane. The reader tolerates a torn final line — a process killed
mid-``write`` leaves a half-record that is counted (``torn_tail``), never
raised.

Activation installs two sinks: the event-timeline tap
(:func:`..events.set_event_sink`) and the trace-commit tap
(:func:`..spans.set_trace_commit_sink`, invoked outside the trace-ring
lock so file I/O never blocks span completion). Both are None when no
journal is active, so the disabled cost is one attribute read.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

from . import events as _events
from . import spans as _spans

JOURNAL_DIR_ENV = "ISOFOREST_TPU_JOURNAL_DIR"
JOURNAL_NAME_ENV = "ISOFOREST_TPU_JOURNAL_NAME"
JOURNAL_FSYNC_ENV = "ISOFOREST_TPU_JOURNAL_FSYNC_EVERY"
JOURNAL_SEGMENT_ENV = "ISOFOREST_TPU_JOURNAL_SEGMENT_BYTES"

DEFAULT_SEGMENT_BYTES = 4 << 20  # rotate spool segments at 4 MiB
DEFAULT_FSYNC_EVERY = 64         # fsync cadence in records (0 = never)
DEFAULT_MAX_SEGMENTS = 8         # keep at most this many segments per spool

SEGMENT_PREFIX = "segment-"
SEGMENT_SUFFIX = ".ndjson"


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    return default


class Journal:
    """One process's append-only spool under ``<root>/<name>/``.

    Thread-safe: the event tap fires from any instrumented thread and the
    trace tap from whichever thread completes a root span. A journal that
    hits an OS error (disk full, directory removed) disarms itself after
    logging once — flight recording must never take the plane down."""

    def __init__(
        self,
        root: str,
        name: str,
        *,
        max_segment_bytes: Optional[int] = None,
        fsync_every: Optional[int] = None,
        max_segments: int = DEFAULT_MAX_SEGMENTS,
    ) -> None:
        self.root = str(root)
        self.name = str(name)
        self.spool_dir = os.path.join(self.root, self.name)
        self.max_segment_bytes = int(
            max_segment_bytes
            if max_segment_bytes is not None
            else _env_int(JOURNAL_SEGMENT_ENV, DEFAULT_SEGMENT_BYTES)
        )
        self.fsync_every = int(
            fsync_every
            if fsync_every is not None
            else _env_int(JOURNAL_FSYNC_ENV, DEFAULT_FSYNC_EVERY)
        )
        self.max_segments = max(1, int(max_segments))
        self._lock = threading.Lock()
        self._fh = None
        self._segment_index = 0
        self._segment_bytes = 0
        self._records = 0
        self._fsyncs = 0
        self._since_fsync = 0
        self._broken = False
        os.makedirs(self.spool_dir, exist_ok=True)
        # resume after the highest existing segment: a restarted replica
        # appends a new segment instead of clobbering its own history
        existing = _segment_indices(self.spool_dir)
        self._segment_index = (existing[-1] + 1) if existing else 0
        self._open_segment()

    # ------------------------------------------------------------ writing #

    def _segment_path(self, index: int) -> str:
        return os.path.join(
            self.spool_dir, f"{SEGMENT_PREFIX}{index:05d}{SEGMENT_SUFFIX}"
        )

    def _open_segment(self) -> None:
        self._fh = open(self._segment_path(self._segment_index), "a")
        self._segment_bytes = self._fh.tell()
        header = {
            "type": "open",
            "name": self.name,
            "pid": os.getpid(),
            "unix_s": round(time.time(), 3),
            "segment": self._segment_index,
        }
        self._write_locked(json.dumps(header, sort_keys=True) + "\n")

    def _rotate_locked(self) -> None:
        self._fh.close()
        self._segment_index += 1
        self._open_segment()
        indices = _segment_indices(self.spool_dir)
        for index in indices[: max(0, len(indices) - self.max_segments)]:
            try:
                os.unlink(self._segment_path(index))
            except OSError:
                pass  # already gone / racing reader: retention is best-effort

    def _write_locked(self, line: str) -> None:
        self._fh.write(line)
        # flush per record: a kill -9 victim's spool is complete up to the
        # record in flight (page cache survives process death; only a
        # machine crash needs the fsync cadence below)
        self._fh.flush()
        self._segment_bytes += len(line.encode("utf-8"))
        self._since_fsync += 1
        if self.fsync_every and self._since_fsync >= self.fsync_every:
            os.fsync(self._fh.fileno())
            self._fsyncs += 1
            self._since_fsync = 0

    def append(self, doc: dict) -> None:
        """Append one record; errors disarm the journal (logged once)."""
        if self._broken:
            return
        try:
            line = json.dumps(doc, sort_keys=True, default=repr) + "\n"
        except (TypeError, ValueError):
            return  # an unserialisable record must not kill the recorder
        try:
            with self._lock:
                if self._fh is None:
                    return
                if (
                    self._segment_bytes + len(line) > self.max_segment_bytes
                    and self._segment_bytes > 0
                ):
                    self._rotate_locked()
                self._write_locked(line)
                self._records += 1
        except OSError as exc:
            self._broken = True
            from ..utils.logging import logger

            logger.warning(
                "journal %s disarmed after write failure: %r", self.spool_dir, exc
            )

    def state(self) -> dict:
        """Spool accounting for ``/debug/bundle`` and the bench gate."""
        with self._lock:
            return {
                "name": self.name,
                "spool_dir": self.spool_dir,
                "segment": self._segment_index,
                "segment_bytes": self._segment_bytes,
                "records": self._records,
                "fsyncs": self._fsyncs,
                "fsync_every": self.fsync_every,
                "max_segment_bytes": self.max_segment_bytes,
                "max_segments": self.max_segments,
                "broken": self._broken,
            }

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# --------------------------------------------------------------------------- #
# reading: torn-tail-tolerant spool recovery
# --------------------------------------------------------------------------- #


def _segment_indices(spool_dir: str) -> List[int]:
    out = []
    try:
        names = os.listdir(spool_dir)
    except OSError:
        return []
    for name in names:
        if name.startswith(SEGMENT_PREFIX) and name.endswith(SEGMENT_SUFFIX):
            try:
                out.append(int(name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]))
            except ValueError:
                continue
    return sorted(out)


def read_spool(spool_dir: str, tail: Optional[int] = None) -> dict:
    """Recover one spool off disk — the dead replica's flight recorder.

    Returns ``{"name", "records", "segments", "torn_tail", "skipped_lines"}``.
    A final line that fails to parse in the LAST segment is the torn tail a
    kill -9 mid-write leaves; it is counted, never raised. Unparseable
    lines elsewhere count as ``skipped_lines``. ``tail`` keeps only the
    newest N records (the bundle embeds a bounded view)."""
    indices = _segment_indices(spool_dir)
    records: List[dict] = []
    torn_tail = False
    skipped = 0
    for pos, index in enumerate(indices):
        path = os.path.join(
            spool_dir, f"{SEGMENT_PREFIX}{index:05d}{SEGMENT_SUFFIX}"
        )
        try:
            with open(path) as fh:
                lines = fh.read().split("\n")
        except OSError:
            continue
        last_segment = pos == len(indices) - 1
        for line_no, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                if last_segment and line_no >= len(lines) - 2:
                    # the final (possibly newline-less) line of the newest
                    # segment: the kill -9 signature, tolerated by design
                    torn_tail = True
                else:
                    skipped += 1
    if tail is not None and tail >= 0:
        records = records[-tail:] if tail else []
    return {
        "name": os.path.basename(spool_dir.rstrip("/")),
        "records": records,
        "segments": len(indices),
        "torn_tail": torn_tail,
        "skipped_lines": skipped,
    }


def list_spools(journal_dir: str) -> List[str]:
    """Spool names (one per process that journaled) under a journal root."""
    try:
        names = os.listdir(journal_dir)
    except OSError:
        return []
    return sorted(
        n for n in names
        if os.path.isdir(os.path.join(journal_dir, n))
        and _segment_indices(os.path.join(journal_dir, n))
    )


# --------------------------------------------------------------------------- #
# activation: install the event + trace-commit taps
# --------------------------------------------------------------------------- #

_active_lock = threading.Lock()
_ACTIVE: Optional[Journal] = None


def activate_journal(
    journal_dir: str,
    name: Optional[str] = None,
    *,
    max_segment_bytes: Optional[int] = None,
    fsync_every: Optional[int] = None,
) -> Journal:
    """Start flight-recording this process into ``<journal_dir>/<name>/``.

    Installs the event-timeline and trace-commit sinks; replaces any
    previously active journal. ``name`` defaults to
    ``ISOFOREST_TPU_JOURNAL_NAME``, then ``ISOFOREST_TPU_REPLICA_NAME``
    (a spawned replica spools under its tier name), then ``pid-<pid>``."""
    global _ACTIVE
    if name is None:
        name = (
            os.environ.get(JOURNAL_NAME_ENV)
            or os.environ.get("ISOFOREST_TPU_REPLICA_NAME")
            or f"pid-{os.getpid()}"
        )
    journal = Journal(
        journal_dir,
        name,
        max_segment_bytes=max_segment_bytes,
        fsync_every=fsync_every,
    )
    with _active_lock:
        previous, _ACTIVE = _ACTIVE, journal
    if previous is not None:
        previous.close()
    _events.set_event_sink(
        lambda event: journal.append({"type": "event", **event.as_dict()})
    )
    _spans.set_trace_commit_sink(
        lambda entry: journal.append({"type": "trace", "trace": entry})
    )
    _events.record_event(
        "journal.start", name=journal.name, spool_dir=journal.spool_dir,
        fsync_every=journal.fsync_every,
        max_segment_bytes=journal.max_segment_bytes,
    )
    return journal


def deactivate_journal() -> None:
    """Stop flight-recording (idempotent); the spool stays on disk."""
    global _ACTIVE
    with _active_lock:
        journal, _ACTIVE = _ACTIVE, None
    if journal is None:
        return
    # record the stop marker while the sink is still armed so the spool's
    # last record says the process stopped cleanly (a spool WITHOUT it and
    # with a torn tail is the kill -9 signature)
    _events.record_event("journal.stop", name=journal.name,
                         records=journal.state()["records"])
    _events.set_event_sink(None)
    _spans.set_trace_commit_sink(None)
    journal.close()


def active_journal() -> Optional[Journal]:
    """The currently recording journal, if any."""
    return _ACTIVE


def maybe_activate_from_env() -> Optional[Journal]:
    """Auto-activate at package import when ``ISOFOREST_TPU_JOURNAL_DIR``
    is set — the same opt-in pattern as the metrics endpoint. A spool
    failure logs a warning instead of breaking the import."""
    raw = os.environ.get(JOURNAL_DIR_ENV)
    if not raw or _ACTIVE is not None:
        return None
    try:
        return activate_journal(raw)
    except Exception as exc:
        from ..utils.logging import logger

        logger.warning(
            "could not activate the telemetry journal from %s=%r: %s",
            JOURNAL_DIR_ENV, raw, exc,
        )
        return None
